"""One retry/backoff vocabulary for the whole tree (ISSUE 9 satellite).

Three hand-rolled retry idioms grew up independently — the batch
scheduler's transient dispatch/fetch retry (``retry_backoff_s * 2**attempt``
inline loops in engine/batch.py), the serving layer's preemption requeue
loop (``for attempt in range(MAX_PREEMPT_REQUEUES + 1)`` in server/api.py),
and the replica supervisor's restart loop (server/replicas.py) — and each
would have answered "what does attempt 3 wait?" differently. This module is
the single definition:

* :class:`BackoffPolicy` — a frozen description of the schedule: total
  ``attempts`` (``UNBOUNDED`` = keep trying), exponential delay
  ``base_s * multiplier**n`` capped at ``max_s``, plus up to ``jitter_s``
  of uniform additive jitter drawn from a caller-supplied RNG.
  **Seeded-jitter contract:** the policy never owns entropy — callers pass
  ``random.Random(seed)`` when determinism matters (tests, chaos replays)
  and an entropy-seeded RNG when it must NOT (the replica restart herd:
  deterministic restart backoff would re-synchronize replicas restored
  from the same image, exactly like the Retry-After jitter satellite of
  ISSUE 8).
* :func:`retry_call` — run a callable under a policy: failures matching
  ``retry_on`` sleep the policy's delay and try again; the last failure
  re-raises when attempts are exhausted. ``on_retry(attempt, exc)`` runs
  before each sleep (metrics hooks; raising from it aborts the loop —
  that is the supervisor's shutdown hatch).

``retry_call`` catches only ``retry_on`` (default ``Exception``):
KeyboardInterrupt/SystemExit always propagate — the PR 3 lesson that a
Ctrl-C must abort, never be retried into a quarantine, is structural here.
"""

from __future__ import annotations

import dataclasses
import time

UNBOUNDED = -1


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """An immutable retry schedule. ``attempts`` counts TOTAL tries
    (``1`` = no retry at all, :data:`UNBOUNDED` = retry forever);
    ``delay_s(n)`` is the wait after failed attempt ``n`` (0-based):
    ``min(base_s * multiplier**n, max_s)`` plus ``uniform(0, jitter_s)``
    from the caller's RNG."""

    attempts: int
    base_s: float = 0.0
    multiplier: float = 2.0
    max_s: float = float("inf")
    jitter_s: float = 0.0

    def __post_init__(self):
        if self.attempts == 0 or self.attempts < UNBOUNDED:
            raise ValueError(
                f"attempts must be >= 1 or UNBOUNDED, got {self.attempts}"
            )
        if self.base_s < 0 or self.max_s < 0 or self.jitter_s < 0:
            raise ValueError("delays must be non-negative")
        if self.multiplier < 1.0:
            raise ValueError(
                f"multiplier must be >= 1 (got {self.multiplier}): a "
                "shrinking backoff is a retry storm with extra steps"
            )

    def delay_s(self, attempt: int, rng=None) -> float:
        """Seconds to wait after failed attempt ``attempt`` (0-based)."""
        # exponent capped at 1023: float**int raises OverflowError past
        # ~1.8e308, and an UNBOUNDED supervision loop (a replica whose
        # rebuild keeps failing for hours) must keep retrying at max_s,
        # not die of arithmetic at attempt ~1024
        d = min(self.base_s * self.multiplier ** min(attempt, 1023), self.max_s)
        if self.jitter_s > 0.0 and rng is not None:
            d += rng.uniform(0.0, self.jitter_s)
        return d

    def more(self, attempt: int) -> bool:
        """True when attempt index ``attempt`` (0-based) is allowed."""
        return self.attempts == UNBOUNDED or attempt < self.attempts


def retry_call(
    fn,
    policy: BackoffPolicy,
    *,
    retry_on=Exception,
    on_retry=None,
    sleep=time.sleep,
    rng=None,
):
    """Call ``fn()`` under ``policy``. Returns ``fn``'s result on the first
    success; re-raises the last failure once attempts are exhausted. Only
    exceptions matching ``retry_on`` are retried — anything else (including
    KeyboardInterrupt/SystemExit, which are not ``Exception``) propagates
    immediately. ``on_retry(attempt, exc)`` is invoked before each backoff
    sleep with the 0-based failed-attempt index; an exception raised from
    it propagates (the caller's way to abort an UNBOUNDED loop).
    ``sleep``/``rng`` are injectable for tests (and for callers that must
    sleep through something other than ``time.sleep``)."""
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            if not policy.more(attempt + 1):
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            d = policy.delay_s(attempt, rng)
            if d > 0.0:
                sleep(d)
            attempt += 1
