"""Block quantization formats Q40 and Q80.

File-format compatible with the reference's block layout
(reference: src/quants.hpp:14-25 — BlockQ40 {f16 d; uint8 qs[16]},
BlockQ80 {f16 d; int8 qs[32]}, QK=32) and with the quantization math of the
reference converter (reference: converter/writer.py:29-74), so `.m` files are
interchangeable between the two runtimes.

Two representations are provided:

* **Wire/file form** — raw bytes, block-interleaved (scale then quants), used
  by the `.m` reader/writer and the converter toolchain (numpy, host only).
* **Device (struct-of-arrays) form** — separate `qs` / `scale` arrays laid out
  for TPU consumption: contiguous int arrays that XLA/Pallas can tile onto the
  MXU/VPU, with per-block scales kept in a parallel array. This is *not* the
  reference's array-of-structs layout: on TPU, mixed scale/payload structs
  would defeat vectorization, so the loader transposes to SoA once at load.

Q40 semantics (reference: converter/writer.py:29-53, src/quants.cpp:137-184):
  blocks of 32 values; delta = signed absmax / -8 stored as f16;
  q = clip(floor(x/delta + 8.5), 0, 15); byte j packs value j in the low
  nibble and value j+16 in the high nibble; dequant = (nibble - 8) * delta.

Q80 semantics (reference: converter/writer.py:55-74, src/quants.cpp:186-288):
  blocks of 32 values; delta = absmax / 127 stored as f16;
  q = round(x/delta) as int8; dequant = q * delta.
"""

from __future__ import annotations

import enum

import numpy as np

QK = 32  # block size shared by Q40 and Q80 (reference: src/quants.hpp:14-15)
Q40_BLOCK_BYTES = 2 + QK // 2  # f16 scale + 16 packed nibble bytes
Q80_BLOCK_BYTES = 2 + QK  # f16 scale + 32 int8


class FloatType(enum.IntEnum):
    """On-disk tensor dtypes (reference: src/quants.hpp:5-12, converter/writer.py:6-10)."""

    F32 = 0
    F16 = 1
    Q40 = 2
    Q80 = 3

    @property
    def short_name(self) -> str:
        return self.name.lower()


FLOAT_TYPE_BY_NAME = {t.short_name: t for t in FloatType}


def parse_float_type(name: str) -> FloatType:
    try:
        return FLOAT_TYPE_BY_NAME[name.lower()]
    except KeyError:
        raise ValueError(f"unsupported float type: {name!r}") from None


def tensor_bytes(float_type: FloatType, n_values: int) -> int:
    """Serialized size of a flat tensor (reference: src/quants.cpp:11-35 getBatchBytes)."""
    if float_type == FloatType.F32:
        return n_values * 4
    if float_type == FloatType.F16:
        return n_values * 2
    if n_values % QK != 0:
        raise ValueError(f"quantized tensor length {n_values} not divisible by {QK}")
    n_blocks = n_values // QK
    if float_type == FloatType.Q40:
        return n_blocks * Q40_BLOCK_BYTES
    if float_type == FloatType.Q80:
        return n_blocks * Q80_BLOCK_BYTES
    raise ValueError(f"unsupported float type: {float_type}")


# ---------------------------------------------------------------------------
# Q40
# ---------------------------------------------------------------------------


def quantize_q40(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Quantize a float array to Q40 struct-of-arrays form.

    Returns ``(qs, scales)`` where ``qs`` is uint8 ``[..., n/32, 16]`` (packed
    nibbles) and ``scales`` is float16 ``[..., n/32]``. Math matches the
    reference converter bit-for-bit (reference: converter/writer.py:29-53).
    """
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[-1]
    if n % QK != 0:
        raise ValueError(f"last dim {n} not divisible by {QK}")
    groups = x.reshape(*x.shape[:-1], n // QK, QK)
    gmax = groups.max(axis=-1)
    gmin = groups.min(axis=-1)
    deltas = np.where(-gmin > gmax, gmin, gmax) / -8.0
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = np.clip(groups * inv[..., None] + 8.5, 0, 15).astype(np.int32)
    lo = q[..., : QK // 2] & 0xF
    hi = (q[..., QK // 2 :] & 0xF) << 4
    qs = (lo | hi).astype(np.uint8)
    return qs, deltas.astype(np.float16)


def dequantize_q40(qs: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Inverse of :func:`quantize_q40` → float32 ``[..., n]``.

    Nibble layout per reference: src/quants.cpp:171-182 (low nibble = value j,
    high nibble = value j+16, both biased by +8).
    """
    lo = (qs & 0xF).astype(np.int8) - 8
    hi = (qs >> 4).astype(np.int8) - 8
    vals = np.concatenate([lo, hi], axis=-1).astype(np.float32)
    vals *= np.asarray(scales, dtype=np.float32)[..., None]
    return vals.reshape(*vals.shape[:-2], vals.shape[-2] * QK)


def q40_to_bytes(qs: np.ndarray, scales: np.ndarray) -> bytes:
    """Serialize to the block-interleaved wire form (BlockQ40 array)."""
    n_blocks = scales.size
    out = np.empty((n_blocks, Q40_BLOCK_BYTES), dtype=np.uint8)
    out[:, :2] = scales.reshape(-1).astype(np.float16).view(np.uint8).reshape(n_blocks, 2)
    out[:, 2:] = qs.reshape(n_blocks, QK // 2)
    return out.tobytes()


def q40_from_bytes(buf: bytes | np.ndarray, n_values: int) -> tuple[np.ndarray, np.ndarray]:
    """Parse a BlockQ40 array back to struct-of-arrays ``(qs, scales)``."""
    if n_values % QK != 0:
        raise ValueError(f"length {n_values} not divisible by {QK}")
    n_blocks = n_values // QK
    raw = np.frombuffer(buf, dtype=np.uint8, count=n_blocks * Q40_BLOCK_BYTES)
    raw = raw.reshape(n_blocks, Q40_BLOCK_BYTES)
    scales = raw[:, :2].copy().view(np.float16).reshape(n_blocks)
    qs = raw[:, 2:].copy()
    return qs, scales


# ---------------------------------------------------------------------------
# Q80
# ---------------------------------------------------------------------------


def quantize_q80(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Quantize to Q80 struct-of-arrays: int8 ``[..., n/32, 32]`` + f16 scales."""
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[-1]
    if n % QK != 0:
        raise ValueError(f"last dim {n} not divisible by {QK}")
    groups = x.reshape(*x.shape[:-1], n // QK, QK)
    absmax = np.abs(groups).max(axis=-1)
    deltas = absmax / 127.0
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = np.round(groups * inv[..., None]).astype(np.int8)
    return q, deltas.astype(np.float16)


def dequantize_q80(qs: np.ndarray, scales: np.ndarray) -> np.ndarray:
    vals = qs.astype(np.float32) * np.asarray(scales, dtype=np.float32)[..., None]
    return vals.reshape(*vals.shape[:-2], vals.shape[-2] * QK)


def q80_to_bytes(qs: np.ndarray, scales: np.ndarray) -> bytes:
    n_blocks = scales.size
    out = np.empty((n_blocks, Q80_BLOCK_BYTES), dtype=np.uint8)
    out[:, :2] = scales.reshape(-1).astype(np.float16).view(np.uint8).reshape(n_blocks, 2)
    out[:, 2:] = qs.reshape(n_blocks, QK).view(np.uint8)
    return out.tobytes()


def q80_from_bytes(buf: bytes | np.ndarray, n_values: int) -> tuple[np.ndarray, np.ndarray]:
    if n_values % QK != 0:
        raise ValueError(f"length {n_values} not divisible by {QK}")
    n_blocks = n_values // QK
    raw = np.frombuffer(buf, dtype=np.uint8, count=n_blocks * Q80_BLOCK_BYTES)
    raw = raw.reshape(n_blocks, Q80_BLOCK_BYTES)
    scales = raw[:, :2].copy().view(np.float16).reshape(n_blocks)
    qs = raw[:, 2:].copy().view(np.int8)
    return qs, scales


# ---------------------------------------------------------------------------
# Generic serialize/deserialize used by the .m reader/writer
# ---------------------------------------------------------------------------


def serialize_tensor(x: np.ndarray, float_type: FloatType) -> bytes:
    """Flatten + encode a tensor the way the reference converter writes it
    (reference: converter/writer.py:92-107)."""
    flat = np.asarray(x, dtype=np.float32).reshape(-1)
    if float_type == FloatType.F32:
        return flat.tobytes()
    if float_type == FloatType.F16:
        return flat.astype(np.float16).tobytes()
    if float_type == FloatType.Q40:
        return q40_to_bytes(*quantize_q40(flat))
    if float_type == FloatType.Q80:
        return q80_to_bytes(*quantize_q80(flat))
    raise ValueError(f"unsupported float type: {float_type}")


def deserialize_tensor(buf: bytes | np.ndarray, float_type: FloatType, n_values: int) -> np.ndarray:
    """Decode a serialized tensor back to float32 (flat)."""
    if float_type == FloatType.F32:
        return np.frombuffer(buf, dtype=np.float32, count=n_values).copy()
    if float_type == FloatType.F16:
        return np.frombuffer(buf, dtype=np.float16, count=n_values).astype(np.float32)
    if float_type == FloatType.Q40:
        try:
            from distributed_llama_tpu import native

            fast = native.q40_dequant_f32(np.frombuffer(buf, np.uint8, tensor_bytes(float_type, n_values)), n_values)
            if fast is not None:
                return fast
        except Exception:
            pass
        return dequantize_q40(*q40_from_bytes(buf, n_values))
    if float_type == FloatType.Q80:
        return dequantize_q80(*q80_from_bytes(buf, n_values))
    raise ValueError(f"unsupported float type: {float_type}")
