"""Offline conversion toolchain: HF safetensors / Meta .pth → `.m`,
tokenizers → `.t`, plus the named-model launcher registry.

Mirrors the reference's converter/ scripts (convert-hf.py, convert-llama.py,
convert-tokenizer-{hf,llama2,llama3}.py, launch.py) as an importable package
with CLI entry points.
"""

from distributed_llama_tpu.converter.hf import convert_hf, permute_qk

__all__ = ["convert_hf", "permute_qk"]
