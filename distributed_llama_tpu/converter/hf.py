"""HF safetensors checkpoint → `.m` (llama / mistral / mixtral / grok-1).

Parity with reference converter/convert-hf.py: the tensor plan order matches
the C++ loader (convert-hf.py:52-90), Q/K projections are permuted from the
HF neox pair layout to the interleaved rope layout (:12-15), and the header
carries rope scaling when config.json has it (:190-196).

Beyond the reference: Grok-1 (``model_type: "grok-1"`` — the hpcai-tech/
grok-1 transformers port's naming: attn.*_proj, moe_block.gate,
moe_block.experts.{e}.{linear,linear_v,linear_1}, pre/post attn/moe norms).
Grok keeps the neox Q/K layout (no permute): the runtime's GROK1 arch
defaults to falcon/neox rope like the reference's FalconRopeCommand. The
original checkpoint's attn_output_multiplier/embedding/output scale
constants are hardcoded in the runtime (models/llama.py, matching
src/grok1-tasks.cpp:11-14, 270-273), so they are not read from config.
"""

from __future__ import annotations

import json
import os

import numpy as np

from distributed_llama_tpu.formats.model_file import (
    ArchType,
    HiddenAct,
    ModelFileWriter,
    ModelSpec,
    RopeType,
)
from distributed_llama_tpu.quants import FloatType

ARCH_BY_MODEL_TYPE = {
    "llama": ArchType.LLAMA,
    "mistral": ArchType.LLAMA,
    "mixtral": ArchType.MIXTRAL,
    "grok-1": ArchType.GROK1,
}

HIDDEN_ACT = {"gelu": HiddenAct.GELU, "silu": HiddenAct.SILU}


def permute_qk(w: np.ndarray, n_heads: int) -> np.ndarray:
    """HF neox rope layout → interleaved pair layout
    (reference: converter/convert-hf.py:12-15). ``w``: [n_heads*head, dim]."""
    d = w.shape[0]
    return (
        w.reshape(n_heads, 2, d // n_heads // 2, *w.shape[1:])
        .swapaxes(1, 2)
        .reshape(w.shape)
    )


def spec_from_hf_config(config: dict, float_type: FloatType) -> ModelSpec:
    arch = ARCH_BY_MODEL_TYPE.get(config["model_type"])
    if arch is None:
        raise ValueError(f"unsupported model type: {config['model_type']}")
    n_experts = int(
        config.get("num_local_experts") or config.get("num_experts") or 0
    )
    n_active = int(
        config.get("num_active_local_experts") or config.get("num_experts_per_tok") or 0
    )
    # grok-1 configs may omit hidden_act (its experts are always gelu)
    act = config.get("hidden_act") or ("gelu" if arch == ArchType.GROK1 else None)
    if act is None:
        raise ValueError("config.json is missing hidden_act")
    spec = ModelSpec(
        arch_type=arch,
        dim=config["hidden_size"],
        hidden_dim=config["intermediate_size"],
        n_layers=config["num_hidden_layers"],
        n_heads=config["num_attention_heads"],
        n_kv_heads=config["num_key_value_heads"],
        vocab_size=config["vocab_size"],
        seq_len=config["max_position_embeddings"],
        n_experts=n_experts,
        n_active_experts=n_active,
        hidden_act=HIDDEN_ACT[act],
        rope_theta=float(config.get("rope_theta") or 10000.0),
        weights_float_type=float_type,
    )
    if arch == ArchType.GROK1:
        # no Q/K permute for grok (see module docstring): leave the header
        # rope unset so both runtimes resolve their falcon/neox default
        return spec
    # The converter permutes Q/K into the interleaved-pair layout, so the
    # correct rope for every converted HF model is LLAMA (interleaved). The
    # reference converter leaves the header rope type unset, which makes the
    # reference runtime default MIXTRAL files to falcon/neox rope
    # (src/transformer.cpp:88-96) on permuted weights — a layout mismatch
    # that silently degrades its Mixtral outputs. Writing the key explicitly
    # is honored by both runtimes.
    spec.rope_type = RopeType.LLAMA
    scaling = config.get("rope_scaling")
    if scaling is not None:
        if scaling.get("rope_type") not in ("llama3",):
            raise ValueError(f"unsupported rope scaling type: {scaling.get('rope_type')}")
        # header stores int32 values, truncated like the reference converter
        # (convert-hf.py:190-196)
        spec.rope_type = RopeType.LLAMA3_1
        spec.rope_scaling_factor = int(scaling["factor"])
        spec.rope_scaling_low_freq_factor = int(scaling["low_freq_factor"])
        spec.rope_scaling_high_freq_factor = int(scaling["high_freq_factor"])
        spec.rope_scaling_orig_max_seq_len = int(scaling["original_max_position_embeddings"])
    return spec


class _LazySafetensors:
    """Multi-file lazy tensor lookup (reference: convert-hf.py:26-44 keeps one
    file open at a time; checkpoints are usually ordered, so misses are rare)."""

    def __init__(self, files: list[str]):
        from safetensors import safe_open

        self._safe_open = safe_open
        self.files = files
        self._index: dict[str, int] = {}
        self._open_idx: int | None = None
        self._open = None

    def _load(self, idx: int):
        if self._open_idx == idx:
            return
        if self._open is not None:
            del self._open
        self._open = self._safe_open(self.files[idx], framework="np", device="cpu")
        self._open_idx = idx
        for key in self._open.keys():
            self._index[key] = idx

    def get(self, name: str) -> np.ndarray:
        if self._open is None:
            self._load(0)
        while name not in self._index:
            nxt = (self._open_idx or 0) + 1
            if nxt >= len(self.files):
                # full scan fallback
                for i in range(len(self.files)):
                    self._load(i)
                if name not in self._index:
                    raise KeyError(f"tensor {name} not found in checkpoint")
                break
            self._load(nxt)
        self._load(self._index[name])
        return np.asarray(self._open.get_tensor(name))


def grok1_tensor_plan(spec: ModelSpec) -> list[tuple[str, str, bool]]:
    """[(m_name, hf_name, permute)] for the hpcai-tech/grok-1 transformers
    port: attn.* projections (no permute — neox rope), moe_block router +
    linear (w1/gate) / linear_v (w3/up) / linear_1 (w2/down) experts, and
    grok's four per-layer norms mapped to rms_att / rms_ffn (post-attn) /
    rms_moe (pre-moe) / rms_ffn2 (post-moe)."""
    plan: list[tuple[str, str, bool]] = [("embedding", "model.embed_tokens.weight", False)]
    for l in range(spec.n_layers):
        hp = f"model.layers.{l}."
        mp = f"layers.{l}."
        plan += [
            (mp + "q", hp + "attn.q_proj.weight", False),
            (mp + "k", hp + "attn.k_proj.weight", False),
            (mp + "v", hp + "attn.v_proj.weight", False),
            (mp + "wo", hp + "attn.o_proj.weight", False),
            (mp + "moe_router", hp + "moe_block.gate.weight", False),
        ]
        for e in range(spec.n_experts):
            ep = hp + f"moe_block.experts.{e}."
            plan += [
                (mp + f"experts.{e}.up", ep + "linear_v.weight", False),
                (mp + f"experts.{e}.gate", ep + "linear.weight", False),
                (mp + f"experts.{e}.down", ep + "linear_1.weight", False),
            ]
        plan += [
            (mp + "rms_att", hp + "pre_attn_norm.weight", False),
            (mp + "rms_ffn", hp + "post_attn_norm.weight", False),
            (mp + "rms_moe", hp + "pre_moe_norm.weight", False),
            (mp + "rms_ffn2", hp + "post_moe_norm.weight", False),
        ]
    plan += [("rms_final", "model.norm.weight", False), ("wcls", "lm_head.weight", False)]
    return plan


def hf_tensor_plan(spec: ModelSpec) -> list[tuple[str, str, bool]]:
    """[(m_name, hf_name, permute)] in `.m` layout order."""
    if spec.arch_type == ArchType.GROK1:
        return grok1_tensor_plan(spec)
    plan: list[tuple[str, str, bool]] = [("embedding", "model.embed_tokens.weight", False)]
    for l in range(spec.n_layers):
        hp = f"model.layers.{l}."
        mp = f"layers.{l}."
        plan += [
            (mp + "q", hp + "self_attn.q_proj.weight", True),
            (mp + "k", hp + "self_attn.k_proj.weight", True),
            (mp + "v", hp + "self_attn.v_proj.weight", False),
            (mp + "wo", hp + "self_attn.o_proj.weight", False),
        ]
        if spec.n_experts > 0:
            plan.append((mp + "moe_router", hp + "block_sparse_moe.gate.weight", False))
            for e in range(spec.n_experts):
                ep = hp + f"block_sparse_moe.experts.{e}."
                plan += [
                    (mp + f"experts.{e}.up", ep + "w3.weight", False),
                    (mp + f"experts.{e}.gate", ep + "w1.weight", False),
                    (mp + f"experts.{e}.down", ep + "w2.weight", False),
                ]
        else:
            plan += [
                (mp + "gate", hp + "mlp.gate_proj.weight", False),
                (mp + "down", hp + "mlp.down_proj.weight", False),
                (mp + "up", hp + "mlp.up_proj.weight", False),
            ]
        plan += [
            (mp + "rms_att", hp + "input_layernorm.weight", False),
            (mp + "rms_ffn", hp + "post_attention_layernorm.weight", False),
        ]
    plan += [("rms_final", "model.norm.weight", False), ("wcls", "lm_head.weight", False)]
    return plan


def convert_hf(
    source_dir: str, float_type: FloatType, output_path: str, progress=print
) -> ModelSpec:
    with open(os.path.join(source_dir, "config.json")) as f:
        config = json.load(f)
    spec = spec_from_hf_config(config, float_type)

    files = sorted(
        os.path.join(source_dir, f)
        for f in os.listdir(source_dir)
        if f.endswith(".safetensors") and not f.startswith(".")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {source_dir}")
    src = _LazySafetensors(files)

    tied = config.get("tie_word_embeddings", False)
    with open(output_path, "wb") as out:
        writer = ModelFileWriter(out, spec)
        for m_name, hf_name, permute in hf_tensor_plan(spec):
            if m_name == "wcls" and tied:
                tensor = src.get("model.embed_tokens.weight")
            else:
                tensor = src.get(hf_name)
            if permute:
                heads = spec.n_heads if m_name.endswith(".q") else spec.n_kv_heads
                tensor = permute_qk(tensor, heads)
            progress(f"🔶 writing {m_name} {tuple(tensor.shape)}")
            writer.write_tensor(np.asarray(tensor, dtype=np.float32), m_name)
        writer.finish()
    return spec


def main(argv=None) -> None:
    import argparse

    from distributed_llama_tpu.quants import parse_float_type

    p = argparse.ArgumentParser(prog="dllama-tpu-convert-hf")
    p.add_argument("source", help="folder with config.json + *.safetensors")
    p.add_argument("float_type", help="f32 | f16 | q40 | q80")
    p.add_argument("name", help="output model name")
    args = p.parse_args(argv)
    out = f"dllama_model_{args.name}_{args.float_type}.m"
    convert_hf(args.source, parse_float_type(args.float_type), out)
    print(f"✅ {out} created successfully")


if __name__ == "__main__":
    main()
