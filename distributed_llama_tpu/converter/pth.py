"""Meta `consolidated.*.pth` checkpoint → `.m` (Llama 1/2/3 official format).

Parity with reference converter/convert-llama.py: shards are concatenated on
axis 1 for embedding/wo/w2 and axis 0 for everything else (:70-94), work is
chunked to bound peak RAM (:50-68), and hidden_dim is inferred from the w1
shard shape × shard count (:64-66).
"""

from __future__ import annotations

import json
import math
import os
from pathlib import Path

import numpy as np

from distributed_llama_tpu.formats.model_file import (
    ArchType,
    HiddenAct,
    ModelFileWriter,
    ModelSpec,
)
from distributed_llama_tpu.quants import FloatType

LAYER_CHUNK_SIZE = 48

# axis-1 concat (the tensor is sharded on its second dim in the checkpoint)
_AXIS1_SUFFIXES = ("tok_embeddings.weight", "attention.wo.weight", "feed_forward.w2.weight")


def _meta_layer_names(n_layers: int) -> list[str]:
    names = ["tok_embeddings.weight"]
    for l in range(n_layers):
        names += [
            f"layers.{l}.attention.wq.weight",
            f"layers.{l}.attention.wk.weight",
            f"layers.{l}.attention.wv.weight",
            f"layers.{l}.attention.wo.weight",
            f"layers.{l}.feed_forward.w1.weight",
            f"layers.{l}.feed_forward.w2.weight",
            f"layers.{l}.feed_forward.w3.weight",
            f"layers.{l}.attention_norm.weight",
            f"layers.{l}.ffn_norm.weight",
        ]
    names += ["norm.weight", "output.weight"]
    return names


_META_TO_M = {
    "tok_embeddings.weight": "embedding",
    "attention.wq.weight": "q",
    "attention.wk.weight": "k",
    "attention.wv.weight": "v",
    "attention.wo.weight": "wo",
    "feed_forward.w1.weight": "gate",
    "feed_forward.w2.weight": "down",
    "feed_forward.w3.weight": "up",
    "attention_norm.weight": "rms_att",
    "ffn_norm.weight": "rms_ffn",
    "norm.weight": "rms_final",
    "output.weight": "wcls",
}


def _m_name(meta_name: str) -> str:
    if meta_name.startswith("layers."):
        _, l, rest = meta_name.split(".", 2)
        return f"layers.{l}.{_META_TO_M[rest]}"
    return _META_TO_M[meta_name]


def convert_meta_pth(
    model_dir: str, float_type: FloatType, output_path: str, progress=print
) -> ModelSpec:
    import torch

    with open(os.path.join(model_dir, "params.json")) as f:
        params = json.load(f)
    if params.get("vocab_size", -1) < 1:
        raise ValueError("vocab_size is invalid, please update params.json")
    if params.get("max_seq_len") is None:
        raise ValueError("max_seq_len is required, please update params.json")

    shard_paths = sorted(Path(model_dir).glob("consolidated.*.pth"))
    if not shard_paths:
        raise FileNotFoundError(f"no consolidated.*.pth in {model_dir}")

    # hidden_dim comes from the first shard's w1 (reference: convert-llama.py:64-66)
    first = torch.load(shard_paths[0], map_location="cpu", weights_only=True)
    hidden_dim = first["layers.0.feed_forward.w1.weight"].shape[0] * len(shard_paths)
    del first

    spec = ModelSpec(
        arch_type=ArchType.LLAMA,
        dim=params["dim"],
        hidden_dim=hidden_dim,
        n_layers=params["n_layers"],
        n_heads=params["n_heads"],
        n_kv_heads=params.get("n_kv_heads") or params["n_heads"],
        vocab_size=params["vocab_size"],
        seq_len=params["max_seq_len"],
        hidden_act=HiddenAct.SILU,
        rope_theta=float(params.get("rope_theta", 10000.0)),
        weights_float_type=float_type,
    )

    names = _meta_layer_names(spec.n_layers)
    with open(output_path, "wb") as out:
        writer = ModelFileWriter(out, spec)
        n_chunks = math.ceil(len(names) / LAYER_CHUNK_SIZE)
        for ci in range(n_chunks):
            chunk = names[ci * LAYER_CHUNK_SIZE : (ci + 1) * LAYER_CHUNK_SIZE]
            gathered: dict[str, list] = {n: [] for n in chunk}
            progress(f"💿 chunk {ci + 1}/{n_chunks}")
            for sp in shard_paths:
                shard = torch.load(sp, map_location="cpu", weights_only=True)
                for n in chunk:
                    if n in shard:
                        gathered[n].append(shard[n])
                del shard
            for n in chunk:
                tensors = gathered[n]
                if len(tensors) == 1 or tensors[0].ndim == 1:
                    merged = tensors[0]
                else:
                    axis = 1 if n.endswith(_AXIS1_SUFFIXES) else 0
                    merged = torch.cat(tensors, dim=axis)
                progress(f"🔶 writing {_m_name(n)} {tuple(merged.shape)}")
                writer.write_tensor(
                    np.asarray(merged.to(torch.float32).numpy()), _m_name(n)
                )
        writer.finish()
    return spec


def main(argv=None) -> None:
    import argparse

    from distributed_llama_tpu.quants import parse_float_type

    p = argparse.ArgumentParser(prog="dllama-tpu-convert-pth")
    p.add_argument("model_dir")
    p.add_argument("float_type")
    args = p.parse_args(argv)
    name = os.path.basename(os.path.normpath(args.model_dir)).lower()
    out = f"dllama_model_{name}_{args.float_type}.m"
    convert_meta_pth(args.model_dir, parse_float_type(args.float_type), out)
    print(f"✅ {out} created successfully")


if __name__ == "__main__":
    main()
