"""Named-model launcher: download converted `.m`/`.t` artifacts and emit a
ready-to-run command (reference: launch.py).

The registry mirrors the reference's published model zoo — the files are the
same `.m`/`.t` artifacts, interchangeable between the two runtimes. Large
models are split into URL parts that concatenate into one local file
(reference: launch.py:42-66).
"""

from __future__ import annotations

import os
import sys
import urllib.request


def _parts(length: int) -> list[str]:
    return [chr(97 + i // 26) + chr(97 + i % 26) for i in range(length)]


_HF = "https://huggingface.co"

# name -> (model_urls, tokenizer_url, weights_float_type, buffer_float_type, kind)
MODELS: dict[str, tuple[list[str], str, str, str, str]] = {
    "tinyllama_1_1b_3t_q40": (
        [f"{_HF}/b4rtaz/TinyLlama-1.1B-3T-Distributed-Llama/resolve/main/dllama_model_tinylama_1.1b_3t_q40.m?download=true"],
        f"{_HF}/b4rtaz/TinyLlama-1.1B-3T-Distributed-Llama/resolve/main/dllama_tokenizer_tinylama_1.1b_3t.t?download=true",
        "q40", "q80", "base",
    ),
    "llama3_8b_q40": (
        [f"{_HF}/b4rtaz/Llama-3-8B-Q40-Distributed-Llama/resolve/main/dllama_model_meta-llama-3-8b_q40.m?download=true"],
        f"{_HF}/b4rtaz/Llama-3-8B-Q40-Distributed-Llama/resolve/main/dllama_tokenizer_llama3.t?download=true",
        "q40", "q80", "base",
    ),
    "llama3_8b_instruct_q40": (
        [f"{_HF}/b4rtaz/Llama-3-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_model_lama3_instruct_q40.m?download=true"],
        f"{_HF}/b4rtaz/Llama-3-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_tokenizer_llama3.t?download=true",
        "q40", "q80", "chat",
    ),
    "llama3_1_8b_instruct_q40": (
        [f"{_HF}/b4rtaz/Llama-3_1-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_model_llama3.1_instruct_q40.m?download=true"],
        f"{_HF}/b4rtaz/Llama-3_1-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_tokenizer_llama_3_1.t?download=true",
        "q40", "q80", "chat",
    ),
    "llama3_1_405b_instruct_q40": (
        [
            f"{_HF}/b4rtaz/Llama-3_1-405B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_model_llama31_405b_q40_{s}?download=true"
            for s in _parts(56)
        ],
        f"{_HF}/b4rtaz/Llama-3_1-405B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_tokenizer_llama_3_1.t?download=true",
        "q40", "q80", "chat",
    ),
}


def download_file(urls: list[str], path: str, progress=print) -> None:
    if os.path.isfile(path):
        progress(f"{os.path.basename(path)} already exists, skipping download")
        return
    tmp = path + ".partial"
    with open(tmp, "wb") as f:
        for url in urls:
            progress(f"📄 {url}")
            with urllib.request.urlopen(url) as r:
                while True:
                    chunk = r.read(1 << 20)
                    if not chunk:
                        break
                    f.write(chunk)
    os.replace(tmp, path)


def launch_command(name: str, models_dir: str = "models") -> list[str]:
    model_urls, tok_url, _wft, _bft, kind = MODELS[name]
    d = os.path.join(models_dir, name)
    model_path = os.path.join(d, f"dllama_model_{name}.m")
    tok_path = os.path.join(d, f"dllama_tokenizer_{name}.t")
    mode = "chat" if kind == "chat" else "inference"
    cmd = [
        "dllama-tpu", mode,
        "--model", model_path,
        "--tokenizer", tok_path,
        "--temperature", "0.8",
        "--max-seq-len", "4096",
    ]
    if mode == "inference":
        cmd += ["--prompt", "Hello world", "--steps", "64"]
    return cmd


def launch(name: str, models_dir: str = "models", run=False) -> list[str]:
    model_urls, tok_url, _wft, _bft, _kind = MODELS[name]
    d = os.path.join(models_dir, name)
    os.makedirs(d, exist_ok=True)
    download_file(model_urls, os.path.join(d, f"dllama_model_{name}.m"))
    download_file([tok_url], os.path.join(d, f"dllama_tokenizer_{name}.t"))
    cmd = launch_command(name, models_dir)
    print("To run the model:\n  " + " ".join(cmd))
    if run:
        from distributed_llama_tpu.apps.cli import main as cli_main

        cli_main(cmd[1:])
    return cmd


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in MODELS:
        print("Usage: python -m distributed_llama_tpu.converter.launch <model> [--run]")
        print("Available models:")
        for name in MODELS:
            print(f"  {name}")
        raise SystemExit(1)
    launch(argv[0], run="--run" in argv)


if __name__ == "__main__":
    main()
