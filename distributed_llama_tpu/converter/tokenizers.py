"""Tokenizer converters → `.t` format.

Parity with reference converter/convert-tokenizer-{hf,llama2,llama3}.py:
HF tokenizer.json BPE vocabs, sentencepiece models, and the tiktoken-style
base64 llama3 format (with its 256 embedded special tokens and chat template).
"""

from __future__ import annotations

import base64
import json
import os

from distributed_llama_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer_file

LLAMA2_CHAT_TEMPLATE = (
    "{% if messages[0]['role'] == 'system' %}{% set loop_messages = messages[1:] %}"
    "{% set system_message = messages[0]['content'] %}{% else %}"
    "{% set loop_messages = messages %}{% set system_message = false %}{% endif %}"
    "{% for message in loop_messages %}{% if (message['role'] == 'user') != (loop.index0 % 2 == 0) %}"
    "{{ raise_exception('Conversation roles must alternate user/assistant/user/assistant/...') }}"
    "{% endif %}{% if loop.index0 == 0 and system_message != false %}"
    "{% set content = '<<SYS>>\\n' + system_message + '\\n<</SYS>>\\n\\n' + message['content'] %}"
    "{% else %}{% set content = message['content'] %}{% endif %}"
    "{% if message['role'] == 'user' %}{{ bos_token + '[INST] ' + content.strip() + ' [/INST]' }}"
    "{% elif message['role'] == 'assistant' %}{{ ' '  + content.strip() + ' ' + eos_token }}"
    "{% endif %}{% endfor %}"
)

LLAMA3_CHAT_TEMPLATE = (
    "{% set loop_messages = messages %}{% for message in loop_messages %}"
    "{% set content = '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n'"
    "+ message['content'] | trim + '<|eot_id|>' %}"
    "{% if loop.index0 == 0 %}{% set content = bos_token + content %}{% endif %}"
    "{{ content }}{% endfor %}{% if add_generation_prompt %}"
    "{{ '<|start_header_id|>assistant<|end_header_id|>\n\n' }}{% endif %}"
)

LLAMA3_N_SPECIAL = 256
LLAMA3_SPECIAL_TOKENS = [
    "<|begin_of_text|>",
    "<|end_of_text|>",
    "<|reserved_special_token_0|>",
    "<|reserved_special_token_1|>",
    "<|reserved_special_token_2|>",
    "<|reserved_special_token_3|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|reserved_special_token_4|>",
    "<|eot_id|>",
] + [f"<|reserved_special_token_{i}|>" for i in range(5, LLAMA3_N_SPECIAL - 5)]


def _write(out_path: str, data: TokenizerData) -> None:
    with open(out_path, "wb") as f:
        write_tokenizer_file(f, data)


def convert_hf_tokenizer(
    dir_path: str, out_path: str, chat_extra_stop: str | None = None
) -> TokenizerData:
    """HF folder (tokenizer_config.json + tokenizer.json or tokenizer.model)
    → `.t` (reference: convert-tokenizer-hf.py)."""
    with open(os.path.join(dir_path, "tokenizer_config.json"), encoding="utf-8") as f:
        cfg = json.load(f)
    cls = cfg.get("tokenizer_class")
    if cls == "PreTrainedTokenizerFast":
        tokens, scores, bos_id, eos_id = _resolve_fast(dir_path, cfg)
    elif cls == "LlamaTokenizer":
        tokens, scores, bos_id, eos_id = _resolve_sentencepiece(
            os.path.join(dir_path, "tokenizer.model")
        )
    else:
        raise ValueError(f"tokenizer class {cls} is not supported")

    template = cfg.get("chat_template")
    data = TokenizerData(
        vocab=tokens,
        scores=scores,
        bos_id=bos_id,
        eos_id=eos_id,
        chat_eos_id=eos_id,
        chat_template=template,
        chat_stop=chat_extra_stop,
    )
    _write(out_path, data)
    return data


def _token_to_bytes(token: str) -> bytes:
    return token.encode("utf-8")


def _resolve_fast(dir_path: str, cfg: dict):
    """BPE vocab from tokenizer.json (reference: convert-tokenizer-hf.py:20-39)."""
    with open(os.path.join(dir_path, "tokenizer.json"), encoding="utf-8") as f:
        tok = json.load(f)
    if tok["model"]["type"] != "BPE":
        raise ValueError("only BPE tokenizer.json vocabularies are supported")
    bos_id = eos_id = None
    tokens: list[bytes] = []
    scores: list[float] = []
    vocab = tok["model"]["vocab"]
    for i, (token, tid) in enumerate(vocab.items()):
        if tid != i:
            raise ValueError("tokenizer.json vocab ids are not dense")
        tokens.append(_token_to_bytes(token))
        scores.append(-float(i))
    for at in tok.get("added_tokens", []):
        if at["id"] != len(tokens):
            raise ValueError("added_tokens ids are not dense")
        if at["content"] == cfg.get("bos_token"):
            bos_id = len(tokens)
        if at["content"] == cfg.get("eos_token"):
            eos_id = len(tokens)
        tokens.append(_token_to_bytes(at["content"]))
        scores.append(-float(len(tokens) - 1))
    if bos_id is None or eos_id is None:
        # fall back to named lookup in the whole vocab
        index = {t: i for i, t in enumerate(tokens)}
        bos = cfg.get("bos_token")
        eos = cfg.get("eos_token")
        bos_id = bos_id if bos_id is not None else index.get(_token_to_bytes(bos), -1) if bos else -1
        eos_id = eos_id if eos_id is not None else index.get(_token_to_bytes(eos), -1) if eos else -1
    return tokens, scores, bos_id, eos_id


def _resolve_sentencepiece(model_path: str):
    """(reference: convert-tokenizer-hf.py:41-56, convert-tokenizer-llama2.py)"""
    from sentencepiece import SentencePieceProcessor

    sp = SentencePieceProcessor(model_file=model_path)
    tokens: list[bytes] = []
    scores: list[float] = []
    for i in range(sp.vocab_size()):
        piece = sp.id_to_piece(i).replace("\u2581", " ")
        tokens.append(piece.encode("utf-8"))
        scores.append(sp.get_score(i))
    return tokens, scores, sp.bos_id(), sp.eos_id()


def convert_llama2_tokenizer(dir_path: str, out_path: str) -> TokenizerData:
    tokens, scores, bos_id, eos_id = _resolve_sentencepiece(
        os.path.join(dir_path, "tokenizer.model")
    )
    data = TokenizerData(
        vocab=tokens,
        scores=scores,
        bos_id=bos_id,
        eos_id=eos_id,
        chat_eos_id=eos_id,
        chat_template=LLAMA2_CHAT_TEMPLATE,
    )
    _write(out_path, data)
    return data


def convert_llama3_tokenizer(model_path: str, out_path: str) -> TokenizerData:
    """tiktoken-style base64 vocab file (reference: convert-tokenizer-llama3.py)."""
    tokens: list[bytes] = []
    scores: list[float] = []
    with open(model_path, "r") as f:
        for line in f:
            if not line.strip():
                continue
            b64, rank = line.split(" ")
            tokens.append(base64.b64decode(b64))
            scores.append(-float(rank))
    for i, tok in enumerate(LLAMA3_SPECIAL_TOKENS):
        tokens.append(tok.encode("utf-8"))
        scores.append(-float(len(tokens) - 1))
    data = TokenizerData(
        vocab=tokens,
        scores=scores,
        bos_id=128000,
        eos_id=128001,
        chat_eos_id=128009,
        chat_template=LLAMA3_CHAT_TEMPLATE,
    )
    _write(out_path, data)
    return data


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(prog="dllama-tpu-convert-tokenizer")
    p.add_argument("kind", choices=["hf", "llama2", "llama3"])
    p.add_argument("path", help="tokenizer folder (hf/llama2) or tokenizer.model (llama3)")
    p.add_argument("name")
    p.add_argument("--chat-extra-stop", default=None)
    args = p.parse_args(argv)
    out = f"dllama_tokenizer_{args.name}.t"
    if args.kind == "hf":
        convert_hf_tokenizer(args.path, out, args.chat_extra_stop)
    elif args.kind == "llama2":
        convert_llama2_tokenizer(args.path, out)
    else:
        convert_llama3_tokenizer(args.path, out)
    print(f"✅ Created {out}")


if __name__ == "__main__":
    main()
