"""Integrity verification for silent data corruption (ISSUE 10).

The failure-domain ladder (docs/ROBUSTNESS.md) catches *loud* failures —
raises, hangs, NaN logits surfacing as out-of-vocab tokens. A bit-flipped
weight or activation that yields plausible-but-WRONG tokens sails through
every one of those checks: the fleet-scale failure mode of "Cores that
don't count" (Hochschild et al., HotOS '21) and Meta's "Silent Data
Corruptions at Scale" (Dixit et al., 2021). This module supplies the three
detection primitives the serving layer composes into canaries, shadow
votes and restart verification (server/replicas.py):

* **Logit fingerprints** — a per-row FNV-1a fold over each decode step's
  full-vocab logit argmax and sampled token, carried through the batched
  decode scan ON DEVICE and fetched as two extra int32 rows packed into
  the chunk's token array (``pack_chunk_outputs``) — the fetch count, and
  therefore the tunnel round-trips per chunk, are unchanged. Since
  ISSUE 13 the fold shares the scan with the FUSED device sampler: the
  packed bundle's int32 rows are the only bytes a chunk ever sends
  host-ward, and the fold keeps its order-statistic stability across
  bucket shapes (argmax, never a bitwise sum) while the sampler's coins
  come from the stateless counter PRNG beside it. A pinned greedy prompt
  then has ONE expected (tokens, fingerprint) pair per weights+config,
  which is what the canary compares. The fold also carries a per-row
  finiteness flag, closing the sampled-path hole: NaN logits pushed
  through a softmax can launder into a perfectly in-vocab token id that
  the fetch-side vocab check cannot see.
* **Weight checksums** — an order-independent wrapping uint32 word sum
  per leaf (floats bit-cast, so a single mantissa-bit flip ALWAYS moves
  the sum — a float32 accumulation would round it away), folded through
  CRC-32 on the host. Computed once per engine load
  (``InferenceEngine.weights_checksum``) and re-verified by the replica
  supervisor before a rebuilt replica re-enters placement.
* **Deterministic corruption** (``corrupt_params``) — the fault the
  ``engine.sdc`` site injects (``kind=corrupt``): a seeded weight slice
  scaled into finite-but-wrong values. Not NaN on purpose; the point is
  producing outputs every pre-ISSUE-10 check calls healthy.

Everything here is stateless and backend-agnostic; policy (canary
cadence, suspicion walks, failover) lives with the replica pool.
"""

from __future__ import annotations

import random
import zlib

import jax
import jax.numpy as jnp
import numpy as np

# FNV-1a constants: cheap, well-distributed for short folds, and trivially
# reproducible from any other runtime that wants to cross-check a stream
FP_BASIS = 2166136261
FP_PRIME = 16777619

# the reserved internal tenant canary/shadow probes bill to: excluded from
# fair admission and from per-tenant fairness metrics (client-supplied
# tenant names may not start with "_" — server/api.py validates)
CANARY_TENANT = "_integrity"

# the reserved internal tenant rollout certification probes bill to
# (ISSUE 18): same contract as the canary tenant — direct lane claim, no
# admission permit, never a client identity
ROLLOUT_TENANT = "_rollout"

RESERVED_TENANTS = (CANARY_TENANT, ROLLOUT_TENANT)


# ----------------------------------------------------------------------
# Device-side logit fingerprints (ride the batched decode scan)
# ----------------------------------------------------------------------


def fingerprint_init(b: int):
    """Per-row fold state for one chunk: (hash uint32 [b], finite bool [b])."""
    return jnp.full((b,), FP_BASIS, jnp.uint32), jnp.ones((b,), bool)


def fingerprint_fold(h, ok, logits, tokens):
    """Fold one decode step into the chunk fingerprint (inside the scan).

    ``logits`` [B, vocab] f32, ``tokens`` [B] int32 (the step's sampled
    ids). Two per-row reductions ride the step:

    * ``argmax`` — the hashed word. Deliberately an ORDER STATISTIC, not
      a bitwise accumulation: XLA compiles a separate program per row
      bucket, and a row's logit BITS drift by ulps across bucket shapes
      (measured on CPU — a bucket-1 and a bucket-2 dispatch of the same
      row disagree in the last bits of a full-vocab sum), so a
      sum-of-logits fingerprint would make the canary golden flap with
      co-batched traffic. The argmax survives ulp drift while still
      witnessing model-state corruption independently of the SAMPLED
      token (a temperature>0 row's draw hides argmax drift; this
      doesn't). Folding the sampled token too makes the chunk word a
      compact (argmax, token) transcript.
    * ``sum`` — the FINITENESS witness only: IEEE propagation means any
      NaN poisons it and any Inf survives or (meeting its opposite)
      becomes NaN, so ``isfinite(sum)`` is a whole-row non-finite
      detector for the price of one add-reduce."""
    finite = jnp.isfinite(jnp.sum(logits.astype(jnp.float32), axis=-1))
    arg = jnp.argmax(logits, axis=-1).astype(jnp.uint32)
    h = (h * jnp.uint32(FP_PRIME)) ^ arg
    h = (h * jnp.uint32(FP_PRIME)) ^ tokens.astype(jnp.uint32)
    return h, ok & finite


def pack_chunk_outputs(tokens, h, ok):
    """Append the fingerprint + finiteness rows to a chunk's token array:
    [n_steps, B] int32 → [n_steps + 2, B] int32, so the whole bundle still
    crosses the host in ONE fetch (row ``n_steps`` = fingerprint bits, row
    ``n_steps + 1`` = finite flag)."""
    fp_row = jax.lax.bitcast_convert_type(h, jnp.int32)[None, :]
    ok_row = ok.astype(jnp.int32)[None, :]
    return jnp.concatenate([tokens.astype(jnp.int32), fp_row, ok_row], axis=0)


def split_chunk_outputs(arr: np.ndarray, n_steps: int):
    """Host-side inverse of :func:`pack_chunk_outputs` on the fetched
    array: returns ``(tokens [n_steps, B], fingerprints uint32 [B],
    finite bool [B])``."""
    arr = np.asarray(arr)
    toks = arr[:n_steps]
    fp = (arr[n_steps].astype(np.int64) & 0xFFFFFFFF).astype(np.uint32)
    finite = arr[n_steps + 1] != 0
    return toks, fp, finite


def fold_run_fingerprint(run: int, chunk_fp: int) -> int:
    """Host-side fold of one chunk's fingerprint into a stream-lifetime
    fingerprint (same FNV-1a step, so a stream's value is a pure function
    of its chunk sequence). Streams start from :data:`FP_BASIS`."""
    return ((int(run) * FP_PRIME) ^ int(chunk_fp)) & 0xFFFFFFFF


# ----------------------------------------------------------------------
# Weight checksums (load-time record, restart-time verification)
# ----------------------------------------------------------------------

_UINT_FOR_SIZE = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint32}


def _leaf_word_sum(leaf):
    """Wrapping uint32 sum of a leaf's underlying WORDS: floats (incl.
    bf16) are bit-cast to the same-width unsigned type first, so the sum
    is exact modulo 2**32 — any single flipped bit changes it, which a
    rounding float accumulation cannot promise."""
    x = jnp.asarray(leaf)
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    if jnp.issubdtype(x.dtype, jnp.floating) or jnp.issubdtype(
        x.dtype, jnp.signedinteger
    ):
        x = jax.lax.bitcast_convert_type(
            x, _UINT_FOR_SIZE.get(x.dtype.itemsize, jnp.uint32)
        )
    return jnp.sum(x.astype(jnp.uint32))


def params_checksum(params) -> str:
    """Deterministic hex checksum of a whole params pytree: per-leaf
    device-side word sums (one HBM pass over the weights — load-cost
    class, done once per engine build), one stacked fetch, CRC-32 fold on
    the host. Identical weights → identical checksum on every backend;
    the replica pool records replica 0's value as the pool reference and
    the restart supervisor verifies every rebuild against it."""
    sums = [
        _leaf_word_sum(leaf)
        for leaf in jax.tree_util.tree_leaves(params)
        if hasattr(leaf, "dtype")
    ]
    if not sums:
        return "00000000"
    vec = np.asarray(jnp.stack(sums), dtype=np.uint32)
    return f"{zlib.crc32(vec.tobytes()) & 0xFFFFFFFF:08x}"


class ChecksumMismatch(RuntimeError):
    """A rebuilt replica's weight checksum disagrees with the pool
    reference: the rebuild itself is corrupt (bad host RAM, a torn read,
    the same flaky core) and must NOT re-enter placement — the restart
    loop treats this like any other failed build attempt and retries
    under backoff (server/replicas.py)."""


# ----------------------------------------------------------------------
# Deterministic corruption (the engine.sdc fault's payload)
# ----------------------------------------------------------------------


def corrupt_params(params, seed: int = 0, scale: float = -1.7319):
    """Perturb one weight slice into finite-but-wrong values and return
    the new pytree (functional — the caller swaps ``engine.params``).

    The target leaf is drawn from the seeded RNG over floating-point
    leaves, preferring NORMALIZATION weights (rms/norm paths): they scale
    every token's residual stream, so the damage provably reaches the
    canary's pinned prompt — whereas a slice of one attention projection
    (let alone an embedding row the prompt never touches) can leave every
    argmax standing, i.e. corruption the injector itself made
    undetectable, which is a useless chaos stand-in. Falls back to
    non-embedding leaves, then to anything float. Returns
    ``(new_params, description)``."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    cand = [
        (i, path)
        for i, (path, leaf) in enumerate(flat)
        if hasattr(leaf, "dtype")
        and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
        and getattr(leaf, "size", 0) > 0
    ]
    if not cand:
        raise ValueError("no floating-point weight leaf to corrupt")
    norms = [
        c for c in cand
        if any(k in str(c[1]).lower() for k in ("rms", "norm"))
    ]
    non_embed = [c for c in cand if "embed" not in str(c[1]).lower()]
    pool = norms or non_embed or cand
    rng = random.Random(seed)
    target, path = pool[rng.randrange(len(pool))]
    leaves = [leaf for _, leaf in flat]
    leaf = jnp.asarray(leaves[target])
    vec = leaf.reshape(-1)
    n = max(1, min(256, vec.shape[0]))
    bad = vec[:n].astype(jnp.float32) * jnp.float32(scale) + jnp.float32(0.125)
    leaves[target] = vec.at[:n].set(bad.astype(leaf.dtype)).reshape(leaf.shape)
    desc = f"weight slice [{n}] of {jax.tree_util.keystr(path)}"
    return treedef.unflatten(leaves), desc
