"""Runtime engine: weight loading, KV-cached generation, stats."""

from distributed_llama_tpu.engine.engine import InferenceEngine

__all__ = ["InferenceEngine"]
