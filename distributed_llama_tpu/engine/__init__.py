"""Runtime engine: weight loading, KV-cached generation, stats."""

from distributed_llama_tpu.engine.engine import InferenceEngine


def __getattr__(name):
    # lazy: batch pulls in the scheduler machinery only when asked for
    if name in ("BatchScheduler", "BatchStream"):
        from distributed_llama_tpu.engine import batch

        return getattr(batch, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["InferenceEngine", "BatchScheduler", "BatchStream"]
