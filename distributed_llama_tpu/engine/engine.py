"""KV-cached inference engine: prefill + decode with I/T stats.

Replaces the reference's Inference driver + TaskLoop
(reference: src/tasks.cpp:158-230, src/utils.cpp:152-231): instead of
re-spawning a thread pool per token, the whole token step is one jitted XLA
program with a donated KV cache, dispatched asynchronously.

The headline I/T (inference/transfer ms per token) split of the reference's
stats (src/tasks.hpp:9-11, src/apps/dllama/dllama.cpp:49-93) is preserved:
on a single chip transfer is 0 (no collectives exist); under TP the
per-token collective cost is MEASURED once per engine by timing the step's
exact collective sequence on the real mesh
(TensorParallelForward.measure_transfer_ms) and subtracted from the step
time — the collectives are fused inside one XLA program, so they cannot be
timed in situ the way the reference times its TASK_TYPE_TRANSFER tasks.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.engine import weights as weights_lib
from distributed_llama_tpu.models import llama
from distributed_llama_tpu.models.config import LlamaConfig


def _prefill_bucket(n: int) -> int:
    """Pad prompt lengths to power-of-two buckets so XLA compiles a handful of
    prefill programs instead of one per prompt length."""
    b = 8
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass
class TokenStats:
    """Per-step timing mirroring the reference's G/I/T printout
    (reference: src/apps/dllama/dllama.cpp:49-50, 88-93). A batched prefill
    is one entry covering ``n_tokens`` positions; decode steps have
    ``n_tokens == 1``."""

    generation_ms: float
    inference_ms: float
    transfer_ms: float
    n_tokens: int = 1


class InferenceEngine:
    """Single-program driver for one model instance.

    ``tp`` > 1 shards the same forward over a tensor-parallel mesh
    (see distributed_llama_tpu.parallel); tp=1 is the single-chip path.
    """

    def __init__(
        self,
        model_path: str,
        dtype=jnp.bfloat16,
        max_seq_len: int | None = None,
        cache_dtype=None,
        tp: int = 1,
        sp: int = 1,
        **cfg_overrides,
    ):
        from distributed_llama_tpu.formats.model_file import ModelFileReader
        from distributed_llama_tpu.models.config import config_from_spec

        quantized = dtype == "q40"
        self.tp = tp
        self.sp = sp
        # the parallel backend is constructed BEFORE the weights load so the
        # q40 sharded load can place each shard's pack straight onto its
        # device via make_array_from_callback — each process reads only its
        # own shards' bytes (multi-host: O(model/tp) host RAM per process,
        # replacing the reference's root-scatter, src/transformer.cpp:432-451)
        reader = ModelFileReader(model_path)
        self.spec = reader.spec.clamp_seq_len(max_seq_len)
        self.cfg = config_from_spec(self.spec, **cfg_overrides)
        if cache_dtype is None:
            # "q40" is a weights-only format; the KV cache stays bf16
            cache_dtype = jnp.bfloat16 if quantized else dtype
        self.cache_dtype = cache_dtype
        if sp > 1:
            from distributed_llama_tpu.parallel import context_parallel as spmod

            # sequence parallelism (optionally composed with tensor
            # parallelism on a 2-D (tp, sp) mesh): sequence-sharded KV cache,
            # ring-attention prefill (see SequenceParallelForward); reuses
            # the tp-engine slot — same duck-typed interface
            self._tp_engine = spmod.SequenceParallelForward(
                self.cfg, sp, tp=tp, quantized=quantized
            )
        elif tp > 1:
            from distributed_llama_tpu.parallel import tensor_parallel as tpmod

            self._tp_engine = tpmod.TensorParallelForward(
                self.cfg, tp, quantized=quantized, layered=True
            )
        else:
            self._tp_engine = None
        # every dtype loads per-shard under tp: each process reads only its
        # own shards' bytes and places them straight onto its devices
        mesh = self._tp_engine.mesh if tp > 1 else None
        host_params = weights_lib.load_params(
            reader, self.cfg, dtype=dtype, tp=tp, mesh=mesh
        )
        reader.close()
        if self._tp_engine is not None:
            self.params = self._tp_engine.shard_params(host_params)
            self.cache = self._tp_engine.init_cache(self.cache_dtype)
            self._forward = self._tp_engine.forward
        else:
            self.params = jax.device_put(host_params)
            # per-layer cache list matching the per-layer params list, so
            # cache updates alias in place (see llama.init_cache)
            self.cache = llama.init_cache(self.cfg, dtype=self.cache_dtype, layered=True)
            self._forward = functools.partial(self._forward_single, self.cfg)
        self.pos = 0
        self.stats: list[TokenStats] = []
        self._transfer_ms: float | None = None  # measured lazily under TP/SP
        self._transfer_measured_at = 0  # token count at the last measurement
        self._pipeline_depth = 0  # >0 while a speculative chunk is in flight

    # decoded tokens between transfer re-measurements: the estimate follows
    # actual interconnect load over a session for the cost of one tiny
    # probe dispatch every ~512 tokens, instead of staying a
    # construction-time constant
    TRANSFER_REFRESH_TOKENS = 512

    def _transfer_ms_per_token(self) -> float:
        """Per-dispatch collective cost: 0 on a single chip; under TP/SP
        measured on the real mesh and re-measured periodically in situ.

        Refreshes happen only at QUIESCENT points (no dispatch in flight):
        inside the pipelined chunk loop a probe would queue behind the
        in-flight chunk and time its compute, poisoning the very split it
        feeds. The prefill/forward/decode_chunk paths all reach here right
        after their own fetch drained the stream, so every API request and
        every stepwise loop refreshes on cadence; generate_chunks reuses
        the last measurement."""
        if self._tp_engine is None:
            return 0.0
        if self._pipeline_depth > 0:
            # never measure mid-flight (even the FIRST time — a caller whose
            # first op is generate_chunks would otherwise cache a poisoned
            # estimate); report 0 until a quiescent call measures
            return self._transfer_ms or 0.0
        n = sum(s.n_tokens for s in self.stats)
        if (
            self._transfer_ms is None
            or n - self._transfer_measured_at >= self.TRANSFER_REFRESH_TOKENS
        ):
            self._transfer_ms = self._tp_engine.measure_transfer_ms()
            self._transfer_measured_at = n
        return self._transfer_ms

    def _last_dispatches(self) -> int:
        """How many device programs the most recent forward issued (the sp
        backend's chunked mid-context prefill issues several; every other
        path is exactly one)."""
        return getattr(self._tp_engine, "last_forward_dispatches", 1) or 1

    def _split_stats(
        self, per_entry_ms: float, n_tokens: int = 1, n_dispatches: int = 1
    ) -> TokenStats:
        """I/T split of one timed dispatch: the measured collective cost is an
        upper bound (XLA overlaps collectives with compute in the real
        program), so clamp it to the observed time — inference_ms must not go
        negative. An entry that covers several dispatches (the sp backend's
        chunked mid-context prefill) pays the collective sequence once per
        dispatch."""
        transfer = min(self._transfer_ms_per_token() * n_dispatches, per_entry_ms)
        return TokenStats(
            per_entry_ms, per_entry_ms - transfer, transfer, n_tokens=n_tokens
        )

    @staticmethod
    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
    def _forward_single(cfg: LlamaConfig, params, tokens, cache, pos):
        return llama.forward_tokens(cfg, params, tokens, cache, pos)

    # ------------------------------------------------------------------
    # Generation API
    # ------------------------------------------------------------------

    def reset(self) -> None:
        self.pos = 0
        self.stats.clear()
        # keep the last transfer measurement (still valid) but restart the
        # refresh cadence with the cleared token count
        self._transfer_measured_at = 0

    def rollback(self, pos: int) -> None:
        """Rewind the stream to ``pos`` (prefix-cache reuse). Cache slots
        beyond ``pos`` are stale but unreachable: attention masks s <= pos and
        every slot is overwritten before the position pointer crosses it."""
        if not 0 <= pos <= self.pos:
            raise ValueError(f"cannot rollback to {pos} from {self.pos}")
        self.pos = pos

    def _forward_device(self, tokens: np.ndarray):
        """Dispatch one forward; returns DEVICE logits [T_padded, vocab].
        Advances pos and records stats (the timing covers dispatch only —
        callers append their fetch to the same stats entry implicitly by
        measuring around their np.asarray)."""
        n = tokens.shape[0]
        if n == 0:
            raise ValueError("empty token batch: at least one token required")
        if self.pos + n > self.cfg.seq_len:
            raise ValueError(f"context overflow: pos {self.pos} + {n} > {self.cfg.seq_len}")
        if n == 1 or (
            # backends that chunk mid-context prompts themselves (sp) pad to
            # their own fixed chunk width — engine bucket-padding on top
            # would only inflate the dispatch count
            self.pos > 0
            and getattr(self._tp_engine, "prefers_exact_mid_prefill", False)
        ):
            padded = tokens
        else:
            bucket = _prefill_bucket(n)
            if self.pos + bucket > self.cfg.seq_len:
                bucket = n  # exact-length compile near the context limit
            padded = np.zeros(bucket, dtype=np.int32)
            padded[:n] = tokens
        logits, self.cache = self._forward(
            self.params, jnp.asarray(padded), self.cache, jnp.int32(self.pos)
        )
        self.pos += n
        return logits

    def forward(self, tokens: list[int] | np.ndarray) -> np.ndarray:
        """Run tokens at the current position; returns f32 logits [T, vocab]
        (padded positions stripped). Advances pos by len(tokens)."""
        tokens = np.asarray(tokens, dtype=np.int32)
        n = tokens.shape[0]
        start = time.perf_counter()
        logits = np.asarray(self._forward_device(tokens)[:n])
        elapsed = (time.perf_counter() - start) * 1000.0
        self.stats.append(
            self._split_stats(elapsed, n_tokens=n, n_dispatches=self._last_dispatches())
        )
        return logits

    def prefill(self, tokens: list[int]) -> np.ndarray:
        """Process a prompt in one batched step; returns last-token logits.

        Only the LAST position's logits row cross the host boundary: a
        64-token prefill of a 32k-vocab model would otherwise ship 8 MB of
        f32 logits per prompt (measured ~2 s through a remote PJRT tunnel
        vs ~tens of ms for the row)."""
        tokens = np.asarray(tokens, dtype=np.int32)
        n = tokens.shape[0]
        start = time.perf_counter()
        logits = np.asarray(self._forward_device(tokens)[n - 1])
        elapsed = (time.perf_counter() - start) * 1000.0
        self.stats.append(
            self._split_stats(elapsed, n_tokens=n, n_dispatches=self._last_dispatches())
        )
        return logits

    def decode_step(self, token: int) -> np.ndarray:
        """One autoregressive step; returns f32 logits [vocab]."""
        return self.forward([token])[0]

    def generate_on_device(
        self,
        first_token: int,
        n_steps: int,
        temperature: float = 0.0,
        topp: float = 0.9,
        seed: int = 0,
    ) -> np.ndarray:
        """Generate n_steps tokens in ONE device program (no per-token host
        round trip). Returns int32 [n_steps]. Under TP the loop is
        shard_map'd over the mesh with collectives riding every step."""
        if self.pos + n_steps > self.cfg.seq_len:
            raise ValueError(f"context overflow: pos {self.pos} + {n_steps}")
        from distributed_llama_tpu.models import sampling

        start = time.perf_counter()
        if self._tp_engine is not None:
            tokens, self.cache = self._tp_engine.decode_loop(
                self.params,
                jnp.int32(first_token),
                self.cache,
                jnp.int32(self.pos),
                n_steps,
                float(temperature),
                float(topp),
                jax.random.PRNGKey(seed),
            )
        else:
            tokens, self.cache = sampling.decode_loop(
                self.cfg,
                self.params,
                jnp.int32(first_token),
                self.cache,
                jnp.int32(self.pos),
                n_steps,
                float(temperature),
                float(topp),
                jax.random.PRNGKey(seed),
            )
        tokens = np.asarray(tokens)
        elapsed_ms = (time.perf_counter() - start) * 1000.0
        self.stats.extend([self._split_stats(elapsed_ms / n_steps)] * n_steps)
        self.pos += n_steps
        return tokens

    def _dispatch_chunk(self, first_token, n_steps: int, temperature, topp, key):
        """Dispatch one decode chunk WITHOUT fetching: returns the device
        token array and the advanced key. ``first_token`` may be a host int
        or a device scalar (the previous chunk's last token — the pipelined
        path never waits on it). Advances pos by n_steps."""
        from distributed_llama_tpu.models import sampling

        if self._tp_engine is not None:
            tokens, self.cache, key = self._tp_engine.decode_chunk(
                self.params, jnp.int32(first_token), self.cache, jnp.int32(self.pos),
                n_steps, temperature, topp, key,
            )
        else:
            tokens, self.cache, key = sampling.decode_chunk(
                self.cfg, self.params, jnp.int32(first_token), self.cache,
                jnp.int32(self.pos), n_steps, jnp.float32(temperature),
                jnp.float32(topp), key,
            )
        self.pos += n_steps
        return tokens, key

    def decode_chunk(self, first_token: int, n_steps: int, temperature, topp, key):
        """Decode ``n_steps`` tokens in one device dispatch with runtime-valued
        temperature/topp (no recompile when a request changes them). Returns
        (tokens np[n_steps], advanced PRNG key). Advances pos by n_steps."""
        start = time.perf_counter()
        tokens, key = self._dispatch_chunk(first_token, n_steps, temperature, topp, key)
        tokens = np.asarray(tokens)
        elapsed_ms = (time.perf_counter() - start) * 1000.0
        self.stats.extend([self._split_stats(elapsed_ms / n_steps)] * n_steps)
        return tokens, key

    def generate_chunks(
        self,
        first_token: int,
        temperature: float = 0.0,
        topp: float = 0.9,
        seed: int = 0,
        chunk: int = 32,
        limit: int | None = None,
    ):
        """Generator of on-device-decoded tokens: ``chunk`` tokens per device
        dispatch (no per-token host round trip), host code between chunks.
        ``first_token`` is consumed first, not yielded. One PRNG key threads
        through the chunks and is split once per step, so the stream for a
        given seed is identical to ``generate_on_device(seed)`` regardless of
        chunk size.

        ``limit`` stops dispatching once ``pos`` reaches it (a stop *hint*:
        the final chunk may overshoot it — chunks keep a fixed size so XLA
        compiles one program, not one per remaining-budget value). Callers
        that stop consuming early (EOS, stop string, budget) MUST
        ``rollback(pos)`` to the stream position after the last token they
        consumed; overshot cache slots are unreachable after rollback.

        This is the user-facing fast path: the stepwise ``decode_step`` loop
        pays a host<->device round trip per token (the reference's regime,
        src/apps/dllama/dllama.cpp:45-59), which behind a remote PJRT tunnel
        costs more than the forward pass itself. The stream is additionally
        PIPELINED: chunk k+1 is dispatched (seeded by chunk k's last token,
        which never leaves the device) BEFORE chunk k's tokens are fetched,
        so the host-fetch latency overlaps the next chunk's compute. An
        early stop wastes at most one speculative chunk — already covered by
        the rollback contract above.
        """
        key = jax.random.PRNGKey(seed)
        stop = self.cfg.seq_len if limit is None else min(limit, self.cfg.seq_len)
        if self.pos >= stop:
            return
        k = min(chunk, self.cfg.seq_len - self.pos)
        pending, key = self._dispatch_chunk(int(first_token), k, temperature, topp, key)
        pending_n = k
        # a speculative chunk is in flight for the rest of the loop: the
        # transfer estimate must not re-measure here (see
        # _transfer_ms_per_token); the generator's finally covers early
        # consumer exits (EOS/stop breaks close the generator)
        self._pipeline_depth += 1
        try:
            yield from self._generate_chunks_pipelined(
                pending, pending_n, stop, chunk, temperature, topp, key
            )
        finally:
            self._pipeline_depth -= 1

    def _generate_chunks_pipelined(
        self, pending, pending_n, stop, chunk, temperature, topp, key
    ):
        while True:
            # the timed window covers dispatch+fetch only — consumer time
            # between yields must not be attributed to the engine's stats
            start = time.perf_counter()
            # speculatively dispatch the next chunk off the device-resident
            # last token before fetching the pending one
            if self.pos < stop:
                k = min(chunk, self.cfg.seq_len - self.pos)
                nxt, key = self._dispatch_chunk(pending[-1], k, temperature, topp, key)
            else:
                nxt, k = None, 0
            try:
                # start the device->host copy without blocking: behind a
                # remote PJRT tunnel the blocking fetch pays a full round
                # trip; enqueued here it overlaps the next chunk's compute
                pending.copy_to_host_async()
            except Exception:
                pass  # optional acceleration; np.asarray below is the contract
            toks = np.asarray(pending)
            elapsed_ms = (time.perf_counter() - start) * 1000.0
            self.stats.extend([self._split_stats(elapsed_ms / pending_n)] * pending_n)
            for t in toks.tolist():
                yield int(t)
            if nxt is None:
                return
            pending, pending_n = nxt, k

    def stream_decode(
        self,
        first_token: int,
        on_token,
        temperature: float = 0.0,
        topp: float = 0.9,
        seed: int = 0,
        chunk: int = 32,
        limit: int | None = None,
    ) -> int:
        """Drive the chunked fast decode with host-side stop handling: the
        shared consumption loop of CLI generate/chat and the API server.

        ``on_token(prev_token, token) -> bool`` is called once per decoded
        token (False = stop). This method owns the early-stop rollback
        contract of :meth:`generate_chunks`: every decoded token counts one
        feed of its predecessor, so on exit the stream position is rewound to
        just after the last decoded token's feed. Returns the number of
        decoded tokens."""
        start_pos = self.pos
        consumed = 0
        prev = int(first_token)
        for t in self.generate_chunks(
            first_token, temperature, topp, seed=seed, chunk=chunk, limit=limit
        ):
            consumed += 1
            keep_going = on_token(prev, t)
            prev = t
            if keep_going is False:
                break
            if limit is not None and start_pos + consumed >= limit:
                break
        self.rollback(start_pos + consumed)
        return consumed

    # ------------------------------------------------------------------
    # Stats (reference: Inference::getStats, src/tasks.cpp:186-189)
    # ------------------------------------------------------------------

    def avg_stats(self) -> TokenStats:
        """Per-token averages, weighting batched-prefill entries by their
        token count (the reference accounts per position, dllama.cpp:88-93)."""
        if not self.stats:
            return TokenStats(0.0, 0.0, 0.0)
        n = sum(s.n_tokens for s in self.stats)
        return TokenStats(
            sum(s.generation_ms for s in self.stats) / n,
            sum(s.inference_ms for s in self.stats) / n,
            sum(s.transfer_ms for s in self.stats) / n,
            n_tokens=n,
        )

    def total_tokens(self) -> int:
        return sum(s.n_tokens for s in self.stats)
