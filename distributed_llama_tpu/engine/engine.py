"""KV-cached inference engine: prefill + decode with I/T stats.

Replaces the reference's Inference driver + TaskLoop
(reference: src/tasks.cpp:158-230, src/utils.cpp:152-231): instead of
re-spawning a thread pool per token, the whole token step is one jitted XLA
program with a donated KV cache, dispatched asynchronously.

The headline I/T (inference/transfer ms per token) split of the reference's
stats (src/tasks.hpp:9-11, src/apps/dllama/dllama.cpp:49-93) is preserved:
on a single chip transfer is 0 (no collectives exist); under TP the
per-token collective cost is MEASURED once per engine by timing the step's
exact collective sequence on the real mesh
(TensorParallelForward.measure_transfer_ms) and subtracted from the step
time — the collectives are fused inside one XLA program, so they cannot be
timed in situ the way the reference times its TASK_TYPE_TRANSFER tasks.

Concurrency: one engine owns the weights and the compiled programs; the
mutable decode state (KV cache, position, stats) lives in
:class:`EngineStream`. ``engine.new_stream()`` adds an independent stream
sharing the same weights — the API server interleaves several completions
this way (the reference is architecturally single-stream: one socket accept
drives one inference at a time, dllama-api.cpp:418-423). The engine itself
delegates the classic single-stream surface to a default stream, so CLI and
tests are unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu import lockcheck, prng, telemetry
from distributed_llama_tpu.engine import faults
from distributed_llama_tpu.engine import weights as weights_lib
from distributed_llama_tpu.telemetry import Stopwatch
from distributed_llama_tpu.models import llama
from distributed_llama_tpu.models.config import LlamaConfig


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (and >= 1): the one bucketing primitive
    behind the prefill/decode-row/page-id buckets."""
    b = 1
    while b < n:
        b *= 2
    return b


def _prefill_bucket(n: int) -> int:
    """Pad prompt lengths to power-of-two buckets (floor 8) so XLA compiles
    a handful of prefill programs instead of one per prompt length."""
    return max(8, next_pow2(n))


@dataclasses.dataclass
class TokenStats:
    """Per-step timing mirroring the reference's G/I/T printout
    (reference: src/apps/dllama/dllama.cpp:49-50, 88-93). A batched prefill
    is one entry covering ``n_tokens`` positions; decode steps have
    ``n_tokens == 1``."""

    generation_ms: float
    inference_ms: float
    transfer_ms: float
    n_tokens: int = 1


class EngineStream:
    """One independent generation stream: its own KV cache, position and
    stats, sharing the owning engine's weights and compiled programs.

    All per-request state lives here so several streams can decode
    concurrently on one engine (each dispatch is whole-program and
    asynchronous; interleaved dispatches from different streams simply queue
    on the device stream in order)."""

    def __init__(self, engine: "InferenceEngine", cache):
        self.engine = engine
        self.cache = cache
        self.pos = 0
        self.stats: list[TokenStats] = []
        # the prefill_device stats entry awaiting its compute-drain time
        # (added when generate_chunks fetches the fused first token)
        self._pending_prefill_entry: TokenStats | None = None
        # True while this stream's un-fetched prefill_device dispatch holds
        # the engine's pipeline depth up (released at the first-token fetch)
        self._depth_held = False
        # per-request deadline (time.monotonic seconds): enforced by the
        # serving layer per token; carried here so both stream kinds share
        # the surface (the batch scheduler additionally enforces it
        # between chunks — see engine/batch.py)
        self.deadline: float | None = None
        # prefix-cache opt-out surface parity with BatchStream (ISSUE 4):
        # the API server sets this per request on whichever stream kind the
        # slot wears; only the batch scheduler's paged prefix cache consumes
        # it — an independent EngineStream has no shared page pool to reuse
        self.prefix_cache_enabled = True
        # multi-tenant labels, surface parity with BatchStream (ISSUE 8):
        # the serving layer stamps them per request; only the batch
        # scheduler consumes them (preempt_below) — independent streams
        # have no shared rows to evict
        self.tenant: str | None = None
        self.priority: int | None = None
        # request trace surface parity with BatchStream (ISSUE 16): the
        # serving layer stamps it per request; only the batch scheduler
        # fans per-row spans into it — the independent-stream decode path
        # records its spans at the serving layer instead
        self.trace = None
        engine._streams.append(self)
        engine._tel.active_streams.set(len(engine._streams))

    @property
    def cfg(self) -> LlamaConfig:
        return self.engine.cfg

    # ------------------------------------------------------------------
    # Telemetry feeds (no-ops unless telemetry was enabled when the engine
    # was constructed; tel.enabled guards keep the disabled path to one
    # attribute check per DISPATCH — never per token, no registry access)
    # ------------------------------------------------------------------

    def _note_prefill(self, entry: "TokenStats") -> None:
        tel = self.engine._tel
        if tel.enabled:
            tel.prompt_tokens.inc(entry.n_tokens)
            tel.prefill_latency.observe(entry.generation_ms / 1000.0)
            tel.kv_occupancy.set(self.pos / self.engine.cfg.seq_len)

    def _note_decode(
        self, n_tokens: int, per_token_ms: float, device_sampled: bool = False
    ) -> None:
        tel = self.engine._tel
        if tel.enabled:
            tel.tokens_generated.inc(n_tokens)
            tel.decode_latency.observe(per_token_ms / 1000.0)
            tel.kv_occupancy.set(self.pos / self.engine.cfg.seq_len)
            if device_sampled:
                # the ISSUE 13 happy-path witness: tokens whose sampling ran
                # inside the device program (the host Sampler counts its own
                # fallback tokens — the two counters partition decode)
                tel.device_sampled_tokens.inc(n_tokens)

    # ------------------------------------------------------------------
    # Generation API
    # ------------------------------------------------------------------

    def reset(self) -> None:
        self.pos = 0
        # the engine's transfer-refresh cadence counts tokens across ALL
        # streams and its last measurement stays valid — one stream's reset
        # must be a NO-OP on the shared cadence (zeroing the engine-wide
        # watermark forced an early re-measurement for every stream under
        # concurrent serving, ADVICE r5). Clearing this stream's stats
        # shrinks the engine-wide token sum, so the watermark shifts down
        # by the same amount to keep (total - watermark) unchanged.
        cleared = sum(s.n_tokens for s in self.stats)
        with self.engine._depth_lock:
            self.engine._transfer_measured_at -= cleared
        self.stats.clear()
        self._release_depth()  # an abandoned un-fetched prefill must not pin the depth
        self._pending_prefill_entry = None
        self.deadline = None
        self.prefix_cache_enabled = True
        self.tenant = None
        self.priority = None

    def rollback(self, pos: int) -> None:
        """Rewind the stream to ``pos`` (prefix-cache reuse). Cache slots
        beyond ``pos`` are stale but unreachable: attention masks s <= pos and
        every slot is overwritten before the position pointer crosses it."""
        if not 0 <= pos <= self.pos:
            raise ValueError(f"cannot rollback to {pos} from {self.pos}")
        self.pos = pos

    def _forward_device(self, tokens: np.ndarray):
        """Dispatch one forward; returns DEVICE logits [T_padded, vocab].
        Advances pos and records stats (the timing covers dispatch only —
        callers append their fetch to the same stats entry implicitly by
        measuring around their np.asarray)."""
        engine = self.engine
        engine._faults.fire("engine.forward")
        n = tokens.shape[0]
        if n == 0:
            raise ValueError("empty token batch: at least one token required")
        if self.pos + n > engine.cfg.seq_len:
            raise ValueError(f"context overflow: pos {self.pos} + {n} > {engine.cfg.seq_len}")
        if n == 1 or getattr(engine._tp_engine, "prefers_exact_mid_prefill", False):
            # backends that pad/chunk multi-token prompts themselves (sp:
            # fixed-width masked-scatter chunks at any position, seq_len
            # padding on the ring path) — engine bucket-padding on top
            # would only inflate the dispatch count
            padded = tokens
        else:
            bucket = _prefill_bucket(n)
            if self.pos + bucket > engine.cfg.seq_len:
                bucket = n  # exact-length compile near the context limit
            padded = np.zeros(bucket, dtype=np.int32)
            padded[:n] = tokens
        if engine._forward_takes_n_real:
            # the real-token count rides into the jitted forward (traced, no
            # recompile) so the capacity-bucketed MoE prefill can keep
            # bucket-pad rows out of its per-expert buckets
            logits, self.cache = engine._forward(
                engine.params, jnp.asarray(padded), self.cache,
                jnp.int32(self.pos), jnp.int32(n),
            )
        else:
            logits, self.cache = engine._forward(
                engine.params, jnp.asarray(padded), self.cache, jnp.int32(self.pos)
            )
        self.pos += n
        return logits

    def forward(self, tokens: list[int] | np.ndarray) -> np.ndarray:
        """Run tokens at the current position; returns f32 logits [T, vocab]
        (padded positions stripped). Advances pos by len(tokens)."""
        # an abandoned fused prefill (prefill_device whose token was never
        # fetched) must not pin the engine depth: this call's own fetch
        # drains the device queue anyway
        self._release_depth()
        tokens = np.asarray(tokens, dtype=np.int32)
        n = tokens.shape[0]
        sw = Stopwatch()
        with self.engine._tel.span("forward", tokens=n, pos=self.pos):
            logits = np.asarray(self._forward_device(tokens)[:n])
        entry = self.engine._split_stats(
            sw.elapsed_ms(), n_tokens=n, n_dispatches=self.engine._last_dispatches()
        )
        self.stats.append(entry)
        if n > 1:
            self._note_prefill(entry)
        else:
            self._note_decode(1, entry.generation_ms)
        return logits

    def prefill(self, tokens: list[int]) -> np.ndarray:
        """Process a prompt in one batched step; returns last-token logits.

        Only the LAST position's logits row cross the host boundary: a
        64-token prefill of a 32k-vocab model would otherwise ship 8 MB of
        f32 logits per prompt (measured ~2 s through a remote PJRT tunnel
        vs ~tens of ms for the row)."""
        self._release_depth()  # see forward()
        tokens = np.asarray(tokens, dtype=np.int32)
        n = tokens.shape[0]
        sw = Stopwatch()
        with self.engine._tel.span("prefill", tokens=n, pos=self.pos):
            logits = np.asarray(self._forward_device(tokens)[n - 1])
        entry = self.engine._split_stats(
            sw.elapsed_ms(), n_tokens=n, n_dispatches=self.engine._last_dispatches()
        )
        self.stats.append(entry)
        self._note_prefill(entry)
        return logits

    def prefill_device(
        self, tokens: list[int], temperature, topp, seed: int, topk: int = 0
    ):
        """Prefill + sample the first generated token ON DEVICE; returns the
        sampled token as a device scalar (NOT fetched). The coin is drawn
        from the counter PRNG at the last prompt token's absolute position,
        so a requeued/replayed request re-draws it exactly — no sampler
        state exists to ship (ISSUE 13).

        This removes the prompt→first-token host round trip entirely: the
        returned scalar feeds :meth:`generate_chunks` without ever visiting
        the host, so time-to-first-token is one device prefill + one chunk
        instead of two tunnel round trips (measured ~96 ms each behind a
        remote PJRT tunnel, docs/PERF.md).

        The stats entry recorded here covers the ASYNC dispatch only; the
        prefill's device compute drains at the first-token fetch inside
        ``generate_chunks(emit_first=True)``, which adds that drain time back
        onto this entry (``_pending_prefill_entry``) so the P line still
        reports true prefill latency."""
        engine = self.engine
        tokens = np.asarray(tokens, dtype=np.int32)
        n = tokens.shape[0]
        sw = Stopwatch()
        # the dispatches below are never fetched here: mark the engine
        # non-quiescent so the transfer probe does not queue behind them and
        # time their compute (see _transfer_ms_per_token). The depth stays
        # RAISED until the fused first token is fetched (_fetch_fused_first)
        # — decrementing here would reopen the probe-poisoning window for
        # the whole prefill-to-first-fetch span.
        self._hold_depth()
        try:
            with engine._tel.span("prefill_dispatch", tokens=n, pos=self.pos):
                logits = self._forward_device(tokens)
                with engine._tel.span("device_sample", pos=self.pos - 1):
                    token = engine._sample_row(
                        logits, jnp.int32(n - 1),
                        jnp.uint32(prng.fold_seed(seed)),
                        jnp.int32(self.pos - 1), jnp.float32(temperature),
                        jnp.float32(topp), jnp.int32(topk),
                    )
            entry = engine._split_stats(
                sw.elapsed_ms(), n_tokens=n, n_dispatches=engine._last_dispatches()
            )
            self.stats.append(entry)
            self._pending_prefill_entry = entry
            # prompt tokens count now; the prefill LATENCY observation waits
            # for _fetch_fused_first, where the entry gains its true
            # device-compute drain time
            if engine._tel.enabled:
                engine._tel.prompt_tokens.inc(n)
        except BaseException:
            self._release_depth()
            raise
        return token

    def _hold_depth(self) -> None:
        """Raise the engine's in-flight depth on this stream's behalf until
        :meth:`_release_depth`. Idempotent: a second hold while the first is
        outstanding is absorbed (at most one un-fetched prefill can exist
        per stream, and the hold is released at its first-token fetch, a
        reset(), or the start of any fetching forward/prefill)."""
        engine = self.engine
        with engine._depth_lock:
            if not self._depth_held:
                engine._pipeline_depth += 1
                self._depth_held = True

    def _release_depth(self) -> None:
        engine = self.engine
        with engine._depth_lock:
            if self._depth_held:
                engine._pipeline_depth -= 1
                self._depth_held = False

    def decode_step(self, token: int) -> np.ndarray:
        """One autoregressive step; returns f32 logits [vocab]."""
        return self.forward([token])[0]

    def generate_on_device(
        self,
        first_token: int,
        n_steps: int,
        temperature: float = 0.0,
        topp: float = 0.9,
        seed: int = 0,
        topk: int = 0,
    ) -> np.ndarray:
        """Generate n_steps tokens in ONE device program (no per-token host
        round trip). Returns int32 [n_steps]. Under TP the loop is
        shard_map'd over the mesh with collectives riding every step."""
        engine = self.engine
        if self.pos + n_steps > engine.cfg.seq_len:
            raise ValueError(f"context overflow: pos {self.pos} + {n_steps}")
        from distributed_llama_tpu.models import sampling

        sw = Stopwatch()
        if engine._tp_engine is not None:
            tokens, self.cache = engine._tp_engine.decode_loop(
                engine.params,
                jnp.int32(first_token),
                self.cache,
                jnp.int32(self.pos),
                n_steps,
                float(temperature),
                float(topp),
                seed=seed,
                topk=topk,
            )
        else:
            tokens, self.cache = sampling.decode_loop(
                engine.cfg,
                engine.params,
                jnp.int32(first_token),
                self.cache,
                jnp.int32(self.pos),
                n_steps,
                float(temperature),
                float(topp),
                seed=seed,
                topk=topk,
            )
        tokens = np.asarray(tokens)
        per_token_ms = sw.elapsed_ms() / n_steps
        self.stats.extend([engine._split_stats(per_token_ms)] * n_steps)
        self.pos += n_steps
        self._note_decode(n_steps, per_token_ms, device_sampled=True)
        return tokens

    def _dispatch_chunk(
        self, first_token, n_steps: int, temperature, topp, topk, seed32
    ):
        """Dispatch one decode chunk WITHOUT fetching: returns the device
        token array. ``first_token`` may be a host int or a device scalar
        (the previous chunk's last token — the pipelined path never waits
        on it); ``seed32`` is the folded uint32 request seed the chunk's
        counter coins re-key from (no sampler state threads between
        chunks). Advances pos by n_steps."""
        from distributed_llama_tpu.models import sampling

        engine = self.engine
        engine._faults.fire("engine.decode_dispatch")
        with engine._tel.span("decode_chunk_dispatch", pos=self.pos, steps=n_steps):
            if engine._tp_engine is not None:
                tokens, self.cache = engine._tp_engine.decode_chunk(
                    engine.params, jnp.int32(first_token), self.cache, jnp.int32(self.pos),
                    n_steps, temperature, topp, topk, seed32,
                )
            else:
                tokens, self.cache = sampling.decode_chunk(
                    engine.cfg, engine.params, jnp.int32(first_token), self.cache,
                    jnp.int32(self.pos), n_steps, jnp.float32(temperature),
                    jnp.float32(topp), jnp.int32(topk), seed32,
                )
        self.pos += n_steps
        return tokens

    def decode_chunk(
        self, first_token: int, n_steps: int, temperature, topp, seed=0, topk=0
    ):
        """Decode ``n_steps`` tokens in one device dispatch with runtime-valued
        temperature/topp/topk (no recompile when a request changes them).
        Returns tokens np[n_steps]. Advances pos by n_steps."""
        sw = Stopwatch()
        tokens = self._dispatch_chunk(
            first_token, n_steps, temperature, topp, topk,
            jnp.uint32(prng.fold_seed(seed)),
        )
        tokens = np.asarray(tokens)
        per_token_ms = sw.elapsed_ms() / n_steps
        self.stats.extend([self.engine._split_stats(per_token_ms)] * n_steps)
        self._note_decode(n_steps, per_token_ms, device_sampled=True)
        return tokens

    def generate_chunks(
        self,
        first_token,
        temperature: float = 0.0,
        topp: float = 0.9,
        seed: int = 0,
        chunk: int = 32,
        limit: int | None = None,
        emit_first: bool = False,
        topk: int = 0,
    ):
        """Generator of on-device-decoded tokens: ``chunk`` tokens per device
        dispatch (no per-token host round trip), host code between chunks.
        ``first_token`` is consumed first, not yielded — a host int, or a
        device scalar from :meth:`prefill_device` (then the stream continues
        without any host round trip; set ``emit_first`` and the unseen first
        token is fetched and yielded after chunk 1 is dispatched, its fetch
        overlapping the chunk's compute). Every step's coin is re-keyed from
        ``(seed, position)`` by the counter PRNG, so the stream for a given
        seed is identical to ``generate_on_device(seed)`` regardless of
        chunk size — no sampler state threads between chunks.

        ``limit`` stops dispatching once ``pos`` reaches it (a stop *hint*:
        the final chunk may overshoot it — chunks keep a fixed size so XLA
        compiles one program, not one per remaining-budget value). Callers
        that stop consuming early (EOS, stop string, budget) MUST
        ``rollback(pos)`` to the stream position after the last token they
        consumed; overshot cache slots are unreachable after rollback.

        This is the user-facing fast path: the stepwise ``decode_step`` loop
        pays a host<->device round trip per token (the reference's regime,
        src/apps/dllama/dllama.cpp:45-59), which behind a remote PJRT tunnel
        costs more than the forward pass itself. The stream is additionally
        PIPELINED: chunk k+1 is dispatched (seeded by chunk k's last token,
        which never leaves the device) BEFORE chunk k's tokens are fetched,
        so the host-fetch latency overlaps the next chunk's compute. An
        early stop wastes at most one speculative chunk — already covered by
        the rollback contract above.
        """
        engine = self.engine
        seed32 = jnp.uint32(prng.fold_seed(seed))
        stop = engine.cfg.seq_len if limit is None else min(limit, engine.cfg.seq_len)
        if self.pos >= stop:
            if emit_first:
                yield self._fetch_fused_first(first_token)
            return
        k = min(chunk, engine.cfg.seq_len - self.pos)
        if isinstance(first_token, (int, np.integer)):
            first_token = int(first_token)
        # a speculative chunk is in flight for the rest of the loop: the
        # transfer estimate must not re-measure while one is queued, so the
        # depth must rise BEFORE the first dispatch (a concurrent stream's
        # probe could otherwise slip between dispatch and increment and time
        # this chunk's compute); the finally covers early consumer exits
        # (EOS/stop breaks close the generator)
        with engine._depth_lock:
            engine._pipeline_depth += 1
        try:
            pending = self._dispatch_chunk(
                first_token, k, temperature, topp, topk, seed32
            )
            pending_n = k
            if emit_first:
                # chunk 1 is already in flight: this scalar fetch overlaps
                # its compute instead of gating the prompt→first-token path
                yield self._fetch_fused_first(first_token)
            yield from self._generate_chunks_pipelined(
                pending, pending_n, stop, chunk, temperature, topp, topk, seed32
            )
        finally:
            with engine._depth_lock:
                engine._pipeline_depth -= 1

    def fetch_first_token(self, first_token) -> int:
        """Fetch a :meth:`prefill_device` token WITHOUT starting a decode
        stream (the 1-token-completion fast path: dispatching a speculative
        chunk would burn a whole chunk of device compute for a request that
        wants exactly one token). Drains the prefill and fixes up its stats
        entry like the streaming path does."""
        return self._fetch_fused_first(first_token)

    def _fetch_fused_first(self, first_token) -> int:
        """Fetch the device-sampled first token; the blocking fetch drains
        the prefill's device compute, so its elapsed time is added back onto
        the prefill's stats entry (prefill_device timed only the async
        dispatch — without this the P line would report ~dispatch overhead
        and the prefill compute would be misattributed to the first chunk).
        Also releases the depth hold prefill_device took: the prefill is
        drained now, so the probe-quiescence hazard it guarded is gone."""
        sw = Stopwatch()
        with self.engine._tel.span("first_token_fetch"):
            tok = int(np.asarray(first_token))
        self._release_depth()
        drained_ms = sw.elapsed_ms()
        entry = self._pending_prefill_entry
        if entry is not None:
            entry.generation_ms += drained_ms
            entry.inference_ms += drained_ms
            self._pending_prefill_entry = None
            # the deferred prefill-latency observation (see prefill_device):
            # the entry now carries dispatch + device-compute drain time.
            # The fused first token counts as GENERATED here — it belongs to
            # no decode chunk (generate_chunks consumes it, never yields it
            # from a chunk), and its latency is folded into the prefill entry
            tel = self.engine._tel
            if tel.enabled:
                tel.prefill_latency.observe(entry.generation_ms / 1000.0)
                tel.tokens_generated.inc(1)
                tel.device_sampled_tokens.inc(1)
                tel.kv_occupancy.set(self.pos / self.engine.cfg.seq_len)
        return tok

    def _generate_chunks_pipelined(
        self, pending, pending_n, stop, chunk, temperature, topp, topk, seed32
    ):
        engine = self.engine
        while True:
            # the timed window covers dispatch+fetch only — consumer time
            # between yields must not be attributed to the engine's stats
            sw = Stopwatch()
            # speculatively dispatch the next chunk off the device-resident
            # last token before fetching the pending one
            if self.pos < stop:
                k = min(chunk, engine.cfg.seq_len - self.pos)
                nxt = self._dispatch_chunk(
                    pending[-1], k, temperature, topp, topk, seed32
                )
            else:
                nxt, k = None, 0
            engine._faults.fire("engine.fetch")
            with engine._tel.span("decode_chunk_fetch", tokens=pending_n):
                try:
                    # start the device->host copy without blocking: behind a
                    # remote PJRT tunnel the blocking fetch pays a full round
                    # trip; enqueued here it overlaps the next chunk's compute
                    pending.copy_to_host_async()
                except Exception:
                    pass  # optional acceleration; np.asarray below is the contract
                toks = np.asarray(pending)
            per_token_ms = sw.elapsed_ms() / pending_n
            self.stats.extend([engine._split_stats(per_token_ms)] * pending_n)
            self._note_decode(pending_n, per_token_ms, device_sampled=True)
            for t in toks.tolist():
                yield int(t)
            if nxt is None:
                return
            pending, pending_n = nxt, k

    def stream_decode(
        self,
        first_token,
        on_token,
        temperature: float = 0.0,
        topp: float = 0.9,
        seed: int = 0,
        chunk: int = 32,
        limit: int | None = None,
        first_prev: int | None = None,
        spec_draft: int = 0,
        spec_ngram: int = 3,
        prompt_tokens=None,
        topk: int = 0,
    ) -> int:
        """Drive the chunked fast decode with host-side stop handling: the
        shared consumption loop of CLI generate/chat and the API server.

        ``on_token(prev_token, token) -> bool`` is called once per decoded
        token (False = stop). This method owns the early-stop rollback
        contract of :meth:`generate_chunks`: every decoded token counts one
        feed of its predecessor, so on exit the stream position is rewound to
        just after the last decoded token's feed. Returns the number of
        decoded tokens.

        ``first_prev`` (prefill→decode fusion): ``first_token`` is a device
        scalar from :meth:`prefill_device` that the caller has NOT seen yet —
        it is ALSO yielded to ``on_token`` as the first decoded token (its
        host value arrives with the first fetched chunk), with ``first_prev``
        (the prompt's last token) as its predecessor.

        ``spec_draft`` > 0 routes through self-speculative decoding
        (:meth:`_stream_decode_spec`): prompt-lookup drafts over
        ``prompt_tokens`` + the emitted output are verified k at a time in
        one weight read per step. Single-chip dense models only — other
        backends fall back to the chunked path, and so do MoE models (a
        T>1 verify window routes through the prefill expert path, which
        has no decode parity contract — same gate as the batch
        scheduler's). Greedy output is identical either way."""
        if spec_draft and spec_draft > 0:
            if self.engine._tp_engine is None and not self.engine.cfg.is_moe:
                return self._stream_decode_spec(
                    first_token, on_token, temperature, topp, seed, spec_draft,
                    spec_ngram, limit, first_prev, prompt_tokens, topk,
                )
            # once per engine, not per request: the operator asked for spec
            # on a backend without it — say so instead of silently serving
            # the plain path (the batch scheduler prints the same warning)
            if not getattr(self.engine, "_spec_fallback_warned", False):
                self.engine._spec_fallback_warned = True
                reason = (
                    "single-chip backend only for now"
                    if self.engine._tp_engine is not None
                    else "MoE verify windows have no decode parity contract"
                )
                print(f"⚠️ --spec-draft ignored: {reason}; plain chunked decode")
        start_pos = self.pos
        consumed = 0
        fused_first = first_prev is not None
        prev = first_prev if fused_first else int(first_token)
        try:
            for t in self.generate_chunks(
                first_token, temperature, topp, seed=seed, chunk=chunk,
                limit=limit, emit_first=fused_first, topk=topk,
            ):
                consumed += 1
                keep_going = on_token(prev, t)
                prev = t
                # with a fused first token, yield i corresponds to stream
                # position start_pos + i - 1 (the first yield was sampled
                # during prefill and occupies no new position until fed)
                fed = consumed - 1 if fused_first else consumed
                if keep_going is False:
                    break
                if limit is not None and start_pos + fed >= limit:
                    break
        finally:
            # the rollback must run even when on_token RAISES (an SSE client
            # disconnect mid-stream, a deadline expiry): without it the slot's
            # next request sees a position inflated by the overshot
            # speculative chunk and needlessly resets its prefix cache
            fed = max(consumed - 1, 0) if fused_first else consumed
            self.rollback(min(start_pos + fed, self.pos))
        # the stream is drained here (generator closed, last chunk fetched):
        # the one quiescent point of the fused serving flow — refresh the
        # transfer estimate on cadence for FUTURE entries (every stats entry
        # of this request was computed mid-flight and used the cached value;
        # without this hook a device-decode-only server would never measure)
        self.engine._maybe_refresh_transfer()
        return consumed

    def _stream_decode_spec(
        self,
        first_token,
        on_token,
        temperature: float,
        topp: float,
        seed: int,
        spec_draft: int,
        spec_ngram: int,
        limit: int | None,
        first_prev: int | None,
        prompt_tokens,
        topk: int = 0,
    ) -> int:
        """Self-speculative decode (``--spec-draft k``): per step the host
        drafts up to k tokens by prompt lookup over the request's own
        prompt + output, ONE verify forward scores draft + bonus positions
        in a single weight read, and the on-device accept/reject keeps the
        longest valid prefix — 1..k+1 tokens emitted per weight read
        instead of exactly 1. Greedy output is bit-identical to plain
        decode (tests/test_speculative.py); sampled output preserves the
        target distribution via Leviathan rejection sampling.

        Unlike :meth:`generate_chunks` this loop cannot pipeline: the next
        step's drafts depend on THIS step's emitted tokens, so each verify
        is dispatched and fetched synchronously (the fetch is k+2 int32s).
        The trade is deliberate — on accepting workloads one round trip
        buys several tokens. ``prompt_tokens`` seeds the lookup corpus
        (without it only the emitted output can match). Single chip only;
        the caller routes other backends to the chunked path."""
        from distributed_llama_tpu.engine.speculative import PromptLookupDrafter
        from distributed_llama_tpu.models import sampling

        engine = self.engine
        seed32 = jnp.uint32(prng.fold_seed(seed))
        stop = engine.cfg.seq_len if limit is None else min(limit, engine.cfg.seq_len)
        drafter = PromptLookupDrafter(spec_draft, max_ngram=spec_ngram)
        # the lookup corpus: prompt + everything emitted (first_token is
        # appended below — callers pass the prompt WITHOUT it)
        history = [int(t) for t in (prompt_tokens if prompt_tokens is not None else [])]
        tel = engine._tel
        start_pos = self.pos
        fused = first_prev is not None
        consumed = 0
        keep = True
        try:
            if fused:
                # the drafter needs the fused first token's host value
                # before anything can be proposed, so the scalar fetch
                # cannot overlap a chunk here — it IS the step boundary
                prev = self._fetch_fused_first(first_token)
                consumed = 1
                history.append(prev)
                keep = on_token(first_prev, prev)
            else:
                prev = int(first_token)
                history.append(prev)
            while keep is not False:
                fed = consumed - 1 if fused else consumed
                if start_pos + fed >= stop:
                    break
                # the verify window never writes past seq_len: shrink T at
                # the context tail (an exact-length compile, same policy as
                # the prefill buckets near the limit)
                T = min(spec_draft + 1, engine.cfg.seq_len - self.pos)
                if T < 1:
                    break
                draft = drafter.draft(history, limit=T - 1)
                feed = np.full(T, prev, np.int32)  # pad tokens are overwritten KV
                feed[1 : 1 + len(draft)] = draft
                engine._faults.fire("engine.spec_verify")
                sw = Stopwatch()
                with tel.span(
                    "spec_verify", pos=self.pos, window=T, drafted=len(draft)
                ):
                    out_dev, self.cache = sampling.spec_verify_step(
                        engine.cfg, engine.params, jnp.asarray(feed), self.cache,
                        jnp.int32(self.pos), jnp.int32(len(draft)),
                        jnp.float32(temperature), jnp.float32(topp),
                        jnp.int32(topk), seed32,
                    )
                    out = np.asarray(out_dev)  # [T+1]: n_emit, tokens...
                n_emit = max(1, min(int(out[0]), T))
                toks = [int(t) for t in out[1 : 1 + n_emit]]
                self.pos += n_emit
                entry = engine._split_stats(sw.elapsed_ms(), n_tokens=n_emit)
                self.stats.append(entry)
                if tel.enabled:
                    tel.tokens_generated.inc(n_emit)
                    tel.device_sampled_tokens.inc(n_emit)
                    tel.decode_latency.observe(sw.elapsed_ms() / n_emit / 1000.0)
                    tel.kv_occupancy.set(self.pos / engine.cfg.seq_len)
                    tel.spec_draft_tokens.inc(len(draft))
                    tel.spec_accepted_tokens.inc(n_emit - 1)
                    if draft:
                        tel.spec_acceptance.observe((n_emit - 1) / len(draft))
                    tel.spec_step_advance.observe(n_emit)
                for t in toks:
                    consumed += 1
                    history.append(t)
                    keep = on_token(prev, t)
                    prev = t
                    fed = consumed - 1 if fused else consumed
                    if keep is False or start_pos + fed >= stop:
                        break
        finally:
            # positions beyond the last consumed token (a rejected-draft
            # overshoot, or tokens emitted past an early stop) are stale:
            # rewind exactly like the chunked path's rollback contract
            fed = max(consumed - 1, 0) if fused else consumed
            self.rollback(min(start_pos + fed, self.pos))
        # end-of-stream quiescent point: same cadence hook as the chunked
        # path (a no-op on today's single-chip-only spec route, but the
        # contract belongs to every stream_decode exit)
        engine._maybe_refresh_transfer()
        return consumed

    # ------------------------------------------------------------------
    # Stats (reference: Inference::getStats, src/tasks.cpp:186-189)
    # ------------------------------------------------------------------

    def avg_stats(self) -> TokenStats:
        """Per-token averages, weighting batched-prefill entries by their
        token count (the reference accounts per position, dllama.cpp:88-93)."""
        if not self.stats:
            return TokenStats(0.0, 0.0, 0.0)
        n = sum(s.n_tokens for s in self.stats)
        return TokenStats(
            sum(s.generation_ms for s in self.stats) / n,
            sum(s.inference_ms for s in self.stats) / n,
            sum(s.transfer_ms for s in self.stats) / n,
            n_tokens=n,
        )

    def total_tokens(self) -> int:
        return sum(s.n_tokens for s in self.stats)


class InferenceEngine:
    """Single-program driver for one model instance.

    ``tp`` > 1 shards the same forward over a tensor-parallel mesh
    (see distributed_llama_tpu.parallel); tp=1 is the single-chip path.
    The engine exposes the classic single-stream surface (prefill/decode/
    stats) by delegating to a default :class:`EngineStream`;
    :meth:`new_stream` adds independent concurrent streams over the same
    weights.
    """

    def __init__(
        self,
        model_path: str,
        dtype=jnp.bfloat16,
        max_seq_len: int | None = None,
        cache_dtype=None,
        tp: int = 1,
        sp: int = 1,
        ep: int = 1,
        **cfg_overrides,
    ):
        from distributed_llama_tpu.formats.model_file import ModelFileReader
        from distributed_llama_tpu.models.config import config_from_spec

        quantized = dtype == "q40"
        self.tp = tp
        self.sp = sp
        self.ep = ep
        # instrument bundle bound ONCE per engine: real registry-backed
        # instruments when telemetry is enabled at construction, shared
        # no-op singletons otherwise (the zero-overhead-when-disabled
        # contract — hot paths hold attributes, never do registry lookups)
        self._tel = telemetry.EngineInstruments()
        if ep > 1 and sp > 1:
            raise ValueError("--ep and --sp do not compose (pick one FFN/context strategy)")
        # fault-injection plan bound ONCE per engine (the same bind-once
        # contract as telemetry: the no-op NULL_PLAN when no chaos plan is
        # installed — hot paths pay one attribute call per dispatch)
        self._faults = faults.active_plan()
        # the parallel backend is constructed BEFORE the weights load so the
        # q40 sharded load can place each shard's pack straight onto its
        # device via make_array_from_callback — each process reads only its
        # own shards' bytes (multi-host: O(model/tp) host RAM per process,
        # replacing the reference's root-scatter, src/transformer.cpp:432-451)
        reader = ModelFileReader(model_path)
        self.spec = reader.spec.clamp_seq_len(max_seq_len)
        self.cfg = config_from_spec(self.spec, **cfg_overrides)
        if cache_dtype is None:
            # "q40" is a weights-only format; the KV cache stays bf16
            cache_dtype = jnp.bfloat16 if quantized else dtype
        self.cache_dtype = cache_dtype
        if ep > 1:
            from distributed_llama_tpu.parallel import expert_parallel as epmod

            # expert parallelism (optionally composed with tensor
            # parallelism on a 2-D (tp, ep) mesh): expert banks sharded by
            # whole experts, all_to_all dispatch for prefill, dense-local
            # decode (see ExpertParallelForward); same duck-typed interface
            self._tp_engine = epmod.ExpertParallelForward(
                self.cfg, ep, tp=tp, quantized=quantized
            )
        elif sp > 1:
            from distributed_llama_tpu.parallel import context_parallel as spmod

            # sequence parallelism (optionally composed with tensor
            # parallelism on a 2-D (tp, sp) mesh): sequence-sharded KV cache,
            # ring-attention prefill (see SequenceParallelForward); reuses
            # the tp-engine slot — same duck-typed interface
            self._tp_engine = spmod.SequenceParallelForward(
                self.cfg, sp, tp=tp, quantized=quantized
            )
        elif tp > 1:
            from distributed_llama_tpu.parallel import tensor_parallel as tpmod

            self._tp_engine = tpmod.TensorParallelForward(
                self.cfg, tp, quantized=quantized, layered=True
            )
        else:
            self._tp_engine = None
        # every dtype loads per-shard under tp: each process reads only its
        # own shards' bytes and places them straight onto its devices.
        # ep>1 loads host-side instead: the expert banks must be re-stacked
        # on a leading expert axis before placement (stack_expert_leaves),
        # which direct-to-device tp placement would fight
        mesh = self._tp_engine.mesh if (tp > 1 and ep == 1) else None
        host_params = weights_lib.load_params(
            reader, self.cfg, dtype=dtype, tp=tp, mesh=mesh
        )
        reader.close()
        if quantized:
            # the block-interleaved activation basis is retired (the int8
            # MXU kernel's scale-product epilogue made it moot — ops/q40.py
            # legacy section); basis-era snapshots still load via the
            # unconditional migration inverse (no-op on standard trees)
            host_params = weights_lib.remove_basis_interleave(host_params, self.cfg)
        if self._tp_engine is not None:
            self.params = self._tp_engine.shard_params(host_params)
            self._forward = self._tp_engine.forward
        else:
            self.params = jax.device_put(host_params)
            self._forward = functools.partial(self._forward_single, self.cfg)
        self._init_runtime()

    @classmethod
    def from_shared(
        cls, cfg, backend, params, cache_dtype=jnp.bfloat16, spec=None
    ) -> "InferenceEngine":
        """An engine over a PRE-BUILT backend and an ALREADY-PLACED params
        tree — the one-process pod's slice engines (parallel/pod.py): N
        replicas' engines share one backend (compiled programs built once
        for the pod) and one params tree (weights resident once per model
        group), while everything per-slice — KV caches, slab, scheduler,
        streams, stats — stays per engine, preserving the replica failure
        domain. A slice REBUILD after failover goes through here too:
        scheduler + lanes are rebuilt, weights are never reloaded (and the
        PR 10 rebuild checksum gate passes against the same bytes)."""
        self = cls.__new__(cls)
        self.tp = getattr(backend, "tp", 1)
        self.sp = 1
        self.ep = 1
        self._tel = telemetry.EngineInstruments()
        self._faults = faults.active_plan()
        self.spec = spec
        self.cfg = cfg
        self.cache_dtype = cache_dtype
        self._tp_engine = backend
        self.params = params
        self._forward = backend.forward
        self._init_runtime()
        return self

    def _init_runtime(self) -> None:
        """Per-engine mutable state, shared by the loading constructor and
        :meth:`from_shared`."""
        # whether the forward accepts the real-token count of a bucket-padded
        # prompt (the capacity-bucketed MoE prefill's pad mask): the
        # single-chip path always does; backends opt in via the attribute
        self._forward_takes_n_real = self._tp_engine is None or getattr(
            self._tp_engine, "accepts_n_real", False
        )
        self._streams: list[EngineStream] = []
        # load-time weight checksum (ISSUE 10): computed lazily on first
        # read and cached — the replica pool records replica 0's value as
        # the pool reference at construction and verifies every rebuilt
        # replica against it before re-entering placement. Lazy, so
        # engines that never join a supervised pool pay nothing; ONE HBM
        # pass over the weights when they do (engine/integrity.py)
        self._weights_checksum: str | None = None
        # which weight VERSION these params are (ISSUE 18): tagged by the
        # serving layer's versioned factory; None outside a rollout-aware
        # pool. The blue-green orchestrator verifies a rebuilt replica's
        # engine against this version's checksum reference
        self.weights_version: str | None = None
        # the classic single-stream surface's stream is created LAZILY on
        # first use: batched serving (engine.batch) never touches it, and
        # eagerly allocating its KV cache would hold one full cache of HBM
        # dead next to the scheduler's slab
        self._default: EngineStream | None = None
        # once-per-engine "--spec-draft ignored" diagnostic latch (the spec
        # route is single-chip dense only; see EngineStream.stream_decode)
        self._spec_fallback_warned = False
        # measured lazily under TP/SP; _init_runtime runs from the
        # constructors BEFORE the engine is published to other threads
        # (the _depth_lock guarding these is itself created 6 lines down)
        self._transfer_ms: float | None = None  # dllama: noqa[LCK-004]
        self._transfer_measured_at = 0  # dllama: noqa[LCK-004]
        self._pipeline_depth = 0  # >0 while a speculative chunk is in flight
        # concurrent streams (API --parallel) bump the depth from several
        # threads; the counter must not lose updates or go negative (a stuck
        # >0 would freeze the transfer estimate, a negative one would let
        # probes run mid-flight)
        self._depth_lock = lockcheck.make_lock("InferenceEngine._depth_lock")
        # mesh-topology gauges (ISSUE 15): axis -> device count of the
        # backend's named mesh, so an operator can read the serving shape
        # off /metrics (the pod group additionally reports weight bytes)
        mesh = getattr(self._tp_engine, "mesh", None)
        if mesh is not None:
            tel = telemetry.MeshInstruments()
            if tel.enabled:
                for axis_name, size in dict(mesh.shape).items():
                    tel.mesh_devices.labels(axis=axis_name).set(size)

    def weights_checksum(self) -> str:
        """The loaded weights' integrity checksum (cached after the first
        computation — call it right after construction to RECORD the
        healthy value before any runtime corruption could land; a later
        :func:`integrity.params_checksum` over ``self.params`` is the
        VERIFY side). The cached value deliberately does NOT track
        ``self.params`` reassignment: it is the load-time record."""
        if self._weights_checksum is None:
            from distributed_llama_tpu.engine import integrity

            self._weights_checksum = integrity.params_checksum(self.params)
        return self._weights_checksum

    def _new_cache(self):
        if self._tp_engine is not None:
            return self._tp_engine.init_cache(self.cache_dtype)
        # per-layer cache list matching the per-layer params list, so
        # cache updates alias in place (see llama.init_cache)
        return llama.init_cache(self.cfg, dtype=self.cache_dtype, layered=True)

    def new_stream(self) -> EngineStream:
        """An independent generation stream (own KV cache + position) over
        this engine's weights. Each stream costs one KV cache of HBM."""
        return EngineStream(self, self._new_cache())

    @property
    def default_stream(self) -> EngineStream:
        if self._default is None:
            self._default = EngineStream(self, self._new_cache())
        return self._default

    # ------------------------------------------------------------------
    # Single-stream delegation (the classic engine surface)
    # ------------------------------------------------------------------

    @property
    def pos(self) -> int:
        return self.default_stream.pos

    @pos.setter
    def pos(self, value: int) -> None:
        self.default_stream.pos = value

    @property
    def cache(self):
        return self.default_stream.cache

    @cache.setter
    def cache(self, value) -> None:
        self.default_stream.cache = value

    @property
    def stats(self) -> list[TokenStats]:
        return self.default_stream.stats

    def reset(self) -> None:
        self.default_stream.reset()

    def rollback(self, pos: int) -> None:
        self.default_stream.rollback(pos)

    def forward(self, tokens) -> np.ndarray:
        return self.default_stream.forward(tokens)

    def prefill(self, tokens) -> np.ndarray:
        return self.default_stream.prefill(tokens)

    def prefill_device(self, tokens, temperature, topp, seed: int, topk: int = 0):
        return self.default_stream.prefill_device(
            tokens, temperature, topp, seed, topk
        )

    def decode_step(self, token: int) -> np.ndarray:
        return self.default_stream.decode_step(token)

    def fetch_first_token(self, first_token) -> int:
        return self.default_stream.fetch_first_token(first_token)

    def generate_on_device(self, *args, **kwargs) -> np.ndarray:
        return self.default_stream.generate_on_device(*args, **kwargs)

    def decode_chunk(self, *args, **kwargs):
        return self.default_stream.decode_chunk(*args, **kwargs)

    def generate_chunks(self, *args, **kwargs):
        return self.default_stream.generate_chunks(*args, **kwargs)

    def stream_decode(self, *args, **kwargs) -> int:
        return self.default_stream.stream_decode(*args, **kwargs)

    def avg_stats(self) -> TokenStats:
        return self.default_stream.avg_stats()

    def total_tokens(self) -> int:
        return self.default_stream.total_tokens()

    # ------------------------------------------------------------------
    # Shared internals
    # ------------------------------------------------------------------

    # decoded tokens between transfer re-measurements: the estimate follows
    # actual interconnect load over a session for the cost of one tiny
    # probe dispatch every ~512 tokens, instead of staying a
    # construction-time constant
    TRANSFER_REFRESH_TOKENS = 512

    def _transfer_ms_per_token(self) -> float:
        """Per-dispatch collective cost: 0 on a single chip; under TP/SP
        measured on the real mesh and re-measured periodically in situ.

        Refreshes happen only at QUIESCENT points (no dispatch in flight on
        ANY stream): inside the pipelined chunk loop a probe would queue
        behind the in-flight chunk and time its compute, poisoning the very
        split it feeds. The prefill/forward/decode_chunk paths all reach here
        right after their own fetch drained the stream, so every API request
        and every stepwise loop refreshes on cadence; generate_chunks reuses
        the last measurement."""
        if self._tp_engine is None:
            return 0.0
        # the depth check and the probe run under the SAME lock that
        # dispatchers raise the depth under, so a concurrent stream cannot
        # enqueue a chunk between the check and the measurement (the probe
        # would queue behind it and time its compute); dispatchers briefly
        # block on the lock during a refresh (~once per 512 tokens)
        with self._depth_lock:
            if self._pipeline_depth > 0:
                # never measure mid-flight (even the FIRST time — a caller
                # whose first op is generate_chunks would otherwise cache a
                # poisoned estimate); report 0 until a quiescent call measures
                return self._transfer_ms or 0.0
            # cadence counts tokens across ALL streams: API traffic on
            # non-default slots must still drive the periodic re-measurement
            n = sum(s.n_tokens for st in self._streams for s in st.stats)
            if (
                self._transfer_ms is None
                or n - self._transfer_measured_at >= self.TRANSFER_REFRESH_TOKENS
            ):
                try:
                    self._transfer_ms = self._tp_engine.measure_transfer_ms()
                except Exception:
                    # a failed probe (flaky interconnect, injected tp.transfer
                    # fault) must not kill the request that happened to
                    # trigger it: keep the previous estimate (0 before any
                    # measurement succeeded) and retry next cadence
                    if self._transfer_ms is None:
                        self._transfer_ms = 0.0
                self._transfer_measured_at = n
            return self._transfer_ms

    def _maybe_refresh_transfer(self) -> None:
        """Opportunistic cadence refresh at the end of a decode stream —
        the device-decode serving flow otherwise computes every stats entry
        mid-flight and would never measure. Only when the cadence is DUE
        (the extra drain fetch costs a tunnel round trip): drain any
        leftover speculative chunk first so the probe cannot queue behind
        it and time its compute."""
        if self._tp_engine is None:
            return
        with self._depth_lock:
            n = sum(s.n_tokens for st in self._streams for s in st.stats)
            due = (
                self._transfer_ms is None
                or n - self._transfer_measured_at >= self.TRANSFER_REFRESH_TOKENS
            )
            if not due or self._pipeline_depth > 0:
                return
        np.asarray(jnp.zeros(2) + 1)  # fence: drains the device queue
        self._transfer_ms_per_token()  # re-checks depth under the lock

    def _last_dispatches(self) -> int:
        """How many device programs the most recent forward issued (the sp
        backend's chunked mid-context prefill issues several; every other
        path is exactly one)."""
        return getattr(self._tp_engine, "last_forward_dispatches", 1) or 1

    def _split_stats(
        self, per_entry_ms: float, n_tokens: int = 1, n_dispatches: int = 1
    ) -> TokenStats:
        """I/T split of one timed dispatch: the measured collective cost is an
        upper bound (XLA overlaps collectives with compute in the real
        program), so clamp it to the observed time — inference_ms must not go
        negative. An entry that covers several dispatches (the sp backend's
        chunked mid-context prefill) pays the collective sequence once per
        dispatch."""
        transfer = min(self._transfer_ms_per_token() * n_dispatches, per_entry_ms)
        return TokenStats(
            per_entry_ms, per_entry_ms - transfer, transfer, n_tokens=n_tokens
        )

    def _sample_row(self, logits, row, seed32, pos, temperature, topp, topk):
        """Sample from one row of device logits entirely on device (the
        prefill→decode fusion: no logits fetch), coin keyed on the row's
        absolute position. Under TP/SP the logits returned by the backend's
        forward are already full-vocab and replicated, so a replicated
        sample is correct on every backend (same counter → same token)."""
        return _sample_row_jit(logits, row, seed32, pos, temperature, topp, topk)

    @staticmethod
    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
    def _forward_single(cfg: LlamaConfig, params, tokens, cache, pos, n_real=None):
        return llama.forward_tokens(cfg, params, tokens, cache, pos, n_real=n_real)


@jax.jit
def _sample_row_jit(logits, row, seed32, pos, temperature, topp, topk):
    from distributed_llama_tpu.models import sampling

    return sampling.sample_token(logits[row], seed32, pos, temperature, topp, topk)
