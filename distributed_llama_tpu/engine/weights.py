"""Load `.m` weights into the stacked pytree consumed by the model functions.

The reference root node mmaps the file and streams per-matrix slices to
workers over TCP (reference: src/transformer.cpp:432-616). On TPU the same
file is read once per host; matrices are transposed to (d_in, d_out) so the
hot matmul is ``x @ W`` with no transposes in the compiled program, layers are
stacked on a leading axis for ``lax.scan``, and the result is `device_put`
(optionally with a NamedSharding so XLA places each shard directly).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.formats.model_file import ArchType, ModelFileReader, ModelSpec
from distributed_llama_tpu.models.config import LlamaConfig, config_from_spec
from distributed_llama_tpu.models.rope import build_rope_table

Params = dict[str, Any]


def _t(x: np.ndarray, dtype) -> np.ndarray:
    """File stores [d_out, d_in] (y = W @ x); we store [d_in, d_out]."""
    return np.ascontiguousarray(x.T).astype(dtype)


def load_params(
    reader: ModelFileReader,
    cfg: LlamaConfig | None = None,
    dtype=jnp.bfloat16,
    rows: tuple[int, int] | None = None,
) -> Params:
    """Build the host-side params pytree (numpy, not yet on device).

    dtype applies to the matmul weights; embeddings and norm scales stay f32
    (they are F32 in the file too — reference: src/transformer.cpp:296-310).
    """
    spec = reader.spec
    cfg = cfg or config_from_spec(spec)
    np_dtype = np.dtype(dtype)  # ml_dtypes registers bfloat16 with numpy

    def cast(x: np.ndarray) -> np.ndarray:
        return x.astype(np_dtype)

    layers: dict[str, list[np.ndarray]] = {}

    def add(key: str, value) -> None:
        layers.setdefault(key, []).append(value)

    for l in range(cfg.n_layers):
        p = f"layers.{l}."
        add("q", cast(_t(reader.tensor(p + "q"), np.float32)))
        add("k", cast(_t(reader.tensor(p + "k"), np.float32)))
        add("v", cast(_t(reader.tensor(p + "v"), np.float32)))
        add("wo", cast(_t(reader.tensor(p + "wo"), np.float32)))
        add("rms_att", reader.tensor(p + "rms_att").astype(np.float32))
        add("rms_ffn", reader.tensor(p + "rms_ffn").astype(np.float32))
        if cfg.is_moe:
            add("router", cast(_t(reader.tensor(p + "moe_router"), np.float32)))
            ups, gates, downs = [], [], []
            for e in range(cfg.n_experts):
                ep = f"{p}experts.{e}."
                ups.append(_t(reader.tensor(ep + "up"), np.float32))
                gates.append(_t(reader.tensor(ep + "gate"), np.float32))
                downs.append(_t(reader.tensor(ep + "down"), np.float32))
            add("moe_up", cast(np.stack(ups)))
            add("moe_gate", cast(np.stack(gates)))
            add("moe_down", cast(np.stack(downs)))
        else:
            add("gate", cast(_t(reader.tensor(p + "gate"), np.float32)))
            add("down", cast(_t(reader.tensor(p + "down"), np.float32)))
            add("up", cast(_t(reader.tensor(p + "up"), np.float32)))
        if cfg.arch == ArchType.GROK1:
            add("rms_moe", reader.tensor(p + "rms_moe").astype(np.float32))
            add("rms_ffn2", reader.tensor(p + "rms_ffn2").astype(np.float32))

    # stays numpy (ml_dtypes handles bf16): placement happens once, in the
    # engine, via device_put — plain or with a NamedSharding under TP — so no
    # full copy ever lands on a single device's HBM first
    stacked = {k: np.stack(vs) for k, vs in layers.items()}
    return {
        "embedding": reader.tensor("embedding").astype(np.float32),
        "layers": stacked,
        "rms_final": reader.tensor("rms_final").astype(np.float32),
        "wcls": cast(_t(reader.tensor("wcls"), np.float32)),
        "rope_table": build_rope_table(cfg),
    }


def load_model(
    path: str, dtype=jnp.bfloat16, max_seq_len: int | None = None, **cfg_overrides
) -> tuple[ModelSpec, LlamaConfig, Params]:
    reader = ModelFileReader(path)
    spec = reader.spec.clamp_seq_len(max_seq_len)
    cfg = config_from_spec(spec, **cfg_overrides)
    params = load_params(reader, cfg, dtype=dtype)
    reader.close()
    return spec, cfg, params
