"""Load `.m` weights into the stacked pytree consumed by the model functions.

The reference root node mmaps the file and streams per-matrix slices to
workers over TCP (reference: src/transformer.cpp:432-616). On TPU the same
file is read once per host; matrices are transposed to (d_in, d_out) so the
hot matmul is ``x @ W`` with no transposes in the compiled program, layers are
stacked on a leading axis for ``lax.scan``, and the result is `device_put`
(optionally with a NamedSharding so XLA places each shard directly).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.formats.model_file import ArchType, ModelFileReader, ModelSpec
from distributed_llama_tpu.models.config import LlamaConfig, config_from_spec
from distributed_llama_tpu.models.rope import build_rope_table

Params = dict[str, Any]


def _t(x: np.ndarray, dtype) -> np.ndarray:
    """File stores [d_out, d_in] (y = W @ x); we store [d_in, d_out]."""
    return np.ascontiguousarray(x.T).astype(dtype)


QUANTIZED_DTYPE = "q40"  # sentinel: keep matmul weights 4-bit on device


def load_params(
    reader: ModelFileReader,
    cfg: LlamaConfig | None = None,
    dtype=jnp.bfloat16,
    tp: int = 1,
    mesh=None,
) -> Params:
    """Build the host-side params pytree (numpy, not yet on device).

    dtype applies to the matmul weights; embeddings and norm scales stay f32
    (they are F32 in the file too — reference: src/transformer.cpp:296-310).
    ``dtype="q40"`` keeps the attention/FFN/wcls matrices packed 4-bit
    (QuantizedMatrix leaves, fed to the fused Pallas matmul), including the
    MoE expert banks (per-expert fused gate|up + down leaves).

    ``tp > 1`` builds every matmul weight as per-shard reads in sharded
    layout — q40 as per-shard packs (raw_rows / raw_row_blocks), bf16/f32
    via row/column-range reads (tensor_rows / tensor_cols) — the read-time
    equivalent of the reference's RowMatmulSlice/ColMatmulSlice scatter
    (src/commands.cpp:11-108 + src/transformer.cpp:432-451). With ``mesh``
    set, shards are placed via ``jax.make_array_from_callback``: each
    PROCESS builds (and reads) only the shards of its addressable devices —
    per-host RAM and file traffic are O(model/tp), the property that makes
    a 238 GB 405B file loadable across a pod. Without a mesh they are
    concatenated on host for a later NamedSharding device_put (single-host
    fallback).
    """
    spec = reader.spec
    cfg = cfg or config_from_spec(spec)
    quantized = dtype == QUANTIZED_DTYPE
    shard_vocab = tp > 1 and cfg.vocab_size % tp == 0
    rule_table = None
    if tp > 1:
        from distributed_llama_tpu.parallel.tensor_parallel import validate_tp

        validate_tp(cfg, tp, quantized=quantized)
        from distributed_llama_tpu.parallel import sharding as sharding_rules

        # the ONE sharding authority (ISSUE 15): the rule table decides
        # every leaf's layout; the load-time shard DIRECTION (row-range
        # "out" reads vs column-range "in" reads) is DERIVED from the
        # resolved spec below, never hand-rolled here
        rule_table = sharding_rules.param_rules(
            cfg, "q40" if quantized else "layered", shard_vocab
        )
    np_dtype = np.dtype(jnp.bfloat16 if quantized else dtype)

    def leaf_spec(path: str):
        return rule_table.spec(path, {"model": "tp"})

    def shard_direction(spec_) -> str:
        # every matmul layout here stores the output dim LAST (q40 packs
        # [n/2, d_out], plain [d_in, d_out], expert stacks [E, d_in,
        # d_out]), so the model axis landing on the last dim means
        # output-sharded (RowMatmulSlice); anywhere else, input-sharded
        # (ColMatmulSlice). An unsharded matmul leaf would be a rule-table
        # bug — surface it as the typed error class
        if "tp" not in spec_:
            from distributed_llama_tpu.parallel import sharding as sharding_rules

            raise sharding_rules.ShardingRuleError(
                f"matmul leaf resolved to replicated spec {spec_} under tp={tp}"
            )
        return "out" if spec_[-1] == "tp" else "in"

    def cast(x: np.ndarray) -> np.ndarray:
        return x.astype(np_dtype)

    def weight(name: str):
        """A matmul weight in x@W orientation: QuantizedMatrix or numpy."""
        if quantized:
            from distributed_llama_tpu.ops.q40 import pack_q40_raw, quantize_q40_tpu
            from distributed_llama_tpu.quants import FloatType

            e = reader.entries[name]
            if e.float_type == FloatType.Q40:
                return pack_q40_raw(reader.raw(name), e.shape)  # exact repack
            return quantize_q40_tpu(_t(reader.tensor(name), np.float32))
        return cast(_t(reader.tensor(name), np.float32))

    def weight_fused(names: list[str]):
        """Several matrices sharing an input dim, packed as ONE matmul with
        their output dims concatenated (q|k|v, gate|up). Merging the small
        per-token matvecs into one big one keeps the Q40 kernel in its
        bandwidth-efficient regime. The file stores [d_out, d_in] row-major
        blocks, so the Q40-exact concat is a plain byte concat."""
        from distributed_llama_tpu.ops.q40 import pack_q40_raw, quantize_q40_tpu
        from distributed_llama_tpu.quants import FloatType

        entries = [reader.entries[n] for n in names]
        if all(e.float_type == FloatType.Q40 for e in entries):
            raw = np.concatenate([reader.raw(n) for n in names])
            d_out = sum(e.shape[0] for e in entries)
            return pack_q40_raw(raw, (d_out, entries[0].shape[1]))
        mats = [_t(reader.tensor(n), np.float32) for n in names]
        return quantize_q40_tpu(np.concatenate(mats, axis=1))

    def shard_out(names: list[str], s: int):
        """Output-dim shard s of (fused) matrices: each source contributes
        rows [s*d/tp, (s+1)*d/tp) (RowMatmulSlice, src/commands.cpp:11-43)."""
        from distributed_llama_tpu.ops.q40 import pack_q40_raw, quantize_q40_tpu
        from distributed_llama_tpu.quants import FloatType

        entries = [reader.entries[n] for n in names]
        if all(e.float_type == FloatType.Q40 for e in entries):
            raws, d_out = [], 0
            for nm, e in zip(names, entries):
                lo, hi = e.shape[0] * s // tp, e.shape[0] * (s + 1) // tp
                raws.append(reader.raw_rows(nm, lo, hi))
                d_out += hi - lo
            return pack_q40_raw(np.concatenate(raws), (d_out, entries[0].shape[1]))
        mats = []
        for nm, e in zip(names, entries):
            lo, hi = e.shape[0] * s // tp, e.shape[0] * (s + 1) // tp
            mats.append(np.ascontiguousarray(reader.tensor_rows(nm, lo, hi).T))
        return quantize_q40_tpu(np.concatenate(mats, axis=1).astype(np.float32))

    def shard_in(name: str, s: int):
        """Input-dim shard s: quant-block-aligned column range of every row
        (ColMatmulSlice, src/commands.cpp:45-73)."""
        from distributed_llama_tpu.ops.q40 import pack_q40_raw, quantize_q40_tpu
        from distributed_llama_tpu.quants import FloatType

        e = reader.entries[name]
        d_out, d_in = e.shape
        lo, hi = d_in * s // tp, d_in * (s + 1) // tp
        if e.float_type == FloatType.Q40:
            sl = reader.raw_row_blocks(name, lo, hi)
            return pack_q40_raw(sl.reshape(-1), (d_out, hi - lo))
        w = _t(reader.tensor(name), np.float32)[lo:hi]
        return quantize_q40_tpu(np.ascontiguousarray(w))

    def sharded(path: str, names):
        """Sharded q40 leaf for destination ``path``: the rule table's
        resolved spec picks the slicing direction (out = fused row-range
        reads, in = quant-block column ranges) and the placement layout."""
        from distributed_llama_tpu.ops.q40 import (
            QuantizedMatrix,
            _d_padded,
            _n_padded,
            concat_shard_packs,
        )

        spec = leaf_spec(path)
        axis = shard_direction(spec)
        if axis == "out":
            names_l = names if isinstance(names, list) else [names]
            builder, args = shard_out, (names_l,)
        else:
            builder, args = shard_in, (names,)
        if mesh is None:
            return concat_shard_packs([builder(*args, s) for s in range(tp)], axis)

        # lazy per-shard placement: analytic shard shapes + a callback that
        # builds (reads) one shard's pack only when a local device asks
        import jax.sharding as shd

        if axis == "out":
            entries_ = [reader.entries[nm] for nm in args[0]]
            d_shard = sum(e.shape[0] for e in entries_) // tp
            n_shard = entries_[0].shape[1]
        else:
            e = reader.entries[args[0]]
            d_shard = e.shape[0]
            n_shard = e.shape[1] // tp
        np_, dp = _n_padded(n_shard), _d_padded(d_shard)
        qs_shard = (np_ // 2, dp)
        sc_shard = (np_ // 32, dp)
        ax = 1 if axis == "out" else 0
        qs_gshape = tuple(
            s * tp if i == ax else s for i, s in enumerate(qs_shard)
        )
        sc_gshape = tuple(
            s * tp if i == ax else s for i, s in enumerate(sc_shard)
        )
        built: dict[int, tuple[np.ndarray, np.ndarray]] = {}

        def build(s: int):
            if s not in built:
                qm = builder(*args, s)
                qs_np, sc_np = np.asarray(qm.qs), np.asarray(qm.scales)
                # a real error, not an assert: under python -O a
                # builder/analytic-shape desync would otherwise surface as an
                # opaque make_array_from_callback failure far from the cause
                if qs_np.shape != qs_shard or sc_np.shape != sc_shard:
                    raise ValueError(
                        f"analytic shard shape mismatch: built {qs_np.shape}/"
                        f"{sc_np.shape}, expected {qs_shard}/{sc_shard}"
                    )
                built[s] = (qs_np, sc_np)
            return built[s]

        def qs_cb(idx):
            return build((idx[ax].start or 0) // qs_shard[ax])[0]

        def sc_cb(idx):
            return build((idx[ax].start or 0) // sc_shard[ax])[1]

        ns = shd.NamedSharding(mesh, spec)
        qs_g = jax.make_array_from_callback(qs_gshape, ns, qs_cb)
        sc_g = jax.make_array_from_callback(sc_gshape, ns, sc_cb)
        built.clear()  # free host copies; the data lives on device now
        return QuantizedMatrix(qs_g, sc_g, n_logical=n_shard, d_logical=d_shard)

    def _read_shard(name: str, axis: str, s: int) -> np.ndarray:
        """Shard ``s`` of one file matrix in logical (x@W) orientation: an
        independent row-range (out) or column-range (in) read."""
        e = reader.entries[name]
        d_out, d_in = e.shape  # file orientation; logical is [d_in, d_out]
        if axis == "out":
            lo, hi = d_out * s // tp, d_out * (s + 1) // tp
            return reader.tensor_rows(name, lo, hi).T
        lo, hi = d_in * s // tp, d_in * (s + 1) // tp
        return reader.tensor_cols(name, lo, hi).T

    def _place_shards(gshape, ax: int, spec, build):
        """Shared placement scaffold of the plain sharded loads: with a mesh,
        each PROCESS builds (reads) only its addressable devices' shards via
        make_array_from_callback; without one, shards concatenate on host
        for a later NamedSharding device_put."""
        import jax.sharding as shd

        built: dict[int, np.ndarray] = {}

        def cached(s: int) -> np.ndarray:
            if s not in built:
                built[s] = build(s)
            return built[s]

        if mesh is None:
            out = np.concatenate([cached(s) for s in range(tp)], axis=ax)
            built.clear()
            return out
        shard_len = gshape[ax] // tp

        def cb(idx):
            return cached((idx[ax].start or 0) // shard_len)

        arr = jax.make_array_from_callback(
            gshape, shd.NamedSharding(mesh, spec), cb
        )
        built.clear()
        return arr

    def sharded_plain(path: str, name: str):
        """Per-shard lazy read of a bf16/f32 matmul weight: the non-quantized
        analogue of ``sharded()`` (reader.tensor_rows / tensor_cols range
        reads) — O(model/tp) file traffic per host for every dtype, not just
        q40 (replacing the reference's root-reads-everything scatter for
        bf16 as well, src/transformer.cpp:432-451). Direction and spec come
        from the rule table, keyed by the destination leaf path."""
        spec = leaf_spec(path)
        axis = shard_direction(spec)
        d_out, d_in = reader.entries[name].shape
        ax = 1 if axis == "out" else 0
        return _place_shards(
            (d_in, d_out), ax, spec,
            lambda s: np.ascontiguousarray(_read_shard(name, axis, s)).astype(np_dtype),
        )

    def sharded_plain_expert_stack(path: str, expert_names: list[str]):
        """Sharded read of a stacked MoE expert bank: [E, d_in, d_out] with
        the matmul dim sharded (moe_up/gate: out; moe_down: in). Each shard
        stacks its per-expert row/column-range reads."""
        spec = leaf_spec(path)
        axis = shard_direction(spec)
        d_out, d_in = reader.entries[expert_names[0]].shape
        ax = 2 if axis == "out" else 1
        return _place_shards(
            (len(expert_names), d_in, d_out), ax, spec,
            lambda s: np.ascontiguousarray(
                np.stack([_read_shard(nm, axis, s) for nm in expert_names])
            ).astype(np_dtype),
        )

    layers: dict[str, list] = {}

    def add(key: str, value) -> None:
        layers.setdefault(key, []).append(value)

    for l in range(cfg.n_layers):
        p = f"layers.{l}."
        lpath = f"layers/{l}"
        if quantized and tp > 1:
            add("qkv", sharded(f"{lpath}/qkv", [p + "q", p + "k", p + "v"]))
            add("wo", sharded(f"{lpath}/wo", p + "wo"))
        elif quantized:
            add("qkv", weight_fused([p + "q", p + "k", p + "v"]))
            add("wo", weight(p + "wo"))
        elif tp > 1:
            add("q", sharded_plain(f"{lpath}/q", p + "q"))
            add("k", sharded_plain(f"{lpath}/k", p + "k"))
            add("v", sharded_plain(f"{lpath}/v", p + "v"))
            add("wo", sharded_plain(f"{lpath}/wo", p + "wo"))
        else:
            add("q", weight(p + "q"))
            add("k", weight(p + "k"))
            add("v", weight(p + "v"))
            add("wo", weight(p + "wo"))
        add("rms_att", reader.tensor(p + "rms_att").astype(np.float32))
        add("rms_ffn", reader.tensor(p + "rms_ffn").astype(np.float32))
        if cfg.is_moe and quantized:
            # per-expert fused gate|up + down QuantizedMatrix leaves: the
            # expert banks stay 4-bit in HBM (the reference keeps experts Q40
            # too, src/transformer.cpp:335-353) and the top-k decode path
            # switches between per-expert kernels (models/moe.py)
            add("router", cast(_t(reader.tensor(p + "moe_router"), np.float32)))
            experts = []
            for e in range(cfg.n_experts):
                ep = f"{p}experts.{e}."
                if tp > 1:
                    experts.append({
                        "gate_up": sharded(
                            f"{lpath}/experts/{e}/gate_up", [ep + "gate", ep + "up"]
                        ),
                        "down": sharded(f"{lpath}/experts/{e}/down", ep + "down"),
                    })
                else:
                    experts.append({
                        "gate_up": weight_fused([ep + "gate", ep + "up"]),
                        "down": weight(ep + "down"),
                    })
            add("experts", experts)
        elif cfg.is_moe and tp > 1:
            add("router", cast(_t(reader.tensor(p + "moe_router"), np.float32)))
            enames = [f"{p}experts.{e}." for e in range(cfg.n_experts)]
            add("moe_up", sharded_plain_expert_stack(
                f"{lpath}/moe_up", [n + "up" for n in enames]))
            add("moe_gate", sharded_plain_expert_stack(
                f"{lpath}/moe_gate", [n + "gate" for n in enames]))
            add("moe_down", sharded_plain_expert_stack(
                f"{lpath}/moe_down", [n + "down" for n in enames]))
        elif cfg.is_moe:
            add("router", cast(_t(reader.tensor(p + "moe_router"), np.float32)))
            ups, gates, downs = [], [], []
            for e in range(cfg.n_experts):
                ep = f"{p}experts.{e}."
                ups.append(_t(reader.tensor(ep + "up"), np.float32))
                gates.append(_t(reader.tensor(ep + "gate"), np.float32))
                downs.append(_t(reader.tensor(ep + "down"), np.float32))
            add("moe_up", cast(np.stack(ups)))
            add("moe_gate", cast(np.stack(gates)))
            add("moe_down", cast(np.stack(downs)))
        elif quantized and tp > 1:
            add("gate_up", sharded(f"{lpath}/gate_up", [p + "gate", p + "up"]))
            add("down", sharded(f"{lpath}/down", p + "down"))
        elif quantized:
            add("gate_up", weight_fused([p + "gate", p + "up"]))
            add("down", weight(p + "down"))
        elif tp > 1:
            add("gate", sharded_plain(f"{lpath}/gate", p + "gate"))
            add("down", sharded_plain(f"{lpath}/down", p + "down"))
            add("up", sharded_plain(f"{lpath}/up", p + "up"))
        else:
            add("gate", weight(p + "gate"))
            add("down", weight(p + "down"))
            add("up", weight(p + "up"))
        if cfg.arch == ArchType.GROK1:
            add("rms_moe", reader.tensor(p + "rms_moe").astype(np.float32))
            add("rms_ffn2", reader.tensor(p + "rms_ffn2").astype(np.float32))

    # layers stay UNSTACKED for every dtype (a list of per-layer dicts,
    # consumed by an unrolled layer loop). For q40, scan-slicing a stacked
    # array would make XLA hoist layout copies of every sliced Pallas operand
    # (observed OOM on v5e); for bf16, the lax.scan-over-stacked-layers path
    # showed ~19 ms/token of pipeline stalls on v5e (profiled round 3) —
    # per-layer leaves keep weight streams and cache updates alias-friendly.
    layers_out: Any = [
        {k: vs[l] for k, vs in layers.items()} for l in range(cfg.n_layers)
    ]
    if quantized and shard_vocab:
        wcls = sharded("wcls", ["wcls"])  # vocab-sharded logits head
    elif shard_vocab:
        wcls = sharded_plain("wcls", "wcls")
    else:
        wcls = weight("wcls")
    return {
        "embedding": reader.tensor("embedding").astype(np.float32),
        "layers": layers_out,
        "rms_final": reader.tensor("rms_final").astype(np.float32),
        "wcls": wcls,
        "rope_table": build_rope_table(cfg),
    }


def interleave_eligible(cfg: LlamaConfig) -> bool:
    """Whether the RETIRED block-interleaved activation basis (ops.q40
    legacy section) could apply to this config: every matmul input basis
    kernel-eligible and the residual basis D unpadded. Kept because the
    migration inverse (:func:`remove_basis_interleave`) needs the same
    predicate to know which leaves a basis-era snapshot permuted."""
    from distributed_llama_tpu.ops.q40 import _n_padded, interleave_window

    D, F = cfg.dim, cfg.hidden_dim
    if _n_padded(D) != D:
        return False
    return (
        interleave_window(_n_padded(D)) is not None
        and interleave_window(_n_padded(F)) is not None
    )


def apply_basis_interleave(params: Params, cfg: LlamaConfig) -> Params:
    """LEGACY producer: move a q40 params tree (fused qkv/gate_up layout,
    tp=1) into the RETIRED block-interleaved activation basis — an EXACT
    row/column-gather transform. The engine no longer calls this (the int8
    MXU kernel's scale-product epilogue made the basis moot and the matmul
    entry points now reject interleaved packs); it is retained so the
    migration test can synthesize a basis-era params tree and prove
    :func:`remove_basis_interleave` restores it bit-exactly.
    DLT_INTERLEAVE=0 disables."""
    import os

    from distributed_llama_tpu.ops import q40 as q

    if os.environ.get("DLT_INTERLEAVE") == "0" or not interleave_eligible(cfg):
        return params
    from distributed_llama_tpu.ops.q40 import (
        _n_padded,
        interleave_perm,
        interleave_window,
    )

    D, F = cfg.dim, cfg.hidden_dim
    perm_d = jnp.asarray(interleave_perm(_n_padded(D), interleave_window(_n_padded(D))))
    out = dict(params)
    out["embedding"] = q.interleave_vector(params["embedding"], D)
    out["rms_final"] = q.interleave_vector(params["rms_final"], D)
    out["wcls"] = q.interleave_input_rows(params["wcls"])
    layers = []
    for lp in params["layers"]:
        lp = dict(lp)
        lp["qkv"] = q.interleave_input_rows(lp["qkv"])  # input D; output heads
        # wo: input is the attention-head basis (NOT interleaved — rope and
        # head reshapes own that order); output columns move to basis D
        lp["wo"] = q.interleaved_output_cols(lp["wo"], D)
        if "experts" in lp:
            # MoE: each expert's FFN follows the dense pattern — gate_up
            # reads D / writes its own F basis, down reads F / writes D;
            # the router (a plain array) reads D, so its rows permute
            lp["router"] = jnp.take(jnp.asarray(lp["router"]), perm_d, axis=0)
            lp["experts"] = [
                {
                    "gate_up": q.interleaved_output_cols(
                        q.interleave_input_rows(e["gate_up"]), F, halves=2
                    ),
                    "down": q.interleaved_output_cols(
                        q.interleave_input_rows(e["down"]), D
                    ),
                }
                for e in lp["experts"]
            ]
        else:
            lp["gate_up"] = q.interleaved_output_cols(
                q.interleave_input_rows(lp["gate_up"]), F, halves=2
            )
            lp["down"] = q.interleaved_output_cols(q.interleave_input_rows(lp["down"]), D)
        lp["rms_att"] = q.interleave_vector(lp["rms_att"], D)
        lp["rms_ffn"] = q.interleave_vector(lp["rms_ffn"], D)
        if "rms_moe" in lp:
            lp["rms_moe"] = q.interleave_vector(lp["rms_moe"], D)
        if "rms_ffn2" in lp:
            lp["rms_ffn2"] = q.interleave_vector(lp["rms_ffn2"], D)
        layers.append(lp)
    out["layers"] = layers
    return out


def remove_basis_interleave(params: Params, cfg: LlamaConfig) -> Params:
    """The converter-side migration shim: move a basis-era params tree
    (one that went through :func:`apply_basis_interleave` before the basis
    was retired — e.g. an external snapshot of the placed tree) back to
    the standard basis, bit-exactly. A standard-basis tree passes through
    unchanged, so loaders can apply this unconditionally to trees of
    unknown vintage. Detection is the layer-0 qkv ``interleaved`` flag:
    the producer always row-interleaved qkv, and the flag rides the pack's
    pytree aux data through any serialization that preserves it."""
    from distributed_llama_tpu.ops import q40 as q

    layers_in = params.get("layers") or []
    if not layers_in or not getattr(layers_in[0].get("qkv"), "interleaved", False):
        return params
    from distributed_llama_tpu.ops.q40 import (
        _n_padded,
        interleave_perm,
        interleave_window,
    )

    D, F = cfg.dim, cfg.hidden_dim
    perm_d = interleave_perm(_n_padded(D), interleave_window(_n_padded(D)))
    inv_d = jnp.asarray(np.argsort(perm_d))
    out = dict(params)
    out["embedding"] = q.deinterleave_vector(params["embedding"], D)
    out["rms_final"] = q.deinterleave_vector(params["rms_final"], D)
    out["wcls"] = q.deinterleave_input_rows(params["wcls"])
    layers = []
    for lp in params["layers"]:
        lp = dict(lp)
        lp["qkv"] = q.deinterleave_input_rows(lp["qkv"])
        lp["wo"] = q.deinterleave_output_cols(lp["wo"], D)
        if "experts" in lp:
            lp["router"] = jnp.take(jnp.asarray(lp["router"]), inv_d, axis=0)
            lp["experts"] = [
                {
                    "gate_up": q.deinterleave_input_rows(
                        q.deinterleave_output_cols(e["gate_up"], F, halves=2)
                    ),
                    "down": q.deinterleave_input_rows(
                        q.deinterleave_output_cols(e["down"], D)
                    ),
                }
                for e in lp["experts"]
            ]
        else:
            lp["gate_up"] = q.deinterleave_input_rows(
                q.deinterleave_output_cols(lp["gate_up"], F, halves=2)
            )
            lp["down"] = q.deinterleave_input_rows(
                q.deinterleave_output_cols(lp["down"], D)
            )
        lp["rms_att"] = q.deinterleave_vector(lp["rms_att"], D)
        lp["rms_ffn"] = q.deinterleave_vector(lp["rms_ffn"], D)
        if "rms_moe" in lp:
            lp["rms_moe"] = q.deinterleave_vector(lp["rms_moe"], D)
        if "rms_ffn2" in lp:
            lp["rms_ffn2"] = q.deinterleave_vector(lp["rms_ffn2"], D)
        layers.append(lp)
    out["layers"] = layers
    return out


def _synthetic_params(
    cfg: LlamaConfig, mat, ones, embedding, rope_table, layered: bool = False
) -> Params:
    """Shared structure for the synthetic-param builders: the single source of
    truth for the pytree shape, kept in lockstep with load_params. ``mat``,
    ``ones``, ``embedding`` are array factories (host numpy or on-device).

    ``layered=True`` builds the production per-layer-list layout directly
    (generating stacked then slicing would transiently double HBM on a
    7B-scale synthetic model)."""
    D, H, K, hd = cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.head_size
    L, F, V = cfg.n_layers, cfg.hidden_dim, cfg.vocab_size

    def layer_tree():
        tree = {
            "q": mat(D, H * hd),
            "k": mat(D, K * hd),
            "v": mat(D, K * hd),
            "wo": mat(H * hd, D),
            "rms_att": ones(D),
            "rms_ffn": ones(D),
        }
        if cfg.is_moe:
            E = cfg.n_experts
            tree.update(
                router=mat(D, E),
                moe_up=mat(E, D, F),
                moe_gate=mat(E, D, F),
                moe_down=mat(E, F, D),
            )
        else:
            tree.update(gate=mat(D, F), down=mat(F, D), up=mat(D, F))
        if cfg.arch == ArchType.GROK1:
            tree.update(rms_moe=ones(D), rms_ffn2=ones(D))
        return tree

    if layered:
        layers: Any = [layer_tree() for _ in range(L)]
    else:
        per_layer = [layer_tree() for _ in range(L)]
        layers = {
            k: np.stack([pl[k] for pl in per_layer])
            if isinstance(per_layer[0][k], np.ndarray)
            else jnp.stack([pl[k] for pl in per_layer])
            for k in per_layer[0]
        }
    return {
        "embedding": embedding(V, D),
        "layers": layers,
        "rms_final": ones(D),
        "wcls": mat(D, V),
        "rope_table": rope_table,
    }


def random_params(
    cfg: LlamaConfig, dtype=jnp.bfloat16, seed: int = 0, layered: bool = False
) -> Params:
    """Synthetic host-side params pytree with the exact structure/shapes of
    load_params. Used by tests and the multichip dry-run."""
    rng = np.random.RandomState(seed)
    np_dtype = np.dtype(dtype)

    def mat(*shape):
        scale = 1.0 / np.sqrt(shape[-2] if len(shape) > 1 else shape[-1])
        return (rng.randn(*shape) * scale).astype(np_dtype)

    def ones(*shape):
        return np.ones(shape, np.float32)

    def embedding(V, D):
        return (rng.randn(V, D) * 0.02).astype(np.float32)

    return _synthetic_params(
        cfg, mat, ones, embedding, build_rope_table(cfg), layered=layered
    )


def random_params_on_device(
    cfg: LlamaConfig, dtype=jnp.bfloat16, seed: int = 0, layered: bool = False
) -> Params:
    """Like :func:`random_params` but generated with jax.random directly on
    the accelerator — no host RNG time and no host-to-device transfer. Used by
    the benchmark, where a 7B-parameter tree would otherwise take minutes to
    synthesize and ship."""
    import jax

    keys = iter(jax.random.split(jax.random.PRNGKey(seed), 16 * cfg.n_layers + 16))

    def mat(*shape):
        scale = 1.0 / np.sqrt(shape[-2] if len(shape) > 1 else shape[-1])
        # generate directly in the target dtype: an f32 intermediate of the
        # largest stacked tensor would transiently cost 2x its bf16 size
        return jax.random.normal(next(keys), shape, dtype=dtype) * jnp.asarray(scale, dtype)

    def ones(*shape):
        return jnp.ones(shape, jnp.float32)

    def embedding(V, D):
        return jax.random.normal(next(keys), (V, D), dtype=jnp.float32) * 0.02

    return _synthetic_params(
        cfg, mat, ones, embedding, jnp.asarray(build_rope_table(cfg)), layered=layered
    )

