"""Prompt-lookup drafting for self-speculative decoding (host side).

Speculative decoding (Leviathan et al., ICML 2023) turns the HBM-bound
one-token-per-weight-read decode step into k-tokens-per-read: a cheap
drafter proposes k tokens, one verify forward scores all of them plus a
bonus position in a single weight read, and an accept/reject pass keeps
the longest valid prefix. Prompt-lookup decoding (Saxena, 2023) supplies
the drafts with NO draft model: the request's own prompt + emitted output
is the corpus, and the most recent earlier occurrence of the current
n-gram tail predicts the continuation. On repetitive or structured output
(code, JSON, extraction, chat replaying its context) acceptance is high
and decode advances several positions per weight read; on novel text
acceptance collapses to zero and the step degenerates to plain decode
plus a k-token verify overhead — which is why ``--spec-draft`` defaults
off and the serving layer records acceptance telemetry
(docs/OBSERVABILITY.md).

The drafter is deliberately host-side and stateful per request: matching
is a few microseconds of numpy against a <= seq_len token history —
noise next to a decode step — and the verify forward
(``models.llama.forward_verify_batched`` / ``forward_tokens``) plus the
on-device accept/reject (``models.sampling``) keep everything heavy on
device. On the prefix-cache hit path the verify window's attention runs
the fused paged Pallas kernel (``ops.attention.fused_paged_verify_attention``
— decode's superstep kernel with T-query windows), so a speculative step
keeps the one-program-per-layer dispatch shape of plain decode.
"""

from __future__ import annotations

import numpy as np

# widest n-gram tried first: longer context keys make rarer but more
# accurate predictions; the ladder falls through to shorter n-grams like
# the reference prompt-lookup implementation
DEFAULT_MAX_NGRAM = 3

# most-recent candidate windows scanned per n-gram width: bounds a draft()
# call on pathological histories (a common token recurring hundreds of
# times with no matching continuation) — the batched scheduler drafts
# under its cond lock, so an unbounded scan would stall every co-batched
# lane's join/leave for the duration
MAX_SCAN_STARTS = 64


class PromptLookupDrafter:
    """Draft up to ``k`` continuation tokens by n-gram lookup over the
    request's own token history (prompt + emitted output).

    For ``n`` from ``max_ngram`` down to 1, the final ``n`` history tokens
    are searched for their most recent EARLIER occurrence; on a match the
    tokens that followed it are proposed. The most recent match wins (the
    continuation closest to the current context), and the draft never
    includes the match window itself, so a drafted token is always a
    genuine prediction.
    """

    def __init__(self, k: int, max_ngram: int = DEFAULT_MAX_NGRAM):
        if k < 1:
            raise ValueError(f"draft length must be >= 1, got {k}")
        if max_ngram < 1:
            raise ValueError(f"max n-gram must be >= 1, got {max_ngram}")
        self.k = int(k)
        self.max_ngram = int(max_ngram)
        # incremental mirror of the caller's history list (the serving
        # loops APPEND-ONLY to one list per request): re-converting the
        # whole list per step would be O(history) of lock-held host work in
        # the batched scheduler — the mirror copies only the new suffix
        self._src: list | None = None
        self._buf: np.ndarray | None = None
        self._len = 0
        # lifetime tokens proposed by this drafter (one drafter per
        # request): the scheduler surfaces it in the request's trace so a
        # span tree shows how much of the stream rode on speculation
        # without a separate metric series per request (ISSUE 16)
        self.drafted_total = 0

    def _as_array(self, history) -> np.ndarray:
        if isinstance(history, np.ndarray):
            return np.ascontiguousarray(history, dtype=np.int64)
        n = len(history)
        if self._src is not history or n < self._len:
            # a new (or rewound) history list: rebuild the mirror. Holding
            # the reference keeps the identity check sound; the contract is
            # append-only mutation between rebuilds.
            self._src = history
            self._buf = np.asarray(history, dtype=np.int64)
            self._len = n
            return self._buf
        if n > self._len:
            if self._buf.shape[0] < n:
                grown = np.empty(max(n, 2 * self._buf.shape[0] + 8), np.int64)
                grown[: self._len] = self._buf[: self._len]
                self._buf = grown
            self._buf[self._len : n] = history[self._len :]
            self._len = n
        return self._buf[:n]

    def draft(self, history: list[int] | np.ndarray, limit: int | None = None) -> list[int]:
        """Up to ``min(k, limit)`` proposed continuation tokens of
        ``history`` (possibly none — no n-gram of the tail recurs)."""
        out = self._draft(history, limit)
        self.drafted_total += len(out)
        return out

    def _draft(self, history, limit: int | None) -> list[int]:
        budget = self.k if limit is None else min(self.k, int(limit))
        h = self._as_array(history)
        n_hist = h.shape[0]
        if budget < 1 or n_hist < 2:
            return []
        for n in range(min(self.max_ngram, n_hist - 1), 0, -1):
            tail = h[n_hist - n :]
            # candidate start positions of an EARLIER occurrence: windows
            # [j, j+n) strictly before the tail window itself
            starts = np.flatnonzero(h[: n_hist - n] == tail[0])
            if starts.size == 0:
                continue
            best: np.ndarray | None = None
            for j in reversed(starts[-MAX_SCAN_STARTS:].tolist()):  # most recent first
                # a window overlapping the tail is a valid periodic match —
                # it only has to START before the tail window does
                if np.array_equal(h[j : j + n], tail):
                    cont = h[j + n : j + n + budget]
                    if cont.size >= budget:
                        return [int(t) for t in cont]
                    # a match near the history end yields a short
                    # continuation; keep it but prefer an older match that
                    # can fill the whole budget (periodic histories always
                    # have one)
                    if best is None or cont.size > best.size:
                        best = cont
            if best is not None and best.size:
                return [int(t) for t in best]
        return []
