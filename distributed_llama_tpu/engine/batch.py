"""Batched multi-stream decode: one weight read per step for B requests.

Decode is HBM-bound — the weight bytes dominate every step (docs/PERF.md) —
so ``--parallel N`` serving built on N independent single-sequence dispatches
buys fairness, not tokens: the dispatches queue on the device stream and
each one re-reads every weight matrix (measured 97.3 tok/s aggregate vs
95.8 single-stream, round 5). Batching the step over B sequences amortizes
each weight read across all active requests — the Orca/vLLM
continuous-batching insight — for near-B× aggregate throughput at modest B
with no new hardware.

Architecture
------------
* :class:`BatchScheduler` owns ONE slab KV cache
  (``llama.init_batch_cache``: per-layer ``(keys, values)`` halves with a
  leading ``[B_max]`` batch axis) and coalesces every joined stream's next
  chunk into ONE batched dispatch
  (``sampling.decode_chunk_batched`` / the tp backend's
  ``batched_decode_chunk``).
* :class:`BatchStream` is one slab row wearing the
  :class:`~distributed_llama_tpu.engine.engine.EngineStream` serving
  surface (``prefill_device`` / ``stream_decode`` / ``rollback`` / ...), so
  the API server's ``StreamSlot``s submit into the shared scheduler without
  changing the completion flow (SSE streaming, per-request stop/seed and
  the chat-prefix NaiveCache all ride on top unchanged).
* Requests join and leave BETWEEN chunks without recompiling: dispatches
  run at fixed power-of-two row buckets (1/2/4/8..., mirroring
  ``_prefill_bucket``) with an active-row mask — an inactive row decodes
  garbage into a DROPPED cache write (``kv_cache.update_row_batched``), so
  a retired slot's cache stays byte-identical for its next prefix reuse.
* Prefill stays per-request: ``_slab_prefill`` runs the ordinary
  single-sequence forward on the stream's slab row (extracted and
  re-inserted inside the jitted program; the donated slab aliases in
  place), reusing the whole blocked-attention/i8/bucketing machinery.
  Long prompts dispatch in ``prefill_chunk``-token pieces with the
  scheduler lock released between them, so other rows' decode chunks
  interleave with a long prefill (Sarathi-style; ISSUE 4 satellite).
* With ``prefix_cache=True`` the scheduler also owns a page pool
  (``llama.init_page_pool`` single-chip, the tp engine's sharded pool on
  multi-chip) and a radix tree over token blocks (``engine/
  prefix_cache.py``): an admission prefill (row position 0) binds its
  matched prefix pages to the row as ``(page_ids, matched_len)`` — the
  row's decode/verify/prefill attention then reads those positions
  **zero-copy through its page table over the pool** (ops.attention paged
  variants) while only the unmatched suffix prefills into the slab row;
  completed full pages are published back (the only copy left in the
  system). Because rows alias tree pages, the matched chain stays
  ref-pinned for the ROW'S LIFETIME (released at reset/quarantine/
  rollback-truncation), so eviction can never recycle a page a live row
  is attending over — chaos-enforced, and a prefix-hit stream is
  bit-identical to the cold prefill (tests/test_prefix_cache.py,
  tests/test_paged_attention.py).
* Per-row seeds, temperatures, top-p and top-k ride the batched program;
  sampling is fused into the scan on counter-PRNG coins keyed
  ``(seed, position)`` (ISSUE 13), so a row's token stream is
  bit-identical to the single-stream chunked decode for the same request
  seed (tests/test_batch_decode.py), requests with different sampling
  settings share one compiled program, and no sampler state exists for
  the scheduler to thread — a requeued or failed-over row re-draws its
  coins from its seed and positions alone.
  (MoE models: the batched step uses dense expert mixing — parity holds up
  to expert-sum reordering, and expert HBM reads amortize only once
  B ≥ E/k; see ``llama.forward_step_batched``.)

Thread model: request threads call into their own :class:`BatchStream`;
whichever thread needs tokens first becomes the dispatcher for everyone
(dispatch under the scheduler condition lock — cheap, asynchronous — then
the blocking fetch outside it). Joins/leaves take the same lock, so the
active set is coherent per dispatch; an epoch counter per stream keeps a
late fetch from delivering a previous request's tokens to a new occupant.
"""

from __future__ import annotations

import collections
import functools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu import lockcheck, retry
from distributed_llama_tpu.engine import faults, integrity
from distributed_llama_tpu.engine.engine import TokenStats, _prefill_bucket, next_pow2
from distributed_llama_tpu.engine.speculative import PromptLookupDrafter
from distributed_llama_tpu.models import llama
from distributed_llama_tpu.models.config import LlamaConfig
from distributed_llama_tpu.ops import kv_cache as kvc
from distributed_llama_tpu.telemetry import Stopwatch, flight


def decode_bucket(n: int, b_max: int) -> int:
    """Power-of-two row bucket covering rows 0..n-1 (capped at b_max): one
    compiled batched program per bucket, holes masked inactive."""
    return min(next_pow2(n), b_max)


def _page_bucket(n: int) -> int:
    """Power-of-two padding for page-id arrays: one compiled gather/publish
    program per bucket, padded entries dropped by out-of-bounds indices."""
    return next_pow2(n)


@jax.jit
def _slice_page(pool, pid):
    """One pool page's bytes across every layer/half as a flat list (the
    spill-entry layout of kv_cache.download_pool_page) — ONE compiled
    program + ONE host transfer per spill instead of 2·layers separate
    fetches (the download runs under the scheduler cond; its wall time is
    lock hold time for every lane)."""
    out = []
    for pk, pv in pool:
        out.extend(kvc.slice_pool_page(pk, pid))
        out.extend(kvc.slice_pool_page(pv, pid))
    return out


@functools.partial(jax.jit, donate_argnums=(0,))
def _upload_page(pool, pid, page_kvs):
    """Write one spilled page's host byte arrays into pool page ``pid``
    across every layer — the spill-tier reload, :func:`_publish_pages` in
    reverse (ISSUE 11). ``page_kvs`` is per layer a pair of flat
    array lists (``[data]``, or ``[data, scales]`` for i8 — the
    download's verbatim layout). The donated pool aliases in place."""
    return [
        (kvc.upload_pool_page(pk, pid, hk), kvc.upload_pool_page(pv, pid, hv))
        for (pk, pv), (hk, hv) in zip(pool, page_kvs)
    ]


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def _publish_pages(page: int, slab, pool, page_ids, src_page, row):
    """Copy slab row ``row``'s page slots ``src_page`` into pool pages
    ``page_ids`` across every layer (the post-prefill publish). The donated
    pool aliases in place; the slab is read-only here (``leaf[0]``/
    ``leaf[1]`` are contiguous views of the fused leaf)."""
    return [
        (
            kvc.publish_row_pages(pk, leaf[0], row, src_page, page_ids, page),
            kvc.publish_row_pages(pv, leaf[1], row, src_page, page_ids, page),
        )
        for leaf, (pk, pv) in zip(slab, pool)
    ]


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def _slab_prefill_single(cfg: LlamaConfig, params, tokens, slab, row, pos, n_real):
    """Prefill ``tokens`` into slab row ``row`` (single chip): the row is
    extracted as an ordinary single-stream fused cache, run through the
    normal forward (blocked attention, i8 quantization, MoE bucketing,
    coalesced K/V updates — all reused), and written back; the donated slab
    aliases every other row in place. Returns (logits [T, vocab], new slab)."""
    row_cache = [kvc.fused_take_row(leaf, row) for leaf in slab]
    logits, new_rows = llama.forward_tokens(
        cfg, params, tokens, row_cache, pos, n_real=n_real
    )
    new_slab = [
        kvc.fused_put_row(leaf, new_leaf, row)
        for leaf, new_leaf in zip(slab, new_rows)
    ]
    return logits, new_slab


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def _slab_prefill_single_paged(
    cfg: LlamaConfig, params, tokens, slab, pool, row, pos, n_real, table, matched
):
    """:func:`_slab_prefill_single` with zero-copy prefix aliasing: the
    row's attention reads positions below ``matched`` from the page pool
    through ``table`` (the admission-time suffix prefill and any later
    continuation prefill on an aliased row). The pool is read-only — only
    the slab is donated."""
    row_cache = [kvc.fused_take_row(leaf, row) for leaf in slab]
    logits, new_rows = llama.forward_tokens(
        cfg, params, tokens, row_cache, pos, n_real=n_real,
        paged=(pool, table, matched),
    )
    new_slab = [
        kvc.fused_put_row(leaf, new_leaf, row)
        for leaf, new_leaf in zip(slab, new_rows)
    ]
    return logits, new_slab


class BatchStream:
    """One slab row of a :class:`BatchScheduler`, wearing the EngineStream
    serving surface. All mutable request state (position, queue, sampler
    settings, the device-resident next-token scalar) lives here; the
    scheduler snapshots it per batched dispatch under its lock."""

    def __init__(self, scheduler: "BatchScheduler", row: int):
        self.scheduler = scheduler
        self.row = row
        self.pos = 0
        self.stats: list[TokenStats] = []
        # register with the engine's stream list: the TP transfer-refresh
        # cadence counts tokens across ALL streams' stats, and batched
        # serving must keep driving the periodic re-measurement
        engine = scheduler.engine
        engine._streams.append(self)
        engine._tel.active_streams.set(len(engine._streams))
        self._queue: collections.deque[int] = collections.deque()
        self._joined = False
        self._epoch = 0  # bumped per join/leave: stale fetches can't deliver
        self._first = None  # device scalar (or host int) feeding the next chunk
        self._seed32 = 0  # folded uint32 request seed (stateless counter PRNG)
        self._temperature = 0.0
        self._topp = 0.9
        self._topk = 0
        self._pending_prefill_entry: TokenStats | None = None
        self._depth_held = False
        # per-request deadline (time.monotonic seconds) set by the serving
        # layer: the scheduler retires an expired row BETWEEN chunks and its
        # next_token raises DeadlineExceeded (ISSUE 3)
        self.deadline: float | None = None
        # multi-tenant serving (ISSUE 8): the serving layer labels the row
        # with its request's tenant and priority for the lifetime of the
        # request (cleared between requests). ``priority is not None``
        # marks the row an active preemption candidate: preempt_below may
        # evict it for a strictly-higher-priority arrival
        self.tenant: str | None = None
        self.priority: int | None = None
        # request trace (ISSUE 16): the serving layer hands the row its
        # request's TraceContext for the request's lifetime (cleared
        # between requests). The scheduler's shared dispatch/fetch paths
        # fan per-row child spans into it — one attribute check when None
        self.trace = None
        # per-request prefix-cache opt-out (the API body's `cache: off`):
        # False skips BOTH the admission match and the post-prefill publish
        # for this row (ISSUE 4); serving restores True between requests
        self.prefix_cache_enabled = True
        # zero-copy prefix aliasing (ISSUE 7): the admission match binds the
        # matched radix chain to this row — attention reads positions below
        # ``matched_len`` THROUGH ``_alias_ids`` (the row's page table) over
        # the shared pool instead of slab copies. ``_alias_chain`` holds the
        # ref-pinned PageNodes for the row's lifetime; the scheduler
        # releases them at reset/quarantine and truncates them on rollback
        # below ``matched_len`` (all under its cond lock)
        self._alias_chain: list = []
        self._alias_ids: list[int] = []
        self.matched_len = 0
        # speculative decode (scheduler spec mode): this row's host-side
        # prompt-lookup corpus (prompt + emitted tokens, extended at chunk
        # delivery) and its lazily-built drafter. ``_spec_on`` False rides
        # the shared verify dispatches with ZERO drafts — a plain decode
        # step on the same weight read, which is how spec and non-spec
        # requests mix in one slab
        self._history: list[int] = []
        self._drafter: PromptLookupDrafter | None = None
        self._spec_on = False
        # per-chunk device logit fingerprints (ISSUE 10), in delivery
        # order (the fetch-ownership design delivers chunk N strictly
        # before N+1), reset at _join so one request = one sequence. A
        # RUNNING fold would be race-dependent — the pipelined chunk
        # dispatched ahead of the stream's last consumed token may or may
        # not deliver before the stream leaves — so readers fold a
        # deterministic PREFIX via run_fingerprint(n_tokens). The
        # spec-verify path does not feed it (stays empty)
        self._chunk_fps: list[int] = []
        # a chunk failure retires ONLY this row (faults.RowQuarantined /
        # StallTimeout / DeadlineExceeded, set by the scheduler under its
        # lock); next_token raises it, surviving co-batched rows keep
        # streaming — this replaces the seed's poison-every-stream behavior
        self._fetch_error: BaseException | None = None

    @property
    def cfg(self):
        return self.scheduler.engine.cfg

    @property
    def engine(self):
        return self.scheduler.engine

    # ------------------------------------------------------------------
    # EngineStream-compatible lifecycle
    # ------------------------------------------------------------------

    def reset(self) -> None:
        self.scheduler._leave(self)
        # release the row's zero-copy page pins: the next occupant matches
        # its own chain, and the old pages become evictable once no other
        # row aliases them
        self.scheduler._release_row_pins(self)
        self.pos = 0
        # same cadence no-op contract as EngineStream.reset(): clearing this
        # stream's stats shrinks the engine-wide token sum, so the transfer
        # watermark shifts down by the same amount
        cleared = sum(s.n_tokens for s in self.stats)
        engine = self.engine
        with engine._depth_lock:
            engine._transfer_measured_at -= cleared
        self.stats.clear()
        self._release_depth()
        self._pending_prefill_entry = None
        self._fetch_error = None
        self.deadline = None
        self.prefix_cache_enabled = True
        self.tenant = None
        self.priority = None
        self._history = []
        self._drafter = None
        self._spec_on = False

    def run_fingerprint(self, n_tokens: int | None = None) -> int:
        """FNV-1a fold of this request's chunk fingerprints (ISSUE 10).
        ``n_tokens`` folds only the chunks that produced the first
        ``n_tokens`` DECODED tokens (the fused first token is sampled
        pre-chunk and carries no fingerprint) — deterministic no matter
        how many speculative chunks the pipeline delivered beyond them,
        which is what lets the integrity canary compare the value against
        a golden. ``None`` folds everything delivered so far."""
        fps = self._chunk_fps
        if n_tokens is not None:
            fps = fps[: -(-max(0, n_tokens) // self.scheduler.chunk)]
        out = integrity.FP_BASIS
        for fp in fps:
            out = integrity.fold_run_fingerprint(out, fp)
        return out

    def rollback(self, pos: int) -> None:
        """Rewind to ``pos`` (prefix-cache reuse / early-stop contract).
        Slab slots beyond ``pos`` — including any written by an in-flight
        speculative chunk — are stale but unreachable: attention masks
        s <= pos and the next prefill overwrites them before the position
        pointer crosses. A rollback BELOW the aliased prefix truncates the
        alias to ``pos`` (the rolled-back-onto tokens are a shared prefix,
        so the pool bytes below ``pos`` stay valid) and releases the pins
        of pages the shortened table no longer reaches — the next prefill
        writes the slab at ``pos`` and must be read from the slab, not the
        pool."""
        if not 0 <= pos <= self.pos:
            raise ValueError(f"cannot rollback to {pos} from {self.pos}")
        self.pos = pos
        if self.matched_len > pos:
            self.scheduler._truncate_alias(self, pos)

    # ------------------------------------------------------------------
    # Prefill (per-request, on this stream's slab row)
    # ------------------------------------------------------------------

    def prefill(self, tokens) -> np.ndarray:
        """Batched-prompt prefill into this slab row; returns the last
        token's logits row (only that row crosses the host boundary)."""
        self._release_depth()
        tokens = np.asarray(tokens, dtype=np.int32)
        n = tokens.shape[0]
        engine = self.engine
        sw = Stopwatch()
        with engine._tel.span("prefill", tokens=n, pos=self.pos, batch_row=self.row):
            logits, last = self.scheduler._prefill_row(self, tokens)
            out = np.asarray(logits[last])
        entry = engine._split_stats(sw.elapsed_ms(), n_tokens=n)
        self.stats.append(entry)
        if engine._tel.enabled:
            engine._tel.prompt_tokens.inc(n)
            engine._tel.prefill_latency.observe(entry.generation_ms / 1000.0)
            engine._tel.kv_occupancy.set(self.pos / engine.cfg.seq_len)
        return out

    def prefill_device(self, tokens, temperature, topp, seed: int, topk: int = 0):
        """Prefill + sample the first token ON DEVICE (the prefill→decode
        fusion of EngineStream.prefill_device, on this slab row): returns
        the device token scalar — nothing visits the host until the fused
        first-token fetch overlaps chunk 1's compute. The coin is keyed on
        the last prompt token's absolute position, so a requeue/failover
        re-run draws it identically with no sampler state shipped."""
        engine = self.engine
        tokens = np.asarray(tokens, dtype=np.int32)
        n = tokens.shape[0]
        sw = Stopwatch()
        self._hold_depth()
        try:
            with engine._tel.span(
                "prefill_dispatch", tokens=n, pos=self.pos, batch_row=self.row
            ):
                logits, last = self.scheduler._prefill_row(self, tokens)
                with engine._tel.span(
                    "device_sample", pos=self.pos - 1, batch_row=self.row
                ):
                    from distributed_llama_tpu import prng

                    token = engine._sample_row(
                        logits, jnp.int32(last),
                        jnp.uint32(prng.fold_seed(seed)),
                        jnp.int32(self.pos - 1), jnp.float32(temperature),
                        jnp.float32(topp), jnp.int32(topk),
                    )
            entry = engine._split_stats(sw.elapsed_ms(), n_tokens=n)
            self.stats.append(entry)
            self._pending_prefill_entry = entry
            if engine._tel.enabled:
                engine._tel.prompt_tokens.inc(n)
        except BaseException:
            self._release_depth()
            raise
        return token

    def fetch_first_token(self, first_token) -> int:
        """Fetch a :meth:`prefill_device` token without starting a decode
        stream (the 1-token-completion fast path)."""
        return self._fetch_fused_first(first_token)

    def _fetch_fused_first(self, first_token) -> int:
        """Blocking fetch of the device-sampled first token; the drain time
        joins the prefill's stats entry (the dispatch-only timing would
        otherwise under-report prefill latency — same contract as
        EngineStream._fetch_fused_first)."""
        engine = self.engine
        sw = Stopwatch()
        with engine._tel.span("first_token_fetch", batch_row=self.row):
            tok = int(np.asarray(first_token))
        self._release_depth()
        drained_ms = sw.elapsed_ms()
        entry = self._pending_prefill_entry
        if entry is not None:
            entry.generation_ms += drained_ms
            entry.inference_ms += drained_ms
            self._pending_prefill_entry = None
            tel = engine._tel
            if tel.enabled:
                tel.prefill_latency.observe(entry.generation_ms / 1000.0)
                tel.tokens_generated.inc(1)
                tel.device_sampled_tokens.inc(1)
                tel.kv_occupancy.set(self.pos / engine.cfg.seq_len)
        return tok

    def _hold_depth(self) -> None:
        engine = self.engine
        with engine._depth_lock:
            if not self._depth_held:
                engine._pipeline_depth += 1
                self._depth_held = True

    def _release_depth(self) -> None:
        engine = self.engine
        with engine._depth_lock:
            if self._depth_held:
                engine._pipeline_depth -= 1
                self._depth_held = False

    # ------------------------------------------------------------------
    # Decode (through the shared batched dispatch)
    # ------------------------------------------------------------------

    def stream_decode(
        self,
        first_token,
        on_token,
        temperature: float = 0.0,
        topp: float = 0.9,
        seed: int = 0,
        chunk: int | None = None,
        limit: int | None = None,
        first_prev: int | None = None,
        spec_draft: int = 0,
        spec_ngram: int = 3,
        prompt_tokens=None,
        topk: int = 0,
    ) -> int:
        """EngineStream.stream_decode over the shared batched dispatch: this
        stream joins the scheduler's active set and consumes its row of
        every batched chunk; other streams' chunks ride the same weight
        reads. ``chunk`` is accepted for signature parity but the scheduler's
        shared chunk size governs (all coalesced rows must step together).
        Owns the early-stop rollback contract; returns tokens consumed.

        With the scheduler in spec mode (``spec_draft`` on the
        BatchScheduler), every dispatch is a batched VERIFY step and rows
        advance a variable number of positions per chunk; ``spec_draft`` 0
        on the call keeps this row's drafts empty (a plain decode step
        riding the shared verify read), which is how spec and non-spec
        requests mix in one slab. ``spec_ngram`` is accepted for signature
        parity — the scheduler's shared drafter config governs."""
        engine = self.engine
        sched = self.scheduler
        start_pos = self.pos
        stop = engine.cfg.seq_len if limit is None else min(limit, engine.cfg.seq_len)
        fused_first = first_prev is not None
        spec_mode = sched.spec_draft > 0
        prev = first_prev if fused_first else int(first_token)
        consumed = 0
        keep = True
        if spec_mode:
            # the drafter needs host token values: fetch the fused first
            # token BEFORE joining (the plain path's fetch-overlap trick is
            # traded for draft context — one round trip buys up to k+1
            # tokens per subsequent step)
            if fused_first:
                tok = self._fetch_fused_first(first_token)
                consumed = 1
                keep = on_token(prev, tok)
                prev = tok
            self._history = [int(t) for t in (prompt_tokens or [])]
            self._history.append(prev)
            self._spec_on = bool(spec_draft and spec_draft > 0)
            first_token = prev  # host int: the next verify window's feed[0]
        sched._join(self, first_token, temperature, topp, seed, topk)
        try:
            if fused_first and not spec_mode:
                # dispatch chunk 1 before the fused fetch so the scalar
                # fetch overlaps the chunk's compute (the prefill_device
                # round-trip elision, batched)
                sched.kick()
                tok = self._fetch_fused_first(first_token)
                consumed += 1
                keep = on_token(prev, tok)
                prev = tok
            while keep is not False:
                fed = consumed - 1 if fused_first else consumed
                if start_pos + fed >= stop:
                    break
                tok = sched.next_token(self)
                consumed += 1
                keep = on_token(prev, tok)
                prev = tok
        finally:
            sched._leave(self)
            fed = max(consumed - 1, 0) if fused_first else consumed
            self.rollback(min(start_pos + fed, self.pos))
        return consumed

    # ------------------------------------------------------------------
    # Stats (EngineStream parity)
    # ------------------------------------------------------------------

    def avg_stats(self) -> TokenStats:
        if not self.stats:
            return TokenStats(0.0, 0.0, 0.0)
        n = sum(s.n_tokens for s in self.stats)
        return TokenStats(
            sum(s.generation_ms for s in self.stats) / n,
            sum(s.inference_ms for s in self.stats) / n,
            sum(s.transfer_ms for s in self.stats) / n,
            n_tokens=n,
        )

    def total_tokens(self) -> int:
        return sum(s.n_tokens for s in self.stats)


class BatchScheduler:
    """Owns the ``[B_max]`` slab cache and coalesces joined streams into
    one batched decode dispatch per chunk. Supported on the single-chip and
    tensor-parallel backends (the sp/ep backends keep their single-stream
    programs)."""

    def __init__(
        self,
        engine,
        n_rows: int,
        chunk: int = 32,
        retries: int = 2,
        retry_backoff_s: float = 0.05,
        stall_timeout_s: float | None = None,
        prefix_cache: bool = False,
        kv_pages: int | None = None,
        page_size: int = 64,
        prefill_chunk: int = 0,
        spec_draft: int = 0,
        spec_ngram: int = 3,
        replica_id: int = 0,
        host_spill_bytes: int = 0,
        spill_dir: str | None = None,
        spill_disk_bytes: int = 0,
        spill_arena=None,
        shared_index=None,
    ):
        tp_engine = engine._tp_engine
        if tp_engine is not None and not hasattr(tp_engine, "batched_decode_chunk"):
            raise ValueError(
                "batched decode is supported on the single-chip and tp "
                "backends only (sp/ep keep single-stream dispatches)"
            )
        if n_rows < 1:
            raise ValueError(f"need at least one batch row, got {n_rows}")
        self.engine = engine
        self.b_max = n_rows
        self.chunk = int(chunk)
        # Sarathi-style chunked prefill (ISSUE 4 satellite): a long prompt
        # is dispatched in prefill_chunk-token pieces with the scheduler
        # lock RELEASED between dispatches, so decode chunks for other rows
        # interleave instead of stalling behind the whole prompt. 0 = one
        # monolithic dispatch (the pre-ISSUE-4 behavior).
        self.prefill_chunk = max(
            0, 0 if prefill_chunk is None else int(prefill_chunk)
        )
        # radix-tree prefix cache over pool pages (ISSUE 4 tentpole, ISSUE 7
        # zero-copy): an admission prefill binds published KV pages to the
        # row's page table (attention reads them straight out of the pool)
        # and prefills only the unmatched suffix
        self._prefix = None
        self._pool = None
        if prefix_cache:
            # misconfiguration disables ONLY the prefix cache (with the
            # real reason printed) — it must never take batched decode
            # down with it (a raised ValueError here would be caught by
            # the server's backend-fallback handler and silently cost the
            # whole one-weight-read-per-step serving path)
            page_ok = 1 <= page_size <= engine.cfg.seq_len
            slab_pages = n_rows * -(-engine.cfg.seq_len // page_size) if page_ok else 0
            if kv_pages is None and page_ok:
                # default HBM budget: with zero-copy aliasing the pool is
                # the PRIMARY store of cached prefixes (rows hold no
                # duplicates), so size it to hold every row's worth of
                # prefix plus headroom for prefixes outliving their rows
                # (--parallel x ceil(seq_len/page) + 25%, at least one row)
                kv_pages = slab_pages + max(
                    slab_pages // 4, -(-engine.cfg.seq_len // page_size)
                )
            if not page_ok:
                print(
                    f"⚠️ prefix cache disabled: page size {page_size} must "
                    f"be in [1, seq_len {engine.cfg.seq_len}]"
                )
            elif kv_pages < 1:
                print("⚠️ prefix cache disabled: --kv-pages 0")
            else:
                if kv_pages < slab_pages:
                    print(
                        f"⚠️ --kv-pages {kv_pages} is smaller than one "
                        f"slab's worth ({slab_pages} pages for {n_rows} "
                        f"rows x seq_len {engine.cfg.seq_len}): the pool is "
                        "the primary prefix store under zero-copy paged "
                        "attention, so concurrent long prompts will "
                        "contend for pages (pinned-page soft failures)"
                    )
                from distributed_llama_tpu.engine.prefix_cache import PrefixCache

                # host-RAM spill tier (ISSUE 11, engine/spill.py): evicted
                # pages' bytes land in a bounded arena (shared across a
                # replica pool when the serving layer passes one) and
                # reload on a later match — re-upload ≪ re-prefill.
                # Single-chip pools only for now: the sharded tp pool's
                # per-shard download/upload programs are the known
                # follow-up, and spill must never take the cache down
                arena = spill_arena
                if arena is None and host_spill_bytes > 0:
                    import os as _os

                    from distributed_llama_tpu.engine.spill import HostArena

                    arena = HostArena(
                        int(host_spill_bytes),
                        disk_path=(
                            _os.path.join(spill_dir, "dllama-kv-spill.bin")
                            if spill_dir and spill_disk_bytes > 0 else None
                        ),
                        disk_budget_bytes=int(spill_disk_bytes),
                    )
                if arena is not None and tp_engine is not None:
                    print(
                        "⚠️ host-RAM spill disabled: the sharded tp page "
                        "pool has no download/upload programs yet "
                        "(single-chip backend only)"
                    )
                    arena = None
                self._prefix = PrefixCache(
                    kv_pages, page_size,
                    page_bytes=llama.page_pool_bytes(
                        engine.cfg, page_size, engine.cache_dtype
                    ),
                    spill=arena,
                    page_fetch=self._download_page if arena is not None else None,
                    owner_id=replica_id,
                    shared_index=shared_index,
                )
                if tp_engine is None:
                    self._pool = llama.init_page_pool(
                        engine.cfg, kv_pages, page_size, dtype=engine.cache_dtype
                    )
                else:
                    # the sharded pool (per-shard [P, page, K/tp, hd]
                    # halves): PR 4 deferred multi-chip; the zero-copy read
                    # made it a plain per-shard local program
                    self._pool = tp_engine.init_page_pool(
                        kv_pages, page_size, dtype=engine.cache_dtype
                    )
                # static per-row page-table width: every table the
                # scheduler builds covers ceil(S/page) entries (one
                # compiled paged program per bucket/chunk shape)
                self._n_table = -(-engine.cfg.seq_len // page_size)
        # self-speculative decode (ISSUE 6): spec_draft > 0 turns every
        # batched dispatch into a VERIFY step — per-row prompt-lookup
        # drafts scored in one weight read, rows advancing a variable
        # number of positions per step. Misconfiguration soft-disables
        # (spec is a perf mode; it must never take batched serving down)
        self.spec_draft = 0
        self.spec_ngram = max(1, int(spec_ngram))
        if spec_draft and int(spec_draft) > 0:
            if tp_engine is not None:
                print(
                    "⚠️ speculative decode disabled: the batched verify "
                    "forward is single-chip only for now (the tp verify "
                    "needs the sharded multi-token program)"
                )
            elif engine.cfg.is_moe:
                print(
                    "⚠️ speculative decode disabled: MoE verify windows "
                    "would route T>1 rows through the prefill expert path "
                    "(no decode parity contract)"
                )
            else:
                self.spec_draft = int(spec_draft)
        # fault tolerance (ISSUE 3): bounded retry with exponential backoff
        # for transient dispatch/fetch failures, an optional stall watchdog,
        # and the bind-once fault-injection plan (NULL_PLAN when no chaos
        # plan is installed — one no-op attribute call per dispatch)
        self.retries = max(0, int(retries))
        self.retry_backoff_s = float(retry_backoff_s)
        # the shared backoff vocabulary (distributed_llama_tpu/retry.py):
        # same schedule the old inline loops slept — base * 2**attempt
        self._retry_policy = retry.BackoffPolicy(
            attempts=self.retries + 1, base_s=self.retry_backoff_s
        )
        self.stall_timeout_s = stall_timeout_s
        self._faults = faults.active_plan()
        # replica-loss fault domain (ISSUE 9): this scheduler IS one
        # data-parallel replica when a server/replicas.py pool owns it.
        # ``replica_id`` scopes the replica.* chaos sites (a rule's row=
        # field selects the replica), ``health_hook(event, value)`` feeds
        # the pool's health state machine ("roundtrip" per chunk fetch,
        # "stall"/"lost" on death) and must only take LEAF locks — never
        # this cond — and ``lost_on_stall`` escalates a watchdog stall
        # from per-row StallTimeout to a whole-replica loss (the victims
        # then REQUEUE onto surviving replicas instead of failing 500)
        self.replica_id = int(replica_id)
        self.health_hook = None
        self.lost_on_stall = False
        # armed by an engine.sdc kind=corrupt message=logits rule: each
        # pending unit perturbs ONE fetched chunk's token columns in-vocab
        # (finite, wrong, invisible to the vocab/finite validation — the
        # class only the canary's golden comparison can see)
        self._sdc_logits_pending = 0
        self._lost = False
        self.lost_cause: str | None = None
        self.lost_victims = 0
        # priority preemption (ISSUE 8): clean evictions performed by
        # preempt_below — a plain counter so tests/loadgen read it with
        # telemetry off (the registry's dllama_preemptions_total mirrors it)
        self.preempted_total = 0
        if tp_engine is None:
            self._slab = llama.init_batch_cache(
                engine.cfg, n_rows, dtype=engine.cache_dtype
            )
        else:
            self._slab = tp_engine.init_batch_cache(n_rows, dtype=engine.cache_dtype)
        # backends whose slab shards its BATCH axis across the mesh (the
        # pod's 'data' axis) dispatch the whole slab every chunk: a sub-
        # bucket's rows would straddle the wrong shards. The floor is set
        # by init_batch_cache above; 1 everywhere else (classic bucketing)
        self._bucket_floor = (
            min(n_rows, max(1, int(getattr(tp_engine, "decode_bucket_floor", 1))))
            if tp_engine is not None else 1
        )
        self._streams: list[BatchStream] = []
        self._cond = lockcheck.make_condition("BatchScheduler._cond")
        # one dispatched-but-unfetched chunk at a time: (tokens_dev, epoch
        # snapshot, bucket, active count, stopwatch)
        self._pending = None
        self._fetching = False
        # fetch generation: bumped when a thread takes the pending chunk; the
        # watchdog kills a stalled generation by flipping _fetching off, and
        # the (eventually-returning) hung fetch sees its generation is dead
        # and discards its delivery
        self._fetch_gen = 0
        self._fetch_started: float | None = None
        self._shutdown = False
        self._watchdog: threading.Thread | None = None
        if stall_timeout_s is not None and stall_timeout_s > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="dllama-batch-watchdog",
                daemon=True,
            )
            self._watchdog.start()

    def close(self) -> None:
        """Stop the watchdog thread (tests; a serving scheduler lives for
        the process)."""
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # Replica loss (ISSUE 9): the whole-scheduler failure domain. A crash
    # (injected or real) at a dispatch, or a stall the watchdog escalates,
    # retires EVERY in-flight request with a typed ReplicaLost — the
    # serving layer requeues them through fair admission onto surviving
    # replicas and replays them bit-identically; the pool supervisor
    # restarts this replica with jittered backoff (server/replicas.py).
    # ------------------------------------------------------------------

    def mark_lost(self, cause: str, corrupt: bool = False) -> None:
        """Declare this replica dead (pool/tests entry point). Idempotent.
        ``corrupt=True`` marks an integrity-detected loss (canary/shadow
        mismatch, ISSUE 10): victims get :class:`faults.ReplicaCorrupt`,
        which the serving layer replays ONLY while nothing has streamed —
        deltas already sent by a silently-corrupt replica may themselves
        be wrong, and a suppressed replay would splice onto them."""
        with self._cond:
            self._mark_lost_locked(cause, corrupt=corrupt)

    def _mark_lost_locked(self, cause: str, corrupt: bool = False) -> None:
        """The one death path (cond held): every stream gets ReplicaLost
        (a mid-prefill request raises it at its next chunk boundary, a
        decoding one at its next ``next_token``), page pins release, the
        dispatched-but-unfetched chunk is dropped with its depth hold, the
        watchdog stands down, and the health hook reports the loss. The
        hook only takes LEAF locks (pool/admission/registry), so calling
        it under this cond cannot deadlock."""
        if self._lost:
            return
        self._lost = True
        self.lost_cause = cause
        self.lost_victims = sum(1 for s in self._streams if s._joined)
        # flight recorder (ISSUE 16): the death certificate — cause,
        # victim count, and the victims' request-trace ids, before the
        # pool's hook records the failover (leaf lock, safe under cond)
        flight.record(
            self.replica_id, "replica_lost", cause=cause,
            corrupt=bool(corrupt), victims=self.lost_victims,
            victim_trace_ids=[
                s.trace.request_id for s in self._streams
                if s.trace is not None
            ],
        )
        err_cls = faults.ReplicaCorrupt if corrupt else faults.ReplicaLost
        for s in self._streams:
            s._fetch_error = err_cls(
                f"replica {self.replica_id} lost: {cause}"
            )
            self._release_pins_locked(s)
        if self._pending is not None:
            # the speculative chunk dies with the replica: nobody will
            # fetch it, so its depth hold releases here
            self._pending = None
            with self.engine._depth_lock:
                self.engine._pipeline_depth -= 1
        self._shutdown = True  # a dead replica's watchdog has no duties
        self._cond.notify_all()
        hook = self.health_hook
        if hook is not None:
            hook("lost", float(self.lost_victims))

    @property
    def lost(self) -> bool:
        return self._lost

    def _watchdog_loop(self) -> None:
        """Detect a hung chunk fetch and fail the batch CLEANLY: joined rows
        get a typed StallTimeout (their requests end 500/504-class instead
        of hanging forever), the dead fetch generation is retired so a late
        completion delivers nothing, and the scheduler is immediately
        serviceable for new requests."""
        interval = max(min(self.stall_timeout_s / 4.0, 1.0), 0.005)
        tel = self.engine._tel
        while not self._shutdown:
            time.sleep(interval)
            with self._cond:
                stalled = (
                    self._fetching
                    and self._fetch_started is not None
                    and time.monotonic() - self._fetch_started > self.stall_timeout_s
                )
                if not stalled:
                    continue
                # take the hung fetch's completion duties: it can no longer
                # claim ownership (_fetch claims under this lock), so ITS
                # depth hold is released here — otherwise a never-returning
                # fetch would pin pipeline_depth > 0 and freeze the transfer
                # probe for the rest of the process
                self._fetching = False
                self._fetch_started = None
                released = 1
                if self._pending is not None:
                    # drop the speculative chunk queued behind the hung
                    # program: every row that wanted it is being retired, and
                    # leaving it would make the LAST _leave's idle-drain
                    # fetch it SYNCHRONOUSLY on a request thread — blocking
                    # that client's error response behind the hang
                    self._pending = None
                    released += 1
                with self.engine._depth_lock:
                    self.engine._pipeline_depth -= released
                tel.watchdog_stalls.inc()
                flight.record(
                    self.replica_id, "watchdog_stall",
                    timeout_s=self.stall_timeout_s,
                    lost_on_stall=self.lost_on_stall,
                )
                if not self.lost_on_stall:
                    # unsupervised stall: rows die with StallTimeout and no
                    # replica-death dump follows — snapshot the evidence
                    # here (the supervised path dumps via the pool's
                    # failover hook). Outside-the-lock would be nicer, but
                    # dump() only spawns a writer thread when dump_dir is
                    # set; the snapshot itself is a leaf-locked copy.
                    flight.RECORDER.dump(
                        self.replica_id, "watchdog_stall",
                        timeout_s=self.stall_timeout_s,
                    )
                if self.lost_on_stall:
                    # supervised replica (ISSUE 9): a stalled chunk is a
                    # replica-level loss — victims requeue onto surviving
                    # replicas instead of dying with StallTimeout, and
                    # the supervisor restarts this replica. The hook's
                    # "stall" event walks the pool's health machine
                    # through suspect before "lost" declares death.
                    hook = self.health_hook
                    if hook is not None:
                        hook("stall", self.stall_timeout_s)
                    self._mark_lost_locked(
                        "chunk fetch exceeded the "
                        f"{self.stall_timeout_s:.1f}s stall timeout"
                    )
                    continue
                for s in self._streams:
                    if s._joined and s._fetch_error is None:
                        s._fetch_error = faults.StallTimeout(
                            "batched chunk fetch exceeded the "
                            f"{self.stall_timeout_s:.1f}s stall timeout"
                        )
                        self._release_pins_locked(s)
                self._cond.notify_all()

    def new_stream(self) -> BatchStream:
        """Hand out the next slab row as an EngineStream-like serving lane."""
        with self._cond:
            if len(self._streams) >= self.b_max:
                raise ValueError(f"all {self.b_max} batch rows are allocated")
            s = BatchStream(self, len(self._streams))
            self._streams.append(s)
            return s

    # ------------------------------------------------------------------
    # Prefill dispatch (serialized with batched chunks via the cond lock:
    # every dispatch consumes and replaces the donated slab)
    # ------------------------------------------------------------------

    def _prefill_row(self, stream: BatchStream, tokens: np.ndarray):
        """Prefill ``tokens`` into ``stream``'s slab row. On an ADMISSION
        prefill (row position 0, prefix cache active, request not opted
        out) the radix tree is consulted first: the matched chain is BOUND
        to the row as its zero-copy page table (no bytes move) and only
        the unmatched suffix is dispatched — its attention reads the
        matched prefix straight out of the pool; the completed prefill's
        full pages are then published back into the tree. Returns
        ``(logits, last)`` — the final dispatch's device logits and the
        index of the last REAL token's row within them."""
        engine = self.engine
        n = tokens.shape[0]
        if self._lost:
            # a request placed on this replica just before it died: fail
            # typed BEFORE touching the slab — the serving layer requeues
            # it onto a surviving replica (no bytes were dispatched)
            raise faults.ReplicaLost(
                f"replica {self.replica_id} lost: {self.lost_cause}"
            )
        if n == 0:
            raise ValueError("empty token batch: at least one token required")
        if stream.pos + n > engine.cfg.seq_len:
            raise ValueError(
                f"context overflow: pos {stream.pos} + {n} > {engine.cfg.seq_len}"
            )
        admission = (
            self._prefix is not None
            and stream.pos == 0
            and stream.prefix_cache_enabled
        )
        chain: list = []
        suffix = tokens
        if admission:
            chain = self._match_alias(stream, tokens)
            if chain:
                suffix = tokens[len(chain) * self._prefix.page :]
        try:
            logits, last = self._dispatch_prefill_chunks(stream, suffix)
        except BaseException:
            # a failed suffix prefill fails the request: unwind the alias
            # bind (release the chain pins, reset the position) so the
            # row is clean for its next occupant and the pages evictable
            if chain:
                self._release_row_pins(stream)
                stream.pos = 0
            raise
        if admission:
            self._publish_row(stream, tokens, chain)
        return logits, last

    def _dispatch_prefill_chunks(self, stream: BatchStream, tokens: np.ndarray):
        """Dispatch a (suffix-offset) prompt at ``stream.pos``, chunked at
        ``prefill_chunk`` tokens: the scheduler lock is released between
        chunk dispatches so other rows' decode chunks interleave with a
        long prefill (Sarathi-style) instead of queueing behind the whole
        prompt. Returns (device logits of the final dispatch, index of the
        last real token's logits row)."""
        engine = self.engine
        n = tokens.shape[0]
        step = self.prefill_chunk if self.prefill_chunk > 0 else n
        logits = None
        off = 0
        c = n
        while off < n:
            if stream._fetch_error is not None:
                # a preemption (or watchdog/quarantine) that landed between
                # prefill chunks: stop dispatching this prompt — the chunk
                # boundaries are the prefill's yield points for eviction
                # exactly as they are for deadlines below
                err = stream._fetch_error
                stream._fetch_error = None
                raise err
            if (
                stream.deadline is not None
                and time.monotonic() >= stream.deadline
            ):
                # the chunk boundaries are the prefill's deadline points
                # (PR 3 enforced pre-prefill and between decode chunks
                # only): an expired request must not keep dispatching its
                # remaining prompt against co-batched rows' decode
                raise faults.DeadlineExceeded(
                    f"deadline expired mid-prefill (row {stream.row}, "
                    f"{off}/{n} prompt tokens dispatched)"
                )
            c = min(step, n - off)
            bucket = _prefill_bucket(c)
            if stream.pos + bucket > engine.cfg.seq_len:
                bucket = c  # exact-length compile near the context limit
            padded = np.zeros(bucket, dtype=np.int32)
            padded[:c] = tokens[off : off + c]
            tr = stream.trace
            t0 = time.perf_counter() if tr is not None else 0.0
            with self._cond:
                try:
                    # whole-replica crash site (ISSUE 9): prefill chunk
                    # dispatches are round-trips too — a crash mid-prompt
                    # must fail over exactly like one mid-decode
                    self._faults.fire("replica.crash", row=self.replica_id)
                except Exception as e:
                    self._mark_lost_locked(f"injected crash at prefill: {e}")
                if self._lost:
                    err = stream._fetch_error or faults.ReplicaLost(
                        f"replica {self.replica_id} lost: {self.lost_cause}"
                    )
                    stream._fetch_error = None
                    raise err
                if self._pool is not None:
                    # pool-enabled scheduler: every prefill runs the paged
                    # program — an unaliased row dispatches with matched 0
                    # (pure slab reads, byte-identical to the plain one),
                    # so one compiled program serves hits and misses
                    table, matched = self._alias_row_arrays_locked(stream)
                    if engine._tp_engine is None:
                        logits, self._slab = _slab_prefill_single_paged(
                            engine.cfg, engine.params, jnp.asarray(padded),
                            self._slab, self._pool, jnp.int32(stream.row),
                            jnp.int32(stream.pos), jnp.int32(c), table, matched,
                        )
                    else:
                        logits, self._slab = engine._tp_engine.slab_forward_paged(
                            engine.params, jnp.asarray(padded), self._slab,
                            self._pool, stream.row, stream.pos, c, table,
                            matched,
                        )
                elif engine._tp_engine is None:
                    logits, self._slab = _slab_prefill_single(
                        engine.cfg, engine.params, jnp.asarray(padded), self._slab,
                        jnp.int32(stream.row), jnp.int32(stream.pos), jnp.int32(c),
                    )
                else:
                    logits, self._slab = engine._tp_engine.slab_forward(
                        engine.params, jnp.asarray(padded), self._slab,
                        stream.row, stream.pos, c,
                    )
                stream.pos += c
            off += c
            if tr is not None:
                # one child span per dispatched prompt chunk: the trace
                # shows exactly how a long prompt interleaved with other
                # rows' decode between these boundaries (ISSUE 16)
                tr.add_span(
                    "prefill_chunk", t0, time.perf_counter() - t0,
                    tokens=c, off=off - c, of=n, row=stream.row,
                )
        return logits, c - 1

    # ------------------------------------------------------------------
    # Prefix cache (ISSUE 4 + 7): admission-time match/alias-bind +
    # publish. Tree state, slab, pool and every row's alias state mutate
    # under the cond lock; the device programs themselves are async
    # dispatches whose ordering the device stream guarantees (a paged read
    # dispatched before a publish reads the pool version it was built
    # against — releasing pins mid-flight is therefore safe: any eviction/
    # republish only manifests as a LATER device program).
    # ------------------------------------------------------------------

    def _download_page(self, pid: int) -> list[np.ndarray]:
        """Host byte arrays of pool page ``pid`` across every layer and
        half, in the flat spill-entry layout (the PrefixCache eviction
        hook). One fused slice program + one pytree transfer: the read
        dispatches before any later publish can recycle the page id
        (device ordering keeps it exact), and the single blocking
        device_get bounds the scheduler-cond hold time per spill."""
        return list(jax.device_get(_slice_page(self._pool, jnp.int32(pid))))

    def _page_pytree(self, arrays: list) -> list:
        """Regroup a flat spill entry back into the per-layer (k, v)
        array-list pairs :func:`_upload_page` consumes. Raises on a layout
        mismatch (a spill entry from an incompatible config must fall
        back to a cold prefill, never upload misshapen bytes)."""
        halves: list[list] = []
        i = 0
        for pk, pv in self._pool:
            for half in (pk, pv):
                n = kvc.pool_page_arrays_per_half(half)
                halves.append(list(arrays[i : i + n]))
                i += n
        if i != len(arrays):
            raise ValueError(
                f"spill entry layout mismatch: {len(arrays)} arrays, "
                f"expected {i}"
            )
        return [(halves[2 * l], halves[2 * l + 1]) for l in range(len(self._pool))]

    def _reload_spilled_locked(self, tokens: np.ndarray) -> int:
        """Pull spilled pages of this prompt's prefix back into the pool
        BEFORE the radix match (cond held): the match then binds the
        reloaded chain zero-copy exactly like always-resident pages. The
        ``engine.spill`` chaos site fires per candidate block (``row=``
        selects the REPLICA id, like engine.sdc): a raise aborts the
        reload — already-uploaded blocks stay, deeper blocks prefill cold
        — and ``kind=corrupt`` flips arena bytes in place so the CRC gate
        must catch them (stale KV is never served)."""
        prefix = self._prefix

        def pre(chain_key):
            rule = self._faults.fires("engine.spill", row=self.replica_id)
            if rule is None:
                return
            if rule.kind == "corrupt":
                # silent in-arena corruption (a host RAM / disk bit flip):
                # nothing raises here — the reload's CRC verification is
                # the only thing standing between this and served-wrong-KV
                prefix.spill_corrupt(chain_key)
            else:
                raise faults.InjectedFault(
                    rule.message or "injected fault at engine.spill"
                )

        def upload(pid, arrays):
            with self.engine._tel.span("prefix_spill_reload", page=int(pid)):
                # the closure runs SYNCHRONOUSLY inside prefix.reload,
                # still under _reload_spilled_locked's cond — the AST
                # can't see through the callback boundary
                self._pool = _upload_page(  # dllama: noqa[LCK-004]
                    self._pool, jnp.int32(pid), self._page_pytree(arrays)
                )

        return prefix.reload(tokens, upload, pre=pre)

    def _match_alias(self, stream: BatchStream, tokens: np.ndarray) -> list:
        """Walk the radix tree for the prompt's longest published prefix
        and bind it to the row ZERO-COPY: the row records the chain's page
        ids as its page table and advances its position past the matched
        tokens — no bytes move; the suffix prefill's (and every later
        step's) attention reads the pages through the table. The chain's
        refs stay held for the row's lifetime. With a spill arena, pages
        of this prefix that were evicted to host RAM (by this replica or
        a peer) are re-uploaded first, so the match sees the full
        reloadable chain."""
        prefix = self._prefix
        tr = stream.trace
        t0 = time.perf_counter() if tr is not None else 0.0
        reloaded = 0
        with self._cond:
            # unwind any stale alias left by a caller that skipped reset
            self._release_pins_locked(stream)
            if prefix.spill is not None and not self._lost:
                # a dead replica must not re-announce chains to the shared
                # index after the pool dropped its ownership
                reloaded = self._reload_spilled_locked(tokens)
            chain = prefix.match(tokens)
            if tr is not None:
                # admission-time cache outcome in the request's own tree:
                # how much prompt the match skipped, and how many spilled
                # pages had to re-upload to get there (ISSUE 16)
                tr.add_span(
                    "prefix_match", t0, time.perf_counter() - t0,
                    matched_tokens=len(chain) * prefix.page,
                    pages=len(chain), reloaded_pages=reloaded,
                )
            if not chain:
                return []
            stream._alias_chain = chain
            stream._alias_ids = [nd.page_id for nd in chain]
            stream.matched_len = len(chain) * prefix.page
            stream.pos = stream.matched_len
        return chain

    def _publish_row(self, stream: BatchStream, tokens: np.ndarray, chain: list) -> None:
        """Publish the admission prefill's completed full pages back into
        the tree (blocks beyond the matched chain) — the ONLY copy in the
        zero-copy design: the row's private suffix KV becomes immutable
        shared pages. The matched chain's refs are NOT released here: the
        row keeps reading those pages through its table until it resets,
        quarantines or rolls back below them."""
        prefix = self._prefix
        page = prefix.page
        with self._cond:
            if self._lost:
                # the replica died between the last suffix chunk and here:
                # a publish now would re-announce chains to the shared
                # index AFTER the pool dropped this replica's ownership
                # (dangling routing); the request's own ReplicaLost
                # surfaces at its next chunk boundary
                return
            new_ids, new_blocks = prefix.publish(tokens, tokens.shape[0], chain)
            if new_ids:
                bucket = _page_bucket(len(new_ids))
                ids = np.full(bucket, prefix.capacity, np.int32)  # pad drops
                src = np.zeros(bucket, np.int32)
                ids[: len(new_ids)] = new_ids
                src[: len(new_ids)] = new_blocks
                with self.engine._tel.span(
                    "prefix_publish", pages=len(new_ids), batch_row=stream.row
                ):
                    try:
                        if self.engine._tp_engine is None:
                            self._pool = _publish_pages(
                                page, self._slab, self._pool, jnp.asarray(ids),
                                jnp.asarray(src), jnp.int32(stream.row),
                            )
                        else:
                            self._pool = self.engine._tp_engine.publish_pages(
                                self._slab, self._pool, ids, src, stream.row,
                            )
                    except BaseException as e:
                        # the copy never dispatched: the just-inserted
                        # nodes map blocks to pages holding garbage (or
                        # a recycled prefix's stale bytes) — detach them
                        # or every future match serves wrong KV. The
                        # REQUEST is fine (its prefill completed):
                        # publishing is an optimization, so swallow
                        # everything except interpreter exits
                        prefix.unpublish(tokens, new_ids, new_blocks)
                        if not isinstance(e, Exception):
                            raise
                        print(f"⚠️ prefix publish failed; pages unwound: {e}")

    # ------------------------------------------------------------------
    # Zero-copy alias lifetime (ISSUE 7): pins released at reset/
    # quarantine, truncated on rollback; page tables materialized per
    # dispatch under the cond lock.
    # ------------------------------------------------------------------

    def _release_pins_locked(self, stream: BatchStream) -> None:
        """Release ``stream``'s page pins and clear its table (cond held).
        Idempotent — quarantine and the subsequent reset both call it."""
        if stream._alias_chain and self._prefix is not None:
            self._prefix.release(stream._alias_chain)
        stream._alias_chain = []
        stream._alias_ids = []
        stream.matched_len = 0

    def _release_row_pins(self, stream: BatchStream) -> None:
        if not stream._alias_chain:
            # nothing pinned (the common miss/reset case): no lock needed —
            # only this row's owner thread binds/clears its alias state
            stream._alias_ids = []
            stream.matched_len = 0
            return
        with self._cond:
            self._release_pins_locked(stream)

    def _truncate_alias(self, stream: BatchStream, pos: int) -> None:
        """Shrink ``stream``'s alias to ``pos`` after a rollback below its
        matched prefix: positions < pos keep reading the pool (a rollback
        lands on a shared TOKEN prefix, so those pages' bytes stay the
        right KV), pages wholly at or beyond ``pos`` lose their pins. The
        next prefill writes the slab from ``pos`` up, and the per-position
        select reads it there."""
        with self._cond:
            if stream.matched_len <= pos:
                return
            if self._prefix is not None:
                keep = -(-pos // self._prefix.page)  # pages covering [0, pos)
                drop = stream._alias_chain[keep:]
                if drop:
                    self._prefix.release(drop)
                stream._alias_chain = stream._alias_chain[:keep]
                stream._alias_ids = stream._alias_ids[:keep]
            stream.matched_len = pos

    def _fire_paged_attn_locked(self, joined):
        """The ``engine.paged_attn`` fault site (chaos contract), fired per
        joined row while a paged batched chunk — plain decode OR spec
        verify — is built: a row-targeted raise quarantines ONLY the
        victim, releases its page pins (the aliased pages stay live for
        every other reader) and drops it from the dispatch; survivors
        proceed bit-identically. Returns the surviving rows (those already
        retired by an earlier failure filtered out too — they ride the
        bucket masked-inactive: no cache write, no advance, no delivery)."""
        if self._pool is not None:
            for s in joined:
                try:
                    self._faults.fire("engine.paged_attn", row=s.row)
                except Exception as e:
                    err = faults.RowQuarantined(
                        "batch row retired: paged-attention dispatch failed "
                        "for this row"
                    )
                    err.__cause__ = e
                    s._fetch_error = err
                    self._release_pins_locked(s)
                    self.engine._tel.rows_quarantined.inc()
        return [s for s in joined if s._fetch_error is None]

    def _fire_fused_step_locked(self, joined):
        """The ``engine.fused_step`` fault site (ISSUE 17 chaos contract):
        fired per joined row while a batched chunk — plain decode OR spec
        verify — is about to launch the fused per-layer superstep programs
        (rmsnorm→Q80→matmul epilogue, fused paged attention, the
        matmul+all-reduce seam). A row-targeted raise mid-superstep
        quarantines ONLY the victim, releases any page pins it holds, and
        drops it from the dispatch; the survivors' streams must be
        bit-identical to a fault-free run — one row's fused program
        failing must never corrupt the shared dispatch."""
        for s in joined:
            try:
                self._faults.fire("engine.fused_step", row=s.row)
            except Exception as e:
                err = faults.RowQuarantined(
                    "batch row retired: fused superstep dispatch failed "
                    "for this row"
                )
                err.__cause__ = e
                s._fetch_error = err
                if self._pool is not None:
                    self._release_pins_locked(s)
                self.engine._tel.rows_quarantined.inc()
        return [s for s in joined if s._fetch_error is None]

    def _alias_arrays_locked(self, rows, live_flags):
        """Per-dispatch page tables [len(rows), n_table] + matched lengths
        (cond held; ``live_flags`` is :meth:`_row_dispatch_arrays_locked`'s
        liveness list — the ONE definition — not re-derived here): LIVE
        rows without an alias (a miss, or retired mid-build) get matched 0
        — the paged program reads their slab rows only, byte-identical to
        the unpaged dispatch. Bucket-padding rows (not joined: outputs
        discarded, cache writes dropped) instead get the max LIVE matched
        length, so a partially-occupied bucket never drags
        ``paged_segments``' pool-only bound down to the mixed path (which
        reads pool AND slab for every row) — their zero tables read pool
        page 0 garbage, which nothing observes."""
        tables = np.zeros((len(rows), self._n_table), np.int32)
        matched = np.zeros(len(rows), np.int32)
        live = np.array(live_flags, bool)
        for b, s in enumerate(rows):
            if live[b] and s._alias_ids:
                tables[b, : len(s._alias_ids)] = s._alias_ids
                matched[b] = s.matched_len
        if live.any():
            matched[~live] = matched[live].max()
        return jnp.asarray(tables), jnp.asarray(matched)

    def _row_dispatch_arrays_locked(self, rows):
        """Per-row arrays shared by the plain-decode and spec-verify chunk
        builders (cond held): the liveness predicate plus positions /
        active mask / sampling params / PRNG keys, inert defaults in
        non-live slots (bucket padding, or rows retired mid-build), and
        the zero-copy alias arrays when the pool is on (None otherwise).
        One definition so a lifecycle change to what counts as a live row
        can never reach one dispatch path and skip the other."""
        live = [s._joined and s._fetch_error is None for s in rows]
        pos = jnp.asarray(
            [s.pos if ok else 0 for s, ok in zip(rows, live)], jnp.int32
        )
        active = jnp.asarray(live, bool)
        temps = jnp.asarray(
            [s._temperature if ok else 1.0 for s, ok in zip(rows, live)], jnp.float32
        )
        topps = jnp.asarray(
            [s._topp if ok else 0.9 for s, ok in zip(rows, live)], jnp.float32
        )
        topks = jnp.asarray(
            [s._topk if ok else 0 for s, ok in zip(rows, live)], jnp.int32
        )
        seeds = jnp.asarray(
            [s._seed32 if ok else 0 for s, ok in zip(rows, live)], jnp.uint32
        )
        tables = matched = None
        if self._pool is not None:
            tables, matched = self._alias_arrays_locked(rows, live)
        return live, pos, active, temps, topps, topks, seeds, tables, matched

    def _alias_row_arrays_locked(self, stream: BatchStream):
        """Single-row form of :meth:`_alias_arrays_locked` (the chunked
        prefill dispatch)."""
        table = np.zeros(self._n_table, np.int32)
        table[: len(stream._alias_ids)] = stream._alias_ids
        return jnp.asarray(table), jnp.int32(stream.matched_len)

    def check_prefix(self) -> None:
        """Tree invariants extended with alias tracking: no page freed or
        unpinned while any live row's table references it (tests, bench
        chaos gate)."""
        with self._cond:
            if self._prefix is not None:
                self._prefix.check(
                    row_pages=[
                        list(s._alias_ids) for s in self._streams if s._alias_ids
                    ]
                )

    # ------------------------------------------------------------------
    # Join/leave (between chunks; the cond lock makes the active set
    # coherent per dispatch)
    # ------------------------------------------------------------------

    def _join(
        self, stream: BatchStream, first_token, temperature, topp, seed, topk
    ) -> None:
        from distributed_llama_tpu import prng

        with self._cond:
            stream._first = first_token
            stream._temperature = float(temperature)
            stream._topp = float(topp)
            stream._topk = int(topk)
            stream._seed32 = prng.fold_seed(seed)
            stream._queue.clear()
            stream._epoch += 1
            stream._joined = True
            stream._chunk_fps = []
            if not isinstance(
                stream._fetch_error, (faults.RowPreempted, faults.ReplicaLost)
            ):
                # stale errors from a previous occupancy clear; a PREEMPTION
                # or REPLICA LOSS that landed between this request's prefill
                # and its decode join must survive the join (the first
                # next_token raises it and the request requeues). Cross-
                # request staleness is impossible: the serving layer
                # retracts an unconsumed preemption when each request ends
                # (retract_preemption), and a lost replica never seats a
                # new request (placement skips dead replicas)
                stream._fetch_error = None
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # Priority preemption (ISSUE 8): evict the lowest-priority active row
    # for a strictly-higher-priority arrival. The victim is retired with a
    # typed RowPreempted exactly like a deadline expiry — between chunks,
    # co-batched rows untouched — and the serving layer REQUEUES it: its
    # admission prefill published its prefix pages, so the re-run prefills
    # through the prefix cache and (same seed) streams bit-identically to
    # an uncontended run.
    # ------------------------------------------------------------------

    def min_preemptible_priority(self) -> int | None:
        """Lowest priority among this scheduler's currently evictable rows
        (None when nothing is evictable): the replica pool ranks replicas
        by this so a multi-replica preemption evicts the GLOBALLY
        lowest-priority victim, not the first replica's local minimum
        (server/replicas.py ``preempt_below``)."""
        with self._cond:
            prios = [
                s.priority for s in self._streams
                if s.priority is not None and s._fetch_error is None
            ]
            return min(prios) if prios else None

    def preempt_below(self, priority: int) -> bool:
        """Evict the lowest-priority active row whose priority is strictly
        below ``priority`` (ties: least-progressed row — the cheapest
        restart). Returns True when a row was cleanly evicted. The
        ``engine.preempt`` chaos site fires on the chosen victim: an
        injected raise QUARANTINES it (typed failure, survivors
        bit-identical) instead of requeueing it.

        Page pins are NOT released here, on purpose: the victim may be
        mid-admission-prefill, and dropping its alias table under the
        cond while its final suffix chunk is still dispatching would make
        that chunk attend over never-written slab positions (matched
        reads 0) and then publish the corrupted KV into the shared radix
        tree. Leaving the table intact keeps every in-flight dispatch —
        and any subsequent publish — byte-correct; the pins release
        through the victim's own unwind exactly like a deadline expiry's:
        the prefill-boundary raise unwinds the alias bind in
        _prefill_row, and a mid-decode victim's pins fall at the row's
        next reset/_match_alias (stale-alias reclaim)."""
        engine = self.engine
        with self._cond:
            victims = [
                s for s in self._streams
                if s.priority is not None
                and s.priority < priority
                and s._fetch_error is None
            ]
            if not victims:
                return False
            victim = min(victims, key=lambda s: (s.priority, s.pos))
            injected: Exception | None = None
            try:
                self._faults.fire("engine.preempt", row=victim.row)
            except Exception as e:
                injected = e
            if injected is None:
                err: BaseException = faults.RowPreempted(
                    f"row {victim.row} (tenant {victim.tenant!r}, priority "
                    f"{victim.priority}) preempted by a priority-{priority} "
                    "arrival; requeued through fair admission"
                )
                self.preempted_total += 1
                engine._tel.preemptions.inc()
            else:
                err = faults.RowQuarantined(
                    "batch row retired: preemptive eviction failed for "
                    "this row"
                )
                err.__cause__ = injected
                engine._tel.rows_quarantined.inc()
            victim._fetch_error = err
            self._cond.notify_all()
            return injected is None

    def retract_preemption(self, stream: BatchStream) -> None:
        """Drop an UNCONSUMED preemption marker at request end (the victim
        finished before its next_token could raise): without this, a
        RowPreempted surviving _join could leak into the row's next
        request and requeue it spuriously."""
        with self._cond:
            if isinstance(stream._fetch_error, faults.RowPreempted):
                stream._fetch_error = None

    def _leave(self, stream: BatchStream) -> None:
        with self._cond:
            if not stream._joined and not stream._queue:
                return
            stream._joined = False
            stream._queue.clear()
            stream._epoch += 1
            self._cond.notify_all()
        # a request that stopped at its fused first token (immediate EOS)
        # may leave its kicked chunk dispatched-but-unfetched; if no joined
        # stream remains to fetch it, drain it now — otherwise the engine
        # pipeline depth stays held across the idle period and the transfer
        # probe treats the engine as permanently mid-flight
        self._drain_if_idle()

    def _begin_fetch_locked(self) -> int:
        """Mark a fetch in flight (cond lock held) and return its
        generation — the token the watchdog invalidates on a stall."""
        self._fetching = True
        self._fetch_gen += 1
        self._fetch_started = time.monotonic()
        return self._fetch_gen

    def _drain_if_idle(self) -> None:
        pend = gen = None
        with self._cond:
            if (
                self._pending is not None
                and not self._fetching
                and not any(s._joined for s in self._streams)
            ):
                pend = self._pending
                self._pending = None
                gen = self._begin_fetch_locked()
        if pend is not None:
            self._fetch(pend, gen)

    def kick(self) -> None:
        """Dispatch a batched chunk now if none is in flight (used to start
        chunk 1 before the fused first-token fetch so the fetch overlaps
        the chunk's compute)."""
        with self._cond:
            if self._pending is None:
                self._dispatch_locked()

    # ------------------------------------------------------------------
    # The pump: dispatch under the lock, fetch outside it
    # ------------------------------------------------------------------

    def next_token(self, stream: BatchStream) -> int:
        """Next decoded token for ``stream``; whichever thread runs dry
        first dispatches/fetches the shared batched chunk for everyone.
        Raises the stream's typed failure (RowQuarantined / StallTimeout)
        when its row was retired, and DeadlineExceeded once the request's
        deadline passes — the expired row leaves the batch between chunks
        (stream_decode's finally) without touching its co-batched rows."""
        while True:
            pend = gen = None
            with self._cond:
                if stream._fetch_error is not None:
                    err = stream._fetch_error
                    stream._fetch_error = None
                    raise err
                if (
                    stream.deadline is not None
                    and time.monotonic() >= stream.deadline
                ):
                    raise faults.DeadlineExceeded(
                        f"request deadline expired mid-decode (row "
                        f"{stream.row}); the row leaves the batch"
                    )
                if stream._queue:
                    return stream._queue.popleft()
                if not stream._joined:
                    raise RuntimeError("next_token on a stream that left the batch")
                if self._pending is None:
                    # dispatch even while another thread is mid-fetch: the
                    # next chunk's compute then overlaps the fetch round
                    # trip (the batched analogue of generate_chunks'
                    # speculative pipelining; at most ONE chunk runs ahead
                    # — the single pending slot bounds it)
                    self._dispatch_locked()
                    if stream._fetch_error is not None:
                        continue  # the dispatch retired this row: re-loop
                        # raises the typed error without a wait cycle
                if self._pending is not None and not self._fetching:
                    pend = self._pending
                    self._pending = None
                    gen = self._begin_fetch_locked()
                else:
                    # another thread is mid-fetch: wait for its notify
                    self._cond.wait(timeout=0.1)
                    continue
            self._fetch(pend, gen)

    def _run_dispatch_locked(self, joined, dispatch_fn, fail_msg: str):
        """The shared dispatch frame of the chunk and spec-verify paths
        (cond lock held): raise the pipeline depth (released when the fetch
        drains), run ``dispatch_fn`` under the bounded retry-with-backoff
        loop (``batch.dispatch`` fault hook fired per attempt), and on
        exhausted retries retire every joined row CLEANLY with a typed
        ``fail_msg`` quarantine — no position advanced, the scheduler keeps
        serving. Returns ``dispatch_fn``'s result, or None after retiring
        the rows. KeyboardInterrupt/SystemExit release the depth and
        propagate (they must abort, not retry into quarantines)."""
        engine = self.engine
        try:
            # whole-replica crash site (ISSUE 9): NOT transient — no retry,
            # no per-row quarantine. The scheduler is lost wholesale and
            # every in-flight request requeues onto a surviving replica.
            self._faults.fire("replica.crash", row=self.replica_id)
        except Exception as e:
            self._mark_lost_locked(f"injected crash at dispatch: {e}")
            return None
        # silent-data-corruption site (ISSUE 10): kind=corrupt perturbs
        # this replica's weights (or the next fetched chunk's tokens) into
        # FINITE wrong values — nothing raises, nothing quarantines; only
        # the integrity layer (canary golden / shadow vote) can see it
        self._fire_sdc_locked()
        with engine._depth_lock:
            engine._pipeline_depth += 1  # released when the fetch drains
        result = None
        error: Exception | None = None

        def attempt_once():
            self._faults.fire("batch.dispatch")
            return dispatch_fn()

        try:
            # transient failures (an injected dispatch raise, a flaky
            # runtime) retry on the shared backoff policy
            # (distributed_llama_tpu/retry.py — same base*2**attempt
            # schedule the old inline loop slept). Briefly blocking joins
            # is the cost of a coherent active set: the bounded
            # retries*backoff sleep inside retry_call is the one
            # sanctioned block under this lock.
            result = retry.retry_call(  # dllama: noqa[LCK-002]
                attempt_once, self._retry_policy,
                on_retry=lambda a, e: engine._tel.dispatch_retries.inc(),
            )
        except Exception as e:
            error = e
        except BaseException:
            with engine._depth_lock:
                engine._pipeline_depth -= 1
            raise
        if error is not None:
            with engine._depth_lock:
                engine._pipeline_depth -= 1
            tel = engine._tel
            tel.rows_quarantined.inc(len(joined))
            flight.record(
                self.replica_id, "rows_quarantined",
                rows=[s.row for s in joined], where="dispatch",
                error=type(error).__name__,
            )
            for s in joined:
                err = faults.RowQuarantined(fail_msg)
                err.__cause__ = error
                s._fetch_error = err
                self._release_pins_locked(s)
            self._cond.notify_all()
            return None
        return result

    def _fire_sdc_locked(self) -> None:
        """The ``engine.sdc`` chaos site (ISSUE 10), fired per batched
        dispatch with ``row=`` selecting the REPLICA id. A ``kind=corrupt``
        rule injects the silent-data-corruption class every other site
        cannot model: ``message=weights`` (the default) deterministically
        perturbs one weight slice of this replica's engine IN PLACE
        (every later decode emits plausible wrong tokens until the canary
        kills the replica and the supervisor rebuilds + checksum-verifies
        it); ``message=logits`` arms a one-chunk in-vocab token
        perturbation applied at the next fetch delivery."""
        rule = self._faults.fires("engine.sdc", row=self.replica_id)
        if rule is None or rule.kind != "corrupt":
            return
        if (rule.message or "weights") == "logits":
            self._sdc_logits_pending += 1
            return
        engine = self.engine
        engine.params, desc = integrity.corrupt_params(
            engine.params, seed=getattr(self._faults, "seed", 0)
        )
        print(
            f"🧬 engine.sdc injected on replica {self.replica_id}: "
            f"corrupted {desc}"
        )

    def _dispatch_locked(self) -> None:
        """Build and dispatch one batched chunk from the joined streams
        (cond lock held; the dispatch itself is asynchronous). Rows inside
        the bucket that are not joined ride along masked-inactive: their
        cache writes DROP and their outputs are discarded. In spec mode the
        chunk is a batched VERIFY step instead (``_dispatch_spec_locked``)."""
        engine = self.engine
        if self._lost:
            return  # every stream already carries its ReplicaLost
        if self.spec_draft > 0:
            self._dispatch_spec_locked()
            return
        joined = [s for s in self._streams if s._joined]
        if not joined:
            return
        joined = self._fire_paged_attn_locked(joined)
        joined = self._fire_fused_step_locked(joined)
        if not joined:
            self._cond.notify_all()
            return
        bucket = decode_bucket(
            max(max(s.row for s in joined) + 1, self._bucket_floor), self.b_max
        )
        rows = self._streams[:bucket]
        live, pos, active, temps, topps, topks, seeds, tables, matched = (
            self._row_dispatch_arrays_locked(rows)
        )
        first = jnp.stack(
            [jnp.asarray(s._first if ok else 0, jnp.int32) for s, ok in zip(rows, live)]
        )
        sw = Stopwatch()

        def dispatch():
            with engine._tel.span(
                "batch_decode_chunk", bucket=bucket, active=len(joined),
                steps=self.chunk,
            ):
                from distributed_llama_tpu.models import sampling

                if engine._tp_engine is None:
                    if self._pool is not None:
                        out, self._slab = (
                            sampling.decode_chunk_batched_paged(
                                engine.cfg, engine.params, first, self._slab,
                                pos, active, self._pool, self.chunk, temps,
                                topps, topks, seeds, tables, matched,
                            )
                        )
                    else:
                        out, self._slab = sampling.decode_chunk_batched(
                            engine.cfg, engine.params, first, self._slab, pos,
                            active, self.chunk, temps, topps, topks, seeds,
                        )
                elif self._pool is not None:
                    out, self._slab = (
                        engine._tp_engine.batched_decode_chunk_paged(
                            engine.params, first, self._slab, self._pool, pos,
                            active, self.chunk, temps, topps, topks, seeds,
                            tables, matched,
                        )
                    )
                else:
                    out, self._slab = (
                        engine._tp_engine.batched_decode_chunk(
                            engine.params, first, self._slab, pos, active,
                            self.chunk, temps, topps, topks, seeds,
                        )
                    )
            return out

        out = self._run_dispatch_locked(
            joined, dispatch,
            f"batched chunk dispatch failed after {self.retries + 1} "
            "attempts; this row's request was retired",
        )
        if out is None:
            return
        # the packed [chunk + 2, B] bundle: token rows 0..chunk-1 plus the
        # per-row fingerprint/finite rows (engine/integrity.py) — with the
        # stateless counter PRNG those int32 rows are the ONLY bytes the
        # chunk ever sends host-ward (no advanced keys return)
        for s in joined:
            # the next chunk seeds from this chunk's last token, which stays
            # device-resident (no fetch on the critical path); its coins
            # re-key from (seed, position) — nothing else carries over
            s._first = out[self.chunk - 1, s.row]
            s.pos += self.chunk
        if engine._tel.enabled:
            engine._tel.batch_occupancy.set(len(joined) / bucket)
        self._pending = (
            "chunk", out, [(s, s._epoch) for s in joined], bucket,
            len(joined), sw, None,
        )

    def _dispatch_spec_locked(self) -> None:
        """Build and dispatch one batched speculative VERIFY step (cond
        lock held): per joined row, up to ``spec_draft`` prompt-lookup
        draft tokens from the row's own history ride behind its previous
        token in a [bucket, k+1] feed window; one
        ``sampling.spec_verify_chunk_batched`` dispatch scores every row's
        window in a single weight read and accepts/rejects on device. Rows
        advance a VARIABLE number of positions — applied at fetch time,
        because the advance (and the next window's drafts) depend on the
        fetched results; spec steps therefore never pipeline a second
        dispatch behind an in-flight fetch."""
        engine = self.engine
        if self._lost:
            return  # every stream already carries its ReplicaLost
        if self._fetching:
            # the next window's drafts depend on THIS step's emitted
            # tokens: wait for the fetch instead of dispatching blind
            return
        joined = [s for s in self._streams if s._joined]
        if not joined:
            return
        joined = self._fire_paged_attn_locked(joined)
        joined = self._fire_fused_step_locked(joined)
        if not joined:
            self._cond.notify_all()
            return
        bucket = decode_bucket(
            max(max(s.row for s in joined) + 1, self._bucket_floor), self.b_max
        )
        rows = self._streams[:bucket]
        T = self.spec_draft + 1
        S = engine.cfg.seq_len
        feed = np.zeros((bucket, T), np.int32)
        lens = np.zeros(bucket, np.int32)
        live, pos, active, temps, topps, topks, seeds, tables, matched = (
            self._row_dispatch_arrays_locked(rows)
        )
        for s, ok in zip(rows, live):
            if not ok:
                continue
            feed[s.row, :] = int(s._first)  # pad tokens: overwritten KV
            # never draft past seq_len: the window writes pos..pos+T-1 and
            # out-of-bounds slots drop, but accepted positions must stay
            # inside the cache
            budget = max(0, min(self.spec_draft, S - s.pos - 1))
            if budget > 0 and s._spec_on:
                if s._drafter is None:
                    s._drafter = PromptLookupDrafter(
                        self.spec_draft, max_ngram=self.spec_ngram
                    )
                d = s._drafter.draft(s._history, limit=budget)
                if d:
                    feed[s.row, 1 : 1 + len(d)] = d
                    lens[s.row] = len(d)
        sw = Stopwatch()
        from distributed_llama_tpu.models import sampling

        def dispatch():
            with engine._tel.span(
                "spec_verify_chunk", bucket=bucket, active=len(joined),
                window=T,
            ):
                if self._pool is not None:
                    out, self._slab = (
                        sampling.spec_verify_chunk_batched_paged(
                            engine.cfg, engine.params, jnp.asarray(feed),
                            self._slab, pos, active, self._pool,
                            jnp.asarray(lens), temps, topps, topks, seeds,
                            tables, matched,
                        )
                    )
                else:
                    out, self._slab = sampling.spec_verify_chunk_batched(
                        engine.cfg, engine.params, jnp.asarray(feed),
                        self._slab, pos, active, jnp.asarray(lens), temps,
                        topps, topks, seeds,
                    )
            return out

        out = self._run_dispatch_locked(
            joined, dispatch,
            f"batched verify dispatch failed after {self.retries + 1} "
            "attempts; this row's request was retired",
        )
        if out is None:
            return
        # pos/_first wait for the fetch (the advance is variable and
        # data-dependent); sampler coins re-key from (seed, position)
        tel = engine._tel
        if tel.enabled:
            tel.batch_occupancy.set(len(joined) / bucket)
            tel.spec_draft_tokens.inc(int(lens.sum()))
        self._pending = (
            "spec", out, [(s, s._epoch) for s in joined], bucket, len(joined),
            sw, lens.copy(),
        )

    def _fetch(self, pend, gen: int) -> None:
        """Blocking fetch of a dispatched chunk (no scheduler lock held);
        delivers each joined row's column into its stream queue. Transient
        fetch failures retry with backoff; a chunk whose tokens come back
        corrupted for ONE row (the NaN-logits class of failure — detected
        as out-of-vocab ids, injectable via the ``batch.row`` site)
        quarantines only that row, and the surviving rows' streams are
        delivered untouched — bit-identical to a fault-free run. The epoch
        check keeps a late fetch from feeding a row's NEXT occupant; the
        generation check keeps a watchdog-killed fetch from delivering at
        all."""
        engine = self.engine
        mode, tokens_dev, snapshot, bucket, n_active, sw, spec_lens = pend
        toks = None
        error: Exception | None = None

        def attempt_once():
            self._faults.fire("batch.fetch")
            # replica chaos (ISSUE 9): `slow` (kind=delay) stretches this
            # round-trip past the pool's suspect threshold, `hang`
            # (kind=hang) sleeps into the stall watchdog — escalated to a
            # whole-replica loss under lost_on_stall
            self._faults.fire("replica.slow", row=self.replica_id)
            self._faults.fire("replica.hang", row=self.replica_id)
            try:
                tokens_dev.copy_to_host_async()
            except Exception:
                pass  # optional acceleration; np.asarray is the contract
            with engine._tel.span("batch_decode_fetch", bucket=bucket):
                return np.asarray(tokens_dev)  # [chunk, bucket]

        try:
            # Exception only (retry_call's contract): a KeyboardInterrupt/
            # SystemExit mid-fetch must abort the process, not be retried
            # into quarantines
            toks = retry.retry_call(
                attempt_once, self._retry_policy,
                on_retry=lambda a, e: engine._tel.fetch_retries.inc(),
            )
        except Exception as e:
            error = e
        except BaseException:
            # a KeyboardInterrupt/SystemExit mid-fetch: release the in-flight
            # accounting (unless the watchdog already took it) and propagate
            with self._cond:
                owned = self._fetching and self._fetch_gen == gen
                if owned:
                    self._fetching = False
                    self._fetch_started = None
                    with engine._depth_lock:
                        engine._pipeline_depth -= 1
                self._cond.notify_all()
            raise
        # phase 1 of the completion claim (the watchdog declares stalls
        # under the same lock, so exactly one side — this fetch or the
        # watchdog — releases the depth hold and settles the rows):
        # clearing _fetch_started makes this fetch un-stallable, but
        # _fetching stays TRUE until the delivery block below — otherwise
        # another thread could take the pending speculative chunk N+1 and
        # deliver its tokens ahead of chunk N's during the stats window
        with self._cond:
            owned = self._fetching and self._fetch_gen == gen
            if owned:
                self._fetch_started = None
                with engine._depth_lock:
                    engine._pipeline_depth -= 1
        if not owned:
            # the watchdog retired this generation mid-fetch: the joined
            # rows already hold StallTimeout errors, the depth hold was
            # released on our behalf, and a newer fetch may be in flight —
            # deliver nothing
            with self._cond:
                self._cond.notify_all()
            return
        hook = self.health_hook
        if hook is not None and error is None:
            # dispatch→fetch round-trip heartbeat: the pool's health state
            # machine turns the replica SUSPECT past its threshold and
            # back HEALTHY on a fast round-trip (server/replicas.py)
            hook("roundtrip", sw.elapsed_s())
        if mode == "spec":
            self._deliver_spec(toks, snapshot, sw, spec_lens, error)
            self._drain_if_idle()
            return
        per_token_ms = sw.elapsed_ms() / self.chunk
        # the I/T split may trigger a transfer re-measurement (a device
        # round trip under TP) — run it BEFORE taking the scheduler
        # lock so a probe never blocks every lane's join/dispatch
        entry = engine._split_stats(per_token_ms)
        tel = engine._tel
        bad_rows: set[int] = set()
        nonfinite_rows: set[int] = set()
        fps = None
        if toks is not None:
            # unpack the [chunk + 2, B] bundle: tokens + per-row logit
            # fingerprint + finiteness flag (ONE fetch moved all three)
            toks, fps, finite = integrity.split_chunk_outputs(toks, self.chunk)
            with self._cond:
                if self._sdc_logits_pending > 0:
                    # engine.sdc message=logits: shift every token column
                    # in-vocab — finite, wrong, and INVISIBLE to the
                    # validation below; only a canary/shadow token
                    # comparison can see it (the fingerprint keeps its
                    # honest pre-corruption value on purpose: the logits
                    # themselves were clean)
                    self._sdc_logits_pending -= 1
                    toks = (toks + 1) % engine.cfg.vocab_size
            rule = self._faults.fires(
                "batch.row", rows=[s.row for s, _ in snapshot]
            )
            if (
                rule is not None
                and rule.row is not None
                and 0 <= rule.row < toks.shape[1]
            ):
                toks = toks.copy()
                toks[:, rule.row] = -1  # rejected by the validation below
            vocab = engine.cfg.vocab_size
            for s, _ in snapshot:
                # the device-side finiteness flag closes the sampled-path
                # hole (ISSUE 10 satellite): NaN logits pushed through the
                # softmax sampler can yield a perfectly in-vocab id the
                # vocab check below would wave through
                if not finite[s.row]:
                    nonfinite_rows.add(s.row)
                    continue
                col = toks[:, s.row]
                if not ((col >= 0) & (col < vocab)).all():
                    bad_rows.add(s.row)
        delivered = 0
        with self._cond:
            # phase 2: deliver and release fetch ownership in ONE block, so
            # the pending chunk N+1 can only be taken (and its tokens
            # queued) strictly after chunk N's tokens are in the queues
            self._fetching = False
            for s, epoch in snapshot:
                if not (s._joined and s._epoch == epoch):
                    continue
                if toks is None or s.row in bad_rows or s.row in nonfinite_rows:
                    # the row's tokens are lost/corrupt and its position
                    # already advanced at dispatch: retire THIS row with
                    # a typed error instead of emitting a silent token
                    # hole — and instead of the seed's poison-everyone
                    if s.row in nonfinite_rows:
                        err: faults.RowQuarantined = faults.NonFiniteLogits(
                            "batch row retired: decode produced non-finite "
                            "logits for this row (caught by the device-side "
                            "finiteness flag before a sampled token could "
                            "launder it in-vocab)"
                        )
                    else:
                        err = faults.RowQuarantined(
                            "batch row retired: chunk "
                            + (
                                f"fetch failed after {self.retries + 1} attempts"
                                if toks is None
                                else "produced corrupt tokens (NaN-logits "
                                "class failure)"
                            )
                        )
                    err.__cause__ = error
                    s._fetch_error = err
                    self._release_pins_locked(s)
                    tel.rows_quarantined.inc()
                    flight.record(
                        self.replica_id, "rows_quarantined", rows=[s.row],
                        where="fetch", error=type(err).__name__,
                    )
                    continue
                s._queue.extend(int(t) for t in toks[:, s.row])
                s._chunk_fps.append(int(fps[s.row]))
                s.stats.extend([entry] * self.chunk)
                delivered += 1
                if s.trace is not None:
                    # per-row child of the SHARED dispatch (ISSUE 16): one
                    # batched chunk fans out into each traced request's own
                    # tree, spanning dispatch → this delivery
                    s.trace.add_span(
                        "batch_decode_chunk_row", sw._t0, sw.elapsed_s(),
                        row=s.row, chunk=self.chunk, bucket=bucket,
                        co_batched=n_active,
                    )
                if tel.enabled:
                    tel.kv_occupancy.set(
                        min(s.pos / engine.cfg.seq_len, 1.0)
                    )
            self._cond.notify_all()
        if tel.enabled and delivered:
            tel.tokens_generated.inc(self.chunk * delivered)
            tel.device_sampled_tokens.inc(self.chunk * delivered)
            tel.decode_latency.observe(per_token_ms / 1000.0)
        # a chunk kicked WHILE this fetch was in flight may already be
        # orphaned (its kicker stopped at the fused first token and its
        # _leave-time drain skipped because _fetching was still true):
        # re-check the idle-drain condition now that the fetch is done —
        # the one-pending-slot invariant bounds the recursion.
        self._drain_if_idle()

    def _deliver_spec(self, toks, snapshot, sw, lens, error) -> None:
        """Deliver one fetched batched VERIFY step: row ``b``'s column is
        ``[n_emit, tokens...]`` — apply its VARIABLE position advance,
        extend its lookup history, and queue the emitted tokens. Runs with
        fetch ownership already claimed (``_fetch``); corrupt or
        chaos-targeted rows quarantine individually, survivors delivered
        bit-identically (the ``engine.spec_verify`` site's contract)."""
        engine = self.engine
        tel = engine._tel
        vocab = engine.cfg.vocab_size
        step_ms = sw.elapsed_ms()
        bad: dict[int, BaseException | None] = {}
        emits: dict[int, list[int]] = {}
        entries: dict[int, TokenStats] = {}
        if toks is not None:
            for s, _ in snapshot:
                # the chaos hook: a row-targeted raise quarantines ONLY this
                # row while its column is validated (outside the cond lock,
                # like the batch.row corruption hook)
                try:
                    self._faults.fire("engine.spec_verify", row=s.row)
                except Exception as e:
                    bad[s.row] = e
                    continue
                # validate against the row's OWN draft budget, not the
                # global window: a corrupt n_emit in (lens+1, T] would pass
                # a T bound (the token tail is zero-padded, in-vocab) and
                # advance pos past the dispatch-side seq_len clamp
                n_emit = int(toks[s.row, 0])
                if not 1 <= n_emit <= int(lens[s.row]) + 1:
                    bad[s.row] = None
                    continue
                col = toks[s.row, 1 : 1 + n_emit]
                if not ((col >= 0) & (col < vocab)).all():
                    bad[s.row] = None  # NaN-logits class corruption
                    continue
                emits[s.row] = [int(t) for t in col]
                # the I/T split may probe the device under TP — build every
                # row's stats entry BEFORE taking the scheduler lock, same
                # rule as the plain chunk delivery
                entries[s.row] = engine._split_stats(step_ms, n_tokens=n_emit)
        delivered_rows = 0
        delivered_tokens = 0
        with self._cond:
            self._fetching = False
            for s, epoch in snapshot:
                if not (s._joined and s._epoch == epoch):
                    continue
                if toks is None or s.row not in emits:
                    err = faults.RowQuarantined(
                        "batch row retired: verify "
                        + (
                            f"fetch failed after {self.retries + 1} attempts"
                            if toks is None
                            else "step failed or produced corrupt tokens"
                        )
                    )
                    err.__cause__ = error if toks is None else bad.get(s.row)
                    s._fetch_error = err
                    self._release_pins_locked(s)
                    tel.rows_quarantined.inc()
                    flight.record(
                        self.replica_id, "rows_quarantined", rows=[s.row],
                        where="spec_verify", error=type(err).__name__,
                    )
                    continue
                col = emits[s.row]
                n_emit = len(col)
                s.pos += n_emit  # the variable advance (deferred from dispatch)
                s._first = col[-1]  # host int: the next window's feed[0]
                s._history.extend(col)
                s._queue.extend(col)
                s.stats.append(entries[s.row])
                delivered_rows += 1
                delivered_tokens += n_emit
                if s.trace is not None:
                    # per-row child of the shared verify step (ISSUE 16);
                    # drafter_total = the request's lifetime drafted tokens
                    s.trace.add_span(
                        "spec_verify_row", sw._t0, sw.elapsed_s(),
                        row=s.row, drafted=int(lens[s.row]), emitted=n_emit,
                        drafter_total=(
                            s._drafter.drafted_total
                            if s._drafter is not None else 0
                        ),
                    )
                if tel.enabled:
                    tel.kv_occupancy.set(min(s.pos / engine.cfg.seq_len, 1.0))
                    tel.spec_accepted_tokens.inc(n_emit - 1)
                    if int(lens[s.row]) > 0:
                        tel.spec_acceptance.observe((n_emit - 1) / int(lens[s.row]))
                    tel.spec_step_advance.observe(n_emit)
            self._cond.notify_all()
        if tel.enabled and delivered_tokens:
            tel.tokens_generated.inc(delivered_tokens)
            tel.device_sampled_tokens.inc(delivered_tokens)
            tel.decode_latency.observe(
                step_ms * delivered_rows / delivered_tokens / 1000.0
            )
