"""Deterministic fault injection for chaos testing (ISSUE 3).

The reference distributed-llama assumes a fault-free world: a worker socket
error or a hung dispatch kills the whole root process (reference:
src/apps/dllama/dllama.cpp:418-423 — no error path at all). This module is
the opposite posture made testable: a process-wide :class:`FaultPlan` with
NAMED injection sites threaded through the engine, the batch scheduler, the
parallel backends and the API server, so chaos tests can provoke the exact
failure they want — deterministically, from a seed — and assert the system
degrades instead of collapsing.

Injection sites (the strings passed to :meth:`FaultPlan.fire`):

==================  =========================================================
``batch.dispatch``  raise inside the batched chunk dispatch
                    (engine/batch.py ``_dispatch_locked``; retried with
                    backoff before the rows are retired)
``batch.fetch``     raise/delay/hang inside the batched chunk fetch
                    (``_fetch``; a raise models a transfer error and is
                    retried, a hang trips the stall watchdog)
``batch.row``       corrupt ONE row of a fetched chunk (``kind=nan`` with a
                    ``row=``) — stands in for NaN logits from a single
                    sequence; the scheduler quarantines only that row
``engine.forward``  raise at any single-stream forward dispatch
``engine.decode_dispatch``  raise at a single-stream decode-chunk dispatch
``engine.fetch``    raise/delay at the single-stream chunk fetch
``engine.spec_verify``  raise at a speculative-decode verify step: fired at
                    the single-stream verify dispatch, and per row while a
                    batched verify's results are validated — a ``row=``
                    rule there quarantines ONLY the targeted row, its
                    co-batched survivors delivered bit-identically
                    (engine/batch.py ``_fetch``)
``engine.paged_attn``  raise at a zero-copy paged-attention dispatch: fired
                    per joined row while a paged batched chunk is built —
                    a ``row=`` rule quarantines ONLY the targeted row AND
                    releases its page pins (the aliased pages stay live
                    for every other row; survivors bit-identical)
``engine.fused_step``  raise mid-superstep (ISSUE 17): fired per joined
                    row as a batched chunk — plain decode or spec verify —
                    is about to launch the fused per-layer programs
                    (rmsnorm→Q80→matmul epilogue, fused paged attention,
                    the matmul+all-reduce seam). A ``row=`` rule
                    quarantines ONLY the victim and releases its page
                    pins; co-batched survivors stream bit-identically
                    (engine/batch.py ``_fire_fused_step_locked``)
``engine.sdc``      silent-data-corruption injection (ISSUE 10): a
                    ``kind=corrupt`` rule fired per batched-chunk dispatch
                    deterministically perturbs this replica's state into
                    FINITE-but-wrong values — ``message=weights`` (the
                    default) flips a weight slice in place so every
                    subsequent decode emits plausible wrong tokens,
                    ``message=logits`` perturbs the next fetched chunk's
                    token columns in-vocab. Neither NaNs nor raises: the
                    class the quarantine path cannot see, detectable only
                    by integrity checks (engine/integrity.py canaries /
                    fingerprints / shadow votes). ``row=`` selects the
                    REPLICA id, like the replica.* sites
``engine.spill``    spill-tier reload fault (ISSUE 11): fired per
                    candidate block while an admission match pulls
                    spilled prefix pages back from the host-RAM arena
                    (engine/spill.py). A raise aborts the reload —
                    already-uploaded blocks stay, deeper blocks fall
                    back to a COLD prefill; ``kind=corrupt`` flips the
                    arena entry's bytes in place (a silent host-RAM/disk
                    bit flip), which the per-entry CRC verification must
                    catch and drop — stale KV is never uploaded, the
                    block prefills cold. ``row=`` selects the REPLICA id
``engine.preempt``  raise during a priority preemption's eviction
                    (engine/batch.py ``preempt_below``): the victim row is
                    QUARANTINED instead of cleanly requeued — its request
                    fails typed, its page pins release through the row's
                    normal unwind, co-batched survivors stay bit-identical,
                    and the preemptor still admits once the quarantined
                    row's slot frees
``replica.crash``   whole-replica loss (ISSUE 9): fired per batched-chunk
                    AND per prefill-chunk dispatch — a raise marks the
                    ENTIRE scheduler lost (every in-flight request on it
                    gets a typed ``ReplicaLost``; the serving layer
                    requeues them through fair admission onto a surviving
                    replica and the supervisor restarts the dead one).
                    ``row=`` selects the REPLICA id, not a batch row
``replica.hang``    ``kind=hang`` sleep inside the batched chunk fetch:
                    the stall watchdog trips and — on a supervised replica
                    (``lost_on_stall``) — escalates the stall to a whole-
                    replica loss instead of per-row StallTimeout.
                    ``row=`` selects the replica id
``replica.slow``    ``kind=delay`` inside the batched chunk fetch: the
                    dispatch round-trip exceeds the replica pool's suspect
                    threshold and the replica turns SUSPECT (skipped for
                    new placements until a fast round-trip clears it).
                    ``row=`` selects the replica id
``tp.transfer``     raise/delay inside the transfer probe (the engine keeps
                    its last estimate instead of dying)
``server.send``     raise ``BrokenPipeError`` from the SSE chunk writer
                    (``kind=disconnect``) — models a client disconnect
``server.rollout``  blue-green rollout chaos (ISSUE 18): fired by the
                    rollout orchestrator once per replica MOVE, ``row=``
                    selecting the replica id. ``kind=corrupt`` perturbs
                    the freshly built new-version engine BEFORE the
                    checksum gate (the gate trips → automatic rollback);
                    ``kind=raise`` fails the move at the canary
                    certification step (a new-version golden mismatch →
                    rollback); ``kind=delay``/``hang`` widens the
                    cutover window so a composed ``replica.crash`` can
                    kill a replica MID-rollout (the supervisor rebuilds
                    on whatever version the state machine pins)
==================  =========================================================

Zero overhead when disabled — the same bind-once trick as telemetry:
components bind ``self._faults = faults.active_plan()`` at construction and
get the shared :data:`NULL_PLAN` singleton (no-op ``fire``/``fires``) when
no plan is installed. Hot paths pay one attribute-bound no-op call per
*dispatch*, never per token, and never touch this module's globals.
Install a plan BEFORE constructing the engine/scheduler/server.

Configuration
-------------
* env: ``DLLAMA_FAULTS="batch.fetch:kind=raise,after=2,count=1"`` (read once
  at import; ``DLLAMA_FAULTS_SEED`` seeds probabilistic rules), or
* flag: ``dllama-tpu-api --faults "<spec>"``, or
* code: ``faults.install(faults.parse(spec, seed=0))``.

A spec is ``;``-separated rules, each ``site:key=val,key=val`` (or a JSON
array of rule objects). Fields: ``kind`` (``raise`` | ``nan`` | ``delay`` |
``hang`` | ``disconnect``), ``after`` (skip the first N hits of the site),
``count`` (fire on this many subsequent hits; -1 = forever), ``p``
(per-hit probability, drawn from the seeded RNG), ``row`` (restrict to one
batch row), ``delay_ms`` (for ``delay``/``hang``). Full format and
semantics: docs/ROBUSTNESS.md.

Determinism: site-hit counters are lock-protected and count every hook
invocation, so ``after``/``count`` rules fire on exactly the same hits on
every run. ``p < 1`` rules draw from one seeded RNG in hit order — fully
reproducible for single-pump sites (the batch scheduler dispatch/fetch),
reproducible up to thread interleaving elsewhere.

Every actual injection increments ``dllama_faults_injected_total{site}``
(when telemetry is enabled) and the plan's plain ``injected_total``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
import time

from distributed_llama_tpu import lockcheck


class InjectedFault(RuntimeError):
    """Raised at an injection site by a ``kind=raise`` rule."""


class DeadlineExceeded(RuntimeError):
    """A request ran past its deadline: the row left the batch and the
    stream ends (the API server maps this to 504 / an SSE error event)."""


class RowQuarantined(RuntimeError):
    """This request's batch row was retired after a failed or corrupted
    chunk (bounded retries exhausted); co-batched rows keep streaming."""


class StallTimeout(RuntimeError):
    """The watchdog declared an in-flight batched chunk stalled and failed
    the batch cleanly (the hung fetch's late result is discarded)."""


class RowPreempted(RuntimeError):
    """This request's batch row was evicted by a higher-priority arrival
    (engine/batch.py ``preempt_below``). NOT a failure: the serving layer
    catches it and REQUEUES the request through weighted-fair admission —
    the re-run prefills through the prefix cache's published pages and,
    at the same seed, streams bit-identically to an uncontended run
    (already-sent SSE deltas are suppressed on replay)."""


class NonFiniteLogits(RowQuarantined):
    """A decode step produced NaN/Inf logits for this row (ISSUE 10): the
    device-side finiteness flag fetched with every batched chunk — or the
    host sampler's pre-sampling validation — caught it BEFORE a sampled
    token could launder the corruption into a plausible in-vocab id. The
    row is quarantined exactly like any corrupt chunk."""


class ReplicaLost(RuntimeError):
    """This request's WHOLE replica (engine + BatchScheduler) died — a
    crashed dispatch, or a hang the stall watchdog escalated (ISSUE 9).
    Like :class:`RowPreempted`, not a request failure: the serving layer
    requeues the request through weighted-fair admission onto a surviving
    replica and REPLAYS it — pinned sampling seed, already-sent SSE deltas
    suppressed, stream bit-identical to an unfaulted run — while the
    replica supervisor restarts the dead replica with jittered backoff
    (server/replicas.py; docs/ROBUSTNESS.md failure-domain table)."""


class ReplicaCorrupt(ReplicaLost):
    """This request's replica was declared dead for SILENT DATA CORRUPTION
    (a canary/shadow integrity mismatch, ISSUE 10) — wrong-but-finite
    outputs, not a crash. Crucially different from a plain
    :class:`ReplicaLost` for a stream that already sent deltas: those
    deltas may themselves be corrupt, so a suppressed replay would SPLICE
    a wrong prefix onto a correct continuation. The serving layer replays
    a ReplicaCorrupt victim only while nothing has streamed; otherwise the
    stream ends with a typed ``replica_corrupt`` error — loud failure
    instead of laundered corruption (server/api.py ``complete``)."""


KINDS = ("raise", "nan", "delay", "hang", "disconnect", "corrupt")

# The registered injection sites — the single source of truth the static
# analyzer's FLT-001 rule cross-checks against every fire()/fires() call
# site in the tree (an unregistered site can't be targeted by --faults
# specs; a registered-but-never-fired site is dead and gets flagged too).
# Keep this tuple and the docstring table above in sync when adding hooks.
SITES = (
    "batch.dispatch",
    "batch.fetch",
    "batch.row",
    "engine.forward",
    "engine.decode_dispatch",
    "engine.fetch",
    "engine.spec_verify",
    "engine.paged_attn",
    "engine.fused_step",
    "engine.preempt",
    "engine.sdc",
    "engine.spill",
    "replica.crash",
    "replica.hang",
    "replica.slow",
    "tp.transfer",
    "server.send",
    "server.rollout",
)

# a "hang" sleeps this long unless the rule sets delay_ms — far beyond any
# stall timeout, short enough that a daemon-threaded test process still exits
HANG_DEFAULT_MS = 60_000.0

# Fire observers (ISSUE 16): called on every ACTUAL injection with
# ``(site, rule, row)`` — the flight recorder's feed
# (telemetry/flight.py), so a dump can always name the chaos site behind
# a death. Observers run under the plan's lock and must only append to
# leaf-locked state; a failing observer is swallowed (chaos bookkeeping
# must never alter the injection it observes).
_fire_observers: list = []


def add_fire_observer(fn) -> None:
    if fn not in _fire_observers:
        _fire_observers.append(fn)


def remove_fire_observer(fn) -> None:
    if fn in _fire_observers:
        _fire_observers.remove(fn)


@dataclasses.dataclass
class FaultRule:
    """One injection rule. See the module docstring for field semantics."""

    site: str
    kind: str = "raise"
    after: int = 0
    count: int = 1
    p: float = 1.0
    row: int | None = None
    delay_ms: float = 0.0
    message: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (one of {KINDS})")
        if not self.site:
            raise ValueError("fault rule needs a site")


class FaultPlan:
    """A seeded set of :class:`FaultRule`\\ s plus the per-site hit
    counters that make ``after``/``count``/``p`` deterministic."""

    enabled = True

    def __init__(self, rules, seed: int = 0):
        self.rules: list[FaultRule] = list(rules)
        self.seed = int(seed)
        self._lock = lockcheck.make_lock("FaultPlan._lock")
        self._hits: dict[str, int] = {}
        self._fired: dict[int, int] = {}
        self._rng = random.Random(self.seed)
        self.injected_total = 0  # plain count: readable with telemetry off

    def reset(self) -> None:
        """Rewind the hit/fired counters and the RNG (same plan, fresh run)."""
        with self._lock:
            self._hits.clear()
            self._fired.clear()
            self._rng = random.Random(self.seed)

    def _match(
        self, site: str, row: int | None = None, rows=None
    ) -> FaultRule | None:
        with self._lock:
            hit = self._hits.get(site, 0)
            self._hits[site] = hit + 1
            for i, r in enumerate(self.rules):
                if r.site != site:
                    continue
                if hit < r.after:
                    continue
                fired = self._fired.get(i, 0)
                if r.count >= 0 and fired >= r.count:
                    continue
                if row is not None and r.row is not None and r.row != row:
                    continue
                if rows is not None and r.row is not None and r.row not in rows:
                    # the targeted row is not riding this hit (e.g. not in
                    # the current batch bucket): hold the rule WITHOUT
                    # consuming its count — it fires when the victim shows up
                    continue
                if r.p < 1.0 and self._rng.random() >= r.p:
                    continue
                self._fired[i] = fired + 1
                self.injected_total += 1
                for obs in _fire_observers:
                    try:
                        obs(site, r, row)
                    except Exception:
                        pass
                # resolved per injection, NOT bound at construction: an
                # env-installed plan exists before a --telemetry flag
                # enables the registry, and injections are rare enough
                # that the lookup costs nothing (telemetry off → null)
                from distributed_llama_tpu import telemetry

                telemetry.counter(
                    "dllama_faults_injected_total",
                    "Faults actually injected by the active chaos plan, "
                    "by site",
                    labelnames=("site",),
                ).labels(site=site).inc()
                return r
        return None

    def fire(self, site: str, row: int | None = None) -> FaultRule | None:
        """The hook call sites thread through the hot paths: raises for
        ``raise``/``disconnect`` rules, sleeps for ``delay``/``hang``,
        returns the matched rule (or None) otherwise."""
        rule = self._match(site, row=row)
        if rule is None:
            return None
        if rule.kind == "raise":
            raise InjectedFault(rule.message or f"injected fault at {site}")
        if rule.kind == "disconnect":
            raise BrokenPipeError(
                rule.message or f"injected client disconnect at {site}"
            )
        if rule.kind in ("delay", "hang"):
            ms = rule.delay_ms or (HANG_DEFAULT_MS if rule.kind == "hang" else 0.0)
            time.sleep(ms / 1000.0)
        return rule

    def fires(self, site: str, row: int | None = None, rows=None) -> FaultRule | None:
        """Non-raising variant for data-corruption sites (``kind=nan``):
        the call site applies the corruption itself from the returned rule.
        ``rows`` names the rows riding this hit — a row-targeted rule holds
        (count unconsumed) until its victim is present."""
        return self._match(site, row=row, rows=rows)


class _NullPlan:
    """Disabled-mode bind target: stateless no-op singleton (the faults
    analogue of telemetry's null instruments)."""

    __slots__ = ()
    enabled = False
    injected_total = 0

    def fire(self, site: str, row: int | None = None) -> None:
        return None

    def fires(self, site: str, row: int | None = None, rows=None) -> None:
        return None


NULL_PLAN = _NullPlan()

_active: FaultPlan | None = None


def install(plan: FaultPlan) -> FaultPlan:
    """Make ``plan`` the process-wide active plan. Components bind at
    construction — install BEFORE building the engine/scheduler/server."""
    global _active
    _active = plan
    return plan


def clear() -> None:
    global _active
    _active = None


def active_plan() -> FaultPlan | _NullPlan:
    """The bind-once entry point: the active plan, or the no-op singleton."""
    return _active if _active is not None else NULL_PLAN


_INT_FIELDS = ("after", "count", "row")
_FLOAT_FIELDS = ("p", "delay_ms")


def parse(spec: str, seed: int = 0) -> FaultPlan:
    """Parse a fault-plan spec: ``;``-separated ``site:key=val,key=val``
    rules, or a JSON array/object of rule fields (docs/ROBUSTNESS.md)."""
    spec = (spec or "").strip()
    rules: list[FaultRule] = []
    if spec.startswith("[") or spec.startswith("{"):
        data = json.loads(spec)
        if isinstance(data, dict):
            data = [data]
        rules = [FaultRule(**d) for d in data]
    else:
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            site, _, kvs = part.partition(":")
            kw: dict = {"site": site.strip()}
            for kv in filter(None, (x.strip() for x in kvs.split(","))):
                k, _, v = kv.partition("=")
                k, v = k.strip(), v.strip()
                if k in _INT_FIELDS:
                    kw[k] = int(v)
                elif k in _FLOAT_FIELDS:
                    kw[k] = float(v)
                elif k in ("kind", "message"):
                    kw[k] = v
                else:
                    raise ValueError(f"unknown fault-rule field {k!r}")
            rules.append(FaultRule(**kw))
    if not rules:
        raise ValueError(f"empty fault plan: {spec!r}")
    return FaultPlan(rules, seed=seed)


_ENV_VAR = "DLLAMA_FAULTS"
_env_spec = os.environ.get(_ENV_VAR, "").strip()
if _env_spec:
    install(parse(_env_spec, seed=int(os.environ.get("DLLAMA_FAULTS_SEED", "0") or 0)))
