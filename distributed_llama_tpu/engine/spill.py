"""Host-RAM (and optional disk) spill tiers below the device page pool.

The reference engine can already place its KV cache on an mmap'd
disc-backed buffer (``--kv-cache-storage disc``, ``newMmapFileBuffer`` —
reference: src/utils.cpp:50-67, src/app.cpp:105-106): capacity there is
bounded by the disc, not RAM. Our HBM page pool (PR 4/7) was strictly less
capable — ``--kv-pages`` was the end of the ladder, and the LRU evictor
DISCARDED pages that cost real prefill compute. This module adds the
missing rungs: an evicted page's bytes land in a bounded host-RAM arena
(re-uploading host bytes is orders of magnitude cheaper than re-prefilling
them), and the arena can demote its own LRU overflow to an mmap'd disk
file, echoing the reference's bottom rung.

Tier contract (engine/prefix_cache.py drives it):

* **Spill** — ``PrefixCache._evict_one`` downloads the victim page's bytes
  (data AND scales, verbatim, for i8 ``QuantizedKV``) and ``put``\\ s them
  here keyed by ``(owner replica, full token-prefix chain)``. The chain
  key makes entries exact: KV at a page's positions depends on every
  token before them, so only a request with the identical prefix may
  reload the bytes.
* **Reload** — an admission match that ran out of device-resident chain
  consults the arena: the owner's own entry is MOVED back to the device
  (``take`` — an entry must never be resident in the arena while its
  pages are live and pinned on the device, the :meth:`PrefixCache.check`
  invariant), another replica's entry is COPIED (``peek_shared`` — the
  cross-replica sharing path: the Zipf head spilled by replica A uploads
  into replica B without B ever prefilling it).
* **Integrity** — every entry carries a CRC of its bytes, verified on
  every read. Host RAM and disk are exactly the substrates silent
  corruption lives in (PR 10), and a corrupt reload would serve wrong KV
  to every future match of the chain: a CRC mismatch raises
  :class:`SpillCorrupt`, the caller drops the entry and falls back to a
  cold prefill (chaos-enforced via the ``engine.spill`` fault site).

Thread model: one arena is shared by every replica's scheduler (and the
pool's death handler), so the arena takes its own LEAF lock — it never
calls back into a scheduler or the pool. Numpy-only on purpose: the
device program that uploads/downloads page bytes belongs to the scheduler
(engine/batch.py); this module stores and checks bytes.
"""

from __future__ import annotations

import os
import threading
import zlib

import numpy as np

from distributed_llama_tpu import lockcheck, telemetry


class SpillCorrupt(RuntimeError):
    """A spilled entry's bytes no longer match their spill-time CRC: host
    RAM or disk corrupted them in place. The entry is already dropped when
    this raises — the caller's only correct move is a cold prefill."""


def _crc(arrays) -> int:
    c = 0
    for a in arrays:
        c = zlib.crc32(np.ascontiguousarray(a).tobytes(), c)
    return c


def _nbytes(arrays) -> int:
    return sum(int(a.nbytes) for a in arrays)


class _Entry:
    __slots__ = ("arrays", "nbytes", "crc", "last_use")

    def __init__(self, arrays, nbytes: int, crc: int, last_use: int):
        self.arrays = arrays
        self.nbytes = nbytes
        self.crc = crc
        self.last_use = last_use


class DiskTier:
    """Fixed-slot mmap'd spill file (the reference's ``newMmapFileBuffer``
    rung). Every spilled page serializes to the same byte length (one
    page's KV across all layers/halves is shape-static per config), so
    the file is a flat slot array: a free list, a key→slot map, and the
    per-slot CRC/LRU bookkeeping live on the host; the bytes live in the
    mmap. The first ``put`` fixes the entry template (shapes/dtypes);
    capacity = ``budget_bytes // entry_bytes`` slots."""

    def __init__(self, path: str, budget_bytes: int, on_drop=None):
        self.path = path
        self.budget = int(budget_bytes)
        self.on_drop = on_drop  # called with the evicted key (LRU overflow)
        self._mm = None
        self._template: list[tuple[tuple, np.dtype]] | None = None
        self.entry_bytes = 0
        self._slots: dict[tuple, tuple[int, int, int]] = {}  # key -> (slot, crc, last_use)
        self._free: list[int] = []
        self._clock = 0
        self.dropped_total = 0

    def _open(self, arrays) -> bool:
        self._template = [(a.shape, a.dtype) for a in arrays]
        self.entry_bytes = _nbytes(arrays)
        n_slots = self.budget // max(self.entry_bytes, 1)
        if n_slots < 1:
            return False  # budget below one entry: disk tier inert
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._mm = np.memmap(
            self.path, dtype=np.uint8, mode="w+",
            shape=(n_slots * self.entry_bytes,),
        )
        self._free = list(range(n_slots))
        return True

    def put(self, key: tuple, arrays, crc: int) -> bool:
        """Write one entry; evicts the LRU slot when full. Returns False
        when the entry cannot be stored (zero-capacity budget or a
        template mismatch — heterogeneous configs never share a file)."""
        if self._mm is None and self._template is None:
            if not self._open(arrays):
                return False
        if self._mm is None:
            return False
        if [(a.shape, a.dtype) for a in arrays] != self._template:
            return False
        old = self._slots.pop(key, None)
        if old is not None:
            self._free.append(old[0])
        if not self._free:
            lru = min(self._slots, key=lambda k: self._slots[k][2])
            self._free.append(self._slots.pop(lru)[0])
            self.dropped_total += 1
            if self.on_drop is not None:
                self.on_drop(lru)
        slot = self._free.pop()
        off = slot * self.entry_bytes
        for a in arrays:
            b = np.ascontiguousarray(a).view(np.uint8).reshape(-1)
            self._mm[off : off + b.size] = b
            off += b.size
        self._clock += 1
        self._slots[key] = (slot, crc, self._clock)
        return True

    def take(self, key: tuple, copy_only: bool = False):
        """Read (and unless ``copy_only`` remove) an entry; CRC-verified.
        Returns the array list or None; raises :class:`SpillCorrupt` on a
        CRC mismatch (the entry is dropped first)."""
        rec = self._slots.get(key)
        if rec is None:
            return None
        slot, crc, _ = rec
        off = slot * self.entry_bytes
        arrays = []
        for shape, dtype in self._template:
            n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            raw = np.array(self._mm[off : off + n])  # copy out of the mmap
            arrays.append(raw.view(dtype).reshape(shape))
            off += n
        if _crc(arrays) != crc:
            del self._slots[key]
            self._free.append(slot)
            raise SpillCorrupt(f"disk spill entry CRC mismatch for {key[0]}")
        if not copy_only:
            del self._slots[key]
            self._free.append(slot)
        else:
            self._clock += 1
            self._slots[key] = (slot, crc, self._clock)
        return arrays

    def drop(self, key: tuple) -> None:
        rec = self._slots.pop(key, None)
        if rec is not None:
            self._free.append(rec[0])

    def keys(self):
        return list(self._slots)

    def __len__(self) -> int:
        return len(self._slots)


class HostArena:
    """Bounded host-RAM spill arena shared across a pool's replicas.

    Keys are ``(owner, chain)``: ``owner`` is the spilling replica id and
    ``chain`` the full token-prefix tuple whose last page the entry holds.
    A budget overflow demotes the LRU entry to the :class:`DiskTier` when
    one is configured, else drops it (counted — silent truncation is how
    capacity claims rot). All methods are thread-safe; the internal lock
    is a LEAF (never calls out)."""

    def __init__(
        self, budget_bytes: int, disk_path: str | None = None,
        disk_budget_bytes: int = 0,
    ):
        self.budget = int(budget_bytes)
        self.disk = (
            DiskTier(disk_path, disk_budget_bytes, on_drop=self._on_disk_drop_locked)
            if disk_path and disk_budget_bytes > 0 else None
        )
        self._lock = lockcheck.make_lock("HostArena._lock")
        self._entries: dict[tuple, _Entry] = {}
        # chain -> owners with a resident entry (host OR disk): the
        # cross-replica peek and the corrupt-chaos hook look up by chain
        self._chains: dict[tuple, set[int]] = {}
        self._clock = 0
        self.resident_bytes = 0
        self.spilled_total = 0
        self.reloaded_total = 0
        self.dropped_total = 0
        self.corrupt_total = 0
        # bound once; the registry dedupes by name, so this is the same
        # series PrefixCacheInstruments.spill_dropped exposes
        self._tel_dropped = telemetry.counter(
            "dllama_prefix_spill_dropped_total",
            "Spilled prefix pages LOST from the capacity ladder: LRU "
            "overflow past the host/disk budgets, or a CRC mismatch "
            "detected at reload (the entry is dropped, the block "
            "prefills cold)",
        )

    def _on_disk_drop_locked(self, key: tuple) -> None:
        # invoked by the disk tier's own LRU eviction, under self._lock
        # (every disk call happens there)
        self.dropped_total += 1
        self._tel_dropped.inc()
        self._unchain_locked(key)

    # ------------------------------------------------------------------
    # Write side
    # ------------------------------------------------------------------

    def put(self, owner: int, chain: tuple, arrays) -> None:
        """Spill one page's byte arrays (verbatim — the caller flattened
        data+scales for i8). Re-putting a key replaces the old entry."""
        arrays = [np.ascontiguousarray(a) for a in arrays]
        entry = _Entry(arrays, _nbytes(arrays), _crc(arrays), 0)
        with self._lock:
            key = (int(owner), tuple(chain))
            self._drop_locked(key)
            self._clock += 1
            entry.last_use = self._clock
            self._entries[key] = entry
            self._chains.setdefault(key[1], set()).add(key[0])
            self.resident_bytes += entry.nbytes
            self.spilled_total += 1
            while self.resident_bytes > self.budget and self._entries:
                # demote the LRU entry (the freshly-put one only when it
                # is alone and over-budget by itself) — to disk when a
                # tier is configured, else a counted drop
                self._demote_lru_locked(
                    keep=key if len(self._entries) > 1 else None
                )

    def _demote_lru_locked(self, keep: tuple | None) -> None:
        lru = min(
            (k for k in self._entries if k != keep),
            key=lambda k: self._entries[k].last_use,
        )
        entry = self._entries.pop(lru)
        self.resident_bytes -= entry.nbytes
        demoted = False
        if self.disk is not None:
            demoted = self.disk.put(lru, entry.arrays, entry.crc)
        if not demoted:
            self.dropped_total += 1
            self._tel_dropped.inc()
            self._unchain_locked(lru)

    def _unchain_locked(self, key: tuple) -> None:
        owners = self._chains.get(key[1])
        if owners is not None:
            owners.discard(key[0])
            if not owners:
                del self._chains[key[1]]

    def _drop_locked(self, key: tuple) -> None:
        entry = self._entries.pop(key, None)
        if entry is not None:
            self.resident_bytes -= entry.nbytes
        if self.disk is not None:
            self.disk.drop(key)
        if entry is not None or self.disk is not None:
            self._unchain_locked(key)

    # ------------------------------------------------------------------
    # Read side
    # ------------------------------------------------------------------

    def _verified_locked(self, key: tuple, remove: bool):
        entry = self._entries.get(key)
        if entry is not None:
            if _crc(entry.arrays) != entry.crc:
                self._drop_locked(key)
                self.corrupt_total += 1
                self._tel_dropped.inc()
                raise SpillCorrupt(
                    f"host spill entry CRC mismatch (owner {key[0]})"
                )
            arrays = entry.arrays
            if remove:
                self._drop_locked(key)
            else:
                self._clock += 1
                entry.last_use = self._clock
                arrays = [a.copy() for a in arrays]
            return arrays
        if self.disk is not None:
            try:
                arrays = self.disk.take(key, copy_only=not remove)
            except SpillCorrupt:
                self.corrupt_total += 1
                self._tel_dropped.inc()
                self._unchain_locked(key)
                raise
            if arrays is not None and remove:
                self._unchain_locked(key)
            return arrays
        return None

    def take(self, owner: int, chain: tuple):
        """MOVE the owner's entry back out (the same-replica reload path:
        the device copy supersedes the arena's, restoring the pinned-
        pages-never-in-arena invariant). None on miss; SpillCorrupt on a
        failed CRC (entry dropped)."""
        with self._lock:
            arrays = self._verified_locked((int(owner), tuple(chain)), remove=True)
            if arrays is not None:
                self.reloaded_total += 1
            return arrays

    def peek_shared(self, chain: tuple, exclude_owner: int):
        """COPY another replica's entry for ``chain`` (cross-replica
        sharing: the reader uploads the bytes into its own pool while the
        spiller's entry stays for the next replica). None when no other
        owner holds the chain."""
        with self._lock:
            owners = self._chains.get(tuple(chain), set())
            for owner in sorted(owners):
                if owner == exclude_owner:
                    continue
                try:
                    arrays = self._verified_locked((owner, tuple(chain)), remove=False)
                except SpillCorrupt:
                    continue  # that copy is gone; try the next owner
                if arrays is not None:
                    self.reloaded_total += 1
                    return arrays
            return None

    def has(self, owner: int, chain: tuple) -> bool:
        key = (int(owner), tuple(chain))
        with self._lock:
            return key[0] in self._chains.get(key[1], set())

    def drop(self, owner: int, chain: tuple) -> None:
        """Remove one entry without reading it (a fresh device publish of
        the chain supersedes the spilled copy)."""
        with self._lock:
            self._drop_locked((int(owner), tuple(chain)))

    def drop_owner(self, owner: int) -> None:
        """A replica died: its spilled bytes are no longer trustworthy
        (a silently-corrupt replica may have spilled corrupt KV, PR 10)
        and its rebuild starts with an empty cache anyway — remove every
        entry it owns, atomically with the death."""
        owner = int(owner)
        with self._lock:
            for key in [k for k in self._entries if k[0] == owner]:
                self._drop_locked(key)
            if self.disk is not None:
                for key in self.disk.keys():
                    if key[0] == owner:
                        self.disk.drop(key)
                        self._unchain_locked(key)

    def corrupt(self, chain: tuple) -> None:
        """Chaos hook (the ``engine.spill`` site's ``kind=corrupt``): flip
        one byte of every resident copy of ``chain`` IN PLACE — silent by
        construction; only the CRC verification can see it."""
        with self._lock:
            for owner in list(self._chains.get(tuple(chain), set())):
                entry = self._entries.get((owner, tuple(chain)))
                if entry is not None and entry.arrays:
                    # downloaded arrays may be read-only views of device
                    # buffers: corrupt a writable copy in the entry
                    flipped = entry.arrays[0].copy()
                    flipped.view(np.uint8).reshape(-1)[0] ^= 0xFF
                    entry.arrays[0] = flipped
                elif self.disk is not None:
                    rec = self.disk._slots.get((owner, tuple(chain)))
                    if rec is not None:
                        off = rec[0] * self.disk.entry_bytes
                        self.disk._mm[off] ^= 0xFF

    def depth(self, owner: int | None = None) -> int:
        """Resident entries (host + disk), optionally for one owner — the
        /readyz per-replica ``spill_depth`` read."""
        with self._lock:
            if owner is None:
                return sum(len(v) for v in self._chains.values())
            return sum(1 for v in self._chains.values() if int(owner) in v)
