"""Radix tree over token blocks: prompt-prefix KV reuse across requests.

The serving workload the ROADMAP targets is dominated by shared prefixes —
the same system prompt and conversation history arrive over and over, and
the reference engine (like our own pre-page scheduler) re-prefills every
one of them from position 0. Prefill is the expensive phase (130 ms warm /
8.6 s cold per 64 tokens vs 9.2 ms/token decode, BENCH_r05), so reusing
prefill compute across requests is the biggest remaining serving win. This
is the RadixAttention idea (SGLang, Zheng et al. 2024) over PagedAttention
pages (vLLM, Kwon et al. 2023), adapted to the TPU-friendly static-shape
slab of engine/batch.py.

Design
------
* The prompt's token stream is split into fixed-size **blocks** of ``page``
  positions. Each radix-tree node owns exactly one block: its edge key is
  the block's token tuple (exact-match keys — no hash collisions to
  reason about) and its payload is one physical page id in the device page
  pool (:func:`~distributed_llama_tpu.models.llama.init_page_pool`).
* Pages are **immutable once published**: the scheduler copies a row's
  completed prefill KV *into* fresh pool pages (publish — the ONLY copy in
  the system). A matched row never copies pages back out: decode/verify/
  prefill attention reads the matched prefix **zero-copy through a per-row
  page table** over the pool (ops.attention paged variants), so each
  cached byte exists exactly once and effective batch + cacheable-prefix
  capacity both rise at fixed HBM. Writes still never touch tree pages —
  a row's private suffix lives in its slab row.
* **Refcounts** pin a matched chain for the **lifetime of the aliasing
  row** (admission match → row reset/quarantine/rollback-truncation), not
  just the admission window: eviction recycling a page that a live row's
  attention reads through its table would serve another prompt's KV.
  ``refs == 0`` nodes are evictable; eviction is leaf-first LRU
  (``last_use`` clock), so a chain ages out from its deepest, least-shared
  end while shared system-prompt roots survive. :meth:`check` extends to
  alias tracking — callers pass the live rows' page tables and it asserts
  none of those pages were freed or left unpinned.
* The pool size (``--kv-pages``) IS the HBM budget: allocation evicts
  LRU-unreferenced leaves only when the free list runs dry, and fails
  softly (the scheduler simply skips publishing) when everything is
  pinned. Eviction is an O(pages-in-tree) host scan per reclaimed page —
  fine at the default budgets (hundreds of pages, tens of µs under the
  scheduler lock); a last_use-ordered leaf index is the known follow-up
  if ``--kv-pages`` grows to the tens of thousands.
* **Tiered capacity below HBM** (ISSUE 11, engine/spill.py): with a
  :class:`~distributed_llama_tpu.engine.spill.HostArena` attached,
  eviction no longer discards the page — its bytes (data+scales verbatim
  for i8) spill to bounded host RAM (and optionally an mmap'd disk file,
  echoing the reference's disc-backed KV), and a later admission match
  that runs out of device-resident chain RELOADS the spilled pages
  (:meth:`reload` — the publish machinery in reverse: alloc a pool page,
  upload the host bytes, re-insert the node). Re-upload is orders of
  magnitude cheaper than re-prefill, so effective cacheable-prefix
  capacity at fixed ``--kv-pages`` multiplies. Every spilled entry is
  CRC-verified on reload; a mismatch (host RAM/disk corrupted it) drops
  the entry and the block prefills cold — stale KV is never served.
* **Cross-replica sharing** (:class:`SharedPrefixIndex`): each replica's
  tree reports its published/evicted chains to one shared host-side
  index; the replica pool routes a request to the replica owning the
  LONGEST matched chain (server/replicas.py ``place``), so the Zipf head
  of a chat workload is prefilled once GLOBALLY instead of once per
  replica. The arena is shared too: a chain spilled by replica A reloads
  into replica B's pool by copy (A's entry stays), which is how hot head
  nodes replicate across pools when routing alone cannot keep up. A
  replica death atomically drops its chains from the index (and its
  arena entries — a silently-corrupt replica's spills are suspect).

Thread model: the owning :class:`~distributed_llama_tpu.engine.batch.
BatchScheduler` calls every method under its condition lock; the tree
itself is lock-free on purpose (one lock, one owner — no ordering hazards
between tree state and slab/pool dispatches). The shared index and the
arena have their own LEAF locks (multiple schedulers and the replica
pool reach them concurrently); neither ever calls back out.
"""

from __future__ import annotations

import threading

from distributed_llama_tpu import lockcheck, telemetry
from distributed_llama_tpu.engine.spill import SpillCorrupt
from distributed_llama_tpu.telemetry import flight


class SharedPrefixIndex:
    """Host-side map ``token-prefix chain -> owning replicas`` over the
    per-replica radix trees (the routing half of the global cache tier).

    Each :class:`PrefixCache` reports node inserts (publish/reload) and
    removals (evict/unpublish) here; :meth:`match` answers "which replica
    owns the longest published chain of this prompt" for placement.
    Per-owner chains stay contiguous from the root by construction (the
    trees publish contiguous chains and evict leaf-first), and the match
    walk enforces contiguity anyway (an owner absent at block i is
    ignored at every deeper block)."""

    def __init__(self, page: int):
        self.page = int(page)
        self._lock = lockcheck.make_lock("SharedPrefixIndex._lock")
        self._owners: dict[tuple, set[int]] = {}

    def publish(self, owner: int, chain: tuple) -> None:
        with self._lock:
            self._owners.setdefault(tuple(chain), set()).add(int(owner))

    def withdraw(self, owner: int, chain: tuple) -> None:
        with self._lock:
            owners = self._owners.get(tuple(chain))
            if owners is not None:
                owners.discard(int(owner))
                if not owners:
                    del self._owners[tuple(chain)]

    def drop_owner(self, owner: int) -> None:
        """A replica died: every chain it owned leaves the index in one
        locked pass — placement must never route to a dead replica's
        pages (the no-dangling-routing contract)."""
        owner = int(owner)
        with self._lock:
            for chain in [c for c, o in self._owners.items() if owner in o]:
                self._owners[chain].discard(owner)
                if not self._owners[chain]:
                    del self._owners[chain]

    def match(self, tokens) -> dict[int, int]:
        """Per-replica depth of the longest contiguous owned chain of
        ``tokens`` (full blocks strictly shorter than the prompt, the
        tree-match bound): ``{replica: n_blocks}``, empty on no match."""
        page = self.page
        max_blocks = (len(tokens) - 1) // page
        # one int-conversion pass OUTSIDE the lock, keys grown
        # incrementally: the cumulative-prefix keys still hash O(depth)
        # each (flat-dict tradeoff), but nothing re-walks the prompt per
        # block while holding the lock every publish/evict also takes
        ids = [int(t) for t in tokens[: max_blocks * page]]
        depths: dict[int, int] = {}
        alive: set[int] | None = None
        key: tuple = ()
        with self._lock:
            for i in range(max_blocks):
                key = key + tuple(ids[i * page : (i + 1) * page])
                owners = self._owners.get(key)
                if not owners:
                    break
                alive = set(owners) if alive is None else alive & owners
                if not alive:
                    break
                for o in alive:
                    depths[o] = i + 1
        return depths

    def owners(self, chain: tuple) -> set[int]:
        with self._lock:
            return set(self._owners.get(tuple(chain), set()))

    def __len__(self) -> int:
        with self._lock:
            return len(self._owners)


class PageNode:
    """One radix-tree node: a ``page``-token block bound to one pool page."""

    __slots__ = ("key", "page_id", "parent", "children", "refs", "last_use")

    def __init__(self, key, page_id: int, parent: "PageNode | None"):
        self.key = key  # tuple of the block's token ids (edge label)
        self.page_id = page_id
        self.parent = parent
        self.children: dict[tuple, PageNode] = {}
        self.refs = 0
        self.last_use = 0


class PrefixCache:
    """Host-side index of the device page pool (see module docstring)."""

    def __init__(
        self, n_pages: int, page: int, page_bytes: int = 0,
        spill=None, page_fetch=None, owner_id: int = 0, shared_index=None,
    ):
        if n_pages < 1:
            raise ValueError(f"need at least one pool page, got {n_pages}")
        if page < 1:
            raise ValueError(f"page size must be positive, got {page}")
        self.page = page
        self.capacity = n_pages
        # tiered capacity + cross-replica sharing (ISSUE 11): ``spill`` is
        # the shared HostArena (engine/spill.py), ``page_fetch(page_id)``
        # the owning scheduler's device→host download of one pool page's
        # byte arrays (the spill side; the upload side is a reload()
        # argument — both device programs belong to the scheduler),
        # ``shared_index`` the pool-wide SharedPrefixIndex this tree
        # reports its chains to, ``owner_id`` this replica's identity in
        # both. All optional: a bare PrefixCache keeps the PR 4 contract.
        self.spill = spill
        self.page_fetch = page_fetch
        self.owner_id = int(owner_id)
        self.shared_index = shared_index
        # logical KV bytes per page across all layers/halves
        # (llama.page_pool_bytes) — feeds the bytes gauge and the
        # copy-traffic-saved counter; 0 = unknown (host-only unit tests)
        self.page_bytes = int(page_bytes)
        self.free: list[int] = list(range(n_pages))
        self.root = PageNode(None, -1, None)
        self._clock = 0
        # running count of refs>0 nodes, maintained at the 0<->1 ref
        # transitions: the gauge updates on every match/release/publish
        # under the scheduler cond lock, so an O(tree) walk there would
        # serialize dispatch behind page-count bookkeeping at large
        # --kv-pages (check() cross-validates this counter against a walk)
        self._pinned = 0
        self.tel = telemetry.PrefixCacheInstruments()
        self.tel.pages.set(0)
        self.tel.bytes.set(0)
        self.tel.pinned_pages.set(0)

    # ------------------------------------------------------------------
    # Introspection (tests + metrics)
    # ------------------------------------------------------------------

    def pages_in_use(self) -> int:
        return self.capacity - len(self.free)

    def pinned_pages(self) -> int:
        """Pages whose refcount is held — by a live aliasing row (row
        lifetime) or a publish in flight. Never evictable. O(1): a running
        counter kept at the ref 0<->1 transitions."""
        return self._pinned

    def _ref(self, node: PageNode) -> None:
        node.refs += 1
        if node.refs == 1:
            self._pinned += 1

    def _unref(self, node: PageNode) -> None:
        node.refs -= 1
        if node.refs == 0:
            self._pinned -= 1

    def _walk(self):
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    @staticmethod
    def chain_key(node: PageNode) -> tuple:
        """Full token-prefix tuple ending at ``node``'s block (root→node
        key concatenation) — the spill-arena / shared-index key: KV bytes
        are exact only for the identical whole prefix."""
        keys = []
        while node is not None and node.key is not None:
            keys.append(node.key)
            node = node.parent
        out: list[int] = []
        for k in reversed(keys):
            out.extend(int(t) for t in k)
        return tuple(out)

    def walk(self, tokens) -> list[PageNode]:
        """The :meth:`match` walk WITHOUT refs, counters or clock ticks —
        the reload path peeks at where the device-resident chain ends
        before deciding what to pull back from the spill arena."""
        page = self.page
        max_blocks = (len(tokens) - 1) // page
        chain: list[PageNode] = []
        node = self.root
        for i in range(max_blocks):
            child = node.children.get(tuple(tokens[i * page : (i + 1) * page]))
            if child is None:
                break
            chain.append(child)
            node = child
        return chain

    def _set_pages_gauges(self) -> None:
        used = self.pages_in_use()
        self.tel.pages.set(used)
        self.tel.bytes.set(used * self.page_bytes)

    def _set_pinned_gauge(self) -> None:
        self.tel.pinned_pages.set(self.pinned_pages())

    def check(self, row_pages=None) -> None:
        """Structural invariants (tests + the eviction stress): every tree
        page is allocated exactly once and disjoint from the free list.

        ``row_pages``: iterable of live rows' aliased page-id sequences
        (their zero-copy page tables). Each referenced page must still be
        mapped in the tree AND ref-pinned — a page freed or unpinned while
        a live row reads KV through it is the aliasing bug class this
        extension exists to catch."""
        seen: dict[int, PageNode] = {}
        for node in self._walk():
            assert 0 <= node.page_id < self.capacity, node.page_id
            assert node.page_id not in seen, f"page {node.page_id} aliased"
            assert node.refs >= 0, f"negative refcount on page {node.page_id}"
            seen[node.page_id] = node
        free = set(self.free)
        assert not (seen.keys() & free), (
            f"tree/free overlap: {sorted(seen.keys() & free)}"
        )
        assert len(seen) + len(free) == self.capacity, (
            f"page leak: {len(seen)} in tree + {len(free)} free "
            f"!= {self.capacity}"
        )
        walked_pinned = sum(1 for n in seen.values() if n.refs > 0)
        assert self._pinned == walked_pinned, (
            f"pinned counter drift: running {self._pinned} "
            f"!= walked {walked_pinned}"
        )
        for ids in row_pages or ():
            for pid in ids:
                assert pid not in free, (
                    f"page {pid} freed while a live row's page table "
                    "references it"
                )
                node = seen.get(pid)
                assert node is not None, (
                    f"page {pid} left the tree while a live row's page "
                    "table references it"
                )
                assert node.refs > 0, (
                    f"page {pid} unpinned while a live row aliases it "
                    "(eviction could recycle it mid-read)"
                )
        if self.spill is not None:
            # spill-tier exclusivity (ISSUE 11): only EVICTED pages live in
            # the arena. A pinned (row-aliased or publish-held) page that
            # also had an arena entry under this owner would mean eviction
            # spilled a live page, or a reload forgot to retire its source
            # entry — either way two copies of "the" bytes with no single
            # owner of truth
            for node in seen.values():
                if node.refs > 0:
                    key = self.chain_key(node)
                    assert not self.spill.has(self.owner_id, key), (
                        f"pinned page {node.page_id} is simultaneously "
                        "resident in the spill arena (chain of "
                        f"{len(key)} tokens)"
                    )

    # ------------------------------------------------------------------
    # Match / release (admission)
    # ------------------------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, tokens) -> list[PageNode]:
        """Longest chain of full-block matches STRICTLY shorter than the
        prompt (at least the last token always prefills — its logits seed
        the first sampled token). Acquires one ref per matched node; the
        pins last for the LIFETIME of the aliasing row (its attention
        reads the pages through its table every step), so the caller
        :meth:`release`\\ s the chain at row reset/quarantine — not after
        admission."""
        page = self.page
        chain = self.walk(tokens)
        t = self._tick()
        for nd in chain:
            self._ref(nd)
            nd.last_use = t
        if chain:
            self.tel.hits.inc()
            self.tel.matched_tokens.observe(len(chain) * page)
            # the copy design gathered every matched page into the slab row
            # (and kept the duplicate for the row's lifetime): count the
            # copy traffic the zero-copy read avoids per hit
            self.tel.copy_bytes_saved.inc(len(chain) * self.page_bytes)
        else:
            self.tel.misses.inc()
        self._set_pinned_gauge()
        return chain

    def release(self, chain: list[PageNode]) -> None:
        for nd in chain:
            self._unref(nd)
        if chain:
            self._set_pinned_gauge()

    # ------------------------------------------------------------------
    # Publish (after a completed admission prefill)
    # ------------------------------------------------------------------

    def publish(
        self, tokens, n_total: int, parent_chain: list[PageNode]
    ) -> tuple[list[int], list[int]]:
        """Insert the full blocks of ``tokens[:n_total]`` beyond
        ``parent_chain`` into the tree. Returns ``(page_ids, block_idx)``
        of the NEWLY allocated pages — the scheduler copies those blocks
        out of the row; blocks already present (a concurrent request
        published them first) are refreshed, not re-copied. Allocation
        evicts LRU-unreferenced leaves when the free list is dry and stops
        early (partial publish) when nothing is evictable."""
        node = parent_chain[-1] if parent_chain else self.root
        page = self.page
        new_ids: list[int] = []
        new_blocks: list[int] = []
        t = self._tick()
        # pin the whole growing chain for the duration of the walk: a
        # mid-publish _alloc may evict, and an unpinned just-inserted (or
        # traversed) node is a refcount-0 leaf — the evictor would detach
        # the very chain being built, double-allocating its page and
        # leaking the rest (reproduced: capacity-1 pool, 2-block publish)
        pinned: list[PageNode] = list(parent_chain)
        for nd in pinned:
            self._ref(nd)
        try:
            for i in range(len(parent_chain), n_total // page):
                key = tuple(tokens[i * page : (i + 1) * page])
                child = node.children.get(key)
                if child is None:
                    pid = self._alloc()
                    if pid is None:
                        break  # budget exhausted and everything pinned
                    child = PageNode(key, pid, node)
                    node.children[key] = child
                    new_ids.append(pid)
                    new_blocks.append(i)
                    self._note_insert(child)
                self._ref(child)
                pinned.append(child)
                child.last_use = t
                node = child
        finally:
            for nd in pinned:
                self._unref(nd)
        self._set_pages_gauges()
        self._set_pinned_gauge()
        return new_ids, new_blocks

    def unpublish(self, tokens, new_ids: list[int], new_blocks: list[int]) -> None:
        """Unwind a :meth:`publish` whose device copy failed to dispatch:
        detach the inserted sub-chain and return its pages to the free
        list. The pages were never written — leaving them mapped would
        serve garbage (or a recycled prefix's stale) KV to every future
        match. ``new_blocks`` is a contiguous tail by construction (once
        publish creates a node, every deeper block is new too), so
        detaching the FIRST new node drops the whole sub-chain."""
        if not new_ids:
            return
        page = self.page
        node = self.root
        for i in range(new_blocks[0]):
            node = node.children[tuple(tokens[i * page : (i + 1) * page])]
        first = new_blocks[0]
        key = tuple(tokens[first * page : (first + 1) * page])
        detached = node.children.pop(key)
        # freshly-inserted nodes can't have been matched (both happen under
        # the scheduler lock), so their refs are 0 — but keep the running
        # pinned counter exact against any future lifecycle change
        stack = [detached]
        while stack:
            nd = stack.pop()
            if nd.refs > 0:
                self._pinned -= 1
            if self.shared_index is not None:
                # the publish already announced these chains; an unwound
                # publish must retract them or placement routes to pages
                # that were never written
                self.shared_index.withdraw(self.owner_id, self.chain_key(nd))
            stack.extend(nd.children.values())
        self.free.extend(new_ids)
        self._set_pages_gauges()
        self._set_pinned_gauge()

    # ------------------------------------------------------------------
    # Allocation / LRU eviction
    # ------------------------------------------------------------------

    def _alloc(self) -> int | None:
        if self.free:
            return self.free.pop()
        if self._evict_one():
            return self.free.pop()
        return None

    def _evict_one(self) -> bool:
        """Reclaim the least-recently-used unreferenced LEAF (children keep
        their ancestors alive: evicting an interior page would strand the
        chain below it). Returns False when every leaf is pinned. With a
        spill arena attached the page's bytes are downloaded and spilled
        BEFORE the page id is freed (the download dispatches against the
        pre-recycle pool contents; device ordering keeps it exact even
        though a later publish may reuse the id immediately)."""
        victim: PageNode | None = None
        for node in self._walk():
            if node.children or node.refs > 0:
                continue
            if victim is None or node.last_use < victim.last_use:
                victim = node
        if victim is None:
            return False
        key = None
        if self.spill is not None or self.shared_index is not None:
            key = self.chain_key(victim)
        if self.spill is not None and self.page_fetch is not None:
            try:
                self.spill.put(self.owner_id, key, self.page_fetch(victim.page_id))
                self.tel.spill_pages.inc()
            except Exception as e:
                # spilling is an optimization: a failed download degrades
                # to the PR 4 behavior (the page simply vanishes)
                print(f"⚠️ page spill failed; evicting without it: {e}")
            self._set_spill_gauges()
        if self.shared_index is not None:
            self.shared_index.withdraw(self.owner_id, key)
        del victim.parent.children[victim.key]
        self.free.append(victim.page_id)
        self.tel.evictions.inc()
        self._set_pages_gauges()
        return True

    # ------------------------------------------------------------------
    # Spill tier (ISSUE 11, engine/spill.py): reload = publish in reverse
    # ------------------------------------------------------------------

    def _note_insert(self, node: PageNode) -> None:
        """A node entered the tree (publish or reload): announce the chain
        to the shared index, and retire any own arena entry — the fresh
        device copy supersedes it (the exclusivity invariant check()
        asserts)."""
        if self.spill is None and self.shared_index is None:
            return
        key = self.chain_key(node)
        if self.spill is not None:
            self.spill.drop(self.owner_id, key)
            self._set_spill_gauges()
        if self.shared_index is not None:
            self.shared_index.publish(self.owner_id, key)

    def _set_spill_gauges(self) -> None:
        self.tel.spill_resident_pages.set(self.spill.depth())
        self.tel.spill_bytes.set(self.spill.resident_bytes)

    def spill_depth(self) -> int:
        """Arena entries owned by this replica (the /readyz read)."""
        return 0 if self.spill is None else self.spill.depth(self.owner_id)

    def spill_take(self, chain: tuple):
        """One reload read: the owner's own entry MOVES back out of the
        arena; another replica's entry is COPIED (cross-replica sharing —
        the spiller keeps serving other readers). A CRC mismatch drops
        the corrupt entry and counts it, then the PEER lookup still runs
        — a bit flip in one replica's copy must not defeat the redundancy
        the shared arena exists for; only when no intact copy survives
        anywhere does the read miss (cold prefill, never stale KV)."""
        if self.spill is None:
            return None
        arrays = None
        try:
            arrays = self.spill.take(self.owner_id, chain)
        except SpillCorrupt as e:
            # own copy corrupt + dropped (counted); try the peers. The
            # flight recorder keeps the CRC verdict (ISSUE 16): a later
            # replica death dump shows whether its spilled KV was rotting
            flight.record(
                self.owner_id, "spill_crc_drop", error=str(e),
            )
        if arrays is None:
            arrays = self.spill.peek_shared(chain, exclude_owner=self.owner_id)
        self._set_spill_gauges()
        return arrays

    def spill_corrupt(self, chain: tuple) -> None:
        """Chaos hook (``engine.spill`` ``kind=corrupt``): flip bytes of
        the resident entries for ``chain`` in place."""
        if self.spill is not None:
            self.spill.corrupt(chain)

    def reload(self, tokens, upload, pre=None) -> int:
        """Extend the device-resident chain of ``tokens`` from the spill
        arena — the :meth:`publish` machinery in reverse: per missing
        block (deepest-first from where :meth:`walk` ends, bounded like
        match at full blocks strictly shorter than the prompt) take the
        spilled bytes, allocate a pool page (may itself evict+spill), run
        the caller's ``upload(page_id, arrays)`` device copy, and insert
        the node. ``pre(chain_key)`` is the scheduler's ``engine.spill``
        chaos hook. ANY failure — arena miss, CRC drop, allocation dry,
        an upload raise, an injected fault — stops the reload cleanly:
        blocks already uploaded stay (they hold verified bytes), deeper
        blocks fall back to the cold prefill, pins taken for the walk are
        released. Returns the number of pages reloaded."""
        if self.spill is None:
            return 0
        page = self.page
        max_blocks = (len(tokens) - 1) // page
        nodes = self.walk(tokens)
        if len(nodes) >= max_blocks:
            return 0
        node = nodes[-1] if nodes else self.root
        # pin the growing chain exactly like publish: a mid-reload _alloc
        # may evict, and the evictor must never detach the chain being
        # rebuilt (or the just-walked parents)
        pinned: list[PageNode] = list(nodes)
        for nd in pinned:
            self._ref(nd)
        n_reloaded = 0
        try:
            for i in range(len(nodes), max_blocks):
                chain = tuple(int(t) for t in tokens[: (i + 1) * page])
                try:
                    if pre is not None:
                        pre(chain)
                    # alloc BEFORE taking the entry: spill_take MOVES the
                    # owner's bytes out of the arena, so an allocation
                    # failure after it would permanently lose them — and
                    # a dry pool is likeliest exactly under the pinned
                    # pressure the spill tier exists for. The chain being
                    # reloaded is not in the tree, so the eviction _alloc
                    # may trigger cannot touch it.
                    pid = self._alloc()
                    if pid is None:
                        break  # everything pinned: no room to reload into
                    arrays = self.spill_take(chain)
                    if arrays is None:
                        self.free.append(pid)
                        break
                    try:
                        upload(pid, arrays)
                    except Exception:
                        self.free.append(pid)
                        # only the upload failed — the bytes themselves
                        # are verified-good: restore the entry so a later
                        # match can retry instead of cold-prefilling the
                        # chain forever
                        self.spill.put(self.owner_id, chain, arrays)
                        raise
                except Exception as e:
                    # an injected engine.spill raise or a failed upload
                    # dispatch: the remaining blocks prefill cold
                    # (interpreter exits are not Exception and propagate)
                    print(f"⚠️ spill reload aborted; prefilling cold: {e}")
                    flight.record(
                        self.owner_id, "spill_reload_abort",
                        reloaded=n_reloaded, error=type(e).__name__,
                    )
                    break
                key = tuple(tokens[i * page : (i + 1) * page])
                child = PageNode(key, pid, node)
                node.children[key] = child
                child.last_use = self._tick()
                self._note_insert(child)
                self._ref(child)
                pinned.append(child)
                node = child
                n_reloaded += 1
                self.tel.spill_reloads.inc()
        finally:
            for nd in pinned:
                self._unref(nd)
        if n_reloaded:
            self._set_pages_gauges()
            self._set_pinned_gauge()
        return n_reloaded
