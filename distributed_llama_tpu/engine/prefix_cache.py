"""Radix tree over token blocks: prompt-prefix KV reuse across requests.

The serving workload the ROADMAP targets is dominated by shared prefixes —
the same system prompt and conversation history arrive over and over, and
the reference engine (like our own pre-page scheduler) re-prefills every
one of them from position 0. Prefill is the expensive phase (130 ms warm /
8.6 s cold per 64 tokens vs 9.2 ms/token decode, BENCH_r05), so reusing
prefill compute across requests is the biggest remaining serving win. This
is the RadixAttention idea (SGLang, Zheng et al. 2024) over PagedAttention
pages (vLLM, Kwon et al. 2023), adapted to the TPU-friendly static-shape
slab of engine/batch.py.

Design
------
* The prompt's token stream is split into fixed-size **blocks** of ``page``
  positions. Each radix-tree node owns exactly one block: its edge key is
  the block's token tuple (exact-match keys — no hash collisions to
  reason about) and its payload is one physical page id in the device page
  pool (:func:`~distributed_llama_tpu.models.llama.init_page_pool`).
* Pages are **immutable once published**: the scheduler copies a row's
  completed prefill KV *into* fresh pool pages (publish) and copies
  matched pages *out* into a new row's slab prefix (admission gather) —
  correctness-first copy semantics; rows never alias tree pages, so a
  quarantined or reset row can NEVER free/corrupt pages the tree still
  references (test- and chaos-enforced). Zero-copy paged attention is the
  documented follow-up.
* **Refcounts** pin a matched chain between the host-side match decision
  and the device gather dispatch (the only window where eviction could
  hand the page to a concurrent publish). ``refs == 0`` nodes are
  evictable; eviction is leaf-first LRU (``last_use`` clock), so a chain
  ages out from its deepest, least-shared end while shared system-prompt
  roots survive.
* The pool size (``--kv-pages``) IS the HBM budget: allocation evicts
  LRU-unreferenced leaves only when the free list runs dry, and fails
  softly (the scheduler simply skips publishing) when everything is
  pinned. Eviction is an O(pages-in-tree) host scan per reclaimed page —
  fine at the default budgets (hundreds of pages, tens of µs under the
  scheduler lock); a last_use-ordered leaf index is the known follow-up
  if ``--kv-pages`` grows to the tens of thousands.

Thread model: the owning :class:`~distributed_llama_tpu.engine.batch.
BatchScheduler` calls every method under its condition lock; the tree
itself is lock-free on purpose (one lock, one owner — no ordering hazards
between tree state and slab/pool dispatches).
"""

from __future__ import annotations

from distributed_llama_tpu import telemetry


class PageNode:
    """One radix-tree node: a ``page``-token block bound to one pool page."""

    __slots__ = ("key", "page_id", "parent", "children", "refs", "last_use")

    def __init__(self, key, page_id: int, parent: "PageNode | None"):
        self.key = key  # tuple of the block's token ids (edge label)
        self.page_id = page_id
        self.parent = parent
        self.children: dict[tuple, PageNode] = {}
        self.refs = 0
        self.last_use = 0


class PrefixCache:
    """Host-side index of the device page pool (see module docstring)."""

    def __init__(self, n_pages: int, page: int):
        if n_pages < 1:
            raise ValueError(f"need at least one pool page, got {n_pages}")
        if page < 1:
            raise ValueError(f"page size must be positive, got {page}")
        self.page = page
        self.capacity = n_pages
        self.free: list[int] = list(range(n_pages))
        self.root = PageNode(None, -1, None)
        self._clock = 0
        self.tel = telemetry.PrefixCacheInstruments()
        self.tel.pages.set(0)

    # ------------------------------------------------------------------
    # Introspection (tests + metrics)
    # ------------------------------------------------------------------

    def pages_in_use(self) -> int:
        return self.capacity - len(self.free)

    def _walk(self):
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def check(self) -> None:
        """Structural invariants (tests + the eviction stress): every tree
        page is allocated exactly once and disjoint from the free list."""
        seen: set[int] = set()
        for node in self._walk():
            assert 0 <= node.page_id < self.capacity, node.page_id
            assert node.page_id not in seen, f"page {node.page_id} aliased"
            assert node.refs >= 0, f"negative refcount on page {node.page_id}"
            seen.add(node.page_id)
        free = set(self.free)
        assert not (seen & free), f"tree/free overlap: {sorted(seen & free)}"
        assert len(seen) + len(free) == self.capacity, (
            f"page leak: {len(seen)} in tree + {len(free)} free "
            f"!= {self.capacity}"
        )

    # ------------------------------------------------------------------
    # Match / release (admission)
    # ------------------------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, tokens) -> list[PageNode]:
        """Longest chain of full-block matches STRICTLY shorter than the
        prompt (at least the last token always prefills — its logits seed
        the first sampled token). Acquires one ref per matched node; the
        caller must :meth:`release` the returned chain once the gathered
        pages have been dispatched."""
        page = self.page
        max_blocks = (len(tokens) - 1) // page
        chain: list[PageNode] = []
        node = self.root
        for i in range(max_blocks):
            child = node.children.get(tuple(tokens[i * page : (i + 1) * page]))
            if child is None:
                break
            chain.append(child)
            node = child
        t = self._tick()
        for nd in chain:
            nd.refs += 1
            nd.last_use = t
        if chain:
            self.tel.hits.inc()
            self.tel.matched_tokens.observe(len(chain) * page)
        else:
            self.tel.misses.inc()
        return chain

    def release(self, chain: list[PageNode]) -> None:
        for nd in chain:
            nd.refs -= 1

    # ------------------------------------------------------------------
    # Publish (after a completed admission prefill)
    # ------------------------------------------------------------------

    def publish(
        self, tokens, n_total: int, parent_chain: list[PageNode]
    ) -> tuple[list[int], list[int]]:
        """Insert the full blocks of ``tokens[:n_total]`` beyond
        ``parent_chain`` into the tree. Returns ``(page_ids, block_idx)``
        of the NEWLY allocated pages — the scheduler copies those blocks
        out of the row; blocks already present (a concurrent request
        published them first) are refreshed, not re-copied. Allocation
        evicts LRU-unreferenced leaves when the free list is dry and stops
        early (partial publish) when nothing is evictable."""
        node = parent_chain[-1] if parent_chain else self.root
        page = self.page
        new_ids: list[int] = []
        new_blocks: list[int] = []
        t = self._tick()
        # pin the whole growing chain for the duration of the walk: a
        # mid-publish _alloc may evict, and an unpinned just-inserted (or
        # traversed) node is a refcount-0 leaf — the evictor would detach
        # the very chain being built, double-allocating its page and
        # leaking the rest (reproduced: capacity-1 pool, 2-block publish)
        pinned: list[PageNode] = list(parent_chain)
        for nd in pinned:
            nd.refs += 1
        try:
            for i in range(len(parent_chain), n_total // page):
                key = tuple(tokens[i * page : (i + 1) * page])
                child = node.children.get(key)
                if child is None:
                    pid = self._alloc()
                    if pid is None:
                        break  # budget exhausted and everything pinned
                    child = PageNode(key, pid, node)
                    node.children[key] = child
                    new_ids.append(pid)
                    new_blocks.append(i)
                child.refs += 1
                pinned.append(child)
                child.last_use = t
                node = child
        finally:
            for nd in pinned:
                nd.refs -= 1
        self.tel.pages.set(self.pages_in_use())
        return new_ids, new_blocks

    def unpublish(self, tokens, new_ids: list[int], new_blocks: list[int]) -> None:
        """Unwind a :meth:`publish` whose device copy failed to dispatch:
        detach the inserted sub-chain and return its pages to the free
        list. The pages were never written — leaving them mapped would
        serve garbage (or a recycled prefix's stale) KV to every future
        match. ``new_blocks`` is a contiguous tail by construction (once
        publish creates a node, every deeper block is new too), so
        detaching the FIRST new node drops the whole sub-chain."""
        if not new_ids:
            return
        page = self.page
        node = self.root
        for i in range(new_blocks[0]):
            node = node.children[tuple(tokens[i * page : (i + 1) * page])]
        first = new_blocks[0]
        del node.children[tuple(tokens[first * page : (first + 1) * page])]
        self.free.extend(new_ids)
        self.tel.pages.set(self.pages_in_use())

    # ------------------------------------------------------------------
    # Allocation / LRU eviction
    # ------------------------------------------------------------------

    def _alloc(self) -> int | None:
        if self.free:
            return self.free.pop()
        if self._evict_one():
            return self.free.pop()
        return None

    def _evict_one(self) -> bool:
        """Reclaim the least-recently-used unreferenced LEAF (children keep
        their ancestors alive: evicting an interior page would strand the
        chain below it). Returns False when every leaf is pinned."""
        victim: PageNode | None = None
        for node in self._walk():
            if node.children or node.refs > 0:
                continue
            if victim is None or node.last_use < victim.last_use:
                victim = node
        if victim is None:
            return False
        del victim.parent.children[victim.key]
        self.free.append(victim.page_id)
        self.tel.evictions.inc()
        self.tel.pages.set(self.pages_in_use())
        return True
