"""Radix tree over token blocks: prompt-prefix KV reuse across requests.

The serving workload the ROADMAP targets is dominated by shared prefixes —
the same system prompt and conversation history arrive over and over, and
the reference engine (like our own pre-page scheduler) re-prefills every
one of them from position 0. Prefill is the expensive phase (130 ms warm /
8.6 s cold per 64 tokens vs 9.2 ms/token decode, BENCH_r05), so reusing
prefill compute across requests is the biggest remaining serving win. This
is the RadixAttention idea (SGLang, Zheng et al. 2024) over PagedAttention
pages (vLLM, Kwon et al. 2023), adapted to the TPU-friendly static-shape
slab of engine/batch.py.

Design
------
* The prompt's token stream is split into fixed-size **blocks** of ``page``
  positions. Each radix-tree node owns exactly one block: its edge key is
  the block's token tuple (exact-match keys — no hash collisions to
  reason about) and its payload is one physical page id in the device page
  pool (:func:`~distributed_llama_tpu.models.llama.init_page_pool`).
* Pages are **immutable once published**: the scheduler copies a row's
  completed prefill KV *into* fresh pool pages (publish — the ONLY copy in
  the system). A matched row never copies pages back out: decode/verify/
  prefill attention reads the matched prefix **zero-copy through a per-row
  page table** over the pool (ops.attention paged variants), so each
  cached byte exists exactly once and effective batch + cacheable-prefix
  capacity both rise at fixed HBM. Writes still never touch tree pages —
  a row's private suffix lives in its slab row.
* **Refcounts** pin a matched chain for the **lifetime of the aliasing
  row** (admission match → row reset/quarantine/rollback-truncation), not
  just the admission window: eviction recycling a page that a live row's
  attention reads through its table would serve another prompt's KV.
  ``refs == 0`` nodes are evictable; eviction is leaf-first LRU
  (``last_use`` clock), so a chain ages out from its deepest, least-shared
  end while shared system-prompt roots survive. :meth:`check` extends to
  alias tracking — callers pass the live rows' page tables and it asserts
  none of those pages were freed or left unpinned.
* The pool size (``--kv-pages``) IS the HBM budget: allocation evicts
  LRU-unreferenced leaves only when the free list runs dry, and fails
  softly (the scheduler simply skips publishing) when everything is
  pinned. Eviction is an O(pages-in-tree) host scan per reclaimed page —
  fine at the default budgets (hundreds of pages, tens of µs under the
  scheduler lock); a last_use-ordered leaf index is the known follow-up
  if ``--kv-pages`` grows to the tens of thousands.

Thread model: the owning :class:`~distributed_llama_tpu.engine.batch.
BatchScheduler` calls every method under its condition lock; the tree
itself is lock-free on purpose (one lock, one owner — no ordering hazards
between tree state and slab/pool dispatches).
"""

from __future__ import annotations

from distributed_llama_tpu import telemetry


class PageNode:
    """One radix-tree node: a ``page``-token block bound to one pool page."""

    __slots__ = ("key", "page_id", "parent", "children", "refs", "last_use")

    def __init__(self, key, page_id: int, parent: "PageNode | None"):
        self.key = key  # tuple of the block's token ids (edge label)
        self.page_id = page_id
        self.parent = parent
        self.children: dict[tuple, PageNode] = {}
        self.refs = 0
        self.last_use = 0


class PrefixCache:
    """Host-side index of the device page pool (see module docstring)."""

    def __init__(self, n_pages: int, page: int, page_bytes: int = 0):
        if n_pages < 1:
            raise ValueError(f"need at least one pool page, got {n_pages}")
        if page < 1:
            raise ValueError(f"page size must be positive, got {page}")
        self.page = page
        self.capacity = n_pages
        # logical KV bytes per page across all layers/halves
        # (llama.page_pool_bytes) — feeds the bytes gauge and the
        # copy-traffic-saved counter; 0 = unknown (host-only unit tests)
        self.page_bytes = int(page_bytes)
        self.free: list[int] = list(range(n_pages))
        self.root = PageNode(None, -1, None)
        self._clock = 0
        # running count of refs>0 nodes, maintained at the 0<->1 ref
        # transitions: the gauge updates on every match/release/publish
        # under the scheduler cond lock, so an O(tree) walk there would
        # serialize dispatch behind page-count bookkeeping at large
        # --kv-pages (check() cross-validates this counter against a walk)
        self._pinned = 0
        self.tel = telemetry.PrefixCacheInstruments()
        self.tel.pages.set(0)
        self.tel.bytes.set(0)
        self.tel.pinned_pages.set(0)

    # ------------------------------------------------------------------
    # Introspection (tests + metrics)
    # ------------------------------------------------------------------

    def pages_in_use(self) -> int:
        return self.capacity - len(self.free)

    def pinned_pages(self) -> int:
        """Pages whose refcount is held — by a live aliasing row (row
        lifetime) or a publish in flight. Never evictable. O(1): a running
        counter kept at the ref 0<->1 transitions."""
        return self._pinned

    def _ref(self, node: PageNode) -> None:
        node.refs += 1
        if node.refs == 1:
            self._pinned += 1

    def _unref(self, node: PageNode) -> None:
        node.refs -= 1
        if node.refs == 0:
            self._pinned -= 1

    def _walk(self):
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def _set_pages_gauges(self) -> None:
        used = self.pages_in_use()
        self.tel.pages.set(used)
        self.tel.bytes.set(used * self.page_bytes)

    def _set_pinned_gauge(self) -> None:
        self.tel.pinned_pages.set(self.pinned_pages())

    def check(self, row_pages=None) -> None:
        """Structural invariants (tests + the eviction stress): every tree
        page is allocated exactly once and disjoint from the free list.

        ``row_pages``: iterable of live rows' aliased page-id sequences
        (their zero-copy page tables). Each referenced page must still be
        mapped in the tree AND ref-pinned — a page freed or unpinned while
        a live row reads KV through it is the aliasing bug class this
        extension exists to catch."""
        seen: dict[int, PageNode] = {}
        for node in self._walk():
            assert 0 <= node.page_id < self.capacity, node.page_id
            assert node.page_id not in seen, f"page {node.page_id} aliased"
            assert node.refs >= 0, f"negative refcount on page {node.page_id}"
            seen[node.page_id] = node
        free = set(self.free)
        assert not (seen.keys() & free), (
            f"tree/free overlap: {sorted(seen.keys() & free)}"
        )
        assert len(seen) + len(free) == self.capacity, (
            f"page leak: {len(seen)} in tree + {len(free)} free "
            f"!= {self.capacity}"
        )
        walked_pinned = sum(1 for n in seen.values() if n.refs > 0)
        assert self._pinned == walked_pinned, (
            f"pinned counter drift: running {self._pinned} "
            f"!= walked {walked_pinned}"
        )
        for ids in row_pages or ():
            for pid in ids:
                assert pid not in free, (
                    f"page {pid} freed while a live row's page table "
                    "references it"
                )
                node = seen.get(pid)
                assert node is not None, (
                    f"page {pid} left the tree while a live row's page "
                    "table references it"
                )
                assert node.refs > 0, (
                    f"page {pid} unpinned while a live row aliases it "
                    "(eviction could recycle it mid-read)"
                )

    # ------------------------------------------------------------------
    # Match / release (admission)
    # ------------------------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, tokens) -> list[PageNode]:
        """Longest chain of full-block matches STRICTLY shorter than the
        prompt (at least the last token always prefills — its logits seed
        the first sampled token). Acquires one ref per matched node; the
        pins last for the LIFETIME of the aliasing row (its attention
        reads the pages through its table every step), so the caller
        :meth:`release`\\ s the chain at row reset/quarantine — not after
        admission."""
        page = self.page
        max_blocks = (len(tokens) - 1) // page
        chain: list[PageNode] = []
        node = self.root
        for i in range(max_blocks):
            child = node.children.get(tuple(tokens[i * page : (i + 1) * page]))
            if child is None:
                break
            chain.append(child)
            node = child
        t = self._tick()
        for nd in chain:
            self._ref(nd)
            nd.last_use = t
        if chain:
            self.tel.hits.inc()
            self.tel.matched_tokens.observe(len(chain) * page)
            # the copy design gathered every matched page into the slab row
            # (and kept the duplicate for the row's lifetime): count the
            # copy traffic the zero-copy read avoids per hit
            self.tel.copy_bytes_saved.inc(len(chain) * self.page_bytes)
        else:
            self.tel.misses.inc()
        self._set_pinned_gauge()
        return chain

    def release(self, chain: list[PageNode]) -> None:
        for nd in chain:
            self._unref(nd)
        if chain:
            self._set_pinned_gauge()

    # ------------------------------------------------------------------
    # Publish (after a completed admission prefill)
    # ------------------------------------------------------------------

    def publish(
        self, tokens, n_total: int, parent_chain: list[PageNode]
    ) -> tuple[list[int], list[int]]:
        """Insert the full blocks of ``tokens[:n_total]`` beyond
        ``parent_chain`` into the tree. Returns ``(page_ids, block_idx)``
        of the NEWLY allocated pages — the scheduler copies those blocks
        out of the row; blocks already present (a concurrent request
        published them first) are refreshed, not re-copied. Allocation
        evicts LRU-unreferenced leaves when the free list is dry and stops
        early (partial publish) when nothing is evictable."""
        node = parent_chain[-1] if parent_chain else self.root
        page = self.page
        new_ids: list[int] = []
        new_blocks: list[int] = []
        t = self._tick()
        # pin the whole growing chain for the duration of the walk: a
        # mid-publish _alloc may evict, and an unpinned just-inserted (or
        # traversed) node is a refcount-0 leaf — the evictor would detach
        # the very chain being built, double-allocating its page and
        # leaking the rest (reproduced: capacity-1 pool, 2-block publish)
        pinned: list[PageNode] = list(parent_chain)
        for nd in pinned:
            self._ref(nd)
        try:
            for i in range(len(parent_chain), n_total // page):
                key = tuple(tokens[i * page : (i + 1) * page])
                child = node.children.get(key)
                if child is None:
                    pid = self._alloc()
                    if pid is None:
                        break  # budget exhausted and everything pinned
                    child = PageNode(key, pid, node)
                    node.children[key] = child
                    new_ids.append(pid)
                    new_blocks.append(i)
                self._ref(child)
                pinned.append(child)
                child.last_use = t
                node = child
        finally:
            for nd in pinned:
                self._unref(nd)
        self._set_pages_gauges()
        self._set_pinned_gauge()
        return new_ids, new_blocks

    def unpublish(self, tokens, new_ids: list[int], new_blocks: list[int]) -> None:
        """Unwind a :meth:`publish` whose device copy failed to dispatch:
        detach the inserted sub-chain and return its pages to the free
        list. The pages were never written — leaving them mapped would
        serve garbage (or a recycled prefix's stale) KV to every future
        match. ``new_blocks`` is a contiguous tail by construction (once
        publish creates a node, every deeper block is new too), so
        detaching the FIRST new node drops the whole sub-chain."""
        if not new_ids:
            return
        page = self.page
        node = self.root
        for i in range(new_blocks[0]):
            node = node.children[tuple(tokens[i * page : (i + 1) * page])]
        first = new_blocks[0]
        key = tuple(tokens[first * page : (first + 1) * page])
        detached = node.children.pop(key)
        # freshly-inserted nodes can't have been matched (both happen under
        # the scheduler lock), so their refs are 0 — but keep the running
        # pinned counter exact against any future lifecycle change
        stack = [detached]
        while stack:
            nd = stack.pop()
            if nd.refs > 0:
                self._pinned -= 1
            stack.extend(nd.children.values())
        self.free.extend(new_ids)
        self._set_pages_gauges()
        self._set_pinned_gauge()

    # ------------------------------------------------------------------
    # Allocation / LRU eviction
    # ------------------------------------------------------------------

    def _alloc(self) -> int | None:
        if self.free:
            return self.free.pop()
        if self._evict_one():
            return self.free.pop()
        return None

    def _evict_one(self) -> bool:
        """Reclaim the least-recently-used unreferenced LEAF (children keep
        their ancestors alive: evicting an interior page would strand the
        chain below it). Returns False when every leaf is pinned."""
        victim: PageNode | None = None
        for node in self._walk():
            if node.children or node.refs > 0:
                continue
            if victim is None or node.last_use < victim.last_use:
                victim = node
        if victim is None:
            return False
        del victim.parent.children[victim.key]
        self.free.append(victim.page_id)
        self.tel.evictions.inc()
        self._set_pages_gauges()
        return True
