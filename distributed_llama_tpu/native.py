"""ctypes bindings for the native host library (native/libdllama_native.so).

The compute path is JAX/XLA/Pallas; this library covers the *host* hot paths
around it — Q40 repacking/dequantization at weight-load time and BPE encode —
the same split the reference makes between its engine and its loaders.

Loading is best-effort: if the library isn't built (``make -C native``), every
caller falls back to the numpy/Python implementation, so the package works
from a clean checkout; the native path is an optimization, not a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_LIB_DIR, "libdllama_native.so")

_lib = None
_load_attempted = False


def _try_build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _LIB_DIR],
            capture_output=True, timeout=120, check=True,
        )
        return True
    except Exception:
        return False


def _stale() -> bool:
    """The built library is older than a source file (e.g. a checkout built
    before an ABI change): calling through a new prototype into an old
    binary corrupts memory, so rebuild first."""
    try:
        lib_mtime = os.path.getmtime(_LIB_PATH)
        # the Makefile is part of the ABI too (CXXFLAGS/defines changes)
        return any(
            os.path.getmtime(os.path.join(_LIB_DIR, f)) > lib_mtime
            for f in os.listdir(_LIB_DIR)
            if f.endswith((".cpp", ".h", ".hpp")) or f == "Makefile"
        )
    except OSError:
        return True


def load_library(build: bool = True):
    """Returns the loaded library or None. Builds it on first use if a
    toolchain is available (and rebuilds when sources are newer than the
    binary — the C ABI may have changed)."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if (not os.path.exists(_LIB_PATH) or _stale()) and build:
        if not _try_build():
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None

    lib.q40_dequant_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.q40_repack_tpu.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.bpe_new.restype = ctypes.c_void_p
    lib.bpe_new.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
    ]
    lib.bpe_free.argtypes = [ctypes.c_void_p]
    lib.bpe_encode.restype = ctypes.c_int32
    lib.bpe_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return load_library() is not None


# ---------------------------------------------------------------------------
# Q40
# ---------------------------------------------------------------------------


def q40_dequant_f32(blocks: np.ndarray, n_values: int) -> np.ndarray | None:
    """Dequantize raw Q40 file bytes → f32 [n_values]; None if lib missing."""
    lib = load_library()
    if lib is None:
        return None
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    out = np.empty(n_values, np.float32)
    lib.q40_dequant_f32(
        blocks.ctypes.data, n_values // 32, out.ctypes.data
    )
    return out


def q40_repack_tpu(blocks: np.ndarray, d_out: int, d_in: int, n_pad: int):
    """Repack raw Q40 file bytes to the half-split layout: (packed
    [n_pad/2, d_out] uint8, scales [n_pad/32, d_out] f32 with zero-scale
    padding rows); None if lib missing. ``n_pad`` is the caller's padded
    input dim (ops.q40._n_padded — the padding rule lives there, once)."""
    lib = load_library()
    if lib is None:
        return None
    if n_pad % 64 or n_pad < d_in:
        raise ValueError(f"n_pad {n_pad} must be a 64-multiple >= d_in {d_in}")
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    packed = np.zeros((n_pad // 2, d_out), np.uint8)  # OR-accumulated
    scales = np.zeros((n_pad // 32, d_out), np.float32)  # padding rows stay 0
    lib.q40_repack_tpu(
        blocks.ctypes.data, d_out, d_in, n_pad, packed.ctypes.data, scales.ctypes.data
    )
    return packed, scales


# ---------------------------------------------------------------------------
# BPE
# ---------------------------------------------------------------------------


class NativeBpe:
    """Owns a native tokenizer handle; mirrors Tokenizer.encode's core loop."""

    def __init__(self, vocab: list[bytes], scores: list[float]):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        blob = b"".join(vocab)
        offsets = np.zeros(len(vocab) + 1, np.int64)
        np.cumsum([len(t) for t in vocab], out=offsets[1:])
        self._blob = np.frombuffer(blob, np.uint8).copy()
        scores_arr = np.asarray(scores, np.float32)
        self._handle = lib.bpe_new(
            self._blob.ctypes.data,
            offsets.ctypes.data,
            scores_arr.ctypes.data,
            len(vocab),
        )

    def encode(self, text: bytes) -> list[int]:
        out = np.empty(len(text) + 1, np.int32)
        n = self._lib.bpe_encode(self._handle, text, len(text), out.ctypes.data)
        return out[:n].tolist()

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.bpe_free(self._handle)
            self._handle = None
