"""`dllama-analyze` — project-specific static analysis (ISSUE 5).

An AST rule engine that machine-checks the invariants this codebase has
actually shipped bugs against: use-after-donation of jitted buffers
(DON-001), scheduler-lock discipline (LCK-001/LCK-002), swallowed
``BaseException`` in recovery paths (EXC-001), wall-clock misuse
(CLK-001), and registry consistency for metric names (TEL-001) and fault
injection sites (FLT-001).

Run it as a module — this is the CI gate::

    python -m distributed_llama_tpu.analysis distributed_llama_tpu/

Inline suppression: ``# dllama: noqa[RULE-ID]`` on the flagged line (with
a comment stating the invariant that makes the site safe). Grandfathered
findings live in the committed baseline file (``analysis-baseline.txt``,
shipped empty). Configuration: ``[tool.dllama.analysis]`` in
pyproject.toml. Catalogue, history and workflow: docs/ANALYSIS.md.

The package imports only the standard library (no jax/numpy), so the gate
runs anywhere the repo checks out.
"""

from .config import AnalysisConfig, load_config
from .engine import Finding, analyze
from .rules import all_rules, rule_ids

__all__ = [
    "AnalysisConfig",
    "Finding",
    "all_rules",
    "analyze",
    "load_config",
    "rule_ids",
]
