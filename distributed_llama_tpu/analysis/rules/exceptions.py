"""EXC-001 — swallowed ``BaseException`` (or bare ``except:``).

History: PR 3's retry loops originally caught ``BaseException`` around the
batched dispatch/fetch, so a Ctrl-C mid-fetch was *retried into a row
quarantine* instead of aborting the process — the review fix narrowed them
to ``except Exception`` and the in-flight accounting moved to dedicated
cleanup-and-reraise handlers. The surviving legitimate shape is exactly
that: ``except BaseException: <undo>; raise``. This rule flags any
``BaseException``/bare handler whose body contains no ``raise`` at all —
the handler that can swallow a KeyboardInterrupt/SystemExit. Conditional
re-raises (``if not isinstance(e, Exception): raise``) count as raising;
the point is that an interpreter-exit path exists.
"""

from __future__ import annotations

import ast

from ..engine import FileCtx, Finding, ProjectContext, Rule


def _catches_base_exception(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True  # bare `except:`
    types = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        if isinstance(t, ast.Name) and t.id == "BaseException":
            return True
        if isinstance(t, ast.Attribute) and t.attr == "BaseException":
            return True
    return False


def _body_raises(handler: ast.ExceptHandler) -> bool:
    stack: list[ast.AST] = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue  # a raise inside a nested def runs later, if ever
        stack.extend(ast.iter_child_nodes(node))
    return False


class BaseExceptionRule(Rule):
    id = "EXC-001"
    severity = "error"
    short = "except BaseException / bare except that never re-raises"

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(fc.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _catches_base_exception(node):
                continue
            if _body_raises(node):
                continue
            what = "bare `except:`" if node.type is None else "`except BaseException`"
            out.append(
                self.finding(
                    fc,
                    node,
                    f"{what} without a re-raise swallows KeyboardInterrupt/"
                    "SystemExit — retry/recovery paths must catch"
                    " `Exception`; cleanup handlers must end in `raise`",
                )
            )
        return out
