"""TEL-001 / FLT-001 / TRC-001 — registry consistency for metric names,
fault injection sites, and trace span names.

* **TEL-001** — every string literal passed as the name of a
  ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` creation call must
  (a) match ``dllama_[a-z0-9_]+`` (one Prometheus namespace, no stray
  casing) and (b) appear in the docs/OBSERVABILITY.md metric table, so the
  scrape surface and its documentation cannot drift apart. The doc is
  parsed for metric-shaped tokens; a missing doc file downgrades the rule
  to regex-only (fixture corpora bring their own doc).

* **FLT-001** — every site string passed to ``FaultPlan.fire("...")`` /
  ``fires("...")`` must be registered in ``engine/faults.py``'s
  module-level ``SITES`` tuple (so ``--faults`` specs can actually target
  it), and — when the registry module itself is inside the scan, i.e. the
  scan plausibly covers all call sites — every registered site must be
  fired somewhere, flagging dead registry entries.

* **TRC-001** — every span-name literal passed to ``span(...)`` /
  ``trace_span(...)`` / ``add_span(...)`` (the ring tracer's and the
  request trace's recording calls) must be registered in
  ``telemetry/spans.py``'s module-level ``SPAN_NAMES`` tuple and
  documented in docs/OBSERVABILITY.md's span table. The FLT-001 shape
  exactly: unregistered names can't drift into the trace surface, and
  registered-but-never-emitted names are flagged dead when the registry
  module is inside the scan. The name literal may be the call's first or
  second positional argument (``ctx.add_span("name", ...)`` vs the
  module helper ``trace.span(ctx, "name")``).
"""

from __future__ import annotations

import ast
import os
import re

from ..engine import FileCtx, Finding, ProjectContext, Rule

_METRIC_FACTORIES = ("counter", "gauge", "histogram")
_SITES_KEY = "flt.sites"
_CALLS_KEY = "flt.calls"
_SPAN_FUNCS = ("span", "trace_span", "add_span")
_SPAN_CALLS_KEY = "trc.calls"


def _terminal_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _first_str_arg(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant):
        v = call.args[0].value
        if isinstance(v, str):
            return v
    return None


def _span_name_arg(call: ast.Call) -> str | None:
    """The span-name literal of a recording call: first positional string
    among args[0:2] — ``tel.span("name", ...)`` / ``add_span("name", ...)``
    put it first, the module helper ``trace.span(ctx, "name", ...)``
    second (behind the context)."""
    for arg in call.args[:2]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
    return None


def _registry_tuple(
    source: str, symbol: str
) -> tuple[set[str] | None, int]:
    """Parse ``symbol = ("...", ...)`` from a registry module's top level
    (the FLT-001/TRC-001 shared shape). Returns (names, lineno)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None, 1
    for node in tree.body:
        if isinstance(node, ast.Assign):
            target_names = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            target_names = [node.target.id]
            value = node.value
        else:
            continue
        if symbol not in target_names or not isinstance(
            value, (ast.Tuple, ast.List)
        ):
            continue
        names = {
            e.value
            for e in value.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
        return names, node.lineno
    return None, 1


class MetricNameRule(Rule):
    id = "TEL-001"
    severity = "warning"
    short = "metric literal malformed or missing from OBSERVABILITY.md"

    def prepare(self, project: ProjectContext) -> None:
        self._prefix = project.config.metric_prefix
        self._name_re = re.compile(
            "^" + re.escape(self._prefix) + r"[a-z0-9_]+$"
        )
        doc = project.read_aux(project.config.observability_doc)
        self._doc_names: set[str] | None = None
        if doc is not None:
            self._doc_names = set(
                re.findall(re.escape(self._prefix) + r"[a-z0-9_]+", doc)
            )

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(fc.tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal_name(node.func) not in _METRIC_FACTORIES:
                continue
            name = _first_str_arg(node)
            if name is None:
                continue
            if not self._name_re.match(name):
                # a missing prefix is the primary namespace drift, not an
                # exemption — every creation-site literal must carry it
                out.append(
                    self.finding(
                        fc,
                        node,
                        f"metric name `{name}` does not match"
                        f" `{self._prefix}[a-z0-9_]+` — one lowercase"
                        " Prometheus namespace, underscores only,"
                        f" `{self._prefix}` prefix required",
                    )
                )
            elif self._doc_names is not None and name not in self._doc_names:
                out.append(
                    self.finding(
                        fc,
                        node,
                        f"metric `{name}` is not documented in"
                        f" {project.config.observability_doc} — add it to"
                        " the metric table (TEL-001 keeps the scrape"
                        " surface and its docs in lockstep)",
                    )
                )
        return out


class FaultSiteRule(Rule):
    id = "FLT-001"
    severity = "warning"
    short = "fault site not registered in faults.SITES (or registered but dead)"

    def prepare(self, project: ProjectContext) -> None:
        self._registry_rel = os.path.normpath(project.config.fault_registry)
        self._sites: set[str] | None = None
        self._sites_lineno = 1
        source = project.read_aux(self._registry_rel)
        if source is not None:
            self._sites, self._sites_lineno = _registry_tuple(source, "SITES")
        project.shared[_CALLS_KEY] = []

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        out: list[Finding] = []
        calls: list = project.shared[_CALLS_KEY]  # type: ignore[assignment]
        for node in ast.walk(fc.tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal_name(node.func) not in ("fire", "fires"):
                continue
            site = _first_str_arg(node)
            if site is None:
                continue
            calls.append(site)
            if self._sites is not None and site not in self._sites:
                out.append(
                    self.finding(
                        fc,
                        node,
                        f"fault site `{site}` is not in the SITES registry"
                        f" of {self._registry_rel} — register it so"
                        " --faults rules can target it",
                    )
                )
        return out

    def finalize(self, project: ProjectContext) -> list[Finding]:
        # dead-site check: only meaningful when the scan covers the call
        # sites — require the registry module to be part of the scan and
        # not be the only scanned file
        fc = project.by_rel.get(self._registry_rel)
        if fc is None or self._sites is None or len(project.files) < 2:
            return []
        fired = set(project.shared[_CALLS_KEY])  # type: ignore[arg-type]
        out: list[Finding] = []
        for site in sorted(self._sites - fired):
            out.append(
                Finding(
                    rule=self.id,
                    severity=self.severity,
                    path=fc.rel,
                    line=self._sites_lineno,
                    col=0,
                    message=(
                        f"registered fault site `{site}` has no"
                        " fire()/fires() call site in the scanned tree —"
                        " dead registry entry (remove it, or wire the hook"
                        " back in)"
                    ),
                    qualname="",
                    source=fc.line_text(self._sites_lineno),
                )
            )
        return out


class SpanNameRule(Rule):
    id = "TRC-001"
    severity = "warning"
    short = (
        "span name not registered in telemetry/spans.py SPAN_NAMES, "
        "undocumented, or registered but dead"
    )

    def prepare(self, project: ProjectContext) -> None:
        self._registry_rel = os.path.normpath(project.config.span_registry)
        self._names: set[str] | None = None
        self._names_lineno = 1
        source = project.read_aux(self._registry_rel)
        if source is not None:
            self._names, self._names_lineno = _registry_tuple(
                source, "SPAN_NAMES"
            )
        # documented span names: any backticked token in the
        # observability doc (the span table); a missing doc downgrades
        # the rule to registry-only, like TEL-001's doc half
        doc = project.read_aux(project.config.observability_doc)
        self._doc_names: set[str] | None = None
        if doc is not None:
            self._doc_names = set(re.findall(r"`([a-z0-9_.]+)`", doc))
        project.shared[_SPAN_CALLS_KEY] = []

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        out: list[Finding] = []
        calls: list = project.shared[_SPAN_CALLS_KEY]  # type: ignore[assignment]
        for node in ast.walk(fc.tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal_name(node.func) not in _SPAN_FUNCS:
                continue
            name = _span_name_arg(node)
            if name is None:
                continue
            calls.append(name)
            if self._names is not None and name not in self._names:
                out.append(
                    self.finding(
                        fc,
                        node,
                        f"span name `{name}` is not in the SPAN_NAMES"
                        f" registry of {self._registry_rel} — register it"
                        " so the trace surface stays enumerable",
                    )
                )
            elif self._doc_names is not None and name not in self._doc_names:
                out.append(
                    self.finding(
                        fc,
                        node,
                        f"span name `{name}` is not documented in"
                        f" {project.config.observability_doc} — add it to"
                        " the span-name table (TRC-001 keeps the trace"
                        " surface and its docs in lockstep)",
                    )
                )
        return out

    def finalize(self, project: ProjectContext) -> list[Finding]:
        # dead-name check, FLT-001's exact shape: only when the registry
        # module is inside the scan and is not the only scanned file
        fc = project.by_rel.get(self._registry_rel)
        if fc is None or self._names is None or len(project.files) < 2:
            return []
        emitted = set(project.shared[_SPAN_CALLS_KEY])  # type: ignore[arg-type]
        out: list[Finding] = []
        for name in sorted(self._names - emitted):
            out.append(
                Finding(
                    rule=self.id,
                    severity=self.severity,
                    path=fc.rel,
                    line=self._names_lineno,
                    col=0,
                    message=(
                        f"registered span name `{name}` has no"
                        " span()/trace_span()/add_span() call site in the"
                        " scanned tree — dead registry entry (remove it,"
                        " or wire the span back in)"
                    ),
                    qualname="",
                    source=fc.line_text(self._names_lineno),
                )
            )
        return out
