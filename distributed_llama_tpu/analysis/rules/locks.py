"""LCK-001 / LCK-002 — lock discipline around ``BatchScheduler._cond``.

History: PR 4's Sarathi-style chunked prefill existed precisely because a
blocking prefill dispatch loop ran while ``self._cond`` was held, starving
co-batched decode joins for the whole prompt. The convention the scheduler
settled on — dispatch under the lock, block outside it, ``_locked``-suffixed
helpers assume the lock — lives in engine/batch.py's section comments.
These rules make the convention machine-checked:

* **LCK-001** — a call to a ``*_locked`` function must happen either
  lexically inside a ``with self._cond:`` (any configured lock attribute)
  or from a function that is itself ``*_locked``. Crossing a nested
  ``def``/``lambda`` boundary discards the guarantee (the closure runs
  later, lock state unknown).
* **LCK-002** — no blocking operation inside a lock-held region (a
  ``with self._cond:`` body or a ``*_locked`` function): device syncs
  (``block_until_ready``, ``jax.device_get``, ``np.asarray`` on device
  values), ``time.sleep``, the scheduler's blocking ``_fetch``, and
  socket/HTTP primitives. ``self._cond.wait()`` is exempt — it *releases*
  the lock while waiting.
* **LCK-003** — the declared lock hierarchy
  (``[tool.dllama.analysis.locks]``: "Class._attr" → rank, ascending
  acquire order, leaf locks max-rank) is enforced over an interprocedural
  acquisition graph: every ``with <lock>:`` region / ``.acquire()``
  window is walked for the locks it acquires lexically or transitively
  (through ``self.method``/``obj.method`` calls resolved within the
  scanned set), and an edge that acquires rank ≤ a held rank — or any
  cycle the graph closes — is a finding. History: PR 15's CPU mocks
  surfaced a real enqueue-order deadlock on the dispatch lock, and the
  scheduler→pool order lived only in prose (server/replicas.py) until
  this rule. Resolution is deliberately under-approximate (ambiguous
  attribute or method names are skipped) so the gate stays quiet on
  correct code; the runtime witness (distributed_llama_tpu/lockcheck.py)
  covers the dynamic edges the AST cannot see (callbacks, supervisor
  threads).
* **LCK-004** — an attribute mutated under a held lock anywhere in its
  class must not be mutated outside one elsewhere (``__init__`` is
  exempt: construction happens-before publication). History: PR 9
  shipped a real lost-update race on a bare ``self.replayed_total += 1``
  next to the locked mutation path.
"""

from __future__ import annotations

import ast

from ..engine import FileCtx, Finding, ProjectContext, Rule

# terminal call names that block the calling thread; np/jax-qualified
# entries are checked with their base, bare entries match any base
_BLOCKING_ATTRS = {"block_until_ready", "_fetch", "urlopen", "getaddrinfo",
                   "create_connection"}
_BLOCKING_QUALIFIED = {
    ("jax", "device_get"),
    ("np", "asarray"),
    ("numpy", "asarray"),
    ("time", "sleep"),
}


def _call_name(func: ast.AST) -> tuple[str | None, str | None]:
    """(base name or None, terminal name) of a call target."""
    if isinstance(func, ast.Name):
        return None, func.id
    if isinstance(func, ast.Attribute):
        base = func.value.id if isinstance(func.value, ast.Name) else None
        return base, func.attr
    return None, None


def _is_lock_expr(node: ast.AST, lock_attrs: tuple[str, ...]) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr in lock_attrs
    if isinstance(node, ast.Name):
        return node.id in lock_attrs
    return False


def _lock_state(fc: FileCtx, node: ast.AST, lock_attrs: tuple[str, ...]) -> bool:
    """True when the lock is known-held at ``node``: a ``with <lock>:``
    ancestor inside the same function, or an enclosing ``*_locked``
    function. Walking stops at the first function boundary — only that
    function's own name can vouch for the lock beyond it."""
    cur = node
    for anc in fc.ancestors(cur):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            if any(_is_lock_expr(i.context_expr, lock_attrs) for i in anc.items):
                return True
        elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc.name.endswith("_locked")
        elif isinstance(anc, ast.Lambda):
            return False
    return False


def _acquire_window_state(
    fc: FileCtx, node: ast.AST, lock_attrs: tuple[str, ...]
) -> bool:
    """True when ``node`` sits between an ``<lock>.acquire()`` call and
    the first matching ``<lock>.release()`` (or function end) in its own
    enclosing function — the try/finally trylock pattern the fleet ops
    path uses, which a ``with``-only check can't see."""
    fn = fc.enclosing_function(node)
    if fn is None or isinstance(fn, ast.Lambda):
        return False
    line = getattr(node, "lineno", 0)
    fn_end = max(getattr(fn, "end_lineno", fn.lineno), fn.lineno)
    for sub in ast.walk(fn):
        if not (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "acquire"
            and _is_lock_expr(sub.func.value, lock_attrs)
        ):
            continue
        end = fn_end
        for sub2 in ast.walk(fn):
            if (
                isinstance(sub2, ast.Call)
                and isinstance(sub2.func, ast.Attribute)
                and sub2.func.attr == "release"
                and ast.dump(sub2.func.value) == ast.dump(sub.func.value)
                and sub2.lineno > sub.lineno
            ):
                end = min(end, sub2.lineno)
        if sub.lineno <= line <= end:
            return True
    return False


class LockedCallRule(Rule):
    """LCK-001: ``*_locked`` helpers reached without the lock."""

    id = "LCK-001"
    severity = "error"
    short = "call to a *_locked function without holding the scheduler lock"

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        lock_attrs = project.config.lock_attrs
        out: list[Finding] = []
        for node in ast.walk(fc.tree):
            if not isinstance(node, ast.Call):
                continue
            _, name = _call_name(node.func)
            if not name or not name.endswith("_locked"):
                continue
            if _lock_state(fc, node, lock_attrs):
                continue
            out.append(
                self.finding(
                    fc,
                    node,
                    f"`{name}` follows the _locked convention (caller must"
                    f" hold {'/'.join(lock_attrs)}) but no enclosing"
                    " `with <lock>:` or *_locked function vouches for the"
                    " lock here",
                )
            )
        return out


class BlockingUnderLockRule(Rule):
    """LCK-002: blocking operations inside a lock-held region."""

    id = "LCK-002"
    severity = "error"
    short = "blocking call while holding the scheduler lock"

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        cfg = project.config
        lock_attrs = cfg.lock_attrs
        extra = set(cfg.blocking_calls)
        out: list[Finding] = []
        for node in ast.walk(fc.tree):
            if not isinstance(node, ast.Call):
                continue
            base, name = _call_name(node.func)
            if not name:
                continue
            blocking = (
                name in _BLOCKING_ATTRS
                or name in extra
                or (base, name) in _BLOCKING_QUALIFIED
                or (
                    name == "sleep"
                    and base is None
                    and fc.from_imports.get("sleep", ("", ""))[0] == "time"
                )
            )
            if not blocking:
                continue
            # cond.wait()/lock.acquire-style calls ON the lock are the
            # coordination primitives themselves, not foreign blocking work
            if isinstance(node.func, ast.Attribute) and _is_lock_expr(
                node.func.value, lock_attrs
            ):
                continue
            if not _lock_state(fc, node, lock_attrs):
                continue
            label = f"{base}.{name}" if base else name
            out.append(
                self.finding(
                    fc,
                    node,
                    f"blocking call `{label}(...)` while"
                    f" {'/'.join(lock_attrs)} is held — joins and co-batched"
                    " decode stall behind it (move it outside the `with`, or"
                    " justify with a noqa stating why the block is bounded)",
                )
            )
        return out


# ---------------------------------------------------------------------------
# LCK-003 — the declared lock hierarchy, statically enforced
# ---------------------------------------------------------------------------


def _enclosing_class(fc: FileCtx, node: ast.AST) -> str | None:
    for anc in fc.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc.name
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # keep walking: methods sit inside their class
            continue
    return None


class _LockIndex:
    """Cross-file facts for LCK-003/LCK-004: class→methods, method-name→
    owning classes, module-level functions, and the rank table. Shared via
    ``project.shared`` so both rules build it once."""

    KEY = "lck.index"

    def __init__(self, project: ProjectContext):
        self.ranks: dict[str, int] = dict(project.config.lock_ranks)
        self.classes: dict[str, dict[str, tuple[FileCtx, ast.AST]]] = {}
        self.method_owners: dict[str, set[str]] = {}
        self.module_funcs: dict[str, list[tuple[FileCtx, ast.AST]]] = {}
        self.class_locks: dict[str, list[str]] = {}
        for key in self.ranks:
            cls, _, _attr = key.rpartition(".")
            self.class_locks.setdefault(cls, []).append(key)
        for fc in project.files:
            for node in fc.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.module_funcs.setdefault(node.name, []).append(
                        (fc, node)
                    )
                elif isinstance(node, ast.ClassDef):
                    methods = self.classes.setdefault(node.name, {})
                    for item in node.body:
                        if isinstance(
                            item, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            methods.setdefault(item.name, (fc, item))
                            self.method_owners.setdefault(
                                item.name, set()
                            ).add(node.name)

    @classmethod
    def of(cls, project: ProjectContext) -> "_LockIndex":
        idx = project.shared.get(cls.KEY)
        if idx is None:
            idx = project.shared[cls.KEY] = cls(project)
        return idx

    # -- resolution (deliberately under-approximate) --------------------

    def resolve_lock(self, expr: ast.AST, cls_name: str | None) -> str | None:
        """"Class._attr" rank-table id for a lock expression, or None when
        the expression is computed or the attr name is ambiguous."""
        if not isinstance(expr, ast.Attribute):
            return None
        if not isinstance(expr.value, ast.Name):
            return None
        base, attr = expr.value.id, expr.attr
        if base == "self" and cls_name:
            key = f"{cls_name}.{attr}"
            if key in self.ranks:
                return key
        cands = [k for k in self.ranks if k.endswith("." + attr)]
        if len(cands) == 1:
            return cands[0]
        if base != "self" and len(cands) > 1:
            # `pool._cond` → ReplicaPool._cond: the variable name names
            # the class (the repo's pervasive convention)
            stem = base.strip("_").lower()
            hits = [k for k in cands if stem and stem in k.split(".")[0].lower()]
            if len(hits) == 1:
                return hits[0]
        return None

    def resolve_call(
        self, func: ast.AST, cls_name: str | None
    ) -> tuple[str, str] | None:
        """(class, method) / ("", function) key for a call target, or None
        when the target is computed, foreign, or ambiguous."""
        if isinstance(func, ast.Name):
            hits = self.module_funcs.get(func.id, [])
            return ("", func.id) if len(hits) == 1 else None
        if not (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
        ):
            return None
        base, name = func.value.id, func.attr
        if base == "self" and cls_name and name in self.classes.get(cls_name, {}):
            return (cls_name, name)
        owners = self.method_owners.get(name, set())
        if len(owners) == 1:
            return (next(iter(owners)), name)
        if base != "self" and len(owners) > 1:
            stem = base.strip("_").lower()
            hits = [o for o in owners if stem and stem in o.lower()]
            if len(hits) == 1:
                return (hits[0], name)
        return None

    def fn_of(self, key: tuple[str, str]) -> tuple[FileCtx, ast.AST] | None:
        cls, name = key
        if cls:
            return self.classes.get(cls, {}).get(name)
        hits = self.module_funcs.get(name, [])
        return hits[0] if len(hits) == 1 else None


class LockOrderRule(Rule):
    """LCK-003: acquisition edges that violate the declared lock ranks."""

    id = "LCK-003"
    severity = "error"
    short = "lock acquisition violates the declared [tool.dllama.analysis.locks] hierarchy"

    def prepare(self, project: ProjectContext) -> None:
        self._idx = _LockIndex.of(project)
        # (class, name) -> {"direct": {lock: node}, "calls": [(key, node, holders)]}
        self._fns: dict[tuple[str, str], dict] = {}
        self._eff: dict[tuple[str, str], dict[str, list[str]]] = {}
        self._edges: list[tuple[str, str, FileCtx, ast.AST, list[str]]] = []
        if not self._idx.ranks:
            return
        for fc in project.files:
            for node in ast.walk(fc.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._scan_function(fc, node)
        self._fixpoint()
        self._transitive_edges()

    # -- per-function lexical walk --------------------------------------

    def _scan_function(self, fc: FileCtx, fn: ast.AST) -> None:
        idx = self._idx
        cls = _enclosing_class(fc, fn)
        key = (cls or "", fn.name)
        info = self._fns.setdefault(
            key, {"direct": {}, "calls": [], "fc": fc}
        )
        # acquire()/release() windows: line spans inside this function
        windows: list[tuple[str, int, int]] = []
        end = max(getattr(fn, "end_lineno", fn.lineno), fn.lineno)
        for sub in ast.walk(fn):
            if not (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "acquire"
            ):
                continue
            lock = idx.resolve_lock(sub.func.value, cls)
            if lock is None:
                continue
            rel_lineno = sub.lineno
            rel_end = end
            for sub2 in ast.walk(fn):
                if (
                    isinstance(sub2, ast.Call)
                    and isinstance(sub2.func, ast.Attribute)
                    and sub2.func.attr == "release"
                    and idx.resolve_lock(sub2.func.value, cls) == lock
                    and sub2.lineno > rel_lineno
                ):
                    rel_end = min(rel_end, sub2.lineno)
            windows.append((lock, rel_lineno, rel_end))
            info["direct"].setdefault(lock, sub)
        held0: list[str] = []
        if fn.name.endswith("_locked") and cls:
            own = self._idx.class_locks.get(cls, [])
            if len(own) == 1:
                held0 = [own[0]]

        def window_holds(lineno: int) -> list[str]:
            return [w[0] for w in windows if w[1] <= lineno <= w[2]]

        def walk(node: ast.AST, held: list[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue  # runs later; lock state unknown (LCK-001's rule)
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    got = [
                        lock
                        for item in child.items
                        if (
                            lock := idx.resolve_lock(item.context_expr, cls)
                        )
                        is not None
                    ]
                    for lock in got:
                        info["direct"].setdefault(lock, child)
                        for held_lock in held + window_holds(child.lineno):
                            self._edge(held_lock, lock, fc, child, [])
                    walk(child, held + got)
                    continue
                if isinstance(child, ast.Call):
                    if (
                        isinstance(child.func, ast.Attribute)
                        and child.func.attr == "acquire"
                    ):
                        lock = idx.resolve_lock(child.func.value, cls)
                        if lock is not None:
                            holders = [
                                h
                                for h in held + window_holds(child.lineno)
                                if h != lock
                            ]
                            for held_lock in holders:
                                self._edge(held_lock, lock, fc, child, [])
                    callee = idx.resolve_call(child.func, cls)
                    if callee is not None:
                        holders = sorted(
                            set(held) | set(window_holds(child.lineno))
                        )
                        info["calls"].append((callee, child, holders))
                walk(child, held)

        walk(fn, held0)
        if held0:
            # the *_locked convention: the class lock is held on entry, so
            # every direct acquisition in the body is an edge from it
            for lock, node in info["direct"].items():
                if lock != held0[0]:
                    self._edge(held0[0], lock, fc, node, [])

    def _edge(
        self,
        held: str,
        acquired: str,
        fc: FileCtx,
        node: ast.AST,
        via: list[str],
    ) -> None:
        if held == acquired:
            return  # reentrant same-lock entry (Condition/RLock); the
            # runtime witness distinguishes plain-Lock self-deadlock
        self._edges.append((held, acquired, fc, node, via))

    # -- interprocedural closure ----------------------------------------

    def _fixpoint(self) -> None:
        # eff[f]: lock -> call-chain (qualnames) that reaches it from f
        eff: dict[tuple[str, str], dict[str, list[str]]] = {}
        for key, info in self._fns.items():
            eff[key] = {lock: [] for lock in info["direct"]}
        changed = True
        guard = 0
        while changed and guard < 50:
            changed = False
            guard += 1
            for key, info in self._fns.items():
                mine = eff[key]
                for callee, _node, _holders in info["calls"]:
                    sub = eff.get(callee)
                    if not sub:
                        continue
                    label = (
                        f"{callee[0]}.{callee[1]}" if callee[0] else callee[1]
                    )
                    for lock, chain in sub.items():
                        if lock not in mine:
                            mine[lock] = [label] + chain
                            changed = True
        self._eff = eff

    def _transitive_edges(self) -> None:
        for key, info in self._fns.items():
            fc = info["fc"]
            for callee, node, holders in info["calls"]:
                if not holders:
                    continue
                sub = self._eff.get(callee)
                if not sub:
                    continue
                label = (
                    f"{callee[0]}.{callee[1]}" if callee[0] else callee[1]
                )
                for lock, chain in sub.items():
                    for held in holders:
                        self._edge(held, lock, fc, node, [label] + chain)

    # -- findings -------------------------------------------------------

    def finalize(self, project: ProjectContext) -> list[Finding]:
        ranks = self._idx.ranks if self._idx.ranks else {}
        out: list[Finding] = []
        seen: set[tuple[str, int, str, str]] = set()
        graph: dict[str, set[str]] = {}
        for held, acquired, fc, node, via in self._edges:
            graph.setdefault(held, set()).add(acquired)
            r_held, r_acq = ranks[held], ranks[acquired]
            if r_acq > r_held:
                continue
            dedup = (fc.rel, getattr(node, "lineno", 0), held, acquired)
            if dedup in seen:
                continue
            seen.add(dedup)
            path = f" via {' -> '.join(via)}" if via else ""
            out.append(
                self.finding(
                    fc,
                    node,
                    f"acquires `{acquired}` (rank {r_acq}){path} while"
                    f" `{held}` (rank {r_held}) is held — the declared"
                    " hierarchy ([tool.dllama.analysis.locks]) requires"
                    " strictly ascending ranks; invert the nesting or"
                    " re-rank the table",
                )
            )
        cycle = self._find_cycle(graph, ranks)
        if cycle is not None:
            locs = self._edge_site(cycle[0], cycle[1])
            if locs is not None:
                fc, node = locs
                out.append(
                    self.finding(
                        fc,
                        node,
                        "lock acquisition graph contains a cycle: "
                        + " -> ".join(cycle + [cycle[0]])
                        + " — two threads taking opposite arcs deadlock",
                    )
                )
        return out

    def _edge_site(
        self, held: str, acquired: str
    ) -> tuple[FileCtx, ast.AST] | None:
        for h, a, fc, node, _via in self._edges:
            if h == held and a == acquired:
                return fc, node
        return None

    def _find_cycle(
        self, graph: dict[str, set[str]], ranks: dict[str, int]
    ) -> list[str] | None:
        """First cycle made ENTIRELY of rank-legal edges (rank-violating
        edges are already individual findings)."""
        legal = {
            n: {m for m in nbrs if ranks[m] > ranks[n]}
            for n, nbrs in graph.items()
        }
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in legal}
        stack: list[str] = []

        def dfs(n: str) -> list[str] | None:
            color[n] = GREY
            stack.append(n)
            for m in sorted(legal.get(n, ())):
                if color.get(m, WHITE) == GREY:
                    return stack[stack.index(m):]
                if color.get(m, WHITE) == WHITE:
                    found = dfs(m)
                    if found is not None:
                        return found
            stack.pop()
            color[n] = BLACK
            return None

        for n in sorted(legal):
            if color[n] == WHITE:
                found = dfs(n)
                if found is not None:
                    return found
        return None


# ---------------------------------------------------------------------------
# LCK-004 — unsynchronized shared-state mutation
# ---------------------------------------------------------------------------


class SharedStateMutationRule(Rule):
    """LCK-004: a ``self.x`` attribute mutated under a lock somewhere in
    its class must not be mutated without one elsewhere (PR 9's
    ``replayed_total`` lost-update). ``__init__`` is exempt both ways —
    construction happens-before publication."""

    id = "LCK-004"
    severity = "error"
    short = "attribute mutated both under a lock and without one"

    def prepare(self, project: ProjectContext) -> None:
        self._locked: dict[tuple[str, str], list[str]] = {}
        self._unlocked: dict[tuple[str, str], list[tuple[FileCtx, ast.AST]]] = {}
        lock_attrs = project.config.lock_attrs
        for fc in project.files:
            for node in ast.walk(fc.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.AugAssign):
                        targets = [sub.target]
                    elif isinstance(sub, ast.Assign):
                        targets = list(sub.targets)
                    elif isinstance(sub, ast.AnnAssign):
                        targets = [sub.target]
                    else:
                        continue
                    attrs = [
                        t.attr
                        for t in targets
                        if isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ]
                    if not attrs:
                        continue
                    fn = fc.enclosing_function(sub)
                    if fn is None or isinstance(fn, ast.Lambda):
                        continue
                    if fn.name == "__init__":
                        continue
                    if _enclosing_class(fc, fn) != node.name:
                        continue  # nested class's method
                    held = _lock_state(
                        fc, sub, lock_attrs
                    ) or _acquire_window_state(fc, sub, lock_attrs)
                    for attr in attrs:
                        if attr in lock_attrs:
                            continue  # rebinding the lock itself
                        key = (node.name, attr)
                        if held:
                            self._locked.setdefault(key, []).append(
                                fc.qualname(sub)
                            )
                        else:
                            self._unlocked.setdefault(key, []).append(
                                (fc, sub)
                            )

    def finalize(self, project: ProjectContext) -> list[Finding]:
        out: list[Finding] = []
        for key, sites in sorted(self._unlocked.items()):
            where = self._locked.get(key)
            if not where:
                continue
            cls, attr = key
            for fc, node in sites:
                out.append(
                    self.finding(
                        fc,
                        node,
                        f"`self.{attr}` is mutated under a lock in"
                        f" {sorted(set(where))[0]} but written here without"
                        " one — concurrent writers lose updates (the PR 9"
                        " `replayed_total` race); move this write under the"
                        " lock or noqa with the reason it cannot race",
                    )
                )
        return out
