"""LCK-001 / LCK-002 — lock discipline around ``BatchScheduler._cond``.

History: PR 4's Sarathi-style chunked prefill existed precisely because a
blocking prefill dispatch loop ran while ``self._cond`` was held, starving
co-batched decode joins for the whole prompt. The convention the scheduler
settled on — dispatch under the lock, block outside it, ``_locked``-suffixed
helpers assume the lock — lives in engine/batch.py's section comments.
These rules make the convention machine-checked:

* **LCK-001** — a call to a ``*_locked`` function must happen either
  lexically inside a ``with self._cond:`` (any configured lock attribute)
  or from a function that is itself ``*_locked``. Crossing a nested
  ``def``/``lambda`` boundary discards the guarantee (the closure runs
  later, lock state unknown).
* **LCK-002** — no blocking operation inside a lock-held region (a
  ``with self._cond:`` body or a ``*_locked`` function): device syncs
  (``block_until_ready``, ``jax.device_get``, ``np.asarray`` on device
  values), ``time.sleep``, the scheduler's blocking ``_fetch``, and
  socket/HTTP primitives. ``self._cond.wait()`` is exempt — it *releases*
  the lock while waiting.
"""

from __future__ import annotations

import ast

from ..engine import FileCtx, Finding, ProjectContext, Rule

# terminal call names that block the calling thread; np/jax-qualified
# entries are checked with their base, bare entries match any base
_BLOCKING_ATTRS = {"block_until_ready", "_fetch", "urlopen", "getaddrinfo",
                   "create_connection"}
_BLOCKING_QUALIFIED = {
    ("jax", "device_get"),
    ("np", "asarray"),
    ("numpy", "asarray"),
    ("time", "sleep"),
}


def _call_name(func: ast.AST) -> tuple[str | None, str | None]:
    """(base name or None, terminal name) of a call target."""
    if isinstance(func, ast.Name):
        return None, func.id
    if isinstance(func, ast.Attribute):
        base = func.value.id if isinstance(func.value, ast.Name) else None
        return base, func.attr
    return None, None


def _is_lock_expr(node: ast.AST, lock_attrs: tuple[str, ...]) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr in lock_attrs
    if isinstance(node, ast.Name):
        return node.id in lock_attrs
    return False


def _lock_state(fc: FileCtx, node: ast.AST, lock_attrs: tuple[str, ...]) -> bool:
    """True when the lock is known-held at ``node``: a ``with <lock>:``
    ancestor inside the same function, or an enclosing ``*_locked``
    function. Walking stops at the first function boundary — only that
    function's own name can vouch for the lock beyond it."""
    cur = node
    for anc in fc.ancestors(cur):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            if any(_is_lock_expr(i.context_expr, lock_attrs) for i in anc.items):
                return True
        elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc.name.endswith("_locked")
        elif isinstance(anc, ast.Lambda):
            return False
    return False


class LockedCallRule(Rule):
    """LCK-001: ``*_locked`` helpers reached without the lock."""

    id = "LCK-001"
    severity = "error"
    short = "call to a *_locked function without holding the scheduler lock"

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        lock_attrs = project.config.lock_attrs
        out: list[Finding] = []
        for node in ast.walk(fc.tree):
            if not isinstance(node, ast.Call):
                continue
            _, name = _call_name(node.func)
            if not name or not name.endswith("_locked"):
                continue
            if _lock_state(fc, node, lock_attrs):
                continue
            out.append(
                self.finding(
                    fc,
                    node,
                    f"`{name}` follows the _locked convention (caller must"
                    f" hold {'/'.join(lock_attrs)}) but no enclosing"
                    " `with <lock>:` or *_locked function vouches for the"
                    " lock here",
                )
            )
        return out


class BlockingUnderLockRule(Rule):
    """LCK-002: blocking operations inside a lock-held region."""

    id = "LCK-002"
    severity = "error"
    short = "blocking call while holding the scheduler lock"

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        cfg = project.config
        lock_attrs = cfg.lock_attrs
        extra = set(cfg.blocking_calls)
        out: list[Finding] = []
        for node in ast.walk(fc.tree):
            if not isinstance(node, ast.Call):
                continue
            base, name = _call_name(node.func)
            if not name:
                continue
            blocking = (
                name in _BLOCKING_ATTRS
                or name in extra
                or (base, name) in _BLOCKING_QUALIFIED
                or (
                    name == "sleep"
                    and base is None
                    and fc.from_imports.get("sleep", ("", ""))[0] == "time"
                )
            )
            if not blocking:
                continue
            # cond.wait()/lock.acquire-style calls ON the lock are the
            # coordination primitives themselves, not foreign blocking work
            if isinstance(node.func, ast.Attribute) and _is_lock_expr(
                node.func.value, lock_attrs
            ):
                continue
            if not _lock_state(fc, node, lock_attrs):
                continue
            label = f"{base}.{name}" if base else name
            out.append(
                self.finding(
                    fc,
                    node,
                    f"blocking call `{label}(...)` while"
                    f" {'/'.join(lock_attrs)} is held — joins and co-batched"
                    " decode stall behind it (move it outside the `with`, or"
                    " justify with a noqa stating why the block is bounded)",
                )
            )
        return out
