"""DON-001 — use-after-donation of ``jax.jit(donate_argnums=...)`` buffers.

The engine's hottest state — the batched slab KV cache, the prefix-page
pool, per-stream caches — is threaded through jitted calls with
``donate_argnums`` so XLA aliases the output over the input buffer. After
the call dispatches, the donated array is DELETED: any later read raises
``RuntimeError: Array has been deleted`` at best, or silently observes
aliased bytes under disabled checking. Every donation call site in this
repo follows the self-healing idiom ``x = f(x)`` (the donated name is
rebound by the result in the same statement); this rule flags the ones
that don't.

Mechanics (two passes):

1. ``prepare`` builds a project-wide donation table:
   * module-level ``def`` decorated with ``jax.jit``/``functools.partial(
     jax.jit, ..., donate_argnums=(k,...))`` — keyed by bare name, reached
     from other files through imported-module attribute calls
     (``sampling.decode_chunk(...)``) or ``from`` imports;
   * ``self.X = jax.jit(fn, donate_argnums=...)`` and the one-step
     propagations ``j = jax.jit(...); self.X = j`` and ``self.X =
     functools.partial(donor, a, b)`` (indices shift left by the number of
     bound leading args) — keyed by attribute name, file-scoped.
2. ``check`` walks each function: at a donating call whose donated
   positional argument is a simple name/attribute chain, the chain is
   poisoned from the end of that statement unless the same statement's
   assignment targets rebind it; any later load of the chain before a
   rebinding statement is a finding. Nested ``def``/``lambda`` bodies are
   skipped (they execute at an unknown time).

This is a lexical, single-block approximation: loops that donate on one
iteration and read on the next are out of scope (none exist here — the
fixture corpus pins the supported shapes).
"""

from __future__ import annotations

import ast

from ..engine import FileCtx, Finding, ProjectContext, Rule, assigned_keys, expr_key

_SHARED_KEY = "don.table"


def _donate_indices_of_jit_call(call: ast.Call) -> set[int] | None:
    """Indices from a ``jax.jit(...)`` or ``functools.partial(jax.jit,
    ...)`` call expression carrying ``donate_argnums``; None if this isn't
    such an expression."""
    func = call.func
    is_jit = (
        isinstance(func, ast.Attribute)
        and func.attr == "jit"
        or isinstance(func, ast.Name)
        and func.id == "jit"
    )
    is_partial_of_jit = (
        isinstance(func, ast.Attribute)
        and func.attr == "partial"
        or isinstance(func, ast.Name)
        and func.id == "partial"
    ) and any(
        (isinstance(a, ast.Attribute) and a.attr == "jit")
        or (isinstance(a, ast.Name) and a.id == "jit")
        for a in call.args[:1]
    )
    if not (is_jit or is_partial_of_jit):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _int_tuple(kw.value)
    return None


def _int_tuple(node: ast.AST) -> set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        }
    return set()


class _DonationTable:
    def __init__(self):
        # bare function name -> donated positional indices (module-level
        # jitted defs, merged project-wide; collisions union)
        self.defs: dict[str, set[int]] = {}
        # (file rel, name) -> indices for file-local `j = jax.jit(...)`
        self.names: dict[tuple[str, str], set[int]] = {}
        # (file rel, attr) -> indices for `self.X = jax.jit(...)` bindings
        self.attrs: dict[tuple[str, str], set[int]] = {}

    def resolve(self, fc: FileCtx, call: ast.Call) -> tuple[set[int], str] | None:
        func = call.func
        if isinstance(func, ast.Name):
            hit = self.names.get((fc.rel, func.id))
            if hit:
                return hit, func.id
            target = fc.from_imports.get(func.id, (None, func.id))[1]
            hit = self.defs.get(target)
            if hit:
                return hit, func.id
            return None
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id in fc.module_aliases:
                hit = self.defs.get(func.attr)
                if hit:
                    return hit, f"{base.id}.{func.attr}"
                return None
            # instance attribute bound to a jitted callable in this file
            hit = self.attrs.get((fc.rel, func.attr))
            if hit:
                return hit, f"<instance>.{func.attr}"
        return None


def _partial_target_indices(
    table: _DonationTable, fc: FileCtx, target: ast.AST
) -> set[int]:
    """Donated indices of the callable being wrapped by ``functools.
    partial(target, ...)``. Unlike call-site resolution, a plain-attribute
    target (``self._forward_single``) falls back to the decorated-def
    table by terminal name — the wrapped function is being *named*, not
    called through an arbitrary object."""
    if isinstance(target, ast.Name):
        return (
            table.names.get((fc.rel, target.id))
            or table.defs.get(fc.from_imports.get(target.id, (None, target.id))[1])
            or set()
        )
    if isinstance(target, ast.Attribute):
        return (
            table.attrs.get((fc.rel, target.attr))
            or table.defs.get(target.attr)
            or set()
        )
    return set()


class DonationRule(Rule):
    id = "DON-001"
    severity = "error"
    short = "read of a buffer after it was donated to a jitted call"

    # -- pass 1: donation table -----------------------------------------

    def prepare(self, project: ProjectContext) -> None:
        table = _DonationTable()
        # sweep 1: decorated defs + direct jax.jit(...) bindings
        for fc in project.files:
            for node in ast.walk(fc.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if isinstance(dec, ast.Call):
                            idxs = _donate_indices_of_jit_call(dec)
                            if idxs:
                                table.defs.setdefault(node.name, set()).update(idxs)
                elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    idxs = _donate_indices_of_jit_call(node.value)
                    if not idxs:
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Attribute):
                            table.attrs.setdefault((fc.rel, t.attr), set()).update(idxs)
                        elif isinstance(t, ast.Name):
                            table.names.setdefault((fc.rel, t.id), set()).update(idxs)
        # sweep 2: one-step propagation (`self.X = jitted_local` and
        # `self.X = functools.partial(donor, a, b, ...)`)
        for fc in project.files:
            for node in ast.walk(fc.tree):
                if not (isinstance(node, ast.Assign) and node.targets):
                    continue
                idxs: set[int] = set()
                value = node.value
                if isinstance(value, ast.Name):
                    idxs = table.names.get((fc.rel, value.id), set())
                elif isinstance(value, ast.Call):
                    func = value.func
                    is_partial = (
                        isinstance(func, ast.Attribute) and func.attr == "partial"
                    ) or (isinstance(func, ast.Name) and func.id == "partial")
                    if is_partial and value.args:
                        inner = _partial_target_indices(table, fc, value.args[0])
                        if inner:
                            bound = len(value.args) - 1
                            idxs = {i - bound for i in inner if i - bound >= 0}
                if not idxs:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        table.attrs.setdefault((fc.rel, t.attr), set()).update(idxs)
                    elif isinstance(t, ast.Name):
                        table.names.setdefault((fc.rel, t.id), set()).update(idxs)
        project.shared[_SHARED_KEY] = table

    # -- pass 2: per-function read-after-donation ------------------------

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        table: _DonationTable = project.shared[_SHARED_KEY]  # type: ignore[assignment]
        out: list[Finding] = []
        scopes: list[ast.AST] = [fc.tree] + [
            n
            for n in ast.walk(fc.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            out.extend(self._check_scope(table, fc, scope))
        return out

    def _walk_scope(self, scope: ast.AST):
        """Walk a function body without descending into nested functions
        (their execution time is unknown)."""
        body = scope.body if hasattr(scope, "body") else []
        stack = list(body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _check_scope(
        self, table: _DonationTable, fc: FileCtx, scope: ast.AST
    ) -> list[Finding]:
        # (poison position, donated key, callee label, donated index)
        poisons: list[tuple[tuple[int, int], str, str, int]] = []
        kills: dict[str, list[tuple[int, int]]] = {}
        loads: dict[str, list[tuple[tuple[int, int], ast.AST]]] = {}
        keys_of_interest: set[str] = set()

        # first sweep of the scope: find donations and rebinding statements
        for node in self._walk_scope(scope):
            if isinstance(node, ast.stmt):
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    # the target rebinds at the loop HEADER — body loads
                    # are healed, loads in the iterable itself are not
                    kill_line = node.iter.end_lineno or node.lineno
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    kill_line = max(
                        i.context_expr.end_lineno or node.lineno
                        for i in node.items
                    )
                else:
                    kill_line = node.end_lineno or node.lineno
                for key in assigned_keys(node):
                    kills.setdefault(key, []).append((kill_line, 10**9))
            elif isinstance(node, ast.NamedExpr):
                key = expr_key(node.target)
                if key:
                    kills.setdefault(key, []).append(
                        (node.end_lineno or node.lineno, 10**9)
                    )
            if not isinstance(node, ast.Call):
                continue
            hit = table.resolve(fc, node)
            if hit is None:
                continue
            indices, label = hit
            stmt = fc.statement_of(node)
            if isinstance(stmt, (ast.Return, ast.Raise)):
                # control flow leaves the scope with the donating call —
                # no later read in this scope is reachable
                continue
            rebound = assigned_keys(stmt)
            for idx in sorted(indices):
                if idx >= len(node.args):
                    continue
                key = expr_key(node.args[idx])
                if key is None or key in rebound:
                    continue  # computed arg, or the self-healing `x = f(x)`
                keys_of_interest.add(key)
                poisons.append(
                    (
                        (stmt.end_lineno or stmt.lineno, 10**9),
                        key,
                        label,
                        idx,
                    )
                )
        if not poisons:
            return []

        # second sweep: loads of the poisoned chains. An AugAssign target
        # (`cache += 1`) READS the deleted value first, so it is a load,
        # never a heal.
        for node in self._walk_scope(scope):
            key = None
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                key = expr_key(node)
            elif isinstance(node, ast.AugAssign):
                key = expr_key(node.target)
            if key in keys_of_interest:
                loads.setdefault(key, []).append(
                    ((node.lineno, node.col_offset), node)
                )

        out: list[Finding] = []
        flagged: set[tuple[int, int]] = set()
        for poison_pos, key, label, idx in poisons:
            for load_pos, load_node in loads.get(key, []):
                if load_pos <= poison_pos:
                    continue
                healed = any(
                    poison_pos < kill_pos < load_pos
                    for kill_pos in kills.get(key, [])
                )
                if healed or load_pos in flagged:
                    continue
                flagged.add(load_pos)
                out.append(
                    self.finding(
                        fc,
                        load_node,
                        f"`{key}` is read here but was donated to"
                        f" `{label}` (donate_argnums index {idx}) on line"
                        f" {poison_pos[0]} — the buffer is deleted at"
                        " dispatch; rebind it from the call's result"
                        " (`x = f(x)`) before any further use",
                    )
                )
        return out
