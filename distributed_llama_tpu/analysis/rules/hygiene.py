"""GEN-002 — suppression hygiene: a ``# dllama: noqa[...]`` that
suppresses nothing is itself a finding.

A noqa is a claim ("this line violates RULE-ID for a reason the AST can't
see"); when the flagged code is later fixed or moved, the stale comment
keeps advertising a violation that no longer exists — and worse, keeps a
blanket hole open for FUTURE violations on that line. The engine tracks
which suppressions actually absorbed a finding during the run and this
rule flags, per noqa comment:

* a rule-scoped id that names an unknown rule (typo — it can never
  suppress anything),
* a rule-scoped id whose rule RAN in this scan and produced nothing on
  that line,
* a bare ``# dllama: noqa`` that absorbed nothing — only on a full scan
  (all rules selected), since a partial ``--select`` run can't prove a
  blanket suppression useless.

``noqa[GEN-002]`` on the same line opts a deliberate placeholder out.
GEN-002 findings are exempt from the line's own suppression (a bare noqa
must not hide its own uselessness) but respect the baseline like every
rule. The logic runs in the engine's post-suppression hook
(:meth:`post_suppression`) because only the driver knows which findings
each noqa absorbed.
"""

from __future__ import annotations

from ..engine import Finding, ProjectContext, Rule


class UselessNoqaRule(Rule):
    """GEN-002: stale/ineffective ``# dllama: noqa`` comments."""

    id = "GEN-002"
    severity = "warning"
    short = "noqa comment that suppresses nothing"

    def post_suppression(
        self,
        project: ProjectContext,
        active_ids: set[str],
        used: set[tuple[str, int, str | None]],
    ) -> list[Finding]:
        from . import rule_ids

        known = set(rule_ids())
        full_scan = active_ids >= known
        out: list[Finding] = []
        for fc in project.files:
            for line, ids in sorted(fc.noqa.items()):
                if ids is None:
                    if full_scan and (fc.rel, line, None) not in used:
                        out.append(
                            self._at(
                                fc,
                                line,
                                "bare `# dllama: noqa` suppresses nothing"
                                " on a full scan — remove it (it also"
                                " blanket-hides any future finding on"
                                " this line)",
                            )
                        )
                    continue
                if "GEN-002" in ids:
                    continue  # deliberate opt-out for the whole line
                for rid in sorted(ids):
                    if rid not in known:
                        out.append(
                            self._at(
                                fc,
                                line,
                                f"`noqa[{rid}]` names an unknown rule id"
                                " — it can never suppress anything"
                                " (typo?)",
                            )
                        )
                    elif rid in active_ids and (fc.rel, line, rid) not in used:
                        out.append(
                            self._at(
                                fc,
                                line,
                                f"`noqa[{rid}]` suppresses nothing —"
                                f" {rid} produced no finding on this"
                                " line; the violation it grandfathered"
                                " is gone, remove the comment",
                            )
                        )
        return out

    def _at(self, fc, line: int, message: str) -> Finding:
        return Finding(
            rule=self.id,
            severity=self.severity,
            path=fc.rel,
            line=line,
            col=0,
            message=message,
            qualname="",
            source=fc.line_text(line),
        )
