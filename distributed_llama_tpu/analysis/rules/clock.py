"""CLK-001 — ``time.time()`` outside the wall-clock allowlist.

History: PR 1's observability sweep found request durations measured with
``time.time()`` in server/api.py — an NTP step mid-request yields negative
or wildly wrong latencies. Every duration in this repo now flows through
``telemetry.Stopwatch`` (``perf_counter``) or ``time.monotonic`` for
deadlines; the only legitimate wall-clock reads are *timestamps shown to
users* — the OpenAI-compatible ``created`` fields. Those sites live in the
``clock_allow`` list of ``[tool.dllama.analysis]`` (``"relpath"`` or
``"relpath::qualname-glob"`` entries); everything else is a finding.
"""

from __future__ import annotations

import ast
import fnmatch

from ..engine import FileCtx, Finding, ProjectContext, Rule


def _is_time_time(node: ast.Call, fc: FileCtx) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr == "time":
        return isinstance(func.value, ast.Name) and func.value.id == "time"
    if isinstance(func, ast.Name):
        # `from time import time` under any alias
        return fc.from_imports.get(func.id, ("", ""))[:2] == ("time", "time")
    return False


class WallClockRule(Rule):
    id = "CLK-001"
    severity = "warning"
    short = "time.time() outside the wall-clock allowlist"

    def _allowed(self, project: ProjectContext, fc: FileCtx, qualname: str) -> bool:
        for entry in project.config.clock_allow:
            path_glob, _, qual_glob = entry.partition("::")
            if not fnmatch.fnmatch(fc.rel, path_glob):
                continue
            if not qual_glob or fnmatch.fnmatch(qualname, qual_glob):
                return True
        return False

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(fc.tree):
            if not (isinstance(node, ast.Call) and _is_time_time(node, fc)):
                continue
            if self._allowed(project, fc, fc.qualname(node)):
                continue
            out.append(
                self.finding(
                    fc,
                    node,
                    "`time.time()` is wall-clock: durations belong to"
                    " telemetry.Stopwatch/perf_counter, deadlines to"
                    " time.monotonic — if this really is a user-facing"
                    " timestamp, add the site to `clock_allow` in"
                    " [tool.dllama.analysis]",
                )
            )
        return out
