"""FLS-001 — the falsy-default bug class: ``param or DEFAULT`` eats a
meaningful zero.

History: this exact shape shipped three times — PR 3's
``admission_queue=0`` (an explicit "unbounded queue" request silently
became the default bound) and twice in PR 9 (``--replica-suspect-s 0``
meaning "suspect immediately" fell back to the 30s default). A numeric
parameter where ``0`` is a legal, meaningful value must be defaulted with
an ``is None`` check, never truthiness.

The rule flags ``param or <number>`` and ``param if param else <number>``
where ``param`` is a parameter of the enclosing function and the fallback
is a numeric literal (int/float, not bool). The numeric-literal
requirement is the precision filter: ``restart_policy or BackoffPolicy()``
style object defaults stay legal, because for object/str parameters
falsiness and missingness coincide in this codebase.
"""

from __future__ import annotations

import ast

from ..engine import FileCtx, Finding, ProjectContext, Rule


def _param_names(fn: ast.AST) -> set[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return {n for n in names if n not in ("self", "cls")}


def _numeric_const(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Constant)
        and isinstance(node.value, (int, float))
        and not isinstance(node.value, bool)
    )


class FalsyDefaultRule(Rule):
    """FLS-001: ``param or <number>`` treats a meaningful 0 as missing."""

    id = "FLS-001"
    severity = "warning"
    short = "falsy-default on a numeric parameter (`x or N` eats a meaningful 0)"

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(fc.tree):
            hit = self._match(fc, node)
            if hit is None:
                continue
            param, default = hit
            out.append(
                self.finding(
                    fc,
                    node,
                    f"`{param} or {default}` swallows an explicit"
                    f" `{param}=0` into the {default} default (the PR 3 /"
                    " PR 9 falsy-default bug) — write"
                    f" `{default} if {param} is None else {param}`",
                )
            )
        return out

    def _match(self, fc: FileCtx, node: ast.AST) -> tuple[str, object] | None:
        """(param name, fallback literal) for a flagged expression."""
        if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or):
            if len(node.values) != 2:
                return None
            lhs, rhs = node.values
            if not (isinstance(lhs, ast.Name) and _numeric_const(rhs)):
                return None
            name, fallback = lhs.id, rhs.value
        elif isinstance(node, ast.IfExp):
            if not (
                isinstance(node.test, ast.Name)
                and isinstance(node.body, ast.Name)
                and node.test.id == node.body.id
                and _numeric_const(node.orelse)
            ):
                return None
            name, fallback = node.test.id, node.orelse.value
        else:
            return None
        fn = fc.enclosing_function(node)
        if fn is None or isinstance(fn, ast.Lambda):
            return None
        if name not in _param_names(fn):
            return None
        return name, fallback
