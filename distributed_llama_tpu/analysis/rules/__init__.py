"""Rule catalogue for `dllama-analyze`. Each rule encodes an invariant
this repo has shipped (and review-caught) a real bug against — the
histories live in the rule modules' docstrings and docs/ANALYSIS.md."""

from __future__ import annotations

from ..engine import Rule
from .clock import WallClockRule
from .donation import DonationRule
from .exceptions import BaseExceptionRule
from .falsy import FalsyDefaultRule
from .hygiene import UselessNoqaRule
from .locks import (
    BlockingUnderLockRule,
    LockedCallRule,
    LockOrderRule,
    SharedStateMutationRule,
)
from .registries import FaultSiteRule, MetricNameRule, SpanNameRule

_RULE_CLASSES = (
    DonationRule,       # DON-001
    LockedCallRule,     # LCK-001
    BlockingUnderLockRule,  # LCK-002
    LockOrderRule,      # LCK-003
    SharedStateMutationRule,  # LCK-004
    BaseExceptionRule,  # EXC-001
    WallClockRule,      # CLK-001
    FalsyDefaultRule,   # FLS-001
    MetricNameRule,     # TEL-001
    FaultSiteRule,      # FLT-001
    SpanNameRule,       # TRC-001
    UselessNoqaRule,    # GEN-002
)


def all_rules(select: set[str] | None = None) -> list[Rule]:
    """Fresh rule instances (rules carry per-run prepare() state), filtered
    to ``select`` ids when given."""
    rules = [cls() for cls in _RULE_CLASSES]
    if select:
        wanted = {s.upper() for s in select}
        rules = [r for r in rules if r.id in wanted]
    return rules


def rule_ids() -> list[str]:
    return [cls.id for cls in _RULE_CLASSES]
