"""Configuration for the `dllama-analyze` rule engine (ISSUE 5).

Configuration is committed, not flag-soup: the `[tool.dllama.analysis]`
section of pyproject.toml holds the baseline path, the registry/doc
locations the consistency rules (TEL-001 / FLT-001) cross-check, and the
allowlists (CLK-001's wall-clock-appropriate sites, extra lock attributes,
extra blocking-call names). The CLI discovers the nearest pyproject.toml
above the first scanned path; tests construct :class:`AnalysisConfig`
directly.

Python 3.10 has no ``tomllib``, and this repo adds no dependencies, so a
minimal TOML-subset reader backs the loader up: table headers, ``key =
"string"`` / ``key = ["a", "b"]`` (arrays may span lines) / booleans /
integers. The committed section stays inside that subset.
"""

from __future__ import annotations

import dataclasses
import os
import re


@dataclasses.dataclass
class AnalysisConfig:
    """Resolved analyzer configuration. Paths are relative to :attr:`root`
    (the directory holding the pyproject.toml they came from)."""

    root: str = "."
    # committed fingerprints of grandfathered findings ("" disables)
    baseline: str = "analysis-baseline.txt"
    # TEL-001: every metric literal must appear in this document's table
    observability_doc: str = "docs/OBSERVABILITY.md"
    # FLT-001: the module whose top-level SITES tuple registers fault sites
    fault_registry: str = "distributed_llama_tpu/engine/faults.py"
    # TRC-001: the module whose top-level SPAN_NAMES tuple registers
    # trace span names
    span_registry: str = "distributed_llama_tpu/telemetry/spans.py"
    # LCK-001/002: attribute names that count as "the scheduler lock".
    # When `lock_ranks` is set (the `[tool.dllama.analysis.locks]` table)
    # this is DERIVED from the declared lock names — the flat list only
    # survives as an override for rank-less setups.
    lock_attrs: tuple[str, ...] = ("_cond",)
    # LCK-003 / lockcheck: the declared lock hierarchy as ("Class._attr",
    # rank) pairs — lower rank acquires first, leaf locks are max-rank.
    # Committed once in pyproject's [tool.dllama.analysis.locks] table;
    # both the static rule and the runtime witness read this.
    lock_ranks: tuple[tuple[str, int], ...] = ()
    # CLK-001: "relpath" or "relpath::qualname-glob" entries where
    # time.time() is wall-clock-appropriate (API `created` fields)
    clock_allow: tuple[str, ...] = ()
    # LCK-002: extra call names (terminal attribute / function name)
    # treated as blocking in addition to the built-in set
    blocking_calls: tuple[str, ...] = ()
    # fnmatch globs of relpaths to skip entirely
    exclude: tuple[str, ...] = ()
    metric_prefix: str = "dllama_"

    def __post_init__(self) -> None:
        if self.lock_ranks:
            # normalize (accept dicts / lists from loaders) and derive the
            # flat attr list the lexical rules key on from the ranked names
            pairs = dict(self.lock_ranks)
            self.lock_ranks = tuple(
                sorted((str(k), int(v)) for k, v in pairs.items())
            )
            derived = {k.rsplit(".", 1)[-1] for k, _ in self.lock_ranks}
            self.lock_attrs = tuple(sorted(derived | set(self.lock_attrs)))

    def rank_of(self, lock_id: str) -> int | None:
        for key, rank in self.lock_ranks:
            if key == lock_id:
                return rank
        return None

    def rel_to_root(self, path: str) -> str:
        return os.path.normpath(os.path.join(self.root, path))


_KEYS = {
    "baseline": str,
    "observability_doc": str,
    "fault_registry": str,
    "span_registry": str,
    "lock_attrs": tuple,
    "clock_allow": tuple,
    "blocking_calls": tuple,
    "exclude": tuple,
    "metric_prefix": str,
}


def _parse_toml_section(text: str, section: str) -> dict:
    """Extract one table from TOML source without a TOML library: scan to
    the ``[section]`` header, then read ``key = value`` pairs (strings,
    string arrays — possibly multi-line — booleans, ints) until the next
    table header."""
    lines = text.splitlines()
    out: dict = {}
    in_section = False
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if line.startswith("["):
            in_section = line == f"[{section}]"
            continue
        if not in_section or not line or line.startswith("#"):
            continue
        # keys may be bare or quoted — the locks table uses quoted
        # "Class._attr" keys, which plain TOML requires to be strings
        m = re.match(r'(?:"([^"]+)"|([A-Za-z0-9_.-]+))\s*=\s*(.*)$', line)
        if not m:
            continue
        key, value = m.group(1) or m.group(2), m.group(3).strip()
        if value.startswith("["):
            # accumulate until the array's brackets balance
            while value.count("[") > value.count("]") and i < len(lines):
                value += " " + lines[i].strip()
                i += 1
            out[key] = re.findall(r'"((?:[^"\\]|\\.)*)"', value)
        elif value.startswith('"'):
            sm = re.match(r'"((?:[^"\\]|\\.)*)"', value)
            out[key] = sm.group(1) if sm else ""
        elif value in ("true", "false"):
            out[key] = value == "true"
        else:
            try:
                out[key] = int(value.split("#")[0].strip())
            except ValueError:
                out[key] = value
        # strip inline comments from bare strings only; quoted forms above
        # already isolated their payload
    return out


def _read_section(pyproject_path: str) -> dict:
    with open(pyproject_path, "r", encoding="utf-8") as f:
        text = f.read()
    try:
        import tomllib  # Python >= 3.11

        data = tomllib.loads(text)
        return data.get("tool", {}).get("dllama", {}).get("analysis", {})
    except ModuleNotFoundError:
        section = _parse_toml_section(text, "tool.dllama.analysis")
        locks = _parse_toml_section(text, "tool.dllama.analysis.locks")
        if locks:
            section["locks"] = locks
        return section


def find_pyproject(start: str) -> str | None:
    """Walk up from ``start`` (file or directory) to the nearest
    pyproject.toml containing a ``[tool.dllama.analysis]`` section, falling
    back to the nearest pyproject.toml at all."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    first_any = None
    while True:
        cand = os.path.join(d, "pyproject.toml")
        if os.path.isfile(cand):
            if first_any is None:
                first_any = cand
            if _read_section(cand):
                return cand
        parent = os.path.dirname(d)
        if parent == d:
            return first_any
        d = parent


def load_config(start: str | None = None, pyproject: str | None = None) -> AnalysisConfig:
    """Build an :class:`AnalysisConfig` from the pyproject.toml nearest to
    ``start`` (or the explicit ``pyproject`` path). Unknown keys are
    ignored; missing file/section yields the defaults rooted at ``start``."""
    path = pyproject or (find_pyproject(start or os.getcwd()))
    if path is None:
        return AnalysisConfig(root=os.path.abspath(start or os.getcwd()))
    section = _read_section(path)
    kwargs: dict = {"root": os.path.dirname(os.path.abspath(path))}
    for key, typ in _KEYS.items():
        if key in section:
            val = section[key]
            kwargs[key] = tuple(val) if typ is tuple else typ(val)
    locks = section.get("locks")
    if isinstance(locks, dict) and locks:
        kwargs["lock_ranks"] = tuple(
            sorted((str(k), int(v)) for k, v in locks.items())
        )
    return AnalysisConfig(**kwargs)
