"""CLI for `dllama-analyze`: ``python -m distributed_llama_tpu.analysis``.

Exit codes: 0 = clean (after noqa + baseline), 1 = findings at or above
``--fail-level``, 2 = usage or internal error. ``--write-baseline``
snapshots the current findings as grandfathered and exits 0 — the
intended workflow keeps that file empty (docs/ANALYSIS.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .config import load_config
from .engine import SEVERITIES, analyze, write_baseline
from .rules import all_rules, rule_ids


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m distributed_llama_tpu.analysis",
        description="AST rule engine enforcing this repo's donation, "
        "lock-discipline and telemetry invariants (docs/ANALYSIS.md)",
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files or directories to scan (default: the installed "
        "distributed_llama_tpu package directory)",
    )
    p.add_argument(
        "--select",
        default="",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text", dest="fmt"
    )
    p.add_argument(
        "--config",
        default=None,
        help="explicit pyproject.toml (default: nearest above the first path)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help="baseline file overriding the configured one",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="report findings even when baselined",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="snapshot current findings into the baseline file and exit 0",
    )
    p.add_argument(
        "--fail-level",
        choices=SEVERITIES,
        default="warning",
        help="minimum severity that fails the run (default: warning — "
        "every finding fails, which is what CI wants)",
    )
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  [{rule.severity}]  {rule.short}")
        return 0

    paths = args.paths or [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    for path in paths:
        if not os.path.exists(path):
            print(f"error: no such path: {path}", file=sys.stderr)
            return 2

    try:
        config = load_config(start=paths[0], pyproject=args.config)
    except Exception as e:  # malformed pyproject is a usage error, not a crash
        print(f"error: could not load configuration: {e}", file=sys.stderr)
        return 2
    if args.baseline is not None:
        config.baseline = args.baseline

    select = {s.strip() for s in args.select.split(",") if s.strip()}
    if select:
        unknown = {s.upper() for s in select} - set(rule_ids())
        if unknown:
            print(
                f"error: unknown rule id(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2
    rules = all_rules(select or None)

    if args.write_baseline:
        if not config.baseline:
            print(
                "error: --write-baseline needs a baseline path (config"
                " `baseline` is empty; pass --baseline PATH)",
                file=sys.stderr,
            )
            return 2
        findings, _ = analyze(paths, config, rules=rules, use_baseline=False)
        target = config.rel_to_root(config.baseline)
        pruned = write_baseline(target, findings)
        print(
            f"wrote {len(findings)} fingerprint(s) to {target}"
            f" ({pruned} stale fingerprint(s) pruned)"
        )
        return 0

    findings, stats = analyze(
        paths, config, rules=rules, use_baseline=not args.no_baseline
    )
    failing = [
        f
        for f in findings
        if SEVERITIES.index(f.severity) >= SEVERITIES.index(args.fail_level)
    ]

    if args.fmt == "json":
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        summary = (
            f"{len(findings)} finding(s) in {stats['files']} file(s)"
            f" ({stats['suppressed']} noqa-suppressed,"
            f" {stats['baselined']} baselined)"
        )
        print(("FAIL: " if failing else "OK: ") + summary)
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
