"""The `dllama-analyze` rule engine (ISSUE 5): AST walking, suppression,
baseline, and the two-pass analyzer driver.

The engine parses every scanned file once into a :class:`FileCtx` (AST +
parent links + import aliases + per-line ``# dllama: noqa[...]``
suppressions), hands the full set to each rule's ``prepare`` pass (where
cross-file facts like the donation table or the fault-site registry are
collected), then runs per-file ``check`` and project-level ``finalize``
passes. Findings that survive inline suppression and the committed
baseline decide the exit code — the CI gate is exactly
``python -m distributed_llama_tpu.analysis distributed_llama_tpu/``.

Rules are deliberately *project-shaped*: each encodes an invariant this
repo has actually shipped a bug against (docs/ANALYSIS.md has the
catalogue and the history). The engine itself is generic; adding a rule is
subclassing :class:`Rule` and listing it in ``rules/__init__.py``.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import hashlib
import os
import re

from .config import AnalysisConfig

SEVERITIES = ("warning", "error")

_NOQA_RE = re.compile(
    r"#\s*dllama:\s*noqa(?:\[([A-Za-z0-9_,\s-]+)\])?", re.IGNORECASE
)


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str
    severity: str  # "warning" | "error"
    path: str  # relative to the scan invocation's config root
    line: int
    col: int
    message: str
    qualname: str = ""  # enclosing function/class dotted path, "" at module level
    source: str = ""  # stripped text of the flagged physical line

    def format(self) -> str:
        where = f"  [{self.qualname}]" if self.qualname else ""
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{self.severity}: {self.message}{where}"
        )

    def fingerprint(self) -> str:
        """Line-number-independent identity for the baseline file: the rule,
        the file, the enclosing scope and the flagged line's text."""
        h = hashlib.sha1(self.source.strip().encode("utf-8")).hexdigest()[:12]
        return f"{self.rule}|{self.path}|{self.qualname}|{h}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class FileCtx:
    """Parsed view of one scanned file: AST with parent links, source
    lines, import aliases, and the per-line noqa suppression map."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # names that (probably) refer to imported modules: `import jax` ->
        # jax, `import numpy as np` -> np, `from a.b import c` -> c (c may
        # be a module or a function; rules treat it as "resolvable import")
        self.module_aliases: set[str] = set()
        # alias -> (module, original_name) for `from time import time` style
        self.from_imports: dict[str, tuple[str, str]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.module_aliases.add(a.asname or a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    alias = a.asname or a.name
                    self.module_aliases.add(alias)
                    self.from_imports[alias] = (node.module, a.name)
        # string-literal spans: noqa text INSIDE a string (docstrings
        # quoting the syntax, generated-file headers) is prose, not a
        # suppression — it must neither suppress findings nor trip GEN-002
        str_spans = [
            (n.lineno, n.col_offset, n.end_lineno, n.end_col_offset)
            for n in ast.walk(self.tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
            and n.end_lineno is not None
        ]

        def in_string(line: int, col: int) -> bool:
            for l0, c0, l1, c1 in str_spans:
                if (l0, c0) <= (line, col) and (line, col) < (l1, c1):
                    return True
            return False

        # line -> None (suppress all rules) | set of rule ids
        self.noqa: dict[int, set[str] | None] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _NOQA_RE.search(text)
            if not m:
                continue
            if in_string(i, m.start()):
                continue
            if m.group(1):
                ids = {part.strip().upper() for part in m.group(1).split(",")}
                self.noqa[i] = {x for x in ids if x}
            else:
                self.noqa[i] = None

    # -- tree queries ---------------------------------------------------

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return anc
        return None

    def qualname(self, node: ast.AST) -> str:
        parts = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(anc.name)
        return ".".join(reversed(parts))

    def statement_of(self, node: ast.AST) -> ast.stmt:
        """The innermost statement containing ``node``."""
        cur: ast.AST = node
        while not isinstance(cur, ast.stmt):
            nxt = self.parents.get(cur)
            if nxt is None:
                break
            cur = nxt
        return cur  # type: ignore[return-value]

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        if lineno not in self.noqa:
            return False
        ids = self.noqa[lineno]
        return ids is None or rule.upper() in ids


def expr_key(node: ast.AST) -> str | None:
    """Dotted-name key for a simple Name / Attribute-of-Names chain
    (``self._slab`` -> "self._slab"); None for anything computed."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def assigned_keys(stmt: ast.stmt) -> set[str]:
    """Dotted keys (re)bound to a NEW value by a statement: assignment
    targets (including tuple unpacking), ``for`` targets and ``with ...
    as`` bindings. AugAssign is deliberately absent — ``x += 1`` READS the
    old value first, so it heals nothing (DON-001 treats its target as a
    load)."""
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, ast.AnnAssign):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items if i.optional_vars]
    out: set[str] = set()
    stack = targets
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        else:
            key = expr_key(t)
            if key:
                out.add(key)
    return out


class ProjectContext:
    """Everything the rules share: the config, the parsed files, and the
    cross-file facts rules deposit during their ``prepare`` pass."""

    def __init__(self, config: AnalysisConfig, files: list[FileCtx]):
        self.config = config
        self.files = files
        self.shared: dict[str, object] = {}
        self.by_rel = {fc.rel: fc for fc in files}

    def read_aux(self, rel_or_abs: str) -> str | None:
        """Source of an auxiliary file (doc table, registry module) —
        served from the scan set when present, else read from disk."""
        fc = self.by_rel.get(os.path.normpath(rel_or_abs))
        if fc is not None:
            return fc.source
        path = self.config.rel_to_root(rel_or_abs)
        if os.path.isfile(path):
            with open(path, "r", encoding="utf-8") as f:
                return f.read()
        return None


class Rule:
    """Base class: subclasses set ``id``/``severity``/``short`` and
    implement any of ``prepare`` (cross-file collection), ``check``
    (per-file findings) and ``finalize`` (project-level findings)."""

    id = "GEN-000"
    severity = "error"
    short = ""

    def prepare(self, project: ProjectContext) -> None:
        pass

    def check(self, project: ProjectContext, fc: FileCtx) -> list[Finding]:
        return []

    def finalize(self, project: ProjectContext) -> list[Finding]:
        return []

    def post_suppression(
        self,
        project: ProjectContext,
        active_ids: set[str],
        used: set[tuple[str, int, str | None]],
    ) -> list[Finding]:
        """Hook run by the driver AFTER the noqa pass: ``used`` holds the
        (rel, line, rule-id-or-None-for-bare) suppressions that actually
        absorbed a finding. Findings returned here bypass inline noqa (but
        not the baseline) — GEN-002 uses this to flag noqa comments that
        suppressed nothing."""
        return []

    def finding(
        self, fc: FileCtx, node: ast.AST, message: str, severity: str | None = None
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=self.id,
            severity=severity or self.severity,
            path=fc.rel,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            qualname=fc.qualname(node),
            source=fc.line_text(line),
        )


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> dict[str, int]:
    """Fingerprint -> allowed count. Missing file = empty baseline; ``#``
    lines are comments."""
    counts: dict[str, int] = {}
    if not path or not os.path.isfile(path):
        return counts
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            counts[line] = counts.get(line, 0) + 1
    return counts


def write_baseline(path: str, findings: list[Finding]) -> int:
    """Snapshot ``findings`` as the new baseline. Returns the number of
    STALE fingerprints pruned — entries of the previous baseline that no
    current finding matches (fixed code whose grandfather entry would
    otherwise silently absorb a future regression)."""
    old = load_baseline(path)
    fresh = {f2.fingerprint() for f2 in findings}
    pruned = sum(n for fp, n in old.items() if fp not in fresh)
    with open(path, "w", encoding="utf-8") as f:
        f.write(
            "# dllama-analyze baseline — grandfathered findings, one"
            " fingerprint per line.\n"
            "# Regenerate with: python -m distributed_llama_tpu.analysis"
            " --write-baseline <paths>\n"
            "# An empty baseline is the healthy state: fix findings or"
            " suppress them inline\n"
            "# with a justified `# dllama: noqa[RULE-ID]` instead of"
            " parking them here.\n"
        )
        for fp in sorted(f2.fingerprint() for f2 in findings):
            f.write(fp + "\n")
    return pruned


def apply_baseline(
    findings: list[Finding], baseline: dict[str, int]
) -> tuple[list[Finding], int]:
    """Drop findings covered by the baseline (each entry absorbs as many
    findings as it is listed times). Returns (kept, n_baselined)."""
    remaining = dict(baseline)
    kept: list[Finding] = []
    absorbed = 0
    for f in findings:
        fp = f.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            absorbed += 1
        else:
            kept.append(f)
    return kept, absorbed


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def collect_files(paths: list[str], config: AnalysisConfig) -> tuple[list[FileCtx], list[Finding]]:
    """Parse every ``.py`` under ``paths`` into FileCtx objects. Returns
    (files, parse_failures) — an unparsable file is a GEN-001 finding, not
    a crash, so one bad file cannot mask the rest of the scan."""
    seen: set[str] = set()
    files: list[FileCtx] = []
    failures: list[Finding] = []
    root = os.path.abspath(config.root)

    def rel_of(abspath: str) -> str:
        try:
            rel = os.path.relpath(abspath, root)
        except ValueError:  # different drive (windows)
            rel = abspath
        return os.path.normpath(rel)

    def excluded(rel: str) -> bool:
        return any(fnmatch.fnmatch(rel, pat) for pat in config.exclude)

    def add(abspath: str) -> None:
        if abspath in seen:
            return
        seen.add(abspath)
        rel = rel_of(abspath)
        if excluded(rel):
            return
        try:
            with open(abspath, "r", encoding="utf-8") as f:
                source = f.read()
            files.append(FileCtx(abspath, rel, source))
        except (SyntaxError, ValueError, OSError) as e:
            failures.append(
                Finding(
                    rule="GEN-001",
                    severity="error",
                    path=rel,
                    line=getattr(e, "lineno", 1) or 1,
                    col=0,
                    message=f"file could not be parsed: {e}",
                    source="",
                )
            )

    for path in paths:
        path = os.path.abspath(path)
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [
                    d for d in sorted(dirnames) if d != "__pycache__"
                ]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        add(os.path.join(dirpath, name))
        elif path.endswith(".py"):
            add(path)
    files.sort(key=lambda fc: fc.rel)
    return files, failures


def analyze(
    paths: list[str],
    config: AnalysisConfig,
    rules: list[Rule] | None = None,
    use_baseline: bool = True,
) -> tuple[list[Finding], dict]:
    """Run the engine. Returns (unsuppressed findings, stats dict with
    ``files``/``suppressed``/``baselined`` counts)."""
    if rules is None:
        from .rules import all_rules

        rules = all_rules()
    files, failures = collect_files(paths, config)
    project = ProjectContext(config, files)
    for rule in rules:
        rule.prepare(project)
    raw: list[Finding] = list(failures)
    for rule in rules:
        for fc in files:
            raw.extend(rule.check(project, fc))
        raw.extend(rule.finalize(project))
    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    kept: list[Finding] = []
    suppressed = 0
    # which suppressions earned their keep: (rel, line, rule-id) for a
    # scoped hit, (rel, line, None) when the bare form absorbed it
    used: set[tuple[str, int, str | None]] = set()
    for f in raw:
        fc = project.by_rel.get(f.path)
        if fc is not None and fc.suppressed(f.rule, f.line):
            suppressed += 1
            ids = fc.noqa.get(f.line)
            used.add(
                (f.path, f.line, None if ids is None else f.rule.upper())
            )
        else:
            kept.append(f)

    active_ids = {r.id for r in rules}
    for rule in rules:
        kept.extend(rule.post_suppression(project, active_ids, used))
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    baselined = 0
    if use_baseline and config.baseline:
        baseline = load_baseline(config.rel_to_root(config.baseline))
        kept, baselined = apply_baseline(kept, baseline)
    stats = {
        "files": len(files),
        "suppressed": suppressed,
        "baselined": baselined,
    }
    return kept, stats
