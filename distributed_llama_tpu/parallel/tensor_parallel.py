"""Tensor parallelism: one SPMD program over a ``tp`` mesh axis.

Layout parity with the reference's slicing math (reference:
src/commands.cpp:11-108):

  * q/k/v, w1(gate)/w3(up) — output-dim sharded  (RowMatmulSlice, :11-43)
  * wo, w2(down)           — input-dim sharded   (ColMatmulSlice, :45-73)
  * attention heads        — ``n_heads/tp`` per shard (MultiHeadAttSlice, :104-108)
  * KV cache               — sharded on the KV-head axis (KvCacheSlice, :97-102)
  * MoE experts            — every shard holds a 1/tp hidden-slice of all
                             experts (transformer.cpp:335-353)
  * wcls                   — output(vocab)-dim sharded + all-gather (the
                             reference keeps logits root-only instead)

What the reference does with 4 TCP hops per layer (broadcast xb, gather xbv,
broadcast xb, gather xbv — README.md:135-147) is here exactly 2 psums per
layer (after wo and after w2) riding ICI, with the activation broadcast
replaced by replicated-by-construction compute.

The divisibility constraint mirrors ``nSlices <= nKvHeads``
(reference: src/transformer.cpp:108-111): tp must divide n_kv_heads (and
n_heads, hidden_dim).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_llama_tpu.models import llama
from distributed_llama_tpu.models.config import LlamaConfig

try:  # jax >= 0.4.35 exposes shard_map at jax.shard_map
    from jax import shard_map as _shard_map_mod  # type: ignore

    shard_map = _shard_map_mod
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def validate_tp(cfg: LlamaConfig, tp: int) -> None:
    """The sharding-divisibility constraint, enforced like the reference's
    nSlices checks (reference: src/transformer.cpp:105-111)."""
    if tp & (tp - 1):
        raise ValueError(f"tp must be a power of two, got {tp}")
    for name, value in (
        ("n_heads", cfg.n_heads),
        ("n_kv_heads", cfg.n_kv_heads),
        ("hidden_dim", cfg.hidden_dim),
    ):
        if value % tp != 0:
            raise ValueError(f"tp={tp} must divide {name}={value}")


def layer_param_specs(cfg: LlamaConfig) -> dict[str, P]:
    """PartitionSpecs for the stacked per-layer tree (leading axis = layer)."""
    specs: dict[str, P] = {
        "q": P(None, None, "tp"),  # [L, D, H*hd] — output sharded
        "k": P(None, None, "tp"),
        "v": P(None, None, "tp"),
        "wo": P(None, "tp", None),  # [L, H*hd, D] — input sharded
        "rms_att": P(None, None),
        "rms_ffn": P(None, None),
    }
    if cfg.is_moe:
        specs.update(
            router=P(None, None, None),  # [L, D, E] replicated
            moe_up=P(None, None, None, "tp"),  # [L, E, D, Hl]
            moe_gate=P(None, None, None, "tp"),
            moe_down=P(None, None, "tp", None),  # [L, E, Hl, D]
        )
    else:
        specs.update(
            gate=P(None, None, "tp"),  # [L, D, hidden]
            down=P(None, "tp", None),  # [L, hidden, D]
            up=P(None, None, "tp"),
        )
    if cfg.arch.name == "GROK1":
        specs.update(rms_moe=P(None, None), rms_ffn2=P(None, None))
    return specs


def param_specs(cfg: LlamaConfig, shard_vocab: bool) -> dict[str, Any]:
    return {
        "embedding": P(None, None),
        "layers": layer_param_specs(cfg),
        "rms_final": P(None),
        "wcls": P(None, "tp") if shard_vocab else P(None, None),
        "rope_table": P(None, None, None),
    }


CACHE_SPEC = P(None, None, None, "tp", None)  # [L, 2, S, K, hd] on KV heads


class TensorParallelForward:
    """Jitted shard_map'd forward over a 1-D ``tp`` mesh."""

    def __init__(self, cfg: LlamaConfig, tp: int, devices=None):
        validate_tp(cfg, tp)
        self.cfg = cfg
        self.tp = tp
        if devices is None:
            devices = jax.devices()[:tp]
        if len(devices) < tp:
            raise ValueError(f"need {tp} devices, have {len(devices)}")
        self.mesh = Mesh(mesh_utils.create_device_mesh((tp,), devices=devices), ("tp",))
        self.shard_vocab = cfg.vocab_size % tp == 0
        self._specs = param_specs(cfg, self.shard_vocab)

        fn = functools.partial(self._step, cfg)
        mapped = shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(self._specs, P(), CACHE_SPEC, P()),
            out_specs=(P(), CACHE_SPEC),
            check_vma=False,
        )
        self._jitted = jax.jit(mapped, donate_argnums=(2,))

    @staticmethod
    def _step(cfg, params, tokens, cache, pos):
        logits, new_cache = llama.forward_tokens(
            cfg, params, tokens, cache, pos, axis_name="tp"
        )
        if logits.shape[-1] != cfg.vocab_size:
            # wcls was vocab-sharded: reassemble full logits on every shard
            logits = jax.lax.all_gather(logits, "tp", axis=1, tiled=True)
        return logits, new_cache

    # ------------------------------------------------------------------

    def shard_params(self, host_params) -> Any:
        # explicit recursion: PartitionSpec is a tuple subclass, so tree.map
        # over the spec tree would descend into the specs themselves
        def rec(p, s):
            if isinstance(p, dict):
                return {k: rec(p[k], s[k]) for k in p}
            return jax.device_put(p, NamedSharding(self.mesh, s))

        return rec(host_params, self._specs)

    def init_cache(self, dtype=jnp.float32):
        shape = (
            self.cfg.n_layers,
            2,
            self.cfg.seq_len,
            self.cfg.n_kv_heads,
            self.cfg.head_size,
        )
        sharding = NamedSharding(self.mesh, CACHE_SPEC)
        per_shard = shape[:3] + (shape[3] // self.tp,) + shape[4:]
        zeros = np.zeros(per_shard, dtype)
        return jax.make_array_from_callback(shape, sharding, lambda idx: zeros)

    def forward(self, params, tokens, cache, pos):
        return self._jitted(params, jnp.asarray(tokens), cache, jnp.asarray(pos))
