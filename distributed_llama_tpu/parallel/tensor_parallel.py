"""Tensor parallelism: one SPMD program over a ``tp`` mesh axis.

Layout parity with the reference's slicing math (reference:
src/commands.cpp:11-108):

  * q/k/v, w1(gate)/w3(up) — output-dim sharded  (RowMatmulSlice, :11-43)
  * wo, w2(down)           — input-dim sharded   (ColMatmulSlice, :45-73)
  * attention heads        — ``n_heads/tp`` per shard (MultiHeadAttSlice, :104-108)
  * KV cache               — sharded on the KV-head axis (KvCacheSlice, :97-102)
  * MoE experts            — every shard holds a 1/tp hidden-slice of all
                             experts (transformer.cpp:335-353)
  * wcls                   — output(vocab)-dim sharded + all-gather (the
                             reference keeps logits root-only instead)

What the reference does with 4 TCP hops per layer (broadcast xb, gather xbv,
broadcast xb, gather xbv — README.md:135-147) is here exactly 2 all-reduces
per layer (after wo and after w2) riding ICI, with the activation broadcast
replaced by replicated-by-construction compute. The all-reduces route
through the seam in ``ops.collectives``: ``lax.psum`` by default, with
the bidirectional ``make_async_remote_copy`` ring kernel (the reduce
overlaps the matmul epilogue instead of serializing after it) behind
``DLT_ALLREDUCE=ring`` until the chip smoke validates its Mosaic build.

The divisibility constraint mirrors ``nSlices <= nKvHeads``
(reference: src/transformer.cpp:108-111): tp must divide n_kv_heads (and
n_heads, hidden_dim).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_llama_tpu import lockcheck
from distributed_llama_tpu.models import llama
from distributed_llama_tpu.models.config import LlamaConfig
from distributed_llama_tpu.parallel import sharding

try:  # jax >= 0.4.35 exposes shard_map at jax.shard_map
    from jax import shard_map as _shard_map_mod  # type: ignore

    shard_map = _shard_map_mod
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def validate_tp(cfg: LlamaConfig, tp: int, quantized: bool = False) -> None:
    """The sharding-divisibility constraint, enforced like the reference's
    nSlices checks (reference: src/transformer.cpp:105-111)."""
    if tp & (tp - 1):
        raise ValueError(f"tp must be a power of two, got {tp}")
    for name, value in (
        ("n_heads", cfg.n_heads),
        ("n_kv_heads", cfg.n_kv_heads),
        ("hidden_dim", cfg.hidden_dim),
    ):
        if value % tp != 0:
            raise ValueError(f"tp={tp} must divide {name}={value}")
    if quantized:
        # input-dim shards must land on 32-wide quant-block boundaries,
        # like ColMatmulSlice's n % (nSlices*blockSize) requirement
        # (reference: src/commands.cpp:49-56)
        from distributed_llama_tpu.quants import QK

        for name, value in (("dim", cfg.dim), ("hidden_dim", cfg.hidden_dim)):
            if value % (tp * QK) != 0:
                raise ValueError(
                    f"q40 tp={tp} needs {name}={value} divisible by {tp * QK}"
                )


def layer_param_specs(cfg: LlamaConfig, axis: str = "tp") -> dict[str, P]:
    """PartitionSpecs for the stacked per-layer tree (leading axis = layer).
    A rule-table lookup (parallel/sharding.py — the one sharding
    authority); kept as the historical call surface."""
    return sharding.param_specs(
        cfg, "stacked", shard_vocab=False, axes={"model": axis}
    )["layers"]


def param_specs(cfg: LlamaConfig, shard_vocab: bool, axis: str = "tp") -> dict[str, Any]:
    return sharding.param_specs(cfg, "stacked", shard_vocab, {"model": axis})


def param_specs_layered(
    cfg: LlamaConfig, n_layers: int, shard_vocab: bool, axis: str = "tp"
) -> dict[str, Any]:
    """Specs for the per-layer-list params layout (engine.weights.load_params):
    a rule-table lookup over the layered skeleton."""
    return sharding.param_specs(
        cfg, "layered", shard_vocab, {"model": axis}, n_layers=n_layers
    )


def q40_layer_specs(cfg: LlamaConfig, axis: str = "tp") -> dict[str, P]:
    """PartitionSpecs for ONE layer of the q40 per-layer-list layout
    (fused qkv/gate_up, QuantizedMatrix leaves — a spec here is a pytree
    prefix covering both the qs and scales arrays, which shard alike)."""
    return sharding.param_specs(
        cfg, "q40", shard_vocab=False, axes={"model": axis}, n_layers=1
    )["layers"][0]


def q40_param_specs(
    cfg: LlamaConfig, n_layers: int, shard_vocab: bool, axis: str = "tp"
) -> dict[str, Any]:
    return sharding.param_specs(
        cfg, "q40", shard_vocab, {"model": axis}, n_layers=n_layers
    )


# Resolved cache layouts for the classic 1-D ``tp`` mesh (the table lives
# in parallel/sharding.py CACHE_AXES; backends on other meshes resolve
# with their own axis mapping)
_TP_AXES = {"model": "tp"}
CACHE_SPEC = sharding.cache_spec("stacked", _TP_AXES)  # [L, 2, S, K, hd]
CACHE_SPEC_LAYER = sharding.cache_spec("stream", _TP_AXES)  # per-layer [S, K, hd]
# batched slab cache (engine.batch): per-layer (keys, values) tuples of
# [B, S, K, hd] — batch and sequence replicated, KV heads sharded
BATCH_CACHE_SPEC_LAYER = sharding.cache_spec("slab", _TP_AXES)
# prefix-cache page pool (engine.prefix_cache): per-layer (keys, values)
# halves of [P, page, K, hd] — pages and positions replicated, KV heads
# sharded exactly like the slab, so each shard's paged attention reads ITS
# OWN pool half through the (replicated) page tables with the same local
# program as the single-chip path
POOL_SPEC_LAYER = sharding.cache_spec("pool", _TP_AXES)


def place_params(host_params, specs, mesh) -> Any:
    """device_put a params tree against a matching PartitionSpec tree.

    Explicit recursion: PartitionSpec is a tuple subclass (and
    QuantizedMatrix a custom node), so tree.map over the spec tree would
    descend into the specs themselves. A single PartitionSpec acts as a
    prefix covering the whole tree (the replicated case)."""
    from jax.sharding import PartitionSpec as _P

    from distributed_llama_tpu.ops.q40 import QuantizedMatrix

    def rec(p, s):
        if isinstance(s, _P):
            if isinstance(p, dict):
                return {k: rec(p[k], s) for k in p}
            if isinstance(p, list):
                return [rec(pi, s) for pi in p]
        elif isinstance(p, dict):
            return {k: rec(p[k], s[k]) for k in p}
        elif isinstance(p, list):
            return [rec(pi, si) for pi, si in zip(p, s)]
        if isinstance(p, QuantizedMatrix):
            # one spec covers both leaves: qs and scales shard along the
            # same axis index
            ns = NamedSharding(mesh, s)
            return QuantizedMatrix(
                jax.device_put(p.qs, ns),
                jax.device_put(p.scales, ns),
                p.n_logical,
                p.d_logical,
            )
        return jax.device_put(p, NamedSharding(mesh, s))

    return rec(host_params, specs)


class TransferProbeMixin:
    """Shared timing harness over a backend's :meth:`transfer_probe`: all
    parallel backends measure their collective ("transfer") cost the same
    way, so the methodology lives once. Each measurement also feeds the
    telemetry registry (all-reduce latency histogram + estimated payload
    bytes) when telemetry is enabled."""

    def _collective_tel(self):
        tel = getattr(self, "_collective_tel_bundle", None)
        if tel is None:
            from distributed_llama_tpu import telemetry as _telemetry

            tel = _telemetry.CollectiveInstruments()
            self._collective_tel_bundle = tel
        return tel

    def _faults_plan(self):
        """Bind-once fault-injection plan (engine/faults.py): the no-op
        NULL_PLAN unless a chaos plan was installed before construction."""
        plan = getattr(self, "_faults_plan_bound", None)
        if plan is None:
            from distributed_llama_tpu.engine import faults as _faults

            plan = _faults.active_plan()
            self._faults_plan_bound = plan
        return plan

    def _enqueue(self, jitted, *args):
        """Dispatch a jitted multi-partition program with the backend's
        enqueue order serialized (when the backend defines a dispatch
        lock). Concurrent callers sharing one backend — the pod's slice
        schedulers — would otherwise interleave their per-device enqueues
        inconsistently, and two in-flight programs spanning overlapping
        device sets deadlock at their first collectives (observed as a
        hung serving window; the same race corrupts the CPU client's heap
        under concurrent python-thread dispatch). The lock covers ONLY
        the asynchronous enqueue, never a fetch — execution still
        overlaps."""
        lock = getattr(self, "_dispatch_lock", None)
        if lock is None:
            return jitted(*args)
        with lock:
            return jitted(*args)

    def transfer_bytes_per_token(self) -> int:
        """Estimated LOGICAL payload bytes the probed collective sequence
        moves per token (f32 activations; backends override with their own
        per-layer collective shapes). 0 when a backend declines to estimate."""
        return 0

    def measure_transfer_ms(self, n_tokens: int = 32) -> float:
        """Per-token collective cost on the real mesh, replayed
        back-to-back (upper bound: XLA may overlap collectives with compute
        in the real program). The engine re-runs this periodically at
        quiescent points, so the printed T follows actual interconnect load
        over a session — the TPU analogue of the reference's
        TASK_TYPE_TRANSFER wall-time accounting (src/utils.cpp:216-218)."""
        from distributed_llama_tpu.telemetry import Stopwatch

        # transfer-error injection site (chaos tests): a raise here models a
        # flaky interconnect — the engine keeps its previous estimate instead
        # of failing the request that triggered the probe (engine.py)
        self._faults_plan().fire("tp.transfer")
        tel = self._collective_tel()
        jitted, args = self._transfer_probe_cached(n_tokens)
        with tel.span("transfer_probe", tokens=n_tokens):
            sw = Stopwatch()
            # fetch, don't block_until_ready: through a remote PJRT tunnel the
            # latter returns before execution finishes (docs/PERF.md)
            np.asarray(self._enqueue(jitted, *args)[0])
            per_token_ms = sw.elapsed_ms() / n_tokens
        if tel.enabled:
            tel.probe_runs.inc()
            tel.allreduce_latency.observe(per_token_ms / 1000.0)
            tel.allreduce_bytes.inc(self.transfer_bytes_per_token() * n_tokens)
        return per_token_ms

    def _transfer_probe_cached(self, n_tokens: int):
        key = ("probe", n_tokens)
        cached = self._decode_cache.get(key)
        if cached is None:
            jitted, args = self.transfer_probe(n_tokens)
            np.asarray(self._enqueue(jitted, *args)[0])  # compile + warm outside the window
            cached = (jitted, args)
            self._decode_cache[key] = cached
        return cached


class TensorParallelForward(TransferProbeMixin):
    """Jitted shard_map'd forward over a 1-D ``tp`` mesh.

    ``quantized=True`` switches the param layout to the q40 per-layer list
    (fused qkv/gate_up QuantizedMatrix leaves, built in sharded layout by
    ``engine.weights.load_params(tp=...)``).
    """

    # the shard_map entry point every program builder routes through; the
    # pod backend (parallel/pod.py) overrides it with the jax-version
    # compat wrapper so one-process pod serving runs on container JAX too
    _shard_map = staticmethod(shard_map)

    def __init__(
        self,
        cfg: LlamaConfig,
        tp: int,
        devices=None,
        quantized: bool = False,
        layered: bool | None = None,
        axis: str = "tp",
        mesh: Mesh | None = None,
    ):
        """``axis``/``mesh`` let a subclass run the same program family on
        a larger named mesh (the one-process pod backend rides a
        ('data', 'model') mesh with ``axis='model'``; every spec below
        resolves through the rule table with that mapping, replicating
        over any axis the mapping never names)."""
        validate_tp(cfg, tp, quantized=quantized)
        self.cfg = cfg
        self.tp = tp
        self.axis = axis
        self.quantized = quantized
        # layered = per-layer-list params + cache (the engine's production
        # layout for every dtype); stacked remains for synthetic-params
        # callers (tests, the driver dryrun)
        self.layered = quantized if layered is None else layered
        if mesh is not None:
            if axis not in mesh.axis_names or mesh.shape[axis] != tp:
                raise ValueError(
                    f"mesh axis {axis!r} of size {tp} required, got "
                    f"{dict(mesh.shape)}"
                )
            self.mesh = mesh
        else:
            if devices is None:
                devices = jax.devices()[:tp]
            if len(devices) < tp:
                raise ValueError(f"need {tp} devices, have {len(devices)}")
            self.mesh = Mesh(
                mesh_utils.create_device_mesh((tp,), devices=devices), (axis,)
            )
        self.shard_vocab = cfg.vocab_size % tp == 0
        self._decode_cache: dict = {}
        self._chunk_cache: dict = {}
        # serializes program ENQUEUE order across callers sharing this
        # backend (the pod's slice schedulers); see TransferProbeMixin._enqueue
        self._dispatch_lock = lockcheck.make_lock("TransferProbeMixin._dispatch_lock")
        axes = {"model": axis}
        if quantized:
            self._specs = q40_param_specs(
                cfg, cfg.n_layers, self.shard_vocab, axis=axis
            )
        elif self.layered:
            self._specs = param_specs_layered(
                cfg, cfg.n_layers, self.shard_vocab, axis=axis
            )
        else:
            self._specs = param_specs(cfg, self.shard_vocab, axis=axis)
        # cache/slab/pool layouts from the same rule table (sharding.py)
        self._stream_cache_spec = sharding.cache_spec("stream", axes)
        self._slab_spec = sharding.cache_spec("slab", axes)
        self._pool_spec_layer = sharding.cache_spec("pool", axes)
        # batched-dispatch vector layouts: per-row scalars ([B] first/pos/
        # active/sampler/seeds), per-row page tables ([B, n_table]) and the
        # packed token bundle ([chunk+2, B]). Replicated on the 1-D mesh;
        # the pod backend re-points them at its 'data' axis when the slab's
        # batch axis is data-sharded (parallel/pod.py)
        self._vec_spec = P()
        self._table_spec = P()
        self._tok_out_spec = P()
        if self.layered:
            # layered cache (list of per-layer arrays): the unrolled forward
            # needs per-leaf in-place aliasing (see llama.init_cache)
            self._cache_spec: Any = [self._stream_cache_spec] * cfg.n_layers
        else:
            self._cache_spec = sharding.cache_spec("stacked", axes)

        fn = functools.partial(self._step, cfg, self.axis)
        mapped = self._shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(self._specs, P(), self._cache_spec, P(), P()),
            out_specs=(P(), self._cache_spec),
            check_vma=False,
        )
        self._jitted = jax.jit(mapped, donate_argnums=(2,))

    # the forward accepts the bucket-padded prompt's real-token count (the
    # capacity-bucketed MoE prefill masks pad rows out of its buckets)
    accepts_n_real = True

    @staticmethod
    def _step(cfg, axis, params, tokens, cache, pos, n_real):
        logits, new_cache = llama.forward_tokens(
            cfg, params, tokens, cache, pos, axis_name=axis, n_real=n_real
        )
        if logits.shape[-1] != cfg.vocab_size:
            # wcls was vocab-sharded: reassemble full logits on every shard
            logits = jax.lax.all_gather(logits, axis, axis=1, tiled=True)
        return logits, new_cache

    # ------------------------------------------------------------------

    def shard_params(self, host_params) -> Any:
        # (the partial block-interleaved TP basis that used to be applied
        # here is retired — ops/q40.py legacy section; packs place in the
        # standard basis and the int8 kernel consumes them directly)
        return place_params(host_params, self._specs, self.mesh)

    def _decode_jitted(self, n_steps: int, temperature: float, topp: float, topk: int):
        # per-instance cache (an lru_cache on the method would pin self and
        # its compiled executables in a class-level cache for process life)
        key = (n_steps, temperature, topp, topk)
        cached = self._decode_cache.get(key)
        if cached is not None:
            return cached
        from distributed_llama_tpu.models import sampling

        cfg = self.cfg

        axis = self.axis

        def fn(params, first_token, cache, pos, seed):
            return sampling.decode_scan(
                cfg, params, first_token, cache, pos, seed, n_steps,
                temperature, topp, topk, axis_name=axis,
            )

        mapped = self._shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(self._specs, P(), self._cache_spec, P(), P()),
            out_specs=(P(), self._cache_spec),
            check_vma=False,
        )
        jitted = jax.jit(mapped, donate_argnums=(2,))
        self._decode_cache[key] = jitted
        return jitted

    def decode_loop(
        self, params, first_token, cache, pos, n_steps, temperature, topp,
        seed: int = 0, topk: int = 0,
    ):
        """On-device autoregressive decode under TP: ONE dispatch for
        ``n_steps`` tokens, collectives riding the mesh every step. Sampling
        runs replicated on counter coins (same (seed, position) → same token
        on every shard)."""
        from distributed_llama_tpu import prng

        jitted = self._decode_jitted(
            int(n_steps), float(temperature), float(topp), int(topk)
        )
        tokens, cache = self._enqueue(
            jitted,
            params, jnp.asarray(first_token), cache, jnp.asarray(pos),
            jnp.uint32(prng.fold_seed(seed)),
        )
        return tokens, cache

    def _chunk_jitted(self, n_steps: int):
        cached = self._chunk_cache.get(n_steps)
        if cached is not None:
            return cached
        from distributed_llama_tpu.models import sampling

        cfg = self.cfg

        axis = self.axis

        def fn(params, first_token, cache, pos, temperature, topp, topk, seed):
            return sampling.decode_scan(
                cfg, params, first_token, cache, pos, seed, n_steps,
                temperature, topp, topk, axis_name=axis,
            )

        mapped = self._shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(self._specs, P(), self._cache_spec, P(), P(), P(), P(), P()),
            out_specs=(P(), self._cache_spec),
            check_vma=False,
        )
        jitted = jax.jit(mapped, donate_argnums=(2,))
        self._chunk_cache[n_steps] = jitted
        return jitted

    def decode_chunk(
        self, params, first_token, cache, pos, n_steps, temperature, topp,
        topk, seed32,
    ):
        """Chunked streaming decode under TP: temperature/topp/topk are
        traced (one compiled program per chunk size, no per-request
        recompiles); coins re-key per position from the folded request
        seed, so no sampler state returns."""
        jitted = self._chunk_jitted(int(n_steps))
        return self._enqueue(
            jitted,
            params, jnp.asarray(first_token), cache, jnp.asarray(pos),
            jnp.float32(temperature), jnp.float32(topp), jnp.int32(topk),
            jnp.asarray(seed32, jnp.uint32),
        )

    def transfer_probe(self, n_tokens: int = 32):
        """(jitted_fn, example_args) replaying one decode step's collective
        sequence per iteration — 2 psums of a [1, dim] f32 activation per
        layer (after wo and after down, the reference's two gather+merge
        hops per layer, src/llama2-tasks.cpp:115-131/196-212) plus the vocab
        all-gather when wcls is sharded — scanned ``n_tokens`` times in one
        dispatch. Exposed separately from :meth:`measure_transfer_ms` so
        tests can compile it and assert the collectives survive XLA DCE
        (the keep-alive arithmetic is what this probe's timing validity
        rests on)."""
        cfg = self.cfg
        shard_vocab = self.shard_vocab
        axis = self.axis
        vshard = cfg.vocab_size // self.tp if shard_vocab else cfg.vocab_size

        def token_step(carry, _):
            x, lg = carry

            def layer_step(c, _):
                # two all-reduces per layer, as in the forward program —
                # through the SAME seam the forward uses (ops.collectives),
                # so the probe times whichever implementation (psum / ring)
                # production decode actually rides
                from distributed_llama_tpu.ops import collectives

                c = collectives.all_reduce(c, axis) * 0.5
                c = collectives.all_reduce(c, axis) * 0.5
                return c, None

            x, _ = jax.lax.scan(layer_step, x, None, length=cfg.n_layers)
            if shard_vocab:
                g = jax.lax.all_gather(lg, axis, axis=1, tiled=True)
                lg = lg + jnp.sum(g) * 1e-9  # keep the gather live
            return (x, lg), None

        def fn(x, lg):
            (x, lg), _ = jax.lax.scan(token_step, (x, lg), None, length=n_tokens)
            return x, lg

        mapped = self._shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(P(), P(None, axis) if shard_vocab else P()),
            out_specs=(P(), P(None, axis) if shard_vocab else P()),
            check_vma=False,
        )
        x = jnp.ones((1, cfg.dim), jnp.float32)
        lg = jnp.ones((1, vshard * self.tp if shard_vocab else cfg.vocab_size), jnp.float32)
        return jax.jit(mapped), (x, lg)

    def transfer_bytes_per_token(self) -> int:
        """2 psums of a [1, dim] f32 activation per layer (after wo and
        after down) plus the vocab all-gather when wcls is sharded — the
        exact sequence :meth:`transfer_probe` replays."""
        n = 2 * self.cfg.n_layers * self.cfg.dim * 4
        if self.shard_vocab:
            n += self.cfg.vocab_size * 4
        return n

    def init_cache(self, dtype=jnp.float32):
        from distributed_llama_tpu.ops import kv_cache as kvc

        kv_shape = (self.cfg.seq_len, self.cfg.n_kv_heads, self.cfg.head_size)
        if self.layered:  # per-layer (keys, values) tuples (see _cache_spec)
            sharding = NamedSharding(self.mesh, self._stream_cache_spec)

            def zeros(shape, dt):
                # shape is GLOBAL; build the local kv-head shard (the spec
                # prefix covers QuantizedKV's rank-3 scales leaf too)
                local = np.zeros((shape[0], shape[1] // self.tp) + shape[2:], dt)
                return jax.make_array_from_callback(shape, sharding, lambda idx: local)

            return [
                (kvc.init_half(kv_shape, dtype, zeros=zeros),
                 kvc.init_half(kv_shape, dtype, zeros=zeros))
                for _ in range(self.cfg.n_layers)
            ]
        if kvc.is_quantized_cache_dtype(dtype):
            raise ValueError("the i8 KV cache requires the layered cache layout")
        shape = (self.cfg.n_layers, 2) + kv_shape
        sharding = NamedSharding(self.mesh, self._cache_spec)
        per_shard = shape[:3] + (shape[3] // self.tp,) + shape[4:]
        zeros = np.zeros(per_shard, dtype)
        return jax.make_array_from_callback(shape, sharding, lambda idx: zeros)

    def forward(self, params, tokens, cache, pos, n_real=None):
        tokens = jnp.asarray(tokens)
        if n_real is None:
            n_real = tokens.shape[0]
        return self._enqueue(
            self._jitted, params, tokens, cache, jnp.asarray(pos),
            jnp.int32(n_real),
        )

    # ------------------------------------------------------------------
    # Batched multi-stream decode (engine.batch.BatchScheduler): the slab
    # cache shards its KV-head axis over tp exactly like the per-stream
    # caches, so the batched step is the same SPMD program family with a
    # leading batch axis. Requires the layered params/cache layout (the
    # engine's production layout for every dtype).
    # ------------------------------------------------------------------

    # -- slab row seam: the pod backend overrides these three to gather/
    # -- scatter one row across its data-sharded batch axis; here they are
    # -- the plain local ops (all run INSIDE the shard_map'd bodies)

    def _local_slab_shape(self, gshape: tuple) -> tuple:
        """One device's shard of a GLOBAL slab-half shape [B, S, K, hd]
        (or its rank-4 scales twin): KV heads divide by the model degree;
        the pod backend additionally divides the batch axis when its slab
        is data-sharded."""
        return gshape[:2] + (gshape[2] // self.tp,) + gshape[3:]

    def _slab_row_take(self, half, row):
        from distributed_llama_tpu.ops import kv_cache as kvc

        return kvc.slab_take_row(half, row)

    def _slab_row_put(self, half, new_row, row):
        from distributed_llama_tpu.ops import kv_cache as kvc

        return kvc.slab_put_row(half, new_row, row)

    def _slab_publish(self, pool_half, slab_half, row, src_page, page_ids):
        from distributed_llama_tpu.ops import kv_cache as kvc

        return kvc.publish_row_pages(
            pool_half, slab_half, row, src_page, page_ids, pool_half.shape[1]
        )

    def init_batch_cache(self, b_max: int, dtype=jnp.float32):
        from distributed_llama_tpu.ops import kv_cache as kvc

        if not self.layered:
            raise ValueError("the batched slab cache requires the layered layout")
        cfg = self.cfg
        shape = (b_max, cfg.seq_len, cfg.n_kv_heads, cfg.head_size)
        sharding = NamedSharding(self.mesh, self._slab_spec)

        def zeros(gshape, dt):
            local = np.zeros(self._local_slab_shape(gshape), dt)
            return jax.make_array_from_callback(gshape, sharding, lambda idx: local)

        return [
            (kvc.init_half(shape, dtype, zeros=zeros),
             kvc.init_half(shape, dtype, zeros=zeros))
            for _ in range(cfg.n_layers)
        ]

    def _batched_chunk_jitted(self, n_steps: int):
        key = ("batched_chunk", n_steps)
        cached = self._chunk_cache.get(key)
        if cached is not None:
            return cached
        from distributed_llama_tpu.models import sampling

        cfg = self.cfg
        axis = self.axis
        batch_cache_spec = [self._slab_spec] * cfg.n_layers

        def fn(params, first_tokens, cache, pos, active, temperature, topp,
               topk, seeds):
            from distributed_llama_tpu.engine import integrity

            tokens, cache, h, okf = sampling.batched_decode_scan(
                cfg, params, first_tokens, cache, pos, active, seeds, n_steps,
                temperature, topp, topk, axis_name=axis,
            )
            # the fingerprint folds the all-gathered full-vocab logits, so
            # every shard packs the same replicated bundle (integrity.py);
            # the sampler's candidate top-k composes over the sharded vocab
            # BEFORE that gather (sampling.sharded_topk_indices)
            return integrity.pack_chunk_outputs(tokens, h, okf), cache

        V = self._vec_spec
        mapped = self._shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(self._specs, V, batch_cache_spec, V, V, V, V,
                      V, V),
            out_specs=(self._tok_out_spec, batch_cache_spec),
            check_vma=False,
        )
        jitted = jax.jit(mapped, donate_argnums=(2,))
        self._chunk_cache[key] = jitted
        return jitted

    def batched_decode_chunk(
        self, params, first_tokens, cache, pos, active, n_steps, temperature,
        topp, topk, seeds,
    ):
        """One chunk of the batched multi-stream decode under TP: B
        sequences step together with per-row positions/seeds/sampler
        settings, collectives riding the mesh each step. One compiled
        program per (bucket, chunk) shape; no sampler state returns."""
        jitted = self._batched_chunk_jitted(int(n_steps))
        return self._enqueue(
            jitted,
            params, jnp.asarray(first_tokens), cache, jnp.asarray(pos),
            jnp.asarray(active), jnp.asarray(temperature), jnp.asarray(topp),
            jnp.asarray(topk), jnp.asarray(seeds),
        )

    def _slab_forward_jitted(self):
        key = ("slab_forward",)
        cached = self._chunk_cache.get(key)
        if cached is not None:
            return cached
        from distributed_llama_tpu.ops import kv_cache as kvc

        cfg = self.cfg
        axis = self.axis
        batch_cache_spec = [self._slab_spec] * cfg.n_layers

        def fn(params, tokens, slab, row, pos, n_real):
            row_cache = [
                (self._slab_row_take(k, row), self._slab_row_take(v, row))
                for k, v in slab
            ]
            logits, new_rows = llama.forward_tokens(
                cfg, params, tokens, row_cache, pos, axis_name=axis,
                n_real=n_real,
            )
            if logits.shape[-1] != cfg.vocab_size:
                logits = jax.lax.all_gather(logits, axis, axis=1, tiled=True)
            new_slab = [
                (self._slab_row_put(k, nk, row), self._slab_row_put(v, nv, row))
                for (k, v), (nk, nv) in zip(slab, new_rows)
            ]
            return logits, new_slab

        mapped = self._shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(self._specs, P(), batch_cache_spec, P(), P(), P()),
            out_specs=(P(), batch_cache_spec),
            check_vma=False,
        )
        jitted = jax.jit(mapped, donate_argnums=(2,))
        self._chunk_cache[key] = jitted
        return jitted

    def slab_forward(self, params, tokens, slab, row: int, pos: int, n_real: int):
        """Prefill ``tokens`` into slab row ``row`` under TP (the
        per-request prefill of the batched serving path): the row runs the
        ordinary sharded forward and is written back in place."""
        jitted = self._slab_forward_jitted()
        return self._enqueue(
            jitted,
            params, jnp.asarray(tokens), slab, jnp.int32(row), jnp.int32(pos),
            jnp.int32(n_real),
        )

    # ------------------------------------------------------------------
    # Sharded prefix-cache page pool (engine.prefix_cache, zero-copy paged
    # attention): per-shard [P, page, K/tp, hd] pool halves mirror the slab
    # sharding, page tables/matched lengths are replicated host indices, and
    # every paged read/publish runs the same local program family as the
    # single-chip backend inside shard_map. PR 4 deferred this — the copy
    # design needed per-shard gather programs; zero-copy needs none.
    # ------------------------------------------------------------------

    def init_page_pool(self, n_pages: int, page: int, dtype=jnp.float32):
        from distributed_llama_tpu.ops import kv_cache as kvc

        if not self.layered:
            raise ValueError("the sharded page pool requires the layered layout")
        cfg = self.cfg
        shape = (n_pages, page, cfg.n_kv_heads, cfg.head_size)
        sharding = NamedSharding(self.mesh, self._pool_spec_layer)

        def zeros(gshape, dt):
            local = np.zeros(gshape[:2] + (gshape[2] // self.tp,) + gshape[3:], dt)
            return jax.make_array_from_callback(gshape, sharding, lambda idx: local)

        return [
            (kvc.init_half(shape, dtype, zeros=zeros),
             kvc.init_half(shape, dtype, zeros=zeros))
            for _ in range(cfg.n_layers)
        ]

    def _pool_spec(self):
        return [(self._pool_spec_layer, self._pool_spec_layer)] * self.cfg.n_layers

    def _publish_pages_jitted(self):
        key = ("publish_pages",)
        cached = self._chunk_cache.get(key)
        if cached is not None:
            return cached
        from distributed_llama_tpu.ops import kv_cache as kvc

        batch_cache_spec = [self._slab_spec] * self.cfg.n_layers

        def fn(slab, pool, page_ids, src_page, row):
            # per-shard publish of the local KV-head slice: the page size is
            # static from the local pool half's shape
            return [
                (
                    self._slab_publish(pk, k, row, src_page, page_ids),
                    self._slab_publish(pv, v, row, src_page, page_ids),
                )
                for (k, v), (pk, pv) in zip(slab, pool)
            ]

        mapped = self._shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(batch_cache_spec, self._pool_spec(), P(), P(), P()),
            out_specs=self._pool_spec(),
            check_vma=False,
        )
        jitted = jax.jit(mapped, donate_argnums=(1,))
        self._chunk_cache[key] = jitted
        return jitted

    def publish_pages(self, slab, pool, page_ids, src_page, row):
        """Copy slab row ``row``'s completed prefill pages into pool pages
        ``page_ids`` on every shard (each shard moves its own KV-head
        slice). The donated pool aliases in place; the slab is read-only."""
        jitted = self._publish_pages_jitted()
        return self._enqueue(
            jitted,
            slab, pool, jnp.asarray(page_ids), jnp.asarray(src_page),
            jnp.int32(row),
        )

    def _batched_chunk_paged_jitted(self, n_steps: int):
        key = ("batched_chunk_paged", n_steps)
        cached = self._chunk_cache.get(key)
        if cached is not None:
            return cached
        from distributed_llama_tpu.models import sampling

        cfg = self.cfg
        axis = self.axis
        batch_cache_spec = [self._slab_spec] * cfg.n_layers

        def fn(params, first_tokens, cache, pool, pos, active, temperature,
               topp, topk, seeds, tables, matched):
            from distributed_llama_tpu.engine import integrity

            tokens, cache, h, okf = sampling.batched_decode_scan(
                cfg, params, first_tokens, cache, pos, active, seeds, n_steps,
                temperature, topp, topk, axis_name=axis,
                paged=(pool, tables, matched),
            )
            return integrity.pack_chunk_outputs(tokens, h, okf), cache

        V = self._vec_spec
        mapped = self._shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(self._specs, V, batch_cache_spec, self._pool_spec(),
                      V, V, V, V, V, V, self._table_spec, V),
            out_specs=(self._tok_out_spec, batch_cache_spec),
            check_vma=False,
        )
        jitted = jax.jit(mapped, donate_argnums=(2,))
        self._chunk_cache[key] = jitted
        return jitted

    def batched_decode_chunk_paged(
        self, params, first_tokens, cache, pool, pos, active, n_steps,
        temperature, topp, topk, seeds, tables, matched,
    ):
        """One batched decode chunk with zero-copy prefix aliasing under
        TP: each shard's attention reads its pool half through the
        replicated page tables for positions below ``matched`` and its slab
        rows beyond — the sharded form of
        ``sampling.decode_chunk_batched_paged``."""
        jitted = self._batched_chunk_paged_jitted(int(n_steps))
        return self._enqueue(
            jitted,
            params, jnp.asarray(first_tokens), cache, pool, jnp.asarray(pos),
            jnp.asarray(active), jnp.asarray(temperature), jnp.asarray(topp),
            jnp.asarray(topk), jnp.asarray(seeds), jnp.asarray(tables),
            jnp.asarray(matched),
        )

    def _slab_forward_paged_jitted(self):
        key = ("slab_forward_paged",)
        cached = self._chunk_cache.get(key)
        if cached is not None:
            return cached
        from distributed_llama_tpu.ops import kv_cache as kvc

        cfg = self.cfg
        axis = self.axis
        batch_cache_spec = [self._slab_spec] * cfg.n_layers

        def fn(params, tokens, slab, pool, row, pos, n_real, table, matched):
            row_cache = [
                (self._slab_row_take(k, row), self._slab_row_take(v, row))
                for k, v in slab
            ]
            logits, new_rows = llama.forward_tokens(
                cfg, params, tokens, row_cache, pos, axis_name=axis,
                n_real=n_real, paged=(pool, table, matched),
            )
            if logits.shape[-1] != cfg.vocab_size:
                logits = jax.lax.all_gather(logits, axis, axis=1, tiled=True)
            new_slab = [
                (self._slab_row_put(k, nk, row), self._slab_row_put(v, nv, row))
                for (k, v), (nk, nv) in zip(slab, new_rows)
            ]
            return logits, new_slab

        mapped = self._shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(self._specs, P(), batch_cache_spec, self._pool_spec(),
                      P(), P(), P(), P(), P()),
            out_specs=(P(), batch_cache_spec),
            check_vma=False,
        )
        jitted = jax.jit(mapped, donate_argnums=(2,))
        self._chunk_cache[key] = jitted
        return jitted

    def slab_forward_paged(
        self, params, tokens, slab, pool, row: int, pos: int, n_real: int,
        table, matched,
    ):
        """:meth:`slab_forward` with zero-copy prefix aliasing: the row's
        suffix prefill attends over pool pages for positions below
        ``matched`` (each shard reading its own half) and the slab row
        beyond."""
        jitted = self._slab_forward_paged_jitted()
        return self._enqueue(
            jitted,
            params, jnp.asarray(tokens), slab, pool, jnp.int32(row),
            jnp.int32(pos), jnp.int32(n_real), jnp.asarray(table),
            jnp.int32(matched),
        )
