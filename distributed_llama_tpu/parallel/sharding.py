"""Declarative mesh-sharding rule tables: the ONE sharding authority.

Before ISSUE 15 every parallel backend hand-rolled its own
``PartitionSpec`` constructions — ``parallel/tensor_parallel.py`` built
three spec-tree builders (stacked / layered / q40),
``parallel/expert_parallel.py`` a fourth, and ``engine/weights.py``
re-derived in/out shard directions inline at load time. Four copies of
the same layout knowledge, drifting independently, with silent
replication as the failure mode when a new leaf matched none of them.

This module replaces all of that with the idiom of SNIPPETS.md [2]
(JAX_llama): an ordered table of ``(leaf-path regex -> axis template)``
rules resolved against a named mesh. Differences from the snippet, on
purpose:

* **Exactly-one-match, not first-match.** An unmatched leaf raises
  :class:`UnmatchedLeafError` and a leaf matched by two rules raises
  :class:`AmbiguousLeafError` — both typed, both at load/construction
  time. Silent replication (the snippet's ``return val`` fallthrough)
  is exactly the bug class a 405B pod cannot afford: a forgotten rule
  would quietly materialize a full-size matrix on every chip.
* **Symbolic axes.** Rules name the :data:`MODEL` / :data:`EXPERT`
  roles, not concrete mesh axis names; resolution substitutes the
  caller's mapping (``{"model": "tp"}`` for the classic 1-D TP mesh,
  ``{"model": "model"}`` for the one-process ``('data','model')`` pod,
  ``{"model": "tp", "expert": "ep"}`` for the EP mesh). One table
  serves every mesh shape; axes the mapping leaves out replicate the
  leaf over them (the pod's ``'data'`` axis never appears in a weight
  rule — weights live once per model group).
* **QuantizedMatrix is one leaf.** A q40 weight's ``qs``/``scales``
  arrays shard along the same logical axis, so a single spec acts as
  the pytree prefix covering both (the contract ``place_params`` and
  ``shard_map`` already rely on).

The KV-cache / slab / page-pool layouts ride the same table mechanism
(:func:`cache_spec`) so "which axis do KV heads shard over" also has
exactly one home.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Iterator

from jax.sharding import PartitionSpec as P

from distributed_llama_tpu.models.config import LlamaConfig

# Symbolic axis roles substituted at resolve time. Distinct sentinel
# strings (not bare mesh names) so a rule table can never accidentally
# hard-code one mesh's axis vocabulary.
MODEL = "<model>"
EXPERT = "<expert>"


class ShardingRuleError(TypeError):
    """A weight leaf the rule table cannot place. TypeError on purpose:
    this is a *structural* mismatch between a params tree and the
    layout's declared rules, not a bad runtime value."""


class UnmatchedLeafError(ShardingRuleError):
    """A leaf no rule matched — the never-silent-replication contract."""


class AmbiguousLeafError(ShardingRuleError):
    """A leaf two or more rules matched: the table itself is broken."""


@dataclasses.dataclass(frozen=True)
class Rule:
    """One ordered table entry: a full-match regex over the '/'-joined
    leaf path and the axis template its matches shard by."""

    pattern: str
    axes: tuple

    def matches(self, path: str) -> bool:
        return re.fullmatch(self.pattern, path) is not None


@dataclasses.dataclass(frozen=True)
class RuleTable:
    """An ordered, exactly-one-match rule set for one params layout."""

    name: str
    rules: tuple[Rule, ...]

    def _match(self, path: str) -> Rule:
        hits = [r for r in self.rules if r.matches(path)]
        if not hits:
            raise UnmatchedLeafError(
                f"sharding table {self.name!r}: weight leaf {path!r} matches "
                f"no rule — refusing to silently replicate it. Add an "
                f"explicit rule (replicated leaves must say so)."
            )
        if len(hits) > 1:
            raise AmbiguousLeafError(
                f"sharding table {self.name!r}: weight leaf {path!r} matches "
                f"{len(hits)} rules ({[r.pattern for r in hits]}) — exactly "
                f"one must own every leaf."
            )
        return hits[0]

    def spec(self, path: str, axes: dict[str, str | None]) -> P:
        """The resolved PartitionSpec of one leaf path."""
        return materialize(self._match(path).axes, axes)

    def resolve(self, tree, axes: dict[str, str | None]):
        """Spec tree with the structure of ``tree`` (every leaf replaced
        by its resolved PartitionSpec); raises on unmatched/ambiguous."""

        def rec(node, path: str):
            if isinstance(node, dict):
                return {k: rec(v, f"{path}/{k}" if path else k) for k, v in node.items()}
            if isinstance(node, (list, tuple)):
                return [rec(v, f"{path}/{i}") for i, v in enumerate(node)]
            return self.spec(path, axes)

        return rec(tree, "")

    def table(self, tree, axes: dict[str, str | None]) -> dict[str, P]:
        """Flat ``{leaf path: resolved spec}`` over a params tree — the
        golden-test surface (snapshot-asserted so a rule edit that moves
        a leaf's layout fails loudly)."""
        return {path: self.spec(path, axes) for path, _ in leaf_paths(tree)}


def materialize(template: tuple, axes: dict[str, str | None]) -> P:
    """Axis template -> PartitionSpec under a role->mesh-axis mapping.
    A role mapped to None (or absent) replicates that dimension."""
    out = []
    for a in template:
        if a is None:
            out.append(None)
        elif a is MODEL:
            out.append(axes.get("model"))
        elif a is EXPERT:
            out.append(axes.get("expert"))
        else:  # a literal mesh axis name in a template is a table bug
            raise ShardingRuleError(
                f"rule template names concrete axis {a!r}; use the MODEL/"
                f"EXPERT symbols and map them at resolve time"
            )
    return P(*out)


def leaf_paths(tree, prefix: str = "") -> Iterator[tuple[str, Any]]:
    """Walk a params tree structurally, yielding ``(path, leaf)`` pairs.
    dicts/lists/tuples are containers; everything else — arrays and
    whole :class:`~distributed_llama_tpu.ops.q40.QuantizedMatrix` nodes
    (qs+scales shard alike, one spec covers both) — is a leaf."""
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from leaf_paths(v, f"{prefix}/{k}" if prefix else str(k))
    elif isinstance(tree, P):  # PartitionSpec IS a tuple subclass: a leaf
        yield prefix, tree
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from leaf_paths(v, f"{prefix}/{i}" if prefix else str(i))
    else:
        yield prefix, tree


# ---------------------------------------------------------------------------
# The tables. One per params layout; every leaf of every supported arch
# (llama dense, Mixtral/Grok MoE) must match exactly one rule — enforced
# by tests/test_sharding_rules.py over real loaded trees, snapshot-pinned.
# ---------------------------------------------------------------------------

_L = r"layers/\d+"  # one per-layer subtree of the layered (list) layouts
_E = r"experts/\d+"

# Rules shared by every layout's top level. Replication is EXPLICIT:
# embedding / norms / rope are declared replicated, not defaulted.
_TOP_RULES = (
    Rule(r"embedding", (None, None)),
    Rule(r"rms_final", (None,)),
    Rule(r"rope_table", (None, None, None)),
)


def _wcls_rule(shard_vocab: bool) -> Rule:
    # vocab-sharded logits head (the reference keeps logits root-only
    # instead); the all-gather that reassembles them lives in the backend
    return Rule(r"wcls", (None, MODEL) if shard_vocab else (None, None))


def _norm_rules(cfg: LlamaConfig, layer: str, stacked: bool) -> tuple[Rule, ...]:
    lead: tuple = (None,) if stacked else ()
    names = ["rms_att", "rms_ffn"]
    if cfg.arch.name == "GROK1":
        names += ["rms_moe", "rms_ffn2"]
    return (Rule(rf"{layer}/({'|'.join(names)})", lead + (None,)),)


def _dense_layer_rules(cfg: LlamaConfig, layer: str, stacked: bool) -> tuple[Rule, ...]:
    """The unfused bf16/f32 layout (one leaf per file matrix): q/k/v and
    gate/up are output-dim sharded (RowMatmulSlice), wo/down input-dim
    sharded (ColMatmulSlice) — reference src/commands.cpp:11-73."""
    lead: tuple = (None,) if stacked else ()
    rules = [
        Rule(rf"{layer}/(q|k|v)", lead + (None, MODEL)),
        Rule(rf"{layer}/wo", lead + (MODEL, None)),
        *_norm_rules(cfg, layer, stacked),
    ]
    if cfg.is_moe:
        rules += [
            Rule(rf"{layer}/router", lead + (None, None)),
            # TP-sliced expert banks [E, D, Hl]/[E, Hl, D]: every shard
            # holds a 1/tp hidden-slice of ALL experts (the reference's
            # MoE layout, src/transformer.cpp:335-353)
            Rule(rf"{layer}/(moe_up|moe_gate)", lead + (None, None, MODEL)),
            Rule(rf"{layer}/moe_down", lead + (None, MODEL, None)),
        ]
    else:
        rules += [
            Rule(rf"{layer}/(gate|up)", lead + (None, MODEL)),
            Rule(rf"{layer}/down", lead + (MODEL, None)),
        ]
    return tuple(rules)


def _q40_layer_rules(cfg: LlamaConfig, layer: str) -> tuple[Rule, ...]:
    """The fused q40 per-layer-list layout: qkv / gate_up pack several
    output-sharded matrices into one QuantizedMatrix leaf; per-expert
    leaves follow the dense FFN pattern."""
    rules = [
        Rule(rf"{layer}/qkv", (None, MODEL)),
        Rule(rf"{layer}/wo", (MODEL, None)),
        *_norm_rules(cfg, layer, stacked=False),
    ]
    if cfg.is_moe:
        rules += [
            Rule(rf"{layer}/router", (None, None)),
            Rule(rf"{layer}/{_E}/gate_up", (None, MODEL)),
            Rule(rf"{layer}/{_E}/down", (MODEL, None)),
        ]
    else:
        rules += [
            Rule(rf"{layer}/gate_up", (None, MODEL)),
            Rule(rf"{layer}/down", (MODEL, None)),
        ]
    return tuple(rules)


def _ep_layer_rules(cfg: LlamaConfig, layer: str, quantized: bool) -> tuple[Rule, ...]:
    """Expert-parallel layouts: expert banks stack on a leading expert
    axis sharded over EXPERT, hidden still sharded over MODEL; the rest
    of the layer follows the matching dense/q40 rules."""
    if quantized:
        base = [r for r in _q40_layer_rules(cfg, layer)
                if "experts/" not in r.pattern]
        return tuple(base) + (
            Rule(rf"{layer}/experts_gate_up", (EXPERT, None, MODEL)),
            Rule(rf"{layer}/experts_down", (EXPERT, MODEL, None)),
        )
    base = [r for r in _dense_layer_rules(cfg, layer, stacked=False)
            if "moe_" not in r.pattern]
    return tuple(base) + (
        Rule(rf"{layer}/(moe_up|moe_gate)", (EXPERT, None, MODEL)),
        Rule(rf"{layer}/moe_down", (EXPERT, MODEL, None)),
    )


LAYOUTS = ("layered", "stacked", "q40", "ep", "ep_q40")


def param_rules(cfg: LlamaConfig, layout: str, shard_vocab: bool) -> RuleTable:
    """The ordered rule table of one params layout.

    * ``layered`` — per-layer-list bf16/f32 (the engine's production
      dense layout, ``engine.weights.load_params``)
    * ``stacked`` — leading-layer-axis bf16/f32 (synthetic/test trees)
    * ``q40`` — per-layer-list fused q40 (QuantizedMatrix leaves)
    * ``ep`` / ``ep_q40`` — expert-parallel stacked expert banks
    """
    layer = _L if layout != "stacked" else "layers"
    if layout in ("layered", "stacked"):
        layer_rules = _dense_layer_rules(cfg, layer, stacked=layout == "stacked")
    elif layout == "q40":
        layer_rules = _q40_layer_rules(cfg, layer)
    elif layout in ("ep", "ep_q40"):
        layer_rules = _ep_layer_rules(cfg, layer, quantized=layout == "ep_q40")
    else:
        raise ValueError(f"unknown params layout {layout!r} (one of {LAYOUTS})")
    return RuleTable(
        name=f"{layout}/{cfg.arch.name.lower()}",
        rules=_TOP_RULES + (_wcls_rule(shard_vocab),) + layer_rules,
    )


def params_skeleton(cfg: LlamaConfig, layout: str, n_layers: int | None = None):
    """Structure-only params tree (every leaf ``None``) for one layout —
    lets spec trees be built from a config alone, without weights. The
    golden test pins this against trees the REAL loaders build, so the
    skeleton and ``engine.weights`` cannot drift apart."""
    n_layers = cfg.n_layers if n_layers is None else n_layers

    def layer():
        t: dict[str, Any] = {}
        if layout in ("layered", "stacked", "ep"):
            t.update(q=None, k=None, v=None, wo=None)
        else:
            t.update(qkv=None, wo=None)
        t.update(rms_att=None, rms_ffn=None)
        if cfg.is_moe:
            t["router"] = None
            if layout == "q40":
                t["experts"] = [
                    {"gate_up": None, "down": None} for _ in range(cfg.n_experts)
                ]
            elif layout == "ep_q40":
                t.update(experts_gate_up=None, experts_down=None)
            else:  # layered / stacked / ep: stacked banks
                t.update(moe_up=None, moe_gate=None, moe_down=None)
        elif layout in ("q40", "ep_q40"):
            t.update(gate_up=None, down=None)
        else:
            t.update(gate=None, down=None, up=None)
        if cfg.arch.name == "GROK1":
            t.update(rms_moe=None, rms_ffn2=None)
        return t

    layers: Any
    if layout == "stacked":
        layers = layer()
    else:
        layers = [layer() for _ in range(n_layers)]
    return {
        "embedding": None,
        "layers": layers,
        "rms_final": None,
        "wcls": None,
        "rope_table": None,
    }


def param_specs(
    cfg: LlamaConfig,
    layout: str,
    shard_vocab: bool,
    axes: dict[str, str | None],
    n_layers: int | None = None,
):
    """Spec tree for one layout from the rule table — the lookup every
    backend's hand-rolled builder reduced to (ISSUE 15)."""
    return param_rules(cfg, layout, shard_vocab).resolve(
        params_skeleton(cfg, layout, n_layers), axes
    )


# ---------------------------------------------------------------------------
# KV-cache / slab / page-pool layouts: same mechanism, one home. These
# are indexed by kind, not path regex — cache trees are homogeneous
# per-layer tuples, so the "which axis do KV heads / sequence slots
# shard over" fact is the whole table.
# ---------------------------------------------------------------------------

SEQ = "<seq>"  # the sequence-parallel axis role (context_parallel)

CACHE_AXES: dict[str, tuple] = {
    # stacked whole-model cache [L, 2, S, K, hd]: KV heads over MODEL
    "stacked": (None, None, None, MODEL, None),
    # per-layer (keys, values) tuples of [S, K, hd]
    "stream": (None, MODEL, None),
    # sequence-sharded per-layer stream cache [S, K, hd] (sp backends)
    "stream_sp": (SEQ, MODEL, None),
    # batched slab [B, S, K, hd]: batch/sequence replicated
    "slab": (None, None, MODEL, None),
    # prefix-cache page pool [P, page, K, hd]
    "pool": (None, None, MODEL, None),
}


def cache_spec(kind: str, axes: dict[str, str | None]) -> P:
    """Resolved cache-layout spec (one spec is the pytree prefix covering
    a QuantizedKV half's data+scales leaves, which shard alike)."""
    template = CACHE_AXES[kind]
    out = []
    for a in template:
        if a is SEQ:
            out.append(axes.get("seq"))
        elif a is MODEL:
            out.append(axes.get("model"))
        else:
            out.append(None)
    return P(*out)
