"""Sequence/context parallelism: ring attention and sharded-KV decode.

The reference has NO long-context strategy — every node holds the full
sequence in its KV slice and attention is quadratic on one node
(SURVEY.md §5: "No ring attention / blockwise / Ulysses / CP anywhere"); its
only levers are --max-seq-len and a disc-backed KV cache. Here sequence
parallelism is first-class:

* :func:`ring_attention` — causal blockwise attention for prefill with the
  sequence sharded over an ``sp`` mesh axis. KV chunks rotate around the
  ring with ``jax.lax.ppermute`` while each device accumulates its query
  chunk's output with an online (flash-style) softmax — compute overlaps the
  ICI transfer, and no device ever materializes the full sequence.
* :func:`sp_decode_attention` — single-token decode against a
  sequence-sharded KV cache: each device attends over its local cache slice,
  then the partial (max, denominator, numerator) triples merge across the
  ring with one pmax + two psums.

Both run inside ``shard_map`` and are validated against full attention on a
virtual CPU mesh (tests/test_context_parallel.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _chunk_attention(
    q: jax.Array,  # [Tq, K, M, hd] f32 (grouped: K kv-heads × M q-per-kv)
    k: jax.Array,  # [Tk, K, hd]
    v: jax.Array,  # [Tk, K, hd]
    q_positions: jax.Array,  # [Tq] global positions
    k_positions: jax.Array,  # [Tk]
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Masked scores of one (q-chunk, kv-chunk) pair → (m, l, o) partials.

    m: running max [Tq, K, M]; l: exp-sum [Tq, K, M]; o: weighted V sum
    [Tq, K, M, hd]. Entirely local — no collectives.
    """
    hd = q.shape[-1]
    scores = jnp.einsum("tkmh,skh->tkms", q, k) / jnp.sqrt(jnp.float32(hd))
    mask = (k_positions[None, :] <= q_positions[:, None])[:, None, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    m = jnp.max(scores, axis=-1)  # [Tq, K, M]
    # fully-masked rows (no kv visible in this chunk) produce m=-inf; guard
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("tkms,skh->tkmh", p, v)
    return safe_m, l, o


def _merge(m1, l1, o1, m2, l2, o2):
    """Merge two online-softmax partials (standard flash-attention merge)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    o = o1 * a1[..., None] + o2 * a2[..., None]
    return m, l, o


def ring_attention(
    q: jax.Array,  # [Tq, H, hd] local query chunk
    k: jax.Array,  # [Tk, K, hd] local key chunk
    v: jax.Array,  # [Tk, K, hd] local value chunk
    axis_name: str,
    chunk_offset: jax.Array | None = None,
) -> jax.Array:
    """Causal blockwise attention with the sequence sharded over
    ``axis_name``. Device i holds positions [i*Tq, (i+1)*Tq). Returns the
    local output chunk [Tq, H, hd] (f32).
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    Tq = q.shape[0]
    Tk = k.shape[0]
    H = q.shape[1]
    K = k.shape[1]
    kv_mul = H // K

    qg = q.reshape(Tq, K, kv_mul, q.shape[-1]).astype(jnp.float32)
    base = idx * Tq if chunk_offset is None else chunk_offset
    q_pos = base + jnp.arange(Tq)

    def step(s, carry):
        kc, vc, m, l, o = carry
        src_chunk = (idx - s) % n  # whose kv chunk we currently hold
        k_pos = src_chunk * Tk + jnp.arange(Tk)
        ms, ls, os_ = _chunk_attention(qg, kc.astype(jnp.float32), vc.astype(jnp.float32), q_pos, k_pos)
        m, l, o = _merge(m, l, o, ms, ls, os_)
        # rotate kv around the ring: device i sends to i+1 (so chunks walk
        # backwards relative to each device's view)
        perm = [(j, (j + 1) % n) for j in range(n)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return kc, vc, m, l, o

    m0 = jnp.full((Tq, K, kv_mul), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((Tq, K, kv_mul), jnp.float32)
    o0 = jnp.zeros((Tq, K, kv_mul, q.shape[-1]), jnp.float32)
    _, _, m, l, o = jax.lax.fori_loop(0, n, step, (k, v, m0, l0, o0))

    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(Tq, H, q.shape[-1])


def sp_decode_attention(
    q: jax.Array,  # [H, hd] the single decode query (replicated)
    k_local: jax.Array,  # [Sl, K, hd] local KV-cache slice (sequence-sharded)
    v_local: jax.Array,  # [Sl, K, hd]
    pos: jax.Array,  # scalar: current absolute position (attend s <= pos)
    axis_name: str,
) -> jax.Array:
    """One-token attention over a sequence-sharded KV cache. Every device
    computes partials over its slice; one pmax + two psums merge them.
    Returns [H, hd] (replicated)."""
    idx = jax.lax.axis_index(axis_name)
    Sl, K, hd = k_local.shape
    H = q.shape[0]
    kv_mul = H // K
    qg = q.reshape(1, K, kv_mul, hd).astype(jnp.float32)
    positions = idx * Sl + jnp.arange(Sl)
    q_pos = jnp.asarray([pos])
    m, l, o = _chunk_attention(
        qg, k_local.astype(jnp.float32), v_local.astype(jnp.float32), q_pos, positions
    )
    # cross-device online-softmax merge
    g_m = jax.lax.pmax(m, axis_name)
    scale = jnp.exp(m - g_m)
    g_l = jax.lax.psum(l * scale, axis_name)
    g_o = jax.lax.psum(o * scale[..., None], axis_name)
    out = g_o / jnp.maximum(g_l, 1e-30)[..., None]
    return out.reshape(H, hd)
