"""Sequence/context parallelism: ring attention and sharded-KV decode.

The reference has NO long-context strategy — every node holds the full
sequence in its KV slice and attention is quadratic on one node
(SURVEY.md §5: "No ring attention / blockwise / Ulysses / CP anywhere"); its
only levers are --max-seq-len and a disc-backed KV cache. Here sequence
parallelism is first-class:

* :func:`ring_attention` — causal blockwise attention for prefill with the
  sequence sharded over an ``sp`` mesh axis. KV chunks rotate around the
  ring with ``jax.lax.ppermute`` while each device accumulates its query
  chunk's output with an online (flash-style) softmax — compute overlaps the
  ICI transfer, and no device ever materializes the full sequence.
* :func:`sp_sharded_attention` — Tq query rows against a sequence-sharded
  KV cache: each device attends over its local cache slice, then the
  partial (max, denominator, numerator) triples merge across the ring with
  one pmax + two psums. Tq==1 (:func:`sp_decode_attention`) is the decode
  step; Tq>1 drives the chunked mid-context prefill (:func:`_sp_chunk_forward`)
  that consumes chat/API delta prompts against a live cache in
  ceil(T/chunk) dispatches.

All run inside ``shard_map`` and are validated against full attention on a
virtual CPU mesh (tests/test_context_parallel.py).
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from distributed_llama_tpu.ops import kv_cache as kvc
from distributed_llama_tpu.ops.attention import (
    blocked_partials,
    chunk_attention,
    merge_partials,
)
from distributed_llama_tpu.parallel.tensor_parallel import TransferProbeMixin

# the online-softmax primitives live in ops.attention (shared with the dense
# blocked-attention path); keep the historical local names — they are part
# of this module's documented surface
_chunk_attention = chunk_attention
_merge = merge_partials


def ring_attention(
    q: jax.Array,  # [Tq, H, hd] local query chunk
    k: jax.Array,  # [Tk, K, hd] local key chunk
    v: jax.Array,  # [Tk, K, hd] local value chunk
    axis_name: str,
    chunk_offset: jax.Array | None = None,
) -> jax.Array:
    """Causal blockwise attention with the sequence sharded over
    ``axis_name``. Device i holds positions [i*Tq, (i+1)*Tq). Returns the
    local output chunk [Tq, H, hd] (f32).
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    Tq = q.shape[0]
    Tk = k.shape[0]
    H = q.shape[1]
    K = k.shape[1]
    kv_mul = H // K

    qg = q.reshape(Tq, K, kv_mul, q.shape[-1]).astype(jnp.float32)
    base = idx * Tq if chunk_offset is None else chunk_offset
    q_pos = base + jnp.arange(Tq)

    def step(s, carry):
        kc, vc, m, l, o = carry
        src_chunk = (idx - s) % n  # whose kv chunk we currently hold
        k_pos = src_chunk * Tk + jnp.arange(Tk)
        # kc/vc stay in cache dtype: the ring ppermute then moves half the
        # bytes for a bf16 cache, and _chunk_attention accumulates in f32
        ms, ls, os_ = _chunk_attention(qg, kc, vc, q_pos, k_pos)
        m, l, o = _merge(m, l, o, ms, ls, os_)
        # rotate kv around the ring: device i sends to i+1 (so chunks walk
        # backwards relative to each device's view)
        perm = [(j, (j + 1) % n) for j in range(n)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return kc, vc, m, l, o

    m0 = jnp.full((Tq, K, kv_mul), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((Tq, K, kv_mul), jnp.float32)
    o0 = jnp.zeros((Tq, K, kv_mul, q.shape[-1]), jnp.float32)
    _, _, m, l, o = jax.lax.fori_loop(0, n, step, (k, v, m0, l0, o0))

    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(Tq, H, q.shape[-1])


# key-axis chunk of the blocked local-slice scan (see ops.attention): local
# slices that are a multiple of this use a dynamic chunk bound — slots past
# the live position are never read, so sp decode cost follows the LIVE
# context, not the allocated S/sp slice (the dense path's round-5 blocked-
# attention win applied to the sequence-parallel slice scan)
SP_ATT_CHUNK = 512


def sp_sharded_attention(
    q: jax.Array,  # [Tq, H, hd] query rows (replicated across the axis)
    k_local: jax.Array,  # [Sl, K, hd] local KV-cache slice (sequence-sharded)
    v_local: jax.Array,  # [Sl, K, hd]
    q_pos: jax.Array,  # [Tq] absolute positions (each attends s <= its pos)
    axis_name: str,
) -> jax.Array:
    """Attention of Tq query rows over a sequence-sharded KV cache. Every
    device computes partials over its slice — blocked with a dynamic bound
    when the slice is chunk-divisible, one masked pass otherwise — and one
    pmax + two psums merge them (cross-device online-softmax merge).
    Returns [Tq, H, hd] (replicated). Tq==1 is the decode step; Tq>1 is
    the chunked mid-context prefill."""
    idx = jax.lax.axis_index(axis_name)
    Sl, K, hd = k_local.shape
    Tq, H = q.shape[0], q.shape[1]
    kv_mul = H // K
    qg = q.reshape(Tq, K, kv_mul, hd).astype(jnp.float32)
    base = idx * Sl
    if Sl % SP_ATT_CHUNK == 0 and Sl > SP_ATT_CHUNK:
        m, l, o = blocked_partials(qg, k_local, v_local, q_pos, base, SP_ATT_CHUNK)
        # the cross-shard pmax needs a finite max everywhere (a no-live-slot
        # shard reports -inf); the merge algebra is invariant to which
        # reference max is used, so clamp like chunk_attention's safe_m
        m = jnp.where(jnp.isfinite(m), m, 0.0)
    else:
        positions = base + jnp.arange(Sl)
        m, l, o = _chunk_attention(qg, k_local, v_local, q_pos, positions)
    g_m = jax.lax.pmax(m, axis_name)
    scale = jnp.exp(m - g_m)
    g_l = jax.lax.psum(l * scale, axis_name)
    g_o = jax.lax.psum(o * scale[..., None], axis_name)
    out = g_o / jnp.maximum(g_l, 1e-30)[..., None]
    return out.reshape(Tq, H, hd)


def sp_decode_attention(
    q: jax.Array,  # [H, hd] the single decode query (replicated)
    k_local: jax.Array,  # [Sl, K, hd] local KV-cache slice (sequence-sharded)
    v_local: jax.Array,  # [Sl, K, hd]
    pos: jax.Array,  # scalar: current absolute position (attend s <= pos)
    axis_name: str,
) -> jax.Array:
    """One-token attention over a sequence-sharded KV cache: the Tq==1 case
    of :func:`sp_sharded_attention`. Returns [H, hd] (replicated)."""
    return sp_sharded_attention(
        q[None], k_local, v_local, jnp.asarray([pos]), axis_name
    )[0]


# ---------------------------------------------------------------------------
# Sequence-parallel engine backend
# ---------------------------------------------------------------------------


class SequenceParallelForward(TransferProbeMixin):
    """Sequence/context parallelism as an engine backend: the KV cache is
    sharded along the SEQUENCE axis over an ``sp`` mesh (device i owns slots
    [i*S/n, (i+1)*S/n)), weights are replicated, prefill runs
    :func:`ring_attention` over position chunks, and decode attends its local
    cache slice with the cross-device online-softmax merge of
    :func:`sp_decode_attention`.

    This is the long-context strategy the reference lacks entirely
    (SURVEY.md §5): per-device KV memory drops to 1/n — the same memory
    shape as the reference's per-node KvCacheSlice (src/commands.cpp:97-102)
    but over the sequence instead of heads, so it composes with long
    contexts rather than head counts.

    Prefill routing: a prompt that fills a large fraction of the context
    (T*RING_PREFILL_FRACTION >= seq_len) takes the ring-attention path,
    which processes the FULL padded context (the prompt is padded to
    seq_len so every device owns exactly its cache slice's positions —
    uniform chunks are what make the ring collective regular; its blockwise
    causal attention and overlapped ppermutes are what win at that scale).
    SHORT prompts instead run the same fixed-width masked-scatter chunk
    path as mid-context prompts (ceil(T/32) dispatches, cost O(prompt) +
    O(S/sp) local attention per chunk) — previously every prompt paid the
    O(S) padded ring pass, which made sp serving of short prompts
    pathological (round-4 verdict item 5).

    ``tp > 1`` composes tensor parallelism on a 2-D ``(tp, sp)`` mesh — the
    scaling-book recipe the reference's 1-D TCP star cannot express: weights
    and attention heads shard over ``tp`` (psum after wo/down rides one mesh
    axis), the sequence and KV cache shard over ``sp`` (ring/online-softmax
    collectives ride the other), and the KV cache shrinks by tp*sp per
    device (heads AND sequence).
    """

    def __init__(self, cfg, sp: int, tp: int = 1, quantized: bool = False, devices=None):
        import functools

        from jax.experimental import mesh_utils
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from distributed_llama_tpu.parallel.tensor_parallel import (
            param_specs_layered,
            q40_param_specs,
            shard_map,
            validate_tp,
        )

        if cfg.seq_len % sp:
            raise ValueError(f"sp={sp} must divide seq_len={cfg.seq_len}")
        if tp > 1:
            validate_tp(cfg, tp, quantized=quantized)
        self.cfg = cfg
        self.sp = sp
        self.tp = tp
        self.quantized = quantized
        n_dev = tp * sp
        if devices is None:
            devices = jax.devices()[:n_dev]
        if len(devices) < n_dev:
            raise ValueError(f"need {n_dev} devices (tp*sp), have {len(devices)}")
        self.mesh = Mesh(
            mesh_utils.create_device_mesh((tp, sp), devices=devices[:n_dev]),
            ("tp", "sp"),
        )
        self._P = P
        self._NamedSharding = NamedSharding
        self._shard_map = shard_map
        self.shard_vocab = tp > 1 and cfg.vocab_size % tp == 0
        # per-layer (keys, values) tuples of [S, K, hd]: sequence slots
        # shard over sp, KV heads over tp (one spec is the pytree prefix
        # covering both tuple leaves)
        cache_ax = P("sp", "tp", None) if tp > 1 else P("sp", None, None)
        self._cache_spec = [cache_ax] * cfg.n_layers
        if tp == 1:
            self._pspecs = P()  # fully replicated params
        elif quantized:
            self._pspecs = q40_param_specs(cfg, cfg.n_layers, self.shard_vocab)
        else:
            self._pspecs = param_specs_layered(cfg, cfg.n_layers, self.shard_vocab)
        self._tp_axis = "tp" if tp > 1 else None
        self._decode_cache: dict = {}
        # the engine must not bucket-pad mid-context prompts for this
        # backend: it chunks them itself (fixed-size masked-scatter passes,
        # see _sp_chunk_forward) so only one program shape compiles
        self.prefers_exact_mid_prefill = True
        # chunk width of the mid-context prefill: one dispatch consumes up
        # to this many tokens (padded to exactly this many)
        self.mid_prefill_chunk = 32
        # dispatches issued by the most recent forward() call ON THIS THREAD
        # — the engine scales its measured per-dispatch transfer estimate by
        # it. Thread-local: concurrent serving streams call forward() from
        # their own request threads, and a shared counter would let stream
        # A's chunked mid-prefill count leak into stream B's I/T stats split
        # (ADVICE r5). Each thread reads back exactly what its own forward
        # issued; threads that never forwarded read the 1-dispatch default.
        self._dispatch_local = threading.local()

        prefill = shard_map(
            functools.partial(_sp_prefill, cfg, self._tp_axis),
            mesh=self.mesh,
            in_specs=(self._pspecs, P("sp"), self._cache_spec),
            out_specs=(P("sp"), self._cache_spec),
            check_vma=False,
        )
        self._prefill = jax.jit(prefill, donate_argnums=(2,))

        step = shard_map(
            functools.partial(_sp_decode_step, cfg, self._tp_axis),
            mesh=self.mesh,
            in_specs=(self._pspecs, P(), self._cache_spec, P()),
            out_specs=(P(), self._cache_spec),
            check_vma=False,
        )
        self._step = jax.jit(step, donate_argnums=(2,))

        chunk_fwd = shard_map(
            functools.partial(_sp_chunk_forward, cfg, self._tp_axis),
            mesh=self.mesh,
            in_specs=(self._pspecs, P(), self._cache_spec, P()),
            out_specs=(P(), self._cache_spec),
            check_vma=False,
        )
        self._chunk_fwd = jax.jit(chunk_fwd, donate_argnums=(2,))

    # -- engine interface ---------------------------------------------------

    @property
    def last_forward_dispatches(self) -> int:
        """Dispatch count of the calling thread's most recent forward()
        (per-thread snapshot — see the ``_dispatch_local`` note)."""
        return getattr(self._dispatch_local, "n", 1)

    def shard_params(self, host_params):
        from distributed_llama_tpu.parallel.tensor_parallel import place_params

        return place_params(host_params, self._pspecs, self.mesh)

    def init_cache(self, dtype=jnp.float32):
        import numpy as np

        cfg = self.cfg
        shape = (cfg.seq_len, cfg.n_kv_heads, cfg.head_size)
        sharding = self._NamedSharding(self.mesh, self._cache_spec[0])

        def zeros(gshape, dt):
            # gshape is GLOBAL; (sequence, kv-head) shard per device — the
            # spec prefix covers QuantizedKV's rank-3 scales leaf too
            local = np.zeros(
                (gshape[0] // self.sp, gshape[1] // self.tp) + gshape[2:], dt
            )
            return jax.make_array_from_callback(gshape, sharding, lambda idx: local)

        return [
            (kvc.init_half(shape, dtype, zeros=zeros),
             kvc.init_half(shape, dtype, zeros=zeros))
            for _ in range(cfg.n_layers)
        ]

    # a prompt whose length * this fraction reaches seq_len takes the ring
    # path; shorter prompts take the O(prompt) chunked path (see class
    # docstring)
    RING_PREFILL_FRACTION = 4

    def forward(self, params, tokens, cache, pos):
        """Engine forward: T==1 routes to the decode step; a long T at pos 0
        (T*RING_PREFILL_FRACTION >= seq_len) is the ring-attention
        full-context prefill (tokens padded to seq_len — every device owns
        exactly its cache slice's positions). Every other multi-token
        forward — short initial prompts AND chat/API delta prompts against
        a live cache — runs chunked: ceil(T/mid_prefill_chunk) fixed-width
        masked-scatter dispatches (see _sp_chunk_forward) instead of the
        O(S) padded ring pass or one dispatch per token."""
        tokens = jnp.asarray(tokens)
        T = tokens.shape[0]
        self._dispatch_local.n = 1
        if T == 1:
            return self._step(params, tokens, cache, jnp.asarray(pos))
        S = self.cfg.seq_len
        if int(pos) != 0 or T * self.RING_PREFILL_FRACTION < S:
            CH = self.mid_prefill_chunk
            rows = []
            p = int(pos)
            for i in range(0, T, CH):
                chunk = tokens[i : i + CH]
                c = chunk.shape[0]
                if c < CH:
                    # pad to the one compiled width; pad rows write stale
                    # cache slots beyond pos+T, unreachable per the engine's
                    # rollback contract (overwritten before pos crosses them)
                    chunk = jnp.pad(chunk, (0, CH - c))
                logits, cache = self._chunk_fwd(
                    params, chunk, cache, jnp.asarray(p)
                )
                rows.append(logits[:c])
                p += c
            self._dispatch_local.n = (T + CH - 1) // CH
            return jnp.concatenate(rows, axis=0), cache
        if T != S:
            tokens = jnp.pad(tokens, (0, S - tokens.shape[0]))
        return self._prefill(params, tokens, cache)

    def decode_loop(
        self, params, first_token, cache, pos, n_steps, temperature, topp,
        seed: int = 0, topk: int = 0,
    ):
        from distributed_llama_tpu import prng

        tokens, cache = self._decode_scan(
            int(n_steps), float(temperature), float(topp), int(topk)
        )(
            params, jnp.asarray(first_token), cache, jnp.asarray(pos),
            jnp.uint32(prng.fold_seed(seed)),
        )
        return tokens, cache

    def decode_chunk(
        self, params, first_token, cache, pos, n_steps, temperature, topp,
        topk, seed32,
    ):
        jitted = self._decode_scan(int(n_steps), None, None, None)
        return jitted(
            params, jnp.asarray(first_token), cache, jnp.asarray(pos),
            jnp.float32(temperature), jnp.float32(topp), jnp.int32(topk),
            jnp.asarray(seed32, jnp.uint32),
        )

    def _decode_scan(self, n_steps: int, temperature, topp, topk):
        """Jitted on-device decode loop; sampler params static when given
        (decode_loop) or traced scalars when None (decode_chunk — one
        compiled program per chunk size serves every sampler setting).
        Coins come from the stateless counter PRNG keyed (seed, position),
        so nothing threads between chunks (ISSUE 13)."""
        from distributed_llama_tpu.models import sampling

        P = self._P
        key_ = (n_steps, temperature, topp, topk)
        cached = self._decode_cache.get(key_)
        if cached is not None:
            return cached
        cfg = self.cfg

        tp_axis = self._tp_axis

        def scan_body(params, first_token, cache, pos, seed, t, p, k_top):
            def step(carry, _):
                token, cache_c, pp = carry
                logits, cache_c = _sp_decode_step(
                    cfg, tp_axis, params, token[None], cache_c, pp
                )
                nxt = sampling.sample_token(logits[0], seed, pp, t, p, k_top)
                return (nxt, cache_c, pp + 1), nxt

            (_, cache, _), tokens = jax.lax.scan(
                step, (first_token.astype(jnp.int32), cache, pos.astype(jnp.int32)),
                None, length=n_steps,
            )
            return tokens, cache

        if temperature is None:  # dynamic sampler params

            def fn(params, first_token, cache, pos, t_in, p_in, k_in, seed):
                return scan_body(
                    params, first_token, cache, pos, seed, t_in, p_in, k_in
                )

            in_specs = (self._pspecs, P(), self._cache_spec, P(), P(), P(), P(), P())
        else:

            def fn(params, first_token, cache, pos, seed):
                return scan_body(
                    params, first_token, cache, pos, seed, temperature, topp,
                    topk,
                )

            in_specs = (self._pspecs, P(), self._cache_spec, P(), P())
        mapped = self._shard_map(
            fn, mesh=self.mesh, in_specs=in_specs,
            out_specs=(P(), self._cache_spec), check_vma=False,
        )
        jitted = jax.jit(mapped, donate_argnums=(2,))
        self._decode_cache[key_] = jitted
        return jitted

    def transfer_probe(self, n_tokens: int = 32):
        """(jitted_fn, example_args) replaying the sp decode's collective
        sequence: per layer one pmax + two psums of the online-softmax
        partials (see sp_sharded_attention), plus the two tp all-reduces
        when a 2-D mesh is in use. Exposed so tests can compile it and
        assert the collectives survive XLA DCE (the keep-alive arithmetic
        is what the timing validity rests on)."""
        cfg = self.cfg
        H, hd = cfg.n_heads, cfg.head_size
        K = cfg.n_kv_heads // self.tp  # local KV heads under the 2-D mesh
        M = max(1, (H // self.tp) // max(K, 1))
        tp_axis = self._tp_axis

        def token_step(carry, _):
            m, o, z = carry

            def layer(c, _):
                mm, oo, zz = c
                g_m = jax.lax.pmax(mm, "sp")
                g_l = jax.lax.psum(mm * 0.5, "sp")
                g_o = jax.lax.psum(oo, "sp")
                if tp_axis is not None:
                    # the wo/down all-reduces carry a FULL [1, dim]
                    # activation each (llama.block_tail), not the smaller
                    # attention partials — model them at true size
                    zz = jax.lax.psum(zz, tp_axis) * 0.5
                    zz = jax.lax.psum(zz, tp_axis) * 0.5
                return (g_m + g_l * 1e-9, g_o * 0.5, zz), None

            (m, o, z), _ = jax.lax.scan(layer, (m, o, z), None, length=cfg.n_layers)
            return (m, o, z), None

        def fn(m, o, z):
            (m, o, z), _ = jax.lax.scan(token_step, (m, o, z), None, length=n_tokens)
            return m, o, z

        P = self._P
        mapped = self._shard_map(
            fn, mesh=self.mesh, in_specs=(P(), P(), P()), out_specs=(P(), P(), P()),
            check_vma=False,
        )
        m = jnp.ones((1, K, M), jnp.float32)
        o = jnp.ones((1, K, M, hd), jnp.float32)
        z = jnp.ones((1, cfg.dim), jnp.float32)
        return jax.jit(mapped), (m, o, z)

    def transfer_bytes_per_token(self) -> int:
        """The probed sp decode sequence per layer: pmax + psum of the
        online-softmax max/normalizer partials ([1, K, M] each) and a psum
        of the output partial ([1, K, M, hd]) over sp, plus the two full
        [1, dim] tp all-reduces on a 2-D mesh (see :meth:`transfer_probe`)."""
        cfg = self.cfg
        K = cfg.n_kv_heads // self.tp
        M = max(1, (cfg.n_heads // self.tp) // max(K, 1))
        per_layer = (2 * K * M + K * M * cfg.head_size) * 4
        if self._tp_axis is not None:
            per_layer += 2 * cfg.dim * 4
        return cfg.n_layers * per_layer


def _sp_logits(cfg, tp_axis, params, x):
    """Final logits with the optional tp vocab-shard all-gather."""
    from distributed_llama_tpu.models import llama

    logits = llama.final_logits(cfg, params, x)
    if tp_axis is not None and logits.shape[-1] != cfg.vocab_size:
        logits = jax.lax.all_gather(logits, tp_axis, axis=1, tiled=True)
    return logits


def _sp_prefill(cfg, tp_axis, params, tokens_local, cache):
    """Per-shard prefill body: ring attention over position chunks. Device i
    processes positions [i*Tl, (i+1)*Tl) — exactly its cache slice. Block
    wiring (norms, projections, residuals, FFN/MoE, logits) is shared with
    the dense path via llama's helpers; only attention differs. Under a 2-D
    mesh, projections/FFN are tp-sharded (psum over ``tp_axis``) while the
    ring rides ``sp`` — the two collective families never mix."""
    from distributed_llama_tpu.models import llama

    idx = jax.lax.axis_index("sp")
    Tl = tokens_local.shape[0]
    offset = idx * Tl
    x = llama.embed(cfg, params, tokens_local)
    rope_rows = jax.lax.dynamic_slice(
        params["rope_table"], (offset, 0, 0),
        (Tl,) + params["rope_table"].shape[1:],
    )

    new_cache = []
    for lp, cache_l in zip(params["layers"], cache):
        q, k, v = llama.project_qkv(cfg, lp, x, rope_rows)
        H = q.shape[1]
        if isinstance(cache_l[0], kvc.QuantizedKV):
            # each device's fresh chunk IS its whole cache slice: store it
            # quantized; the ring below attends the raw rows (bf16 on the
            # wire — quantizing the ring would only trade accuracy for ICI
            # bytes the prefill doesn't bottleneck on)
            kq, ks = kvc.quantize_rows(k)
            vq, vs = kvc.quantize_rows(v)
            new_cache.append(
                (kvc.QuantizedKV(kq, ks), kvc.QuantizedKV(vq, vs))
            )
        else:
            cdt = cache_l[0].dtype
            k = k.astype(cdt)
            v = v.astype(cdt)
            new_cache.append((k, v))
        att = ring_attention(
            q.astype(jnp.float32), k, v, "sp", chunk_offset=offset
        ).reshape(Tl, H * cfg.head_size)
        x = llama.block_tail(cfg, x, att, lp, tp_axis)

    return _sp_logits(cfg, tp_axis, params, x), new_cache


def _sp_chunk_forward(cfg, tp_axis, params, tokens, cache, pos):
    """Per-shard mid-context chunk forward: C tokens at global positions
    pos..pos+C-1 against the LIVE sequence-sharded cache (a chat/API delta
    prompt). Compute is replicated across ``sp`` except attention:

    * each shard masked-scatters the chunk's new K/V rows into its own cache
      slice (rows owned by other shards — or pad rows past seq_len — drop
      via an out-of-bounds sentinel index),
    * then attends the C queries over its updated local slice and merges
      partials across the ring with the same pmax/psum online-softmax merge
      as :func:`sp_decode_attention` (generalized to C query rows).

    One dispatch consumes C tokens — replacing the one-dispatch-per-token
    fallback that made ``--sp`` unusable for multi-turn chat."""
    from distributed_llama_tpu.models import llama

    idx = jax.lax.axis_index("sp")
    C = tokens.shape[0]
    hd = cfg.head_size
    x = llama.embed(cfg, params, tokens)  # [C, dim]
    gpos = pos + jnp.arange(C)
    # gather (not dynamic_slice): a padded chunk near the context limit would
    # clamp a slice's START and shift every real token's rope row
    rope_rows = jnp.take(
        params["rope_table"], jnp.clip(gpos, 0, cfg.seq_len - 1), axis=0
    )

    new_cache = []
    for lp, cache_l in zip(params["layers"], cache):
        Sl = cache_l[0].shape[0]
        q, k, v = llama.project_qkv(cfg, lp, x, rope_rows)
        H, K = q.shape[1], k.shape[1]

        local = gpos - idx * Sl
        in_range = (local >= 0) & (local < Sl)
        slot = jnp.where(in_range, local, Sl)  # Sl is out of bounds -> drop
        keys = kvc.scatter_rows(cache_l[0], slot, k)
        values = kvc.scatter_rows(cache_l[1], slot, v)
        new_cache.append((keys, values))

        att = sp_sharded_attention(
            q.astype(jnp.float32), keys, values, gpos, "sp"
        ).reshape(C, H * hd)
        x = llama.block_tail(cfg, x, att, lp, tp_axis)

    return _sp_logits(cfg, tp_axis, params, x), new_cache


def _sp_decode_step(cfg, tp_axis, params, tokens, cache, pos):
    """Per-shard single-token decode: replicated compute except attention,
    which reads only the local cache slice and merges partials across the
    ring. The new token's K/V row is written on the owning shard only."""
    from distributed_llama_tpu.models import llama

    idx = jax.lax.axis_index("sp")
    x = llama.embed(cfg, params, tokens)  # [1, dim]
    rope_rows = jax.lax.dynamic_slice(
        params["rope_table"], (pos, 0, 0), (1,) + params["rope_table"].shape[1:]
    )
    hd = cfg.head_size

    new_cache = []
    for lp, cache_l in zip(params["layers"], cache):
        Sl = cache_l[0].shape[0]
        q, k, v = llama.project_qkv(cfg, lp, x, rope_rows)
        H, K = q.shape[1], k.shape[1]

        # write the new K/V row on the owning shard: every shard performs the
        # same dynamic_update_slice (aliasing-friendly), non-owners write the
        # row they already had back into place
        owner = (pos >= idx * Sl) & (pos < (idx + 1) * Sl)
        lpos = jnp.clip(pos - idx * Sl, 0, Sl - 1)
        keys = kvc.select_row_update(cache_l[0], k, lpos, owner)
        values = kvc.select_row_update(cache_l[1], v, lpos, owner)
        new_cache.append((keys, values))

        att = sp_decode_attention(
            q[0].astype(jnp.float32), keys, values, pos, "sp"
        ).reshape(1, H * hd)
        x = llama.block_tail(cfg, x, att, lp, tp_axis)

    return _sp_logits(cfg, tp_axis, params, x), new_cache
