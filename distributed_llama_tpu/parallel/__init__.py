"""Parallelism: device meshes, tensor-parallel sharding, multi-host setup.

The reference's only inter-node strategy is tensor parallelism over raw TCP
(SURVEY.md §2); here TP is a `shard_map` over a named mesh axis with XLA
collectives riding ICI/DCN, and the same mesh machinery extends to dp/sp/ep
axes (see distributed_llama_tpu.parallel.context for sequence parallelism).
"""

from distributed_llama_tpu.parallel.tensor_parallel import TensorParallelForward

__all__ = ["TensorParallelForward"]
# parallel.sharding (the declarative rule tables) and parallel.pod (the
# one-process ('data','model') pod) are imported directly by their
# consumers — no eager import here: sharding is pure-python cheap, but
# pod pulls mesh construction into import time for every CLI entry
