"""Parallelism: device meshes, tensor-parallel sharding, multi-host setup.

The reference's only inter-node strategy is tensor parallelism over raw TCP
(SURVEY.md §2); here TP is a `shard_map` over a named mesh axis with XLA
collectives riding ICI/DCN, and the same mesh machinery extends to dp/sp/ep
axes (see distributed_llama_tpu.parallel.context for sequence parallelism).
"""

from distributed_llama_tpu.parallel.tensor_parallel import TensorParallelForward

__all__ = ["TensorParallelForward"]
