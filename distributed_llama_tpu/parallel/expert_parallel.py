"""True expert parallelism (prototype): experts partitioned over an ``ep``
mesh axis with ``lax.all_to_all`` token routing.

The production MoE path TP-slices experts exactly like the reference (every
shard holds a 1/tp hidden-slice of ALL experts,
reference: src/transformer.cpp:335-353) — that is the right layout when
E is small and tokens are few (decode). TRUE expert parallelism is the
named extension beyond the reference (SURVEY.md §2 parallelism table):
device d owns E/ep WHOLE experts, and tokens travel to their experts:

1. tokens are sharded over ``ep`` ([Tl, D] per device); the (replicated)
   router picks top-k experts per local token,
2. each (token, choice) pair is scattered into a per-destination-device
   send buffer at a collision-free slot (slot = t*k + j, capacity Tl*k —
   the prototype never drops tokens),
3. one ``lax.all_to_all`` moves the buffers: device d receives every
   token routed to ITS experts,
4. d runs its local expert bank on the received rows (masked one-hot
   mixing over its E/ep experts),
5. a second ``all_to_all`` returns the outputs to the tokens' home
   devices, which combine them with the renormalized router weights.

This is the classic dispatch/compute/combine MoE exchange (two all-to-alls
riding ICI) — the communication pattern the reference's TCP star cannot
express at all. Prototype status: capacity is Tl*k with unique slots
(collision-free but sparse — a production version would sort-compact the
buckets), and the expert compute is the stacked-bf16 bank path. Validated
against the dense MoE path on the virtual CPU mesh
(tests/test_expert_parallel.py), which also micro-benchmarks it against
TP-sliced experts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from distributed_llama_tpu.models.config import LlamaConfig


def ep_moe_ffn_local(
    cfg: LlamaConfig,
    ep: int,
    axis_name: str,
    xn_local: jax.Array,  # [Tl, D] this device's token slice (normed)
    router: jax.Array,  # [D, E] replicated
    gate_l: jax.Array,  # [El, D, H] this device's expert slice
    up_l: jax.Array,  # [El, D, H]
    down_l: jax.Array,  # [El, H, D]
) -> jax.Array:
    """shard_map body: expert-parallel MoE FFN for one layer. Returns the
    local [Tl, D] output slice (f32)."""
    from distributed_llama_tpu.models.llama import _activation
    from distributed_llama_tpu.models.moe import router_probs

    Tl, D = xn_local.shape
    E = cfg.n_experts
    El = E // ep
    k = cfg.n_active_experts
    C = Tl * k  # per-destination capacity: one unique slot per (token, choice)

    probs = router_probs(cfg, xn_local, router)  # [Tl, E]
    top_vals, top_idx = jax.lax.top_k(probs, k)  # [Tl, k]
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)

    dest = top_idx // El  # owning device of each choice [Tl, k]
    local_eid = top_idx % El  # expert id within the owner's bank
    t_ids = jnp.broadcast_to(jnp.arange(Tl)[:, None], (Tl, k))
    slot = t_ids * k + jnp.broadcast_to(jnp.arange(k)[None, :], (Tl, k))  # unique

    # dispatch buffers: send[d, c] = the token row bound for device d's slot c
    send_x = jnp.zeros((ep, C, D), xn_local.dtype).at[dest, slot].set(
        xn_local[t_ids]
    )
    send_eid = jnp.full((ep, C), -1, jnp.int32).at[dest, slot].set(local_eid)

    # all_to_all #1: recv[s, c] = what device s sent me (tokens for MY experts)
    recv_x = jax.lax.all_to_all(send_x, axis_name, 0, 0)
    recv_eid = jax.lax.all_to_all(send_eid, axis_name, 0, 0)

    # local expert compute: masked one-hot mixing over this device's bank
    flat = recv_x.reshape(ep * C, D)
    eid = recv_eid.reshape(ep * C)
    xc = flat.astype(gate_l.dtype)
    g = jnp.einsum("td,edh->teh", xc, gate_l, preferred_element_type=jnp.float32)
    u = jnp.einsum("td,edh->teh", xc, up_l, preferred_element_type=jnp.float32)
    h = _activation(g, cfg.hidden_act) * u  # [ep*C, El, H]
    d_out = jnp.einsum(
        "teh,ehd->ted", h.astype(down_l.dtype), down_l,
        preferred_element_type=jnp.float32,
    )  # [ep*C, El, D]
    onehot = jax.nn.one_hot(eid, El, dtype=jnp.float32)  # -1 rows -> all-zero
    out_flat = jnp.einsum("te,ted->td", onehot, d_out)  # [ep*C, D]

    # all_to_all #2: outputs return to their home devices in slot order
    back = jax.lax.all_to_all(out_flat.reshape(ep, C, D), axis_name, 0, 0)

    # combine: out[t] = sum_j w[t, j] * back[dest[t, j], slot[t, j]]
    gathered = back[dest, slot]  # [Tl, k, D]
    return jnp.einsum("tk,tkd->td", top_vals, gathered)


class ExpertParallelMoE:
    """A single expert-parallel MoE FFN layer over a 1-D ``ep`` mesh.

    Holds the jitted shard_map'd exchange; expert banks shard over the
    expert axis (device d owns whole experts [d*E/ep, (d+1)*E/ep)), tokens
    shard over the same axis. The benchmark comparison point is the
    TP-sliced layout (models/moe.moe_ffn under a tp axis)."""

    def __init__(self, cfg: LlamaConfig, ep: int, devices=None):
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh, PartitionSpec as P

        from distributed_llama_tpu.parallel.tensor_parallel import shard_map

        if cfg.n_experts % ep:
            raise ValueError(f"ep={ep} must divide n_experts={cfg.n_experts}")
        if devices is None:
            devices = jax.devices()[:ep]
        self.cfg = cfg
        self.ep = ep
        self.mesh = Mesh(
            mesh_utils.create_device_mesh((ep,), devices=devices), ("ep",)
        )
        fn = functools.partial(ep_moe_ffn_local, cfg, ep, "ep")
        mapped = shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(
                P("ep", None),  # tokens
                P(),  # router replicated
                P("ep", None, None),  # gate bank
                P("ep", None, None),  # up bank
                P("ep", None, None),  # down bank
            ),
            out_specs=P("ep", None),
            check_vma=False,
        )
        self._jitted = jax.jit(mapped)

    def __call__(self, xn, router, gate, up, down):
        """xn: [T, D] (T divisible by ep); banks: [E, D, H] / [E, H, D].
        Returns [T, D] f32."""
        if xn.shape[0] % self.ep:
            raise ValueError(f"T={xn.shape[0]} must be divisible by ep={self.ep}")
        return self._jitted(xn, router, gate, up, down)
