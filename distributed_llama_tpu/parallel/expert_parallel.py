"""Expert parallelism: experts partitioned over an ``ep`` mesh axis with
``lax.all_to_all`` token routing — a first-class engine backend.

The production default MoE path TP-slices experts exactly like the reference
(every shard holds a 1/tp hidden-slice of ALL experts,
reference: src/transformer.cpp:335-353) — the right layout when E is small
and tokens are few (decode). TRUE expert parallelism is the named extension
beyond the reference (SURVEY.md §2 parallelism table): device d owns E/ep
WHOLE experts and tokens travel to their experts over ICI — the
dispatch/compute/combine exchange the reference's TCP star cannot express
(its MoE broadcasts every token to every node, src/grok1-tasks.cpp:121-202).

Two compute paths, chosen per batch shape inside one jitted program family:

* **Dispatch (prefill, T % ep == 0)** — the switch-transformer exchange with
  SORT-COMPACTED per-expert capacity buckets: each shard takes its T/ep
  token slice, ranks every (token, choice) pair within its target expert
  (a cumsum over the one-hot expert assignment), scatters rows into a
  ``[E, Ce, D]`` send buffer, and two ``all_to_all``s move rows to expert
  owners and outputs back. Each local expert computes ONE dense
  [ep·Ce, D] matmul — no masking in the hot compute, no Tl·k sparse slots
  (the round-4 prototype's layout). ``Ce`` follows
  ``cfg.moe_capacity_factor``: 0 (default) sizes buckets for the drop-free
  worst case (EXACT outputs); >0 uses the standard lossy capacity
  semantics (``ceil(factor·Tl·k/E)``, overflow drops) — opt-in via
  ``--moe-capacity``.
* **Dense-local (decode / tiny batches)** — every shard runs its El local
  experts on the (replicated) tokens, weights them with its slice of the
  router matrix, and a psum over ``ep`` combines. For T=1 this costs El
  expert-FFNs per shard in parallel — already ≤ the TP-sliced path's k
  sequential expert kernels when ep ≥ E/k — with zero all_to_alls on the
  decode critical path.

``ExpertParallelForward`` is the engine backend on a ``(tp, ep)`` mesh:
attention/dense weights shard over ``tp`` (replicated over ``ep``), expert
banks shard over BOTH (experts over ``ep``, hidden over ``tp``), the KV
cache shards over ``tp`` heads. Q40 expert banks stay 4-bit: per-expert
QuantizedMatrix leaves are stacked on a leading expert axis sharded over
``ep`` (note: on real TPU, slicing Pallas operands out of a stacked array
can make XLA hoist per-expert copies — acceptable here because EP>1 is a
multi-chip capability validated on the CPU mesh; single-chip serving uses
the TP-sliced path).

Validated against the dense MoE path on the virtual CPU mesh
(tests/test_expert_parallel.py), which also micro-benchmarks the exchange
against TP-sliced experts.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.models.config import LlamaConfig
from distributed_llama_tpu.parallel.tensor_parallel import TransferProbeMixin

def local_expert_weights(lp, e: int):
    """Weights of LOCAL expert ``e`` from EP layer params: stacked q40
    leaves (``experts_gate_up``/``experts_down`` QuantizedMatrix with a
    leading local-expert axis) or stacked bf16 banks."""
    from distributed_llama_tpu.ops.q40 import QuantizedMatrix

    if "experts_gate_up" in lp:
        gu, dn = lp["experts_gate_up"], lp["experts_down"]
        return {
            "gate_up": QuantizedMatrix(
                gu.qs[e], gu.scales[e], gu.n_logical, gu.d_logical
            ),
            "down": QuantizedMatrix(
                dn.qs[e], dn.scales[e], dn.n_logical, dn.d_logical
            ),
        }
    return {"gate": lp["moe_gate"][e], "up": lp["moe_up"][e], "down": lp["moe_down"][e]}


def _n_local_experts(cfg: LlamaConfig, lp) -> int:
    if "experts_gate_up" in lp:
        return lp["experts_gate_up"].qs.shape[0]
    return lp["moe_gate"].shape[0]


def ep_moe_ffn(
    cfg: LlamaConfig,
    xn: jax.Array,  # [T, D] normed tokens, REPLICATED across ep
    lp,
    ep_axis: str,
) -> jax.Array:
    """Expert-parallel MoE FFN inside shard_map: expert banks in ``lp`` hold
    only this shard's E/ep experts. Returns [T, D] f32, complete over the
    expert partition (all ep collectives happen here); still a hidden-slice
    partial under TP — the caller's psum over the tp axis applies on top."""
    T = xn.shape[0]
    ep = jax.lax.psum(1, ep_axis)
    if T % ep == 0 and T >= ep and T > 1:
        return _ep_dispatch(cfg, xn, lp, ep_axis, ep)
    return _ep_dense_local(cfg, xn, lp, ep_axis, ep)


def _ep_dense_local(cfg, xn, lp, ep_axis: str, ep: int) -> jax.Array:
    """Decode/tiny-batch path: each shard computes its El local experts on
    the replicated tokens, weighted by its slice of the [T, E] router
    weights; psum over ep combines the expert partition."""
    from distributed_llama_tpu.models.moe import _expert_ffn, router_weights

    El = _n_local_experts(cfg, lp)
    idx = jax.lax.axis_index(ep_axis)
    weights = router_weights(cfg, xn, lp["router"])  # [T, E] replicated
    w_local = jax.lax.dynamic_slice(
        weights, (0, idx * El), (xn.shape[0], El)
    )  # [T, El]
    out = jnp.zeros(xn.shape, jnp.float32)
    for e in range(El):
        out = out + w_local[:, e : e + 1] * _expert_ffn(
            cfg, xn, local_expert_weights(lp, e)
        )
    return jax.lax.psum(out, ep_axis)


def _ep_dispatch(cfg, xn, lp, ep_axis: str, ep: int) -> jax.Array:
    """Prefill path: sort-compacted capacity buckets + two all_to_alls
    (dispatch/combine) + one all_gather (token re-replication). Bucket
    algebra shared with the dense bucketed prefill (models.moe). Capacity
    follows cfg.moe_capacity_factor: 0 (default) = drop-free worst-case
    buckets (exact), >0 = standard capacity-drop semantics."""
    from distributed_llama_tpu.models.moe import (
        MOE_BUCKETED_MIN_T,
        _expert_ffn,
        bucket_capacity,
        bucket_combine,
        bucket_rank,
        bucket_scatter,
        router_topk,
    )

    T, D = xn.shape
    E = cfg.n_experts
    El = _n_local_experts(cfg, lp)
    k = cfg.n_active_experts
    Tl = T // ep
    idx = jax.lax.axis_index(ep_axis)
    # the dense path guards lossy capacity bucketing behind
    # MOE_BUCKETED_MIN_T; apply the same guard per shard — below it the
    # capacity estimate is noisy (drops bite hard at small Tl) and the
    # exchange is expert-HBM-bound anyway, so fall back to the drop-free
    # worst-case buckets (factor<=0 semantics: Ce = Tl, exact)
    factor = cfg.moe_capacity_factor if Tl >= MOE_BUCKETED_MIN_T else 0.0
    Ce = bucket_capacity(factor, Tl, k, E)

    x_local = jax.lax.dynamic_slice(xn, (idx * Tl, 0), (Tl, D))
    top_vals, top_idx = router_topk(cfg, x_local, lp["router"])  # [Tl, k]

    flat_e, rank, t_ids = bucket_rank(top_idx, E)
    send = bucket_scatter(x_local, flat_e, rank, t_ids, E, Ce)

    # all_to_all #1: rows travel to their expert's owner shard.
    # send viewed as [ep owners, El, Ce, D]; recv[s] = what shard s sent
    # for MY El experts
    recv = jax.lax.all_to_all(
        send.reshape(ep, El, Ce, D), ep_axis, split_axis=0, concat_axis=0
    )  # [ep, El, Ce, D]

    # local expert compute: ONE dense FFN per local expert over its
    # [ep*Ce, D] bucket — no masking, no one-hot in the hot loop
    outs = []
    for e in range(El):
        rows = recv[:, e].reshape(ep * Ce, D)
        outs.append(_expert_ffn(cfg, rows, local_expert_weights(lp, e)))  # f32
    out_banks = jnp.stack(outs)  # [El, ep*Ce, D]

    # all_to_all #2: outputs return to the rows' home shards in slot order
    back = jax.lax.all_to_all(
        out_banks.reshape(El, ep, Ce, D).transpose(1, 0, 2, 3),
        ep_axis, split_axis=0, concat_axis=0,
    )  # [ep, El, Ce, D] -> global expert order is (owner, local) = e_global
    back = back.reshape(E, Ce, D)

    # combine on the home shard: dropped choices contribute zero
    out_local = bucket_combine(back, top_idx, rank, top_vals, Ce)  # [Tl, D] f32

    # re-replicate the token axis for the (replicated) rest of the network
    return jax.lax.all_gather(out_local, ep_axis, axis=0, tiled=True)  # [T, D]


class ExpertParallelMoE:
    """A single expert-parallel MoE FFN layer over a 1-D ``ep`` mesh: the
    test/micro-benchmark harness around :func:`ep_moe_ffn` (the engine path
    is :class:`ExpertParallelForward`). Expert banks shard over the expert
    axis; tokens dispatch with the capacity-bucket all_to_all exchange
    (T % ep == 0) or fall back to dense-local compute."""

    def __init__(self, cfg: LlamaConfig, ep: int, devices=None):
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh, PartitionSpec as P

        from distributed_llama_tpu.parallel.tensor_parallel import shard_map

        if cfg.n_experts % ep:
            raise ValueError(f"ep={ep} must divide n_experts={cfg.n_experts}")
        if devices is None:
            devices = jax.devices()[:ep]
        self.cfg = cfg
        self.ep = ep
        self.mesh = Mesh(
            mesh_utils.create_device_mesh((ep,), devices=devices), ("ep",)
        )

        def body(xn, lp):
            return ep_moe_ffn(cfg, xn, lp, "ep")

        lp_specs = {
            "router": P(),
            "moe_gate": P("ep", None, None),
            "moe_up": P("ep", None, None),
            "moe_down": P("ep", None, None),
        }
        mapped = shard_map(
            body, mesh=self.mesh, in_specs=(P(), lp_specs), out_specs=P(),
            check_vma=False,
        )
        self._jitted = jax.jit(mapped)

    def __call__(self, xn, router, gate, up, down):
        """xn: [T, D]; banks: [E, D, H] / [E, H, D]. Returns [T, D] f32."""
        lp = {
            "router": jnp.asarray(router),
            "moe_gate": jnp.asarray(gate),
            "moe_up": jnp.asarray(up),
            "moe_down": jnp.asarray(down),
        }
        return self._jitted(jnp.asarray(xn), lp)


# ---------------------------------------------------------------------------
# Expert-parallel engine backend
# ---------------------------------------------------------------------------


def ep_param_specs(cfg: LlamaConfig, quantized: bool, shard_vocab: bool):
    """PartitionSpecs of the EP params layout on the ("tp", "ep") mesh:
    attention/dense weights follow the TP layout (replicated over ep),
    expert banks shard experts over ep AND hidden over tp. A rule-table
    lookup (parallel/sharding.py — one spec is a pytree prefix over a
    stacked QuantizedMatrix: qs [E, n2, d] + scales [E, ns, d] shard
    alike)."""
    from distributed_llama_tpu.parallel import sharding

    return sharding.param_specs(
        cfg,
        "ep_q40" if quantized else "ep",
        shard_vocab,
        {"model": "tp", "expert": "ep"},
    )


def stack_expert_leaves(host_params) -> Any:
    """Convert load_params' per-expert q40 list layout (``experts``:
    [{gate_up, down}, ...]) into the EP stacked layout
    (``experts_gate_up``/``experts_down`` QuantizedMatrix with a leading
    expert axis) — the form whose leading axis a PartitionSpec can shard
    over ``ep``. bf16 banks (moe_gate/up/down) are already stacked."""
    from distributed_llama_tpu.ops.q40 import QuantizedMatrix

    def stack(mats: list) -> QuantizedMatrix:
        return QuantizedMatrix(
            np.stack([np.asarray(m.qs) for m in mats]),
            np.stack([np.asarray(m.scales) for m in mats]),
            mats[0].n_logical,
            mats[0].d_logical,
        )

    out = dict(host_params)
    out["layers"] = []
    for lp in host_params["layers"]:
        lp = dict(lp)
        if "experts" in lp:
            experts = lp.pop("experts")
            lp["experts_gate_up"] = stack([e["gate_up"] for e in experts])
            lp["experts_down"] = stack([e["down"] for e in experts])
        out["layers"].append(lp)
    return out


class ExpertParallelForward(TransferProbeMixin):
    """Engine backend: expert parallelism over a ("tp", "ep") mesh.

    Duck-typed like TensorParallelForward/SequenceParallelForward (the
    engine's ``_tp_engine`` slot): shard_params / init_cache / forward /
    decode_loop / decode_chunk / measure_transfer_ms. Attention and dense
    weights shard over ``tp`` only; expert banks shard experts over ``ep``
    and hidden over ``tp``; the KV cache shards over ``tp`` heads and is
    replicated over ``ep`` (every shard runs the same attention — EP's
    memory win is the expert banks, which dominate a MoE model's bytes:
    Mixtral 8x7B is ~45/47 GB experts)."""

    def __init__(self, cfg: LlamaConfig, ep: int, tp: int = 1,
                 quantized: bool = False, devices=None):
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from distributed_llama_tpu.parallel.tensor_parallel import (
            shard_map,
            validate_tp,
        )

        if not cfg.is_moe:
            raise ValueError("--ep requires a mixture-of-experts model")
        if cfg.n_experts % ep:
            raise ValueError(f"ep={ep} must divide n_experts={cfg.n_experts}")
        if tp > 1:
            validate_tp(cfg, tp, quantized=quantized)
        self.cfg = cfg
        self.ep = ep
        self.tp = tp
        self.quantized = quantized
        n_dev = tp * ep
        if devices is None:
            devices = jax.devices()[:n_dev]
        if len(devices) < n_dev:
            raise ValueError(f"need {n_dev} devices (tp*ep), have {len(devices)}")
        self.mesh = Mesh(
            mesh_utils.create_device_mesh((tp, ep), devices=devices[:n_dev]),
            ("tp", "ep"),
        )
        self._P = P
        self._NamedSharding = NamedSharding
        self._shard_map = shard_map
        self.shard_vocab = tp > 1 and cfg.vocab_size % tp == 0
        self._tp_axis = "tp" if tp > 1 else None
        self._specs = ep_param_specs(cfg, quantized, self.shard_vocab)
        cache_ax = P(None, "tp", None) if tp > 1 else P(None, None, None)
        self._cache_spec = [cache_ax] * cfg.n_layers
        self._decode_cache: dict = {}

        step = shard_map(
            functools.partial(_ep_forward, cfg, self._tp_axis),
            mesh=self.mesh,
            in_specs=(self._specs, P(), self._cache_spec, P()),
            out_specs=(P(), self._cache_spec),
            check_vma=False,
        )
        self._jitted = jax.jit(step, donate_argnums=(2,))

    # -- engine interface ---------------------------------------------------

    def shard_params(self, host_params):
        from distributed_llama_tpu.parallel.tensor_parallel import place_params

        if self.quantized:
            host_params = stack_expert_leaves(host_params)
        return place_params(host_params, self._specs, self.mesh)

    def init_cache(self, dtype=jnp.float32):
        from distributed_llama_tpu.ops import kv_cache as kvc

        cfg = self.cfg
        shape = (cfg.seq_len, cfg.n_kv_heads, cfg.head_size)
        sharding = self._NamedSharding(self.mesh, self._cache_spec[0])

        def zeros(gshape, dt):
            local = np.zeros((gshape[0], gshape[1] // self.tp) + gshape[2:], dt)
            return jax.make_array_from_callback(gshape, sharding, lambda idx: local)

        return [
            (kvc.init_half(shape, dtype, zeros=zeros),
             kvc.init_half(shape, dtype, zeros=zeros))
            for _ in range(cfg.n_layers)
        ]

    def forward(self, params, tokens, cache, pos):
        return self._jitted(params, jnp.asarray(tokens), cache, jnp.asarray(pos))

    def decode_loop(
        self, params, first_token, cache, pos, n_steps, temperature, topp,
        seed: int = 0, topk: int = 0,
    ):
        from distributed_llama_tpu import prng

        tokens, cache = self._decode_scan(
            int(n_steps), float(temperature), float(topp), int(topk)
        )(
            params, jnp.asarray(first_token), cache, jnp.asarray(pos),
            jnp.uint32(prng.fold_seed(seed)),
        )
        return tokens, cache

    def decode_chunk(
        self, params, first_token, cache, pos, n_steps, temperature, topp,
        topk, seed32,
    ):
        jitted = self._decode_scan(int(n_steps), None, None, None)
        return jitted(
            params, jnp.asarray(first_token), cache, jnp.asarray(pos),
            jnp.float32(temperature), jnp.float32(topp), jnp.int32(topk),
            jnp.asarray(seed32, jnp.uint32),
        )

    def _decode_scan(self, n_steps: int, temperature, topp, topk):
        from distributed_llama_tpu.models import sampling

        P = self._P
        key_ = (n_steps, temperature, topp, topk)
        cached = self._decode_cache.get(key_)
        if cached is not None:
            return cached
        cfg = self.cfg
        tp_axis = self._tp_axis

        def scan_body(params, first_token, cache, pos, seed, t, p, k_top):
            def step(carry, _):
                token, cache_c, pp = carry
                logits, cache_c = _ep_forward(cfg, tp_axis, params, token[None], cache_c, pp)
                nxt = sampling.sample_token(logits[0], seed, pp, t, p, k_top)
                return (nxt, cache_c, pp + 1), nxt

            (_, cache, _), tokens = jax.lax.scan(
                step, (first_token.astype(jnp.int32), cache, pos.astype(jnp.int32)),
                None, length=n_steps,
            )
            return tokens, cache

        if temperature is None:

            def fn(params, first_token, cache, pos, t_in, p_in, k_in, seed):
                return scan_body(
                    params, first_token, cache, pos, seed, t_in, p_in, k_in
                )

            in_specs = (self._specs, P(), self._cache_spec, P(), P(), P(), P(), P())
        else:

            def fn(params, first_token, cache, pos, seed):
                return scan_body(
                    params, first_token, cache, pos, seed, temperature, topp,
                    topk,
                )

            in_specs = (self._specs, P(), self._cache_spec, P(), P())
        mapped = self._shard_map(
            fn, mesh=self.mesh, in_specs=in_specs,
            out_specs=(P(), self._cache_spec), check_vma=False,
        )
        jitted = jax.jit(mapped, donate_argnums=(2,))
        self._decode_cache[key_] = jitted
        return jitted

    def transfer_probe(self, n_tokens: int = 32):
        """Replay of the EP decode's per-layer collective sequence: one
        ep-psum of the [1, dim] expert-partition partial (plus the two tp
        all-reduces and the vocab all-gather when composed with TP).
        Keep-alive arithmetic prevents XLA DCE (see TransferProbeMixin)."""
        cfg = self.cfg
        tp_axis = self._tp_axis
        P = self._P

        def token_step(carry, _):
            x, z = carry

            def layer(c, _):
                xx, zz = c
                xx = jax.lax.psum(xx, "ep") * 0.5
                if tp_axis is not None:
                    zz = jax.lax.psum(zz, tp_axis) * 0.5
                    zz = jax.lax.psum(zz, tp_axis) * 0.5
                return (xx, zz), None

            (x, z), _ = jax.lax.scan(layer, (x, z), None, length=cfg.n_layers)
            return (x, z), None

        def fn(x, z):
            (x, z), _ = jax.lax.scan(token_step, (x, z), None, length=n_tokens)
            return x, z

        mapped = self._shard_map(
            fn, mesh=self.mesh, in_specs=(P(), P()), out_specs=(P(), P()),
            check_vma=False,
        )
        x = jnp.ones((1, cfg.dim), jnp.float32)
        z = jnp.ones((1, cfg.dim), jnp.float32)
        return jax.jit(mapped), (x, z)

    def transfer_bytes_per_token(self) -> int:
        """The probed EP decode sequence per layer: one ep-psum of the
        [1, dim] expert-partition partial, plus the two [1, dim] tp
        all-reduces when composed with TP (see :meth:`transfer_probe`)."""
        per_layer = self.cfg.dim * 4
        if self._tp_axis is not None:
            per_layer += 2 * self.cfg.dim * 4
        return self.cfg.n_layers * per_layer


def _ep_forward(cfg, tp_axis, params, tokens, cache, pos):
    """Per-shard forward body on the (tp, ep) mesh: the shared llama wiring
    with ep_axis="ep" threading expert banks through the EP exchange."""
    from distributed_llama_tpu.models import llama

    logits, new_cache = llama.forward_tokens(
        cfg, params, tokens, cache, pos, axis_name=tp_axis, ep_axis="ep"
    )
    if tp_axis is not None and logits.shape[-1] != cfg.vocab_size:
        logits = jax.lax.all_gather(logits, tp_axis, axis=1, tiled=True)
    return logits, new_cache
