"""One-process pod serving: replicas as slices of a ('data', 'model') mesh.

The reference scales by running 2^n OS processes that each hold a full
1/n weight slice and talk over TCP; our ReplicaPool (PRs 9-11)
reproduced that shape as N independent engines — N full weight copies in
HBM, batch scaling capped at process boundaries. This module is ROADMAP
item 3's alternative shape: ONE process, ONE named mesh

    ('data', 'model')  =  (replica slices, tensor-parallel shards)

with tensor parallelism riding the ``'model'`` axis inside every slice,
and the weights placed ONCE — resolved through the declarative rule
table (parallel/sharding.py) with the ``'data'`` axis never appearing in
a weight rule, so a pod serves N replicas from one params tree instead
of materializing N copies. Scale batch by widening ``'data'``, scale
model size by widening ``'model'``.

What stays exactly the same is the serving contract on top: each data
slice IS a replica — a :class:`~distributed_llama_tpu.engine.batch.
BatchScheduler` + serving lanes behind the ReplicaPool front door, with
the PR 9/10 health ladder, placement, failover-replay and
restart-supervision semantics untouched. A mesh-slice failure is a
replica loss: its in-flight requests requeue through fair admission and
replay bit-identically on surviving slices, and the supervisor rebuilds
the slice — WITHOUT reloading weights, because the pod's params tree is
shared (a rebuild is a new scheduler + lanes over the same arrays, and
the PR 10 rebuild checksum gate verifies the same bytes trivially).

Compute model: every slice's programs are the proven TP program family
(TensorParallelForward), shard_map'd over the FULL pod mesh with the
``'model'`` axis doing the work and ``'data'`` as a replication axis —
slices share ONE compiled batched-decode program (the jit caches live on
the shared backend), and greedy streams are bit-identical to the
N-independent-engines pool at the same model degree (the per-shard
programs and collective groups are the same). The honest cost under CPU
mesh mocks: a slice's dispatch occupies all data rows (replicated
compute); the N-process pool stacked all replicas on the same devices
too, so at matched lanes the aggregate is no worse (BENCH_POD_r08.json)
— on real hardware the follow-up is data-sharded slabs per dispatch.

Everything runs under ``JAX_PLATFORMS=cpu`` +
``--xla_force_host_platform_device_count`` mesh mocks, the way PR 7's TP
pool does — including on container JAX (0.4.x) via the
:func:`compat_shard_map` signature shim.
"""

from __future__ import annotations

import inspect
import re
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from distributed_llama_tpu.models.config import LlamaConfig
from distributed_llama_tpu.parallel import sharding
from distributed_llama_tpu.parallel.tensor_parallel import (
    TensorParallelForward,
    shard_map,
)

DATA_AXIS = "data"
MODEL_AXIS = "model"

_SHARD_MAP_PARAMS = None


def compat_shard_map(fn, mesh, in_specs, out_specs, check_vma: bool = False, **kw):
    """``shard_map`` across jax versions: newer jax names the replication
    check ``check_vma``, 0.4.x names it ``check_rep``. The legacy 1-D
    backends keep calling ``check_vma`` directly (their env failures are
    a pinned baseline); the pod routes through this shim so one-process
    pod serving runs on both."""
    global _SHARD_MAP_PARAMS
    if _SHARD_MAP_PARAMS is None:
        _SHARD_MAP_PARAMS = frozenset(inspect.signature(shard_map).parameters)
    if "check_vma" in _SHARD_MAP_PARAMS:
        kw["check_vma"] = check_vma
    elif "check_rep" in _SHARD_MAP_PARAMS:
        kw["check_rep"] = check_vma
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def parse_pod(spec: str) -> tuple[int, int]:
    """``--pod DATAxMODEL`` (e.g. ``2x2``) -> (data, model)."""
    m = re.fullmatch(r"(\d+)\s*[xX*]\s*(\d+)", str(spec).strip())
    if not m:
        raise ValueError(
            f"--pod wants DATAxMODEL (e.g. 2x2), got {spec!r}"
        )
    data, model = int(m.group(1)), int(m.group(2))
    if data < 1 or model < 1:
        raise ValueError(f"--pod axes must be >= 1, got {data}x{model}")
    return data, model


def pod_mesh(data: int, model: int, devices=None) -> Mesh:
    """The single named pod mesh. Slices are its rows: replica i owns
    ``mesh.devices[i, :]`` conceptually — programs are SPMD over the
    whole mesh with weights/compute invariant along ``'data'``."""
    n = data * model
    if devices is None:
        devices = jax.devices()
    if len(devices) < n:
        raise ValueError(
            f"pod {data}x{model} needs {n} devices, have {len(devices)} "
            "(CPU mocks: set --xla_force_host_platform_device_count)"
        )
    grid = mesh_utils.create_device_mesh((data, model), devices=devices[:n])
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


class PodForward(TensorParallelForward):
    """The TP program family on the pod mesh: tensor parallelism over
    ``'model'``, every spec resolved through the rule table with
    ``{"model": "model"}`` — the ``'data'`` axis never appears in a
    weight or cache rule, so arrays replicate over it and one instance
    (shared by every slice's engine) serves the whole pod with one
    compiled program per shape."""

    _shard_map = staticmethod(compat_shard_map)

    def __init__(
        self,
        cfg: LlamaConfig,
        data: int,
        model: int,
        devices=None,
        quantized: bool = False,
    ):
        self.data = data
        # flips on at init_batch_cache when the lane count divides 'data':
        # the slab's BATCH axis then shards across data rows, so one
        # slice's chunk dispatch does B rows of work total on the whole
        # mesh (matched with the N-engine baseline) instead of B rows
        # replicated per data row (data x the FLOPs)
        self._slab_data_sharded = False
        self._slab_rows: int | None = None
        super().__init__(
            cfg,
            model,
            quantized=quantized,
            layered=True,
            axis=MODEL_AXIS,
            mesh=pod_mesh(data, model, devices=devices),
        )

    # ------------------------------------------------------------------
    # Data-sharded slab: the batched-decode hot path parallelizes its
    # rows over 'data'; single-row ops (prefill take/put, page publish)
    # gather/scatter the owning shard's row with exact masked psums
    # (zeros elsewhere — bit-identical to the local op).
    # ------------------------------------------------------------------

    def init_batch_cache(self, b_max: int, dtype=jnp.float32):
        from jax.sharding import PartitionSpec as P

        sharded = self.data > 1 and b_max % self.data == 0
        if self._slab_rows is not None and (
            b_max != self._slab_rows or sharded != self._slab_data_sharded
        ):
            # every slice scheduler shares this backend's compiled
            # programs; a second slab layout would silently recompile
            # against the wrong specs
            raise ValueError(
                f"pod slab layout is fixed at first use: {self._slab_rows} "
                f"rows (data-sharded={self._slab_data_sharded}), got {b_max}"
            )
        if self._slab_rows is None:
            self._slab_rows = b_max
            if sharded:
                self._slab_data_sharded = True
                self._slab_spec = P(DATA_AXIS, None, MODEL_AXIS, None)
                self._vec_spec = P(DATA_AXIS)
                self._table_spec = P(DATA_AXIS, None)
                self._tok_out_spec = P(None, DATA_AXIS)
                # sub-buckets would straddle shards: dispatch the whole slab
                self.decode_bucket_floor = b_max
            elif self.data > 1:
                print(
                    f"⚠️ pod slab stays data-replicated: {b_max} lanes per "
                    f"slice do not divide data={self.data} (decode costs "
                    f"{self.data}x the FLOPs; pick --parallel divisible by "
                    "the data extent)"
                )
        return super().init_batch_cache(b_max, dtype)

    def _local_slab_shape(self, gshape: tuple) -> tuple:
        out = super()._local_slab_shape(gshape)
        if self._slab_data_sharded:
            out = (out[0] // self.data,) + out[1:]
        return out

    def _slab_row_take(self, half, row):
        """Global slab row -> a REPLICATED single-row cache half: the
        owning data shard contributes its row, everyone else exact zeros,
        one psum broadcasts it (int8 rides an int32 psum)."""
        if not self._slab_data_sharded:
            return super()._slab_row_take(half, row)
        from distributed_llama_tpu.ops import kv_cache as kvc

        Bl = half.shape[0]  # local batch rows inside shard_map
        idx = jax.lax.axis_index(DATA_AXIS)
        local = row - idx * Bl
        owned = (local >= 0) & (local < Bl)
        piece = kvc.slab_take_row(half, jnp.clip(local, 0, Bl - 1))
        if isinstance(piece, kvc.QuantizedKV):
            di = jnp.where(owned, piece.data.astype(jnp.int32), 0)
            sc = jnp.where(owned, piece.scales, jnp.zeros_like(piece.scales))
            return kvc.QuantizedKV(
                jax.lax.psum(di, DATA_AXIS).astype(piece.data.dtype),
                jax.lax.psum(sc, DATA_AXIS),
            )
        z = jnp.where(owned, piece, jnp.zeros_like(piece))
        return jax.lax.psum(z, DATA_AXIS)

    def _slab_row_put(self, half, new_row, row):
        """Write a (replicated) row half back: only the owning data shard
        keeps the update; the rest keep their rows byte-identical."""
        if not self._slab_data_sharded:
            return super()._slab_row_put(half, new_row, row)
        from distributed_llama_tpu.ops import kv_cache as kvc

        Bl = half.shape[0]
        idx = jax.lax.axis_index(DATA_AXIS)
        local = row - idx * Bl
        owned = (local >= 0) & (local < Bl)
        upd = kvc.slab_put_row(half, new_row, jnp.clip(local, 0, Bl - 1))
        if isinstance(half, kvc.QuantizedKV):
            return kvc.QuantizedKV(
                jnp.where(owned, upd.data, half.data),
                jnp.where(owned, upd.scales, half.scales),
            )
        return jnp.where(owned, upd, half)

    def _slab_publish(self, pool_half, slab_half, row, src_page, page_ids):
        """Publish a data-sharded slab row's pages into the (replicated)
        pool: gather the row once, then the ordinary local publish."""
        if not self._slab_data_sharded:
            return super()._slab_publish(
                pool_half, slab_half, row, src_page, page_ids
            )
        from distributed_llama_tpu.ops import kv_cache as kvc

        row_half = self._slab_row_take(slab_half, row)
        if isinstance(row_half, kvc.QuantizedKV):
            one = kvc.QuantizedKV(row_half.data[None], row_half.scales[None])
        else:
            one = row_half[None]
        return kvc.publish_row_pages(
            pool_half, one, 0, src_page, page_ids, pool_half.shape[1]
        )


def max_device_weight_bytes(params_trees) -> int:
    """MEASURED weight bytes on the most-loaded device across one or
    more placed params trees: walks every leaf's addressable shards and
    sums per device. This is the number the bench's memory gate reads —
    for the N-engine pool it shows N stacked copies on the shared model
    group's devices; for the pod, one model-sharded copy per data row —
    so a broken rule table (silent replication) shows up as REAL bytes,
    not as an attribution formula."""
    per_device: dict = {}
    for params in params_trees:
        for _, leaf in sharding.leaf_paths(params):
            arrays = (
                (leaf.qs, leaf.scales) if hasattr(leaf, "qs") else (leaf,)
            )
            for arr in arrays:
                shards = getattr(arr, "addressable_shards", None)
                if not shards:
                    continue
                for sh in shards:
                    d = sh.device
                    per_device[d] = per_device.get(d, 0) + int(sh.data.nbytes)
    return max(per_device.values(), default=0)


def tree_weight_bytes(params) -> int:
    """Logical resident bytes of a params tree (QuantizedMatrix counts
    its packed qs + scales). For a pod tree this is the bytes of the ONE
    shared copy; an N-engine pool holds N trees of this size."""
    total = 0
    for _, leaf in sharding.leaf_paths(params):
        qs = getattr(leaf, "qs", None)
        if qs is not None:
            total += int(qs.nbytes) + int(leaf.scales.nbytes)
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total


class PodGroup:
    """One pod's shared substrate: the mesh, the backend, and the ONE
    placed params tree — plus the engine factory the serving layer's
    replica builds (and REBUILDS, after a slice death) draw slices from.

    Every engine this hands out shares ``backend`` (so compiled programs
    are built once for the whole pod) and ``params`` (so weights are
    resident once per model group). Per-slice state — slab, page pool,
    KV caches, scheduler, lanes — stays per engine, which is exactly the
    failure domain the ReplicaPool supervises."""

    def __init__(
        self,
        cfg: LlamaConfig,
        backend: PodForward,
        params: Any,
        cache_dtype=jnp.bfloat16,
        spec=None,
        dtype=jnp.bfloat16,
    ):
        self.cfg = cfg
        self.backend = backend
        self.params = params
        self.cache_dtype = cache_dtype
        self.spec = spec
        self.dtype = dtype  # the load dtype, so sibling() loads alike
        self.data = backend.data
        self.model = backend.tp
        self.weight_bytes = tree_weight_bytes(params)
        self._note_telemetry()

    # ------------------------------------------------------------------

    @classmethod
    def build(
        cls,
        model_path: str,
        data: int,
        model: int,
        dtype=jnp.bfloat16,
        max_seq_len: int | None = None,
        cache_dtype=None,
        devices=None,
        **cfg_overrides,
    ) -> "PodGroup":
        """Load the model ONCE and place it on the pod mesh through the
        rule table. The file is read per-shard exactly like the classic
        tp load (O(model/tp) matrix traffic), then placed by
        ``backend.shard_params`` — one tree for every slice, vs the
        N-engine pool's N loads + N trees."""
        from distributed_llama_tpu.engine import weights as weights_lib
        from distributed_llama_tpu.formats.model_file import ModelFileReader
        from distributed_llama_tpu.models.config import config_from_spec

        quantized = dtype == weights_lib.QUANTIZED_DTYPE
        reader = ModelFileReader(model_path)
        spec = reader.spec.clamp_seq_len(max_seq_len)
        cfg = config_from_spec(spec, **cfg_overrides)
        if cache_dtype is None:
            cache_dtype = jnp.bfloat16 if quantized else dtype
        backend = PodForward(cfg, data, model, devices=devices, quantized=quantized)
        host_params = weights_lib.load_params(
            reader, cfg, dtype=dtype, tp=model, mesh=None
        )
        reader.close()
        params = backend.shard_params(host_params)
        return cls(
            cfg, backend, params, cache_dtype=cache_dtype, spec=spec,
            dtype=dtype,
        )

    def sibling(self, model_path: str) -> "PodGroup":
        """A SECOND PodGroup over the SAME mesh/backend with a different
        weight file placed as a second params tree — the pod's blue-green
        rollout shape (ISSUE 18): slice engines cut over tree-by-tree via
        :meth:`slice_engine` on the sibling, compiled programs are reused
        (same backend, same shapes), and the OLD tree is released by
        dropping the old group when the last slice moves (the serving
        layer pops the old version's factory; JAX frees the placed
        arrays with it). The new file must match the serving config —
        same architecture, new weights."""
        from distributed_llama_tpu.engine import weights as weights_lib
        from distributed_llama_tpu.formats.model_file import ModelFileReader

        reader = ModelFileReader(model_path)
        host_params = weights_lib.load_params(
            reader, self.cfg, dtype=self.dtype, tp=self.model, mesh=None
        )
        reader.close()
        params = self.backend.shard_params(host_params)
        return PodGroup(
            self.cfg, self.backend, params,
            cache_dtype=self.cache_dtype, spec=self.spec,
            dtype=self.dtype,
        )

    def slice_engine(self):
        """A fresh slice engine over the shared backend + params: what a
        ReplicaPool replica build (or post-failure REBUILD) costs under
        the pod — scheduler + lanes + caches, never a weight reload."""
        from distributed_llama_tpu.engine.engine import InferenceEngine

        return InferenceEngine.from_shared(
            self.cfg,
            self.backend,
            self.params,
            cache_dtype=self.cache_dtype,
            spec=self.spec,
        )

    # engine_factory surface for ApiState (a zero-arg callable)
    def __call__(self):
        return self.slice_engine()

    # ------------------------------------------------------------------

    def resident_weight_bytes_per_replica(self) -> int:
        """The pod's headline memory accounting: the one shared tree's
        bytes attributed across its ``data`` slices. The N-engine pool's
        equivalent figure is the full tree PER replica (docs/PERF.md
        "One-process pod serving: weight memory")."""
        return self.weight_bytes // max(1, self.data)

    def _note_telemetry(self) -> None:
        from distributed_llama_tpu import telemetry

        tel = telemetry.MeshInstruments()
        if tel.enabled:
            tel.mesh_devices.labels(axis=DATA_AXIS).set(self.data)
            tel.mesh_devices.labels(axis=MODEL_AXIS).set(self.model)
            tel.resident_weight_bytes.labels(group="pod").set(self.weight_bytes)
            tel.resident_weight_bytes.labels(group="per_replica").set(
                self.resident_weight_bytes_per_replica()
            )
