"""Counter-mode PRNG for device-resident sampling (ISSUE 13).

The decode scan samples ON DEVICE; the coin for the token drawn after
consuming stream position ``p`` is a pure function of
``(request seed, p, draw channel)`` — no generator state exists anywhere.
That statelessness is the whole contract:

* **Replay** — PR 9's failover replay and PR 8's preemption requeue re-run
  a request from its prompt on another replica; positions are defined by
  token content (prompt length + decode index), so the replayed stream
  draws the exact coins of the original without any sampler state crossing
  replicas. The jax.random split-chain this replaces carried an advanced
  key per row per chunk — device-resident state the scheduler had to
  thread through every dispatch and that could never migrate.
* **Chunk independence** — a stream's draws depend only on positions,
  never on how the decode was chunked into dispatches (the old key-thread
  gave the same guarantee by carrying state; this gives it by having
  none).
* **Host parity** — the generator is pure uint32 arithmetic (xorshift/
  multiply avalanche rounds, counter mode), implemented twice: in jnp for
  the fused device sampler and in plain Python ints for the host
  ``Sampler``'s counter mode. Integer ops are bit-identical by
  construction, so a host replay of a device stream consumes the same
  coins — the xorshift-parity verification mode the reference's seeded
  runs had (src/utils.cpp:79-90), now spanning the host/device boundary.

The mixer is the 32-bit xorshift-multiply avalanche (two
shift-xor/multiply rounds — "lowbias32"-class): full avalanche on every
input bit, 5 integer ops per round, trivially vectorizable. Not
cryptographic, and not meant to be: sampling needs decorrelated uniforms,
replay needs determinism.

Draw channels keep the independent draws a single position can need from
colliding: the plain categorical coin, the speculative accept coin, and
the speculative redraw coin (Leviathan rejection re-draws at the same
position its accept coin was spent on).
"""

from __future__ import annotations

import numpy as np

_M32 = 0xFFFFFFFF
_GOLD = 0x9E3779B9  # 2**32 / phi — the standard odd increment
_MIX1 = 0x7FEB352D
_MIX2 = 0x846CA68B
_SALT = 0x85EBCA6B

# draw channels (the third counter word): one position can legitimately
# consume several independent uniforms
DRAW_SAMPLE = 0  # the categorical coin of the fused sampler
DRAW_SPEC_ACCEPT = 1  # speculative accept/reject coin at a draft position
DRAW_SPEC_REDRAW = 2  # speculative residual/bonus redraw coin

# 2**-24: coins are the top 24 bits of the mixed word — exactly
# representable in f32, so host and device land on the identical float
_INV24 = 1.0 / 16777216.0


# ----------------------------------------------------------------------
# Host side: plain Python ints (exact, no numpy overflow semantics)
# ----------------------------------------------------------------------


def mix32(x: int) -> int:
    """One 32-bit xorshift-multiply avalanche (shift-xor, multiply, twice
    over): every output bit depends on every input bit."""
    x &= _M32
    x ^= x >> 16
    x = (x * _MIX1) & _M32
    x ^= x >> 15
    x = (x * _MIX2) & _M32
    x ^= x >> 16
    return x


def fold_seed(seed: int) -> int:
    """Fold an arbitrary-width request seed into the uint32 word the
    counter is keyed on (seeds below 2**32 stay distinct; the high word is
    avalanched in, not dropped). Host-side only — the device receives the
    folded word, never the raw seed."""
    s = int(seed) & 0xFFFFFFFFFFFFFFFF
    return mix32((s & _M32) ^ mix32(((s >> 32) & _M32) ^ _GOLD))


def coin_u32(seed32: int, pos: int, draw: int = DRAW_SAMPLE) -> int:
    """The counter word for ``(seed32, pos, draw)`` — double-avalanched so
    adjacent positions/draws decorrelate."""
    return mix32(
        (seed32 & _M32)
        ^ mix32(((int(pos) * _GOLD) & _M32) ^ ((int(draw) * _SALT) & _M32))
    )


def coin_f32(seed32: int, pos: int, draw: int = DRAW_SAMPLE) -> np.float32:
    """Uniform f32 in [0, 1): the top 24 mixed bits scaled by 2**-24 —
    every value exact in f32, bit-identical to :func:`device_coin`."""
    return np.float32((coin_u32(seed32, pos, draw) >> 8) * _INV24)


# ----------------------------------------------------------------------
# Device side: the same arithmetic on jnp.uint32 (wrapping by dtype)
# ----------------------------------------------------------------------


def device_mix32(x):
    """:func:`mix32` on jnp uint32 arrays (elementwise)."""
    import jax.numpy as jnp

    x = x.astype(jnp.uint32)
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(_MIX1)
    x = x ^ (x >> jnp.uint32(15))
    x = x * jnp.uint32(_MIX2)
    x = x ^ (x >> jnp.uint32(16))
    return x


def device_coin_u32(seeds, pos, draw: int = DRAW_SAMPLE):
    """:func:`coin_u32` on device: ``seeds`` uint32 [...], ``pos`` int32
    [...] (broadcast together), ``draw`` a static int channel."""
    import jax.numpy as jnp

    seeds = jnp.asarray(seeds).astype(jnp.uint32)
    p = jnp.asarray(pos).astype(jnp.uint32) * jnp.uint32(_GOLD)
    d = jnp.uint32((draw * _SALT) & _M32)
    return device_mix32(seeds ^ device_mix32(p ^ d))


def device_coin(seeds, pos, draw: int = DRAW_SAMPLE):
    """Uniform f32 coins in [0, 1) on device — bit-identical to
    :func:`coin_f32` for the same counter (the top-24-bit construction is
    exact in f32 on both sides)."""
    import jax.numpy as jnp

    u = device_coin_u32(seeds, pos, draw) >> jnp.uint32(8)
    return u.astype(jnp.float32) * jnp.float32(_INV24)
