"""Quantized (int8) KV cache: the TPU-native answer to the reference's
disc-backed KV storage.

The reference offloads its KV cache to disc files to run contexts larger
than RAM (reference: src/utils.cpp:50-67, src/transformer.cpp:312-318,
``--kv-cache-storage disc`` at src/app.cpp:105-106). On TPU the cache lives
in HBM and a disc round trip per token is not a design point — the
TPU-native lever for the same capability (longer contexts in the same
memory) is a narrower cache dtype: int8 rows with per-(slot, head) f32
scales halve the cache bytes vs bf16 (scales add hd/4 overhead, ~3% at
hd=128) AND halve the attention HBM read stream, which is the
second-largest bandwidth consumer after the weights.

Layout: each cache half is a :class:`QuantizedKV` pytree of
``data`` int8 [S, K, hd] and ``scales`` f32 [S, K, 1]. The scales keep a
trailing unit axis ON PURPOSE: both leaves are rank-3 and shard identically
on (sequence, kv-head) axes, so every existing cache PartitionSpec —
``P(None, "tp", None)`` under tensor parallelism, ``P("sp", "tp", None)``
under sequence parallelism — applies to a QuantizedKV as a pytree prefix
with no spec surgery anywhere.

Dequantization never materializes: the score einsum runs on int8 data cast
to bf16 in-register (int8 magnitudes are exact in bf16) and the per-slot
scale folds into the score afterwards; the value einsum folds the scale
into the softmax weights BEFORE the mix, so the cache bytes crossing HBM
stay int8 in both reads.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

I8_SENTINELS = ("i8", "int8")


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedKV:
    """One cache half (keys or values): int8 rows + per-(slot, head) scales.

    Also the container of a FUSED per-layer cache (keys and values stacked
    on a leading 2-axis, see the fused-layout note below): indexing slices
    both leaves, so ``fused[0]``/``fused[1]`` are the (keys, values) halves
    exactly like a ``(keys, values)`` tuple's elements."""

    data: jax.Array  # int8 [S, K, hd]
    scales: jax.Array  # f32 [S, K, 1]

    @property
    def shape(self):  # mirror the raw-array cache half
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def __getitem__(self, idx):
        return QuantizedKV(self.data[idx], self.scales[idx])

    def __iter__(self):  # unpack a fused leaf like a (keys, values) tuple
        return iter((self[0], self[1]))

    def tree_flatten(self):
        return (self.data, self.scales), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def is_quantized_cache_dtype(dtype) -> bool:
    return isinstance(dtype, str) and dtype in I8_SENTINELS


def init_half(shape, dtype, zeros=jnp.zeros):
    """One cache half of [S, K, hd]: a plain array, or a QuantizedKV when
    ``dtype`` is the "i8" sentinel. ``zeros`` is injectable so sharded
    builders (make_array_from_callback closures) reuse the same layout."""
    if is_quantized_cache_dtype(dtype):
        return QuantizedKV(
            zeros(shape, jnp.int8), zeros(shape[:-1] + (1,), jnp.float32)
        )
    return zeros(shape, dtype)


def quantize_rows(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[T, K, hd] f32/bf16 -> (int8 [T, K, hd], f32 scales [T, K, 1]),
    symmetric per-(row, head): scale = max|x| / 127."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scales = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scales), -127, 127).astype(jnp.int8)
    return q, scales


def update_rows(half, rows: jax.Array, pos) -> "QuantizedKV | jax.Array":
    """Write ``rows`` [T, K, hd] at slots pos..pos+T-1 (the dense/TP decode
    and prefill write). Quantizes on the fly for an i8 half; aliases in
    place per leaf either way."""
    if isinstance(half, QuantizedKV):
        q, s = quantize_rows(rows)
        return QuantizedKV(
            jax.lax.dynamic_update_slice(half.data, q, (pos, 0, 0)),
            jax.lax.dynamic_update_slice(half.scales, s, (pos, 0, 0)),
        )
    return jax.lax.dynamic_update_slice(half, rows.astype(half.dtype), (pos, 0, 0))


def scatter_rows(half, slot: jax.Array, rows: jax.Array):
    """Masked scatter of ``rows`` [T, K, hd] at per-row slot indices (the
    sequence-parallel chunk write): out-of-bounds slots drop."""
    if isinstance(half, QuantizedKV):
        q, s = quantize_rows(rows)
        return QuantizedKV(
            half.data.at[slot].set(q, mode="drop"),
            half.scales.at[slot].set(s, mode="drop"),
        )
    return half.at[slot].set(rows.astype(half.dtype), mode="drop")


def select_row_update(half, row: jax.Array, lpos, owner):
    """Owner-masked single-row write (the sequence-parallel decode step):
    every shard writes at ``lpos``; non-owners re-write the row they already
    had. ``row``: [1, K, hd]."""
    if isinstance(half, QuantizedKV):
        q, s = quantize_rows(row)
        old_q = jax.lax.dynamic_slice(half.data, (lpos, 0, 0), q.shape)
        old_s = jax.lax.dynamic_slice(half.scales, (lpos, 0, 0), s.shape)
        return QuantizedKV(
            jax.lax.dynamic_update_slice(
                half.data, jnp.where(owner, q, old_q), (lpos, 0, 0)
            ),
            jax.lax.dynamic_update_slice(
                half.scales, jnp.where(owner, s, old_s), (lpos, 0, 0)
            ),
        )
    K, hd = half.shape[1], half.shape[2]
    old = jax.lax.dynamic_slice(half, (lpos, 0, 0), (1, K, hd))
    return jax.lax.dynamic_update_slice(
        half, jnp.where(owner, row.astype(half.dtype), old), (lpos, 0, 0)
    )


def slice_rows(half, start, n: int):
    """Read ``n`` cache slots [start, start+n) (the blocked-attention chunk
    read). ``start`` may be traced; ``n`` is static."""
    if isinstance(half, QuantizedKV):
        S, K, hd = half.data.shape
        return QuantizedKV(
            jax.lax.dynamic_slice(half.data, (start, 0, 0), (n, K, hd)),
            jax.lax.dynamic_slice(half.scales, (start, 0, 0), (n, K, 1)),
        )
    S, K, hd = half.shape
    return jax.lax.dynamic_slice(half, (start, 0, 0), (n, K, hd))


# ---------------------------------------------------------------------------
# Batched slab cache (engine.batch): one [B, S, K, hd] slab per half per
# layer serves B concurrent decode streams — the leading batch axis is the
# ONLY layout difference from the single-stream [S, K, hd] half, so every
# dtype (bf16/f32 arrays, i8 QuantizedKV) batches with the same pytree
# shape rules (scales gain the batch axis too: [B, S, K, 1]).
# ---------------------------------------------------------------------------


def update_row_batched(half, rows: jax.Array, slot: jax.Array):
    """Per-row single-slot write of the batched decode step: row ``b`` of
    ``rows`` [B, K, hd] lands at cache slot ``slot[b]`` of slab row ``b``.
    A slot index >= S DROPS the write — the batch scheduler retires a
    stream by pointing its slot out of bounds, so an inactive row's garbage
    decode never touches the retired cache (its prefix stays reusable)."""
    b_idx = jnp.arange(rows.shape[0])
    if isinstance(half, QuantizedKV):
        q, s = quantize_rows(rows)
        return QuantizedKV(
            half.data.at[b_idx, slot].set(q, mode="drop"),
            half.scales.at[b_idx, slot].set(s, mode="drop"),
        )
    return half.at[b_idx, slot].set(rows.astype(half.dtype), mode="drop")


def scatter_verify_rows(half, b_idx: jax.Array, slots: jax.Array, rows: jax.Array):
    """Per-half multi-token verify scatter (the tuple-slab counterpart of
    :func:`fused_update_verify_batched`): ``rows`` [B, T, K, hd] land at
    ``half[b, slots[b, t]]``; out-of-bounds slots drop."""
    if isinstance(half, QuantizedKV):
        q, s = quantize_rows(rows)
        return QuantizedKV(
            half.data.at[b_idx, slots].set(q, mode="drop"),
            half.scales.at[b_idx, slots].set(s, mode="drop"),
        )
    return half.at[b_idx, slots].set(rows.astype(half.dtype), mode="drop")


def slice_rows_batched(half, start, n: int, rows: int | None = None):
    """Read ``n`` slots [start, start+n) of the first ``rows`` slab rows
    (the batched blocked-attention chunk read). ``start`` may be traced;
    ``n``/``rows`` are static. ``rows`` defaults to every slab row — a
    dispatch bucket smaller than the slab reads only its own rows."""
    if isinstance(half, QuantizedKV):
        B, S, K, hd = half.data.shape
        b = B if rows is None else rows
        return QuantizedKV(
            jax.lax.dynamic_slice(half.data, (0, start, 0, 0), (b, n, K, hd)),
            jax.lax.dynamic_slice(half.scales, (0, start, 0, 0), (b, n, K, 1)),
        )
    B, S, K, hd = half.shape
    b = B if rows is None else rows
    return jax.lax.dynamic_slice(half, (0, start, 0, 0), (b, n, K, hd))


def slab_take_row(half, row):
    """Extract slab row ``row`` as a single-stream [S, K, hd] cache half
    (the slab prefill reuses the whole single-stream attention path on it)."""
    if isinstance(half, QuantizedKV):
        B, S, K, hd = half.data.shape
        return QuantizedKV(
            jax.lax.dynamic_slice(half.data, (row, 0, 0, 0), (1, S, K, hd))[0],
            jax.lax.dynamic_slice(half.scales, (row, 0, 0, 0), (1, S, K, 1))[0],
        )
    B, S, K, hd = half.shape
    return jax.lax.dynamic_slice(half, (row, 0, 0, 0), (1, S, K, hd))[0]


def slab_put_row(half, row_half, row):
    """Write a single-stream cache half back into slab row ``row``. With the
    slab donated, XLA aliases the untouched rows in place."""
    if isinstance(half, QuantizedKV):
        return QuantizedKV(
            jax.lax.dynamic_update_slice(half.data, row_half.data[None], (row, 0, 0, 0)),
            jax.lax.dynamic_update_slice(half.scales, row_half.scales[None], (row, 0, 0, 0)),
        )
    return jax.lax.dynamic_update_slice(half, row_half[None], (row, 0, 0, 0))


# ---------------------------------------------------------------------------
# Page pool (engine.prefix_cache): immutable prefix KV pages shared across
# requests. A pool half is [P, page, K, hd] — the same dtype/pytree rules as
# the slab (i8 pools carry [P, page, K, 1] scales), so published pages hold
# the EXACT cache bytes of the row they came from, and the zero-copy paged
# read (pool_chunk/select_kv below, consumed by ops.attention's paged
# variants) sees bytes identical to what the PR 4 copy design gathered into
# the slab (the prefix-hit == cold-prefill bit-parity contract). Cached
# bytes exist ONCE — in the pool — and rows alias them through per-row page
# tables instead of holding duplicates.
# ---------------------------------------------------------------------------


def init_page_pool_half(n_pages: int, page: int, kl: int, hd: int, dtype):
    """One pool half of ``n_pages`` fixed-size pages: [P, page, K, hd] (or a
    QuantizedKV of int8 data + [P, page, K, 1] scales for the i8 sentinel)."""
    return init_half((n_pages, page, kl, hd), dtype)


def pool_page_size(pool_half) -> int:
    """Static page size of a pool half ([P, page, K, hd] — shapes are known
    at trace time, so paged-vs-plain branching stays Python-level)."""
    return (pool_half.data if isinstance(pool_half, QuantizedKV) else pool_half).shape[1]


def gather_pool_pages(pool_half, ids):
    """Read pool pages ``ids`` [..., n] -> [..., n*page, K, hd]: the
    zero-copy page-table read. The gathered positions are CONSUMED by the
    attention einsums in-register — nothing is written back to the slab, so
    cached bytes exist exactly once (in the pool). Out-of-bounds ids clamp
    (jnp gather default); callers mask those positions out by ``matched``."""
    if isinstance(pool_half, QuantizedKV):
        d = pool_half.data[ids]  # [..., n, page, K, hd]
        s = pool_half.scales[ids]
        return QuantizedKV(
            d.reshape(d.shape[:-4] + (-1,) + d.shape[-2:]),
            s.reshape(s.shape[:-4] + (-1,) + s.shape[-2:]),
        )
    v = pool_half[ids]
    return v.reshape(v.shape[:-4] + (-1,) + v.shape[-2:])


def pool_chunk(pool_half, tables, i, pages_per_chunk: int):
    """One attention chunk's KV read THROUGH the page tables: pages
    ``tables[:, i*ppc : (i+1)*ppc]`` of every row -> [B, ppc*page, K, hd].
    ``i`` may be traced (the blocked fori_loop index)."""
    B = tables.shape[0]
    ids = jax.lax.dynamic_slice(tables, (0, i * pages_per_chunk), (B, pages_per_chunk))
    return gather_pool_pages(pool_half, ids)


def pool_chunk_row(pool_half, table, i, pages_per_chunk: int):
    """Single-row form of :func:`pool_chunk`: ``table`` [n_table] ->
    [ppc*page, K, hd]."""
    ids = jax.lax.dynamic_slice(table, (i * pages_per_chunk,), (pages_per_chunk,))
    return gather_pool_pages(pool_half, ids)


def select_kv(sel, pool_kv, slab_kv):
    """Per-position source select of a mixed chunk: ``sel`` [..., n] True
    takes the pool byte, False the slab byte. Pages hold the EXACT bytes the
    copy design would have gathered into the slab, so the selected chunk is
    byte-identical to the copied one — the bit-parity contract of the
    zero-copy read."""
    m = sel[..., None, None]
    if isinstance(slab_kv, QuantizedKV):
        return QuantizedKV(
            jnp.where(m, pool_kv.data, slab_kv.data),
            jnp.where(m, pool_kv.scales, slab_kv.scales),
        )
    return jnp.where(m, pool_kv, slab_kv)


def virtual_row(half, pool_half, table, matched):
    """Full virtual [S, K, hd] view of one cache row: pool bytes below
    ``matched``, the slab row beyond. The einsum-fallback read for caches
    too small/odd to block — it materializes the select, so the blocked
    segmented read is the production path."""
    S = half.shape[0]
    pooled = gather_pool_pages(pool_half, table)[:S]
    sel = jnp.arange(S) < matched
    return select_kv(sel, pooled, half)


def virtual_rows_batched(half_b, pool_half, tables, matched):
    """Batched :func:`virtual_row`: [B, S, K, hd] virtual slab with per-row
    page tables and matched lengths."""
    S = half_b.shape[1]
    pooled = gather_pool_pages(pool_half, tables)[:, :S]
    sel = jnp.arange(S)[None, :] < matched[:, None]
    return select_kv(sel, pooled, half_b)


def slice_pool_page(pool_half, pid) -> list:
    """Pool page ``pid`` as a FLAT slice list — the spill-entry layout
    (engine/spill.py): ``[data]`` for plain halves, ``[data, scales]``
    for i8 ``QuantizedKV``. Traceable (``pid`` may be a tracer), so the
    scheduler fuses every layer's slices into ONE download program; the
    flat layout lets the arena checksum and byte-account without knowing
    the dtype."""
    if isinstance(pool_half, QuantizedKV):
        return [pool_half.data[pid], pool_half.scales[pid]]
    return [pool_half[pid]]


def download_pool_page(pool_half, pid: int) -> list[np.ndarray]:
    """Host byte arrays of pool page ``pid`` — the unfused (per-half)
    spill download, verbatim bytes (the reload byte-parity contract).
    Blocking (np.asarray): tests and tools; the scheduler's production
    path fuses :func:`slice_pool_page` across layers instead."""
    return [np.asarray(a) for a in slice_pool_page(pool_half, pid)]


def upload_pool_page(pool_half, pid, arrays: list):
    """Write one downloaded page's arrays back into pool page ``pid`` —
    the spill-tier reload (publish in reverse). Inverse of
    :func:`download_pool_page`'s flat layout; traced under jit (``pid``
    may be a tracer), callers donate the pool."""
    if isinstance(pool_half, QuantizedKV):
        return QuantizedKV(
            pool_half.data.at[pid].set(arrays[0]),
            pool_half.scales.at[pid].set(arrays[1]),
        )
    return pool_half.at[pid].set(arrays[0])


def pool_page_arrays_per_half(pool_half) -> int:
    """How many flat arrays :func:`download_pool_page` yields for this
    half (2 for i8 data+scales, 1 otherwise) — the spill entry's layout
    contract."""
    return 2 if isinstance(pool_half, QuantizedKV) else 1


def publish_row_pages(pool_half, slab_half, row, src_page, page_ids, page: int):
    """Copy slab row ``row``'s page slots ``src_page[i]`` into pool pages
    ``page_ids[i]`` (the prefix-cache publish: the row's completed prefill
    KV becomes an immutable shared page). A ``page_ids`` entry at or beyond
    P DROPS its write, so padded entries are inert. Returns the updated pool
    half (callers donate the pool)."""
    p_idx = jnp.arange(page)
    slots = (src_page[:, None] * page + p_idx[None, :]).reshape(-1)
    n = src_page.shape[0]
    if isinstance(pool_half, QuantizedKV):
        vals = slab_half.data[row, slots]  # [Np*page, K, hd]
        scal = slab_half.scales[row, slots]
        return QuantizedKV(
            pool_half.data.at[page_ids].set(
                vals.reshape((n, page) + vals.shape[1:]), mode="drop"
            ),
            pool_half.scales.at[page_ids].set(
                scal.reshape((n, page) + scal.shape[1:]), mode="drop"
            ),
        )
    vals = slab_half[row, slots]
    return pool_half.at[page_ids].set(
        vals.reshape((n, page) + vals.shape[1:]), mode="drop"
    )


# ---------------------------------------------------------------------------
# Fused (coalesced) per-layer cache: keys and values stacked on a LEADING
# 2-axis — [2, S, K, hd] single-stream, [2, B, S, K, hd] slab — so each
# layer's K/V write is ONE dynamic_update_slice / scatter instead of the
# historical (keys, values) pair. The leading axis is fully covered by
# every write (index 0, extent 2), so XLA aliases the donated leaf in
# place exactly like the tuple halves did; reads are static leading-index
# slices (``fused[0]``/``fused[1]``) — contiguous views, no copy. PERF.md
# names the per-layer update pair on the decode critical path; halving the
# op count is the point. i8 fuses the same way (QuantizedKV with
# [2, ...] data+scales: 2 updates per layer instead of 4). The tensor/
# sequence/expert-parallel backends keep tuple halves (their cache
# PartitionSpecs shard the unfused rank), so every update helper here
# keeps its tuple form too.
# ---------------------------------------------------------------------------


def init_fused(shape, dtype, zeros=jnp.zeros):
    """One fused per-layer cache leaf: keys+values as [2, *shape]."""
    if is_quantized_cache_dtype(dtype):
        return QuantizedKV(
            zeros((2,) + shape, jnp.int8),
            zeros((2,) + shape[:-1] + (1,), jnp.float32),
        )
    return zeros((2,) + shape, dtype)


def is_fused_leaf(cache_l) -> bool:
    """Fused leaves are a single array/QuantizedKV; tuple = split halves."""
    return not isinstance(cache_l, (tuple, list))


def fused_update_rows(leaf, k_rows: jax.Array, v_rows: jax.Array, pos):
    """The coalesced write of :func:`update_rows` pairs: T tokens' keys AND
    values land at slots pos..pos+T-1 of a fused leaf in one
    dynamic_update_slice (two — data+scales — for i8)."""
    if isinstance(leaf, QuantizedKV):
        kq, ks = quantize_rows(k_rows)
        vq, vs = quantize_rows(v_rows)
        return QuantizedKV(
            jax.lax.dynamic_update_slice(leaf.data, jnp.stack([kq, vq]), (0, pos, 0, 0)),
            jax.lax.dynamic_update_slice(leaf.scales, jnp.stack([ks, vs]), (0, pos, 0, 0)),
        )
    stacked = jnp.stack([k_rows, v_rows]).astype(leaf.dtype)
    return jax.lax.dynamic_update_slice(leaf, stacked, (0, pos, 0, 0))


def fused_update_row_batched(leaf, k_rows: jax.Array, v_rows: jax.Array, slot: jax.Array):
    """Coalesced batched decode write: row ``b``'s key AND value land at
    slab slot ``slot[b]`` in one scatter (slot >= S drops, retiring rows
    exactly like :func:`update_row_batched`)."""
    b_idx = jnp.arange(k_rows.shape[0])
    if isinstance(leaf, QuantizedKV):
        kq, ks = quantize_rows(k_rows)
        vq, vs = quantize_rows(v_rows)
        return QuantizedKV(
            leaf.data.at[:, b_idx, slot].set(jnp.stack([kq, vq]), mode="drop"),
            leaf.scales.at[:, b_idx, slot].set(jnp.stack([ks, vs]), mode="drop"),
        )
    stacked = jnp.stack([k_rows, v_rows]).astype(leaf.dtype)
    return leaf.at[:, b_idx, slot].set(stacked, mode="drop")


def fused_update_verify_batched(leaf, k_rows: jax.Array, v_rows: jax.Array, slots: jax.Array):
    """Coalesced multi-token verify write (speculative decode): row ``b``'s
    T keys AND values land at its per-row slots ``slots[b, t]`` in ONE
    scatter per layer. ``k_rows``/``v_rows``: [B, T, K, hd]; out-of-bounds
    slots drop (inactive rows and context-limit clamps write nothing)."""
    b_idx = jnp.arange(k_rows.shape[0])[:, None]
    if isinstance(leaf, QuantizedKV):
        kq, ks = quantize_rows(k_rows)
        vq, vs = quantize_rows(v_rows)
        return QuantizedKV(
            leaf.data.at[:, b_idx, slots].set(jnp.stack([kq, vq]), mode="drop"),
            leaf.scales.at[:, b_idx, slots].set(jnp.stack([ks, vs]), mode="drop"),
        )
    stacked = jnp.stack([k_rows, v_rows]).astype(leaf.dtype)
    return leaf.at[:, b_idx, slots].set(stacked, mode="drop")


def fused_take_row(leaf, row):
    """Extract slab row ``row`` of a fused [2, B, S, K, hd] leaf as a fused
    single-stream [2, S, K, hd] leaf (the slab prefill's row view)."""
    if isinstance(leaf, QuantizedKV):
        _, B, S, K, hd = leaf.data.shape
        return QuantizedKV(
            jax.lax.dynamic_slice(leaf.data, (0, row, 0, 0, 0), (2, 1, S, K, hd))[:, 0],
            jax.lax.dynamic_slice(leaf.scales, (0, row, 0, 0, 0), (2, 1, S, K, 1))[:, 0],
        )
    _, B, S, K, hd = leaf.shape
    return jax.lax.dynamic_slice(leaf, (0, row, 0, 0, 0), (2, 1, S, K, hd))[:, 0]


def fused_put_row(slab_leaf, row_leaf, row):
    """Write a fused single-stream row back into fused slab row ``row`` —
    one dynamic_update_slice covers both halves."""
    if isinstance(slab_leaf, QuantizedKV):
        return QuantizedKV(
            jax.lax.dynamic_update_slice(
                slab_leaf.data, row_leaf.data[:, None], (0, row, 0, 0, 0)
            ),
            jax.lax.dynamic_update_slice(
                slab_leaf.scales, row_leaf.scales[:, None], (0, row, 0, 0, 0)
            ),
        )
    return jax.lax.dynamic_update_slice(slab_leaf, row_leaf[:, None], (0, row, 0, 0, 0))


def scores_einsum_verify(qg: jax.Array, keys, prec) -> jax.Array:
    """Batched multi-token verify scores: scores[b,t,k,m,s] =
    q[b,t,k,m,:] . key_row[b,s,k,:] (same i8 scale-folding contract as
    :func:`scores_einsum_batched`, with a T axis riding along)."""
    if isinstance(keys, QuantizedKV):
        raw = jnp.einsum(
            "btkmh,bskh->btkms",
            qg,
            keys.data.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        )
        return raw * jnp.transpose(keys.scales[..., 0], (0, 2, 1))[:, None, :, None, :]
    return jnp.einsum(
        "btkmh,bskh->btkms", qg, keys, precision=prec,
        preferred_element_type=jnp.float32,
    )


def mix_einsum_verify(weights: jax.Array, values, cdt, prec) -> jax.Array:
    """Batched multi-token verify value mix: att[b,t,k,m,h] =
    sum_s w[b,t,k,m,s] * v[b,s,k,h]; the i8 scale folds into the weights
    BEFORE the mix (the value read stays int8)."""
    if isinstance(values, QuantizedKV):
        wv = weights * jnp.transpose(values.scales[..., 0], (0, 2, 1))[:, None, :, None, :]
        return jnp.einsum(
            "btkms,bskh->btkmh",
            wv.astype(cdt),
            values.data.astype(cdt),
            preferred_element_type=jnp.float32,
        )
    return jnp.einsum(
        "btkms,bskh->btkmh", weights.astype(cdt), values, precision=prec,
        preferred_element_type=jnp.float32,
    )


def compute_dtype(half):
    """The einsum operand dtype for a cache half: the storage dtype for
    plain caches (bf16 reads stay bf16, f32 parity stays f32); bf16 for i8
    (int8 magnitudes are exact in bf16, and the MXU wants bf16)."""
    return jnp.bfloat16 if isinstance(half, QuantizedKV) else half.dtype


def einsum_precision(half):
    """f32 caches (parity tests) keep true-f32 multiplies via HIGHEST."""
    dt = half.dtype if not isinstance(half, QuantizedKV) else None
    return jax.lax.Precision.HIGHEST if dt == jnp.float32 else None


def scores_einsum(qg: jax.Array, keys, prec) -> jax.Array:
    """scores[t,k,m,s] = q[t,k,m,:] . key_row[s,k,:] with f32 accumulation;
    for an i8 half the per-(slot, head) scale folds in AFTER the int8 dot
    (the HBM read is int8)."""
    if isinstance(keys, QuantizedKV):
        raw = jnp.einsum(
            "tkmh,skh->tkms",
            qg,
            keys.data.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        )
        return raw * jnp.transpose(keys.scales[..., 0])[None, :, None, :]
    return jnp.einsum(
        "tkmh,skh->tkms", qg, keys, precision=prec,
        preferred_element_type=jnp.float32,
    )


def mix_einsum(weights: jax.Array, values, cdt, prec) -> jax.Array:
    """att[t,k,m,h] = sum_s w[t,k,m,s] * value_row[s,k,h]; for an i8 half
    the scale folds into the weights BEFORE the mix, so the value read
    stays int8."""
    if isinstance(values, QuantizedKV):
        wv = weights * jnp.transpose(values.scales[..., 0])[None, :, None, :]
        return jnp.einsum(
            "tkms,skh->tkmh",
            wv.astype(cdt),
            values.data.astype(cdt),
            preferred_element_type=jnp.float32,
        )
    return jnp.einsum(
        "tkms,skh->tkmh", weights.astype(cdt), values, precision=prec,
        preferred_element_type=jnp.float32,
    )


def scores_einsum_batched(qg: jax.Array, keys, prec) -> jax.Array:
    """Batched-slab scores: row ``b`` of qg [B, K, M, hd] scores ONLY its
    own cache row — scores[b,k,m,s] = q[b,k,m,:] . key_row[b,s,k,:]. Same
    i8 scale-folding contract as :func:`scores_einsum`."""
    if isinstance(keys, QuantizedKV):
        raw = jnp.einsum(
            "bkmh,bskh->bkms",
            qg,
            keys.data.astype(qg.dtype),
            preferred_element_type=jnp.float32,
        )
        return raw * jnp.transpose(keys.scales[..., 0], (0, 2, 1))[:, :, None, :]
    return jnp.einsum(
        "bkmh,bskh->bkms", qg, keys, precision=prec,
        preferred_element_type=jnp.float32,
    )


def mix_einsum_batched(weights: jax.Array, values, cdt, prec) -> jax.Array:
    """Batched-slab value mix: att[b,k,m,h] = sum_s w[b,k,m,s] * v[b,s,k,h];
    the i8 scale folds into the weights BEFORE the mix (the value read stays
    int8), mirroring :func:`mix_einsum`."""
    if isinstance(values, QuantizedKV):
        wv = weights * jnp.transpose(values.scales[..., 0], (0, 2, 1))[:, :, None, :]
        return jnp.einsum(
            "bkms,bskh->bkmh",
            wv.astype(cdt),
            values.data.astype(cdt),
            preferred_element_type=jnp.float32,
        )
    return jnp.einsum(
        "bkms,bskh->bkmh", weights.astype(cdt), values, precision=prec,
        preferred_element_type=jnp.float32,
    )
