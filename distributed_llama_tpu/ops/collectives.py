"""The all-reduce seam + ICI ring all-reduce kernel (ROADMAP item 1).

Every tensor-parallel layer pays exactly two all-reduces (after wo and
after down — ``models.llama.block_tail``/``ffn``; the reference's two
gather+merge TCP hops per layer, src/llama2-tasks.cpp:115-131/196-212).
XLA lowers ``lax.psum`` to its own fused all-reduce, which SERIALIZES
after the matmul producing its operand: the collective cannot start until
the full [T, dim] product lands, and nothing overlaps the wire time. The
ring kernel here (`ring_all_reduce`, per SNIPPETS.md [1] /
docs.jax.dev pallas distributed) instead runs reduce-scatter + all-gather
as explicit bidirectional ``make_async_remote_copy`` steps, so on TPU the
per-chunk sends overlap the remaining chunks' adds — and, fused into the
same Mosaic program as a consumer, the matmul epilogue — instead of
fencing behind them.

Determinism contract (the reason this is NOT a naive rotate-and-add
ring): each output chunk's sum is accumulated ONCE, on the shard the
reduce-scatter assigns it, in a FIXED ring order, then broadcast verbatim
by the all-gather — so every shard holds byte-identical results, exactly
like ``psum`` (a rotate-and-add ring would give each shard a different
f32 association of the same addends, and replicated sampling would
diverge across shards).

Three implementations behind one seam (:func:`all_reduce`):

* ``psum``     — ``jax.lax.psum``, the default off-TPU (and the safety
                 net everywhere: any ring-path build failure falls back).
* ``ring_xla`` — the ring SCHEDULE via ``lax.ppermute`` steps: the same
                 chunk walk without Pallas, runnable on the CPU test mesh
                 (the container's jax cannot interpret remote DMA — the
                 version-gate/soft-fallback policy of the tp clamp), and
                 the parity reference for the kernel's schedule.
* ``ring``     — the Pallas remote-DMA kernel, TPU compiled mode only.

``DLT_ALLREDUCE`` pins an implementation (``psum`` / ``ring_xla`` /
``ring``); unset, EVERY platform defaults to psum for now — the ring
kernel has never been Mosaic-compiled (no chip in this tree's CI) and a
lowering failure would surface at XLA compile of the whole jitted
forward, past any fallback; flipping the TPU default is the first chip-
validation follow-up (ROADMAP item 1). Every selection is counted in
``dllama_kernel_path_total{kernel="all_reduce"}`` so the implementation
actually serving is visible in /metrics.
"""

from __future__ import annotations

import os as _os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map across the jax versions this tree supports: current jax
    wants ``jax.shard_map(check_vma=False)``, the container's 0.4.37 only
    has ``jax.experimental.shard_map.shard_map(check_rep=False)``. The
    production backends keep their pinned ``check_vma`` call (the known
    env-failure ceiling); NEW collective tests/benches use this compat so
    the ring parity gates run everywhere."""
    try:
        from jax import shard_map as _sm  # type: ignore

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def _axis_size(axis_name: str) -> int | None:
    """Static size of a named mesh axis during a shard_map trace, across
    the jax versions this tree supports; None when unresolvable (→ psum)."""
    try:
        fr = jax.core.axis_frame(axis_name)  # returns the int itself on 0.4.x
        return int(getattr(fr, "size", fr))
    except Exception:
        pass
    try:
        from jax._src.core import get_axis_env

        return int(get_axis_env().axis_size(axis_name))
    except Exception:
        return None


def _note(path: str) -> None:
    from distributed_llama_tpu import telemetry

    telemetry.note_kernel_path("all_reduce", path)


def default_impl() -> str:
    """psum unless ``DLT_ALLREDUCE`` pins otherwise — INCLUDING on TPU for
    now: the ring kernel has never been Mosaic-compiled (no chip in this
    tree's CI), and the seam's try/except can only catch TRACE-time
    failures — a Mosaic lowering error surfaces later, at XLA compile of
    the whole jitted forward, where no fallback can run. Flipping the TPU
    default to "ring" is the first item of the chip-validation follow-up
    (ROADMAP item 1); until then the kernel is an explicit opt-in."""
    return _os.environ.get("DLT_ALLREDUCE") or "psum"


def all_reduce(x: jax.Array, axis_name: str | None, impl: str | None = None) -> jax.Array:
    """THE all-reduce seam: sum ``x`` over ``axis_name`` replicated-
    identically on every shard. ``axis_name=None`` is the single-chip
    no-op, mirroring the psum call sites it replaces."""
    if axis_name is None:
        return x
    if impl is None:
        impl = default_impl()
    if impl in ("ring", "ring_xla"):
        n = _axis_size(axis_name)
        if n is None or n <= 1 or x.shape[-1] < n:
            impl = "psum"  # tiny/odd payloads: the ring buys nothing
    if impl == "ring":
        try:
            out = ring_all_reduce(x, axis_name, n)
            _note("ici_ring")
            return out
        except Exception:
            # version-gated Pallas surface missing (or the kernel failed to
            # trace): the collective must not take the program down
            impl = "psum"
    if impl == "ring_xla":
        _note("ring_xla")
        return ring_all_reduce_xla(x, axis_name, n)
    _note("psum")
    return lax.psum(x, axis_name)


# ---------------------------------------------------------------------------
# Fused matmul + all-reduce (decode superstep, part b)
# ---------------------------------------------------------------------------
#
# Under ``psum`` (and the unfused ring) the wo/down matmul and the
# all-reduce are strictly sequential: the collective's first byte cannot
# leave until the LAST output column lands. But the ring schedule only
# needs ONE chunk to start its first hop — so the fused kernel below
# computes each output chunk's int8 matmul ON DEMAND inside the
# reduce-scatter walk and starts both directions' remote copies BEFORE
# computing the next step's chunks: the next tile's MXU work runs while
# the copies are in flight, which is the overlap the ISSUE's superstep
# buys over psum. The seam (:func:`matmul_all_reduce`) keeps the same
# safety ladder as :func:`all_reduce`: the fused kernel engages only
# under ``DLT_ALLREDUCE=ring`` + the int8 q40 path + an eligible shape,
# and ANY failure falls back to the unfused matmul + all_reduce arms
# (whose ring_xla/psum parity is pinned on the CPU mesh).


def _fused_ring_eligible(x: jax.Array, qm, n: int) -> bool:
    """Shape/VMEM gate for the fused kernel: the 2n column chunks must be
    lane-aligned (w % 128), the n tiling must match the standalone int8
    kernel's (same f32 accumulation order → bit-parity by construction),
    and every operand must fit VMEM simultaneously (the kernel takes no
    grid — decode payloads only)."""
    from distributed_llama_tpu.quants import QK
    from distributed_llama_tpu.ops.q40 import BLOCK_N, _largest_divisor_tile

    T = x.shape[0]
    np_, dp = qm.n_padded, qm.d_padded
    if T > 8 or dp % (2 * n) or qm.qs.ndim != 2:
        return False
    w = dp // (2 * n)
    if w % 128 or _largest_divisor_tile(np_, BLOCK_N, 512) is None:
        return False
    vmem = (
        np_ // 2 * dp  # qs (uint8)
        + np_ // QK * dp * 4  # scales (f32)
        + T * np_  # xq (int8)
        + 2 * T * np_ // QK * 4  # sx + xsum (f32)
        + 3 * 2 * n * T * w * 4  # out + comm/scratch slots (f32)
    )
    return vmem < 10 * 2**20  # ~16 MB/core VMEM, leave headroom


def _make_fused_matmul_ring_kernel(axis_name: str, n: int, nj: int, w: int):
    """The fused int8-matmul + bidirectional-ring kernel factory.

    Ring schedule and chunk layout are IDENTICAL to
    :func:`_make_ring_kernel` (index 2c+d = ring d's chunk at position c);
    the difference is that ``local_chunk`` COMPUTES its chunk — the
    Q40×Q80 per-block int8 dot over output columns [k*w, (k+1)*w) plus the
    +8-bias correction — instead of loading a precomputed product, and the
    reduce-scatter step starts both remote copies BEFORE computing the
    next chunks so the MXU work overlaps the in-flight DMAs.

    The per-chunk matmul replicates the standalone kernel's accumulation
    structure exactly (``nj`` sequential block_n tiles, each adding its
    lo-half then hi-half per-block sums into the f32 accumulator — the
    ``_q40_matmul_int8`` grid order) so fused and unfused paths agree
    bitwise, not just approximately."""
    from distributed_llama_tpu.quants import QK

    def kernel(xq_ref, sx_ref, xsum_ref, qs_ref, scales_ref, out_ref,
               comm_ref, scratch_ref, send_sem, recv_sem):
        my = lax.axis_index(axis_name)
        neighbor = (jnp.mod(my + 1, n), jnp.mod(my - 1, n))  # cw, ccw
        np2 = qs_ref.shape[0]  # packed rows = n_pad/2
        bn2 = np2 // nj  # packed rows per block_n tile
        nbt = bn2 // QK  # quant blocks per tile per half
        T = xq_ref.shape[0]

        def compute_chunk(k):
            """out[:, k*w:(k+1)*w] of THIS shard's x @ dequant(qm): the
            int8 block-dot epilogue, on demand."""
            cols = pl.ds(k * w, w)

            def half(xqh, sxh, nib, swh):
                xb = xqh.reshape(T, nbt, QK)
                wb = nib.reshape(nbt, QK, w)
                P = jax.lax.dot_general(
                    xb, wb, (((2,), (1,)), ((1,), (0,))),
                    preferred_element_type=jnp.int32,
                )  # [nbt, T, w]
                scaled = P.astype(jnp.float32) * swh[:, None, :]
                return jnp.sum(scaled * jnp.transpose(sxh)[:, :, None], axis=0)

            def tile(j, acc):
                qs = qs_ref[pl.ds(j * bn2, bn2), cols]
                lo = (qs & 0xF).astype(jnp.int8)
                hi = (qs >> 4).astype(jnp.int8)
                acc += half(
                    xq_ref[:, pl.ds(j * bn2, bn2)],
                    sx_ref[:, pl.ds(j * nbt, nbt)],
                    lo,
                    scales_ref[pl.ds(j * nbt, nbt), cols],
                )
                acc += half(
                    xq_ref[:, pl.ds((nj + j) * bn2, bn2)],
                    sx_ref[:, pl.ds((nj + j) * nbt, nbt)],
                    hi,
                    scales_ref[pl.ds((nj + j) * nbt, nbt), cols],
                )
                return acc

            acc = lax.fori_loop(0, nj, tile, jnp.zeros((T, w), jnp.float32))
            # the +8 nibble-bias correction for THESE columns (per-shard:
            # the cross-shard sum of per-shard corrections is the global
            # correction, so the ring's adds need no special casing)
            corr = jax.lax.dot_general(
                xsum_ref[:], scales_ref[:, cols],
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            return acc - 8.0 * corr

        def start_hop(d, slot, value):
            scratch_ref[d, slot] = value
            rdma = pltpu.make_async_remote_copy(
                src_ref=scratch_ref.at[d, slot],
                dst_ref=comm_ref.at[d, slot],
                send_sem=send_sem.at[d],
                recv_sem=recv_sem.at[d],
                device_id=(neighbor[d],),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            return rdma

        def rs_step(s, carry):
            p_cw, p_ccw = carry
            slot = s % 2
            r0 = start_hop(0, slot, p_cw)
            r1 = start_hop(1, slot, p_ccw)
            # THE overlap: this step's chunk matmuls run on the MXU while
            # both remote copies are in flight
            add_cw = compute_chunk(2 * jnp.mod(my - s, n))
            add_ccw = compute_chunk(2 * jnp.mod(my + s, n) + 1)
            r0.wait()
            r1.wait()
            return comm_ref[0, slot] + add_cw, comm_ref[1, slot] + add_ccw

        p_cw, p_ccw = lax.fori_loop(
            1, n, rs_step, (compute_chunk(2 * my), compute_chunk(2 * my + 1))
        )
        pl.store(out_ref, (2 * jnp.mod(my + 1, n),), p_cw)
        pl.store(out_ref, (2 * jnp.mod(my - 1, n) + 1,), p_ccw)

        def ag_step(s, carry):
            c_cw, c_ccw = carry
            slot = s % 2
            r0 = start_hop(0, slot, c_cw)
            r1 = start_hop(1, slot, c_ccw)
            r0.wait()
            r1.wait()
            got_cw, got_ccw = comm_ref[0, slot], comm_ref[1, slot]
            pl.store(out_ref, (2 * jnp.mod(my - s + 1, n),), got_cw)
            pl.store(out_ref, (2 * jnp.mod(my + s - 1, n) + 1,), got_ccw)
            return got_cw, got_ccw

        lax.fori_loop(1, n, ag_step, (p_cw, p_ccw))

    return kernel


def fused_matmul_ring_all_reduce(x: jax.Array, qm, axis_name: str, n: int) -> jax.Array:
    """psum_over_shards(x @ dequant(qm)) as ONE Pallas program: Q80
    quantize (outside — elementwise, XLA fuses it into the caller), then
    the int8 matmul computed chunk-by-chunk INSIDE the bidirectional ring
    reduce-scatter, remote copies overlapping the next chunks' MXU work.
    TPU compiled mode only, exactly like :func:`ring_all_reduce` (remote
    DMA cannot run interpreted on the container's jax); callers reach it
    through the :func:`matmul_all_reduce` seam, which guards eligibility
    and falls back to the unfused arms on any failure."""
    from distributed_llama_tpu.quants import QK
    from distributed_llama_tpu.ops.q40 import (
        BLOCK_N,
        _largest_divisor_tile,
        quantize_q80,
        tpu_compiler_params,
    )

    params = tpu_compiler_params(has_side_effects=True, collective_id=1)
    if not params:
        raise RuntimeError(
            "pallas compiler params lack has_side_effects/collective_id; "
            "refusing to build the fused matmul+ring kernel without them"
        )
    np_, dp = qm.n_padded, qm.d_padded
    T = x.shape[0]
    w = dp // (2 * n)
    bn = _largest_divisor_tile(np_, BLOCK_N, 512)
    nj = np_ // bn
    if x.shape[-1] != np_:
        x = jnp.pad(x, ((0, 0), (0, np_ - x.shape[-1])))
    xq, sx = quantize_q80(x)
    qsum = jnp.sum(xq.astype(jnp.float32).reshape(T, np_ // QK, QK), axis=-1)
    xsum = sx * qsum
    slot = (2, 2, T, w)
    out = pl.pallas_call(
        _make_fused_matmul_ring_kernel(axis_name, n, nj, w),
        out_shape=jax.ShapeDtypeStruct((2 * n, T, w), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 5,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM(slot, jnp.float32),  # recv slots (remote writes)
            pltpu.VMEM(slot, jnp.float32),  # send staging
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        **params,
    )(xq, sx, xsum, qm.qs, qm.scales)
    flat = jnp.concatenate(list(out), axis=-1)  # [T, dp]
    return flat[:, : qm.d] if dp != qm.d else flat


def matmul_all_reduce(
    x: jax.Array, w, axis_name: str | None, impl: str | None = None
) -> jax.Array:
    """THE matmul+all-reduce seam: ``sum_over_shards(x @ w)``, replicated
    identically on every shard — what ``models.llama.block_tail``/``ffn``
    route the wo/down projections through. ``axis_name=None`` is the
    single-chip plain matmul. Dispatch ladder: the fused int8+ring Pallas
    kernel when ``DLT_ALLREDUCE=ring`` + the int8 q40 path + an eligible
    shape (noted ``fused_ring``); otherwise the unfused matmul followed by
    :func:`all_reduce` under the chosen impl (psum / ring_xla / ring).
    Arm parity (tests/test_kernel_parity.py): the psum arm is exactly the
    unfused composition; ring-schedule arms agree within summation-order
    tolerance (a ring accumulates each chunk in ring order — a different
    f32 association than psum); the fused kernel replicates the unfused
    int8 matmul's tile accumulation order per chunk, so its divergence
    from the psum arm is the same association-only delta."""
    from distributed_llama_tpu.models.llama import _matmul

    if axis_name is None:
        return _matmul(x, w)
    if impl is None:
        impl = default_impl()
    if impl == "ring":
        from distributed_llama_tpu.ops.q40 import QuantizedMatrix, default_q40_path

        n = _axis_size(axis_name)
        if (
            n is not None
            and n > 1
            and isinstance(w, QuantizedMatrix)
            and not w.interleaved
            and default_q40_path() == "int8"
            and _fused_ring_eligible(x, w, n)
        ):
            try:
                out = fused_matmul_ring_all_reduce(x, w, axis_name, n)
                _note("fused_ring")
                return out
            except Exception:
                pass  # unfused arms below are the safety net
    return all_reduce(_matmul(x, w), axis_name, impl)


# ---------------------------------------------------------------------------
# Ring schedule via ppermute (the CPU-mesh realization + parity reference)
# ---------------------------------------------------------------------------


def _ring_chunks(x: jax.Array, n: int):
    """Split the last axis into n equal chunks (zero-padded), stacked on a
    leading axis: [n, ..., ceil(d/n)]."""
    d = x.shape[-1]
    pad = (-d) % n
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    return jnp.stack(jnp.split(x, n, axis=-1)), pad


def ring_all_reduce_xla(x: jax.Array, axis_name: str, n: int) -> jax.Array:
    """Ring all-reduce as N-1 reduce-scatter + N-1 all-gather ppermute
    steps — the exact chunk schedule of the Pallas kernel, expressed in
    XLA collectives. Each chunk c accumulates in the fixed ring order
    (c, c+1, ..., c+n-1) on its owner, so all shards end byte-identical.
    Runnable on the CPU test mesh; the parity gate vs psum lives in
    tests/test_kernel_parity.py."""
    orig = x.shape[-1]
    chunks, pad = _ring_chunks(x, n)
    me = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    # reduce-scatter: at step s, each shard forwards the partial it holds
    # and folds its local copy of the chunk arriving next; after n-1 steps
    # shard i owns the full sum of chunk (i + 1) mod n
    partial = jnp.take(chunks, me % n, axis=0)
    for s in range(1, n):
        partial = lax.ppermute(partial, axis_name, perm)
        partial = partial + jnp.take(chunks, (me - s) % n, axis=0)

    # all-gather: circulate the owned chunks; shard i receives chunk
    # (i - s + 1) mod n at step s and writes it at its global index
    out = jnp.zeros_like(chunks)
    cur = partial
    out = lax.dynamic_update_index_in_dim(out, cur, (me + 1) % n, 0)
    for s in range(1, n):
        cur = lax.ppermute(cur, axis_name, perm)
        out = lax.dynamic_update_index_in_dim(out, cur, (me - s + 1) % n, 0)

    flat = jnp.concatenate(list(out), axis=-1)
    return flat[..., :orig] if pad else flat


# ---------------------------------------------------------------------------
# Pallas remote-DMA ring kernel (TPU compiled mode)
# ---------------------------------------------------------------------------
#
# Bidirectional ring per the pallas distributed guide: the chunk axis is
# split into two halves, one walked clockwise and one counter-clockwise, so
# both ICI directions carry payload and the per-step wire time halves. Each
# direction runs the same reduce-scatter (+ all-gather) schedule as
# ring_all_reduce_xla. The remote copies are started as soon as a partial
# is ready — on TPU the next chunk's local add (and the surrounding
# program's epilogue) proceeds while the copy is in flight, which is the
# overlap psum structurally cannot give. The container's jax cannot
# interpret make_async_remote_copy (version gate), so this path is
# TPU-compiled-only; the schedule itself is pinned by the ring_xla parity
# tests and the two share their chunk arithmetic by construction.


def _make_ring_kernel(axis_name: str, n: int):
    """Kernel factory for the bidirectional ring. Chunk layout: ref index
    ``2*c + d`` holds ring ``d``'s chunk at ring position ``c`` (d = 0
    clockwise, d = 1 counter-clockwise), so both ICI directions carry half
    the payload. The two rings advance TOGETHER each step with both remote
    copies in flight concurrently — each direction's wire time hides under
    the other's wait+add, which is where the bidirectional win actually
    comes from (two sequential half-payload rings would just re-serialize
    it). Per ring, the schedule is IDENTICAL to
    :func:`ring_all_reduce_xla`'s (that parity is what the CPU-mesh tests
    pin): reduce-scatter accumulates chunk c in fixed ring order on its
    owner, then the all-gather circulates the owned chunks verbatim."""

    def kernel(chunks_ref, out_ref, comm_ref, scratch_ref, send_sem, recv_sem):
        my = lax.axis_index(axis_name)
        neighbor = (jnp.mod(my + 1, n), jnp.mod(my - 1, n))  # cw, ccw

        def start_hop(d, slot, value):
            """Stage ``value`` and start its copy to ring ``d``'s
            neighbor; the caller waits AFTER both rings' copies are in
            flight."""
            scratch_ref[d, slot] = value
            rdma = pltpu.make_async_remote_copy(
                src_ref=scratch_ref.at[d, slot],
                dst_ref=comm_ref.at[d, slot],
                send_sem=send_sem.at[d],
                recv_sem=recv_sem.at[d],
                device_id=(neighbor[d],),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            return rdma

        def both_hops(slot, v_cw, v_ccw):
            r0 = start_hop(0, slot, v_cw)
            r1 = start_hop(1, slot, v_ccw)  # both directions in flight
            r0.wait()
            r1.wait()
            return comm_ref[0, slot], comm_ref[1, slot]

        def local_chunk(d, c):
            return pl.load(chunks_ref, (2 * c + d,))

        def rs_step(s, carry):
            p_cw, p_ccw = carry
            got_cw, got_ccw = both_hops(s % 2, p_cw, p_ccw)
            return (
                got_cw + local_chunk(0, jnp.mod(my - s, n)),
                got_ccw + local_chunk(1, jnp.mod(my + s, n)),
            )

        p_cw, p_ccw = lax.fori_loop(
            1, n, rs_step, (local_chunk(0, my), local_chunk(1, my))
        )
        pl.store(out_ref, (2 * jnp.mod(my + 1, n),), p_cw)
        pl.store(out_ref, (2 * jnp.mod(my - 1, n) + 1,), p_ccw)

        def ag_step(s, carry):
            c_cw, c_ccw = carry
            got_cw, got_ccw = both_hops(s % 2, c_cw, c_ccw)
            pl.store(out_ref, (2 * jnp.mod(my - s + 1, n),), got_cw)
            pl.store(out_ref, (2 * jnp.mod(my + s - 1, n) + 1,), got_ccw)
            return got_cw, got_ccw

        lax.fori_loop(1, n, ag_step, (p_cw, p_ccw))

    return kernel


def ring_all_reduce(x: jax.Array, axis_name: str, n: int) -> jax.Array:
    """Bidirectional Pallas remote-DMA ring all-reduce over ``axis_name``
    (TPU compiled mode only; see the module note on why the container
    cannot run it interpreted — any TRACE-time failure falls back to psum
    via :func:`all_reduce`, and the decode payloads are small enough that
    every operand sits in VMEM)."""
    from distributed_llama_tpu.ops.q40 import tpu_compiler_params

    params = tpu_compiler_params(has_side_effects=True, collective_id=0)
    if not params:
        # has_side_effects/collective_id are CORRECTNESS-critical for a
        # cross-device DMA kernel (DCE/reordering and the rendezvous id),
        # not droppable hints: a jax whose params class can't express them
        # must not run the ring at all (the seam converts this to psum)
        raise RuntimeError(
            "pallas compiler params lack has_side_effects/collective_id; "
            "refusing to build the ring kernel without them"
        )
    orig_shape = x.shape
    d = x.shape[-1]
    # 2n chunks: index 2c+0 rides the clockwise ring, 2c+1 the counter ring
    pad = (-d) % (2 * n)
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    flat = x.reshape(-1, x.shape[-1])
    chunks = jnp.stack(jnp.split(flat, 2 * n, axis=-1))  # [2n, rows, d/2n]
    slot = (2, 2) + chunks.shape[1:]
    out = pl.pallas_call(
        _make_ring_kernel(axis_name, n),
        out_shape=jax.ShapeDtypeStruct(chunks.shape, chunks.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM(slot, chunks.dtype),  # recv slots (remote writes)
            pltpu.VMEM(slot, chunks.dtype),  # send staging
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        **params,
    )(chunks)
    flat_out = jnp.concatenate(list(out), axis=-1)
    flat_out = flat_out[..., :d] if pad else flat_out
    return flat_out.reshape(orig_shape)
