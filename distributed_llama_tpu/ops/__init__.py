"""TPU compute kernels (Pallas) and quantized-tensor containers.

The reference's hand-written NEON/AVX2 kernels (src/funcs.cpp) map here:
matmul over Q40 weights is a Pallas kernel that keeps weights packed in HBM
and dequantizes in VMEM on the way into the MXU; everything else (rmsnorm,
softmax, silu/gelu, rope) is left to XLA fusion, which already emits optimal
VPU code for elementwise chains.
"""

from distributed_llama_tpu.ops.q40 import QuantizedMatrix, pack_q40_tpu, q40_matmul

__all__ = ["QuantizedMatrix", "pack_q40_tpu", "q40_matmul"]
