"""Fused Q40 matmul: weights stay 4-bit in HBM, dequantize in VMEM, MXU dot.

This replaces the reference's production kernel path — hand-written NEON/AVX2
`matmulQ40vQ80` (reference: src/funcs.cpp:287-396) — with a Pallas TPU kernel.
The reference's entire throughput story is "keep weights 4-bit so a Pi's
memory bus can feed the cores"; the TPU version is the same story at HBM
scale: a bf16 7B model is ~13.5 GB of HBM traffic per decoded token, the Q40
form is ~4.2 GB, so the bandwidth-bound decode roofline rises ~3×.

Layout (``pack_q40_tpu``): for a matmul ``y[T,d] = x[T,n] @ W[n,d]``, with
n padded to ``n_pad`` (zero-scale rows) and ``half = n_pad/2``:
  * ``qs``     uint8 [n_pad/2, d] — W[i,j] in the low nibble and
               W[i+half,j] in the high nibble ("half-split" pairing),
               values biased by +8 (the file format's bias, reference:
               src/quants.cpp:171-182)
  * ``scales`` f32 [n_pad/32, d] — per-(32-input-block, output-column) scale

The repack from the file's row-major block form is *exact*: nibbles are
reordered, never re-quantized. Half-split pairing is what makes the matmul
gather-free: the kernel contracts the low nibbles against x[:, :half] and
the high nibbles against x[:, half:] — two CONTIGUOUS windows of x (a
matmul contraction is permutation-invariant when both operands are permuted
alike). The previous even/odd-row pairing needed strided x[:, 0::2] splits,
which XLA lowers to gathers costing ~6 ms/token on a 7B decode.

On non-TPU backends (tests) the kernel runs in Pallas interpret mode.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_llama_tpu.quants import QK

# Tile sizes tuned on v5e (profiled in-model on real decode programs):
# (1024, 1024) runs the kernel at ~375 GB/s of packed bytes in a 7B decode;
# small divisor tiles (256x256) are ~10x slower — per-grid-step overhead
# dominates. Env overrides exist for tuning on other chip generations.
import os as _os

BLOCK_N = int(_os.environ.get("DLT_BN", 1024))  # input tile (multiple of 512:
# the x window needs bn/2 % 128 == 0 and the scales tile bn/64 % 8 == 0)
BLOCK_D = int(_os.environ.get("DLT_BD", 2048))  # output tile (multiple of 128;
# 2048 profiled ~4% faster than 1024 on v5e decode; T>8 shrinks it for VMEM)


# The pallas compiler-params class moved names across jax releases
# (CompilerParams on current jax, TPUCompilerParams on the container's
# 0.4.37); resolve whichever exists ONCE and soft-fall-back to no params —
# a missing class must cost the dimension-semantics hint, never the kernel
# (the same version-gate policy as the shard_map check_vma clamp).
_COMPILER_PARAMS_CLS = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)


def tpu_compiler_params(**kw) -> dict:
    """kwargs for ``pl.pallas_call``: ``{"compiler_params": ...}`` when the
    running jax exposes the class, ``{}`` otherwise (interpret mode ignores
    the params anyway, so the gate only changes what compiled TPU builds
    see)."""
    if _COMPILER_PARAMS_CLS is None:
        return {}
    try:
        return {"compiler_params": _COMPILER_PARAMS_CLS(**kw)}
    except TypeError:  # a param this jax's class doesn't know
        return {}


def _note_path(kernel: str, path: str) -> None:
    """Count one kernel-dispatch decision (trace-time — once per compiled
    program, not per token; docs/OBSERVABILITY.md `dllama_kernel_path_total`)."""
    from distributed_llama_tpu import telemetry

    telemetry.note_kernel_path(kernel, path)


def _validate_env_tiles() -> None:
    """Validates the DLT_BN/DLT_BD env overrides at first kernel use, not
    import time: a bad tuning value must fail pointing at the knob, not make
    the whole package (including --help) unimportable. Only the env-derived
    module defaults are checked (explicit block_n/block_d arguments have
    looser rules — _largest_divisor_tile snaps them to legal tiles)."""
    if BLOCK_N % 512 or BLOCK_N <= 0:
        raise ValueError(f"DLT_BN={BLOCK_N} must be a positive multiple of 512 "
                         "(otherwise every matmul silently takes the slow XLA fallback)")
    if BLOCK_D % 128 or BLOCK_D <= 0:
        raise ValueError(f"DLT_BD={BLOCK_D} must be a positive multiple of 128")


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedMatrix:
    """Q40 weight for ``x @ W``: packed nibbles + block scales.

    Registered as a pytree so it can live inside the params tree like a
    plain array. The packed arrays may be PADDED up to tile-friendly sizes
    (padding carries zero *scales*, so padded rows/columns dequantize to
    exact zeros); ``n``/``d`` are the logical (unpadded) matmul dims.

    ``interleaved``: the input rows are stored in the RETIRED
    block-interleaved basis (see the legacy section below) — such packs
    only exist transiently at load time now; every matmul entry point
    rejects them, and ``deinterleave_input_rows`` /
    ``weights.remove_basis_interleave`` move them back to the standard
    basis. ``packed_bn`` records the block_n the interleave was built for
    (the inverse gather needs exactly that window).
    """

    qs: jax.Array  # uint8 [..., n_pad/2, d_pad]
    scales: jax.Array  # f32 [..., n_pad/32, d_pad]
    n_logical: int = 0  # 0 = unpadded (use packed size)
    d_logical: int = 0
    interleaved: bool = False
    packed_bn: int = 0

    @property
    def n(self) -> int:
        return self.n_logical or self.qs.shape[-2] * 2

    @property
    def d(self) -> int:
        return self.d_logical or self.qs.shape[-1]

    @property
    def n_padded(self) -> int:
        return self.qs.shape[-2] * 2

    @property
    def d_padded(self) -> int:
        return self.qs.shape[-1]

    @property
    def shape(self) -> tuple[int, ...]:
        return (*self.qs.shape[:-2], self.n, self.d)

    @property
    def dtype(self):
        return jnp.bfloat16  # activation dtype the matmul expects

    def tree_flatten(self):
        return (self.qs, self.scales), (
            self.n_logical, self.d_logical, self.interleaved, self.packed_bn,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


def _n_padded(n: int) -> int:
    """Padded input dim: 512-multiples for kernel-eligible matrices (the
    scales-tile sublane rule needs block_n % 512 == 0), 64-multiples below
    that (half-split block alignment; such matrices take the XLA fallback)."""
    m = 512 if n > 512 else 64
    return -(-n // m) * m


def _d_padded(d: int) -> int:
    """Padded output dim: only pad dims that exceed the tile target — small
    matrices take small tiles (or the XLA fallback) without a blow-up."""
    return -(-d // 1024) * 1024 if d > 1024 else d


def _pack_halves(vals_t: np.ndarray, scales_t: np.ndarray, n: int, d: int) -> QuantizedMatrix:
    """Pack BIASED nibble values [n, d] into the half-split layout after
    zero-scale padding. Padded regions contribute exact zeros to the matmul
    (scale 0), so no output slicing is needed for chained layers — only
    logits consumers must trim to d_logical."""
    n_pad, d_pad = _n_padded(n), _d_padded(d)
    if n_pad != n or d_pad != d:
        vals_t = np.pad(vals_t, ((0, n_pad - n), (0, d_pad - d)))
        scales_t = np.pad(
            scales_t, ((0, n_pad // 32 - scales_t.shape[0]), (0, d_pad - d))
        )
    half = n_pad // 2
    packed = (vals_t[:half] | (vals_t[half:] << 4)).astype(np.uint8)
    return QuantizedMatrix(
        qs=jnp.asarray(packed), scales=jnp.asarray(scales_t),
        n_logical=n, d_logical=d,
    )


def pack_q40_tpu(file_qs: np.ndarray, file_scales: np.ndarray, shape: tuple[int, int]) -> QuantizedMatrix:
    """Repack file-form Q40 (row-major [d_out, d_in] blocks, reference:
    converter/writer.py:29-53) into the transposed TPU layout — exactly.

    ``file_qs``: uint8 [n_blocks, 16]; ``file_scales``: f16 [n_blocks];
    ``shape``: the file tensor's (d_out, d_in). Returns the packed form for
    computing ``x[T, d_in] @ W.T[d_in, d_out]``.
    """
    d_out, d_in = shape
    if d_in % QK:
        raise ValueError(f"d_in {d_in} not divisible by {QK}")
    blocks_per_row = d_in // QK

    try:  # native repack (native/q40_native.cpp) — same output, much faster
        from distributed_llama_tpu import native

        raw = np.empty((d_out * blocks_per_row, 2 + QK // 2), np.uint8)
        raw[:, :2] = (
            np.ascontiguousarray(file_scales).astype(np.float16).view(np.uint8).reshape(-1, 2)
        )
        raw[:, 2:] = np.asarray(file_qs).reshape(-1, QK // 2)
        fast = _pack_raw_native(native, raw.reshape(-1), d_out, d_in)
        if fast is not None:
            return fast
    except Exception:
        pass
    qs = file_qs.reshape(d_out, blocks_per_row, QK // 2)
    # biased nibble values 0..15 in file order: low nibble = value j,
    # high = value j+16 within the 32-block
    lo = qs & 0xF
    hi = qs >> 4
    vals = np.concatenate([lo, hi], axis=-1).reshape(d_out, d_in)  # uint8 biased
    scales = file_scales.reshape(d_out, blocks_per_row).astype(np.float32)
    return _pack_halves(
        np.ascontiguousarray(vals.T), np.ascontiguousarray(scales.T), d_in, d_out
    )


def _pack_raw_native(native, raw: np.ndarray, d_out: int, d_in: int):
    """Native half-split repack: the C++ side writes directly into the
    padded packed/scales arrays (padding rows are zero-scale)."""
    n_pad = _n_padded(d_in)
    out = native.q40_repack_tpu(raw, d_out, d_in, n_pad)
    if out is None:
        return None
    packed, scales = out
    d_pad = _d_padded(d_out)
    if d_pad != d_out:
        packed = np.pad(packed, ((0, 0), (0, d_pad - d_out)))
        scales = np.pad(scales, ((0, 0), (0, d_pad - d_out)))
    return QuantizedMatrix(
        qs=jnp.asarray(packed), scales=jnp.asarray(scales),
        n_logical=d_in, d_logical=d_out,
    )


def pack_q40_raw(raw: np.ndarray | bytes, shape: tuple[int, int]) -> QuantizedMatrix:
    """Repack a tensor directly from its raw `.m` bytes (the loader path).
    Uses the native repacker when built; falls back to numpy."""
    d_out, d_in = shape
    try:
        from distributed_llama_tpu import native

        fast = _pack_raw_native(native, np.frombuffer(raw, np.uint8), d_out, d_in)
        if fast is not None:
            return fast
    except Exception:
        pass
    from distributed_llama_tpu.quants import q40_from_bytes

    qs, scales = q40_from_bytes(raw, d_out * d_in)
    return pack_q40_tpu(qs, scales, shape)


def quantize_q40_tpu(w: np.ndarray) -> QuantizedMatrix:
    """Quantize a float matrix W [n, d] (already in x@W orientation) directly
    to the TPU layout. Quantization blocks run along the input dim n,
    mirroring the file format's along-row blocks after transpose (half-split
    pairing is on input rows, so d has no parity constraint)."""
    from distributed_llama_tpu.quants import quantize_q40

    n, d = w.shape
    qs_file, scales_file = quantize_q40(np.ascontiguousarray(w.T))  # blocks along n
    return pack_q40_tpu(
        qs_file.reshape(-1, QK // 2), scales_file.reshape(-1), (d, n)
    )


def concat_shard_packs(mats: list[QuantizedMatrix], axis: str) -> QuantizedMatrix:
    """Assemble per-shard packs into ONE host-layout matrix whose equal-size
    blocks along the sharded axis are the shards, so a ``device_put`` with a
    ``NamedSharding`` places each shard's pack on its device verbatim.

    ``axis``: "out" for output-dim (column) shards (qkv / gate_up / wcls —
    RowMatmulSlice layout, reference: src/commands.cpp:11-43), "in" for
    input-dim (row) shards (wo / down — ColMatmulSlice, :45-73).

    The returned aux dims (n_logical/d_logical) are the PER-SHARD logical
    dims: the matrix is only ever consumed inside shard_map, where each
    device sees exactly one shard's block.
    """
    m0 = mats[0]
    for m in mats[1:]:
        if m.qs.shape != m0.qs.shape or (m.n, m.d) != (m0.n, m0.d):
            raise ValueError("shard packs must be identically shaped")
    ax = -1 if axis == "out" else -2
    qs = np.concatenate([np.asarray(m.qs) for m in mats], axis=ax)
    scales = np.concatenate([np.asarray(m.scales) for m in mats], axis=ax)
    return QuantizedMatrix(qs, scales, n_logical=m0.n, d_logical=m0.d)


def dequantize_tpu(qm: QuantizedMatrix) -> np.ndarray:
    """Reference unpacking of the TPU layout → f32 [n, d] (standard basis).
    Trims any tile padding back to the logical dims."""
    if qm.interleaved:
        raise ValueError(
            "interleaved pack: the block-interleaved basis is retired — "
            "de-interleave at load (q40.deinterleave_input_rows / "
            "weights.remove_basis_interleave)"
        )
    qs = np.asarray(qm.qs)
    scales = np.asarray(qm.scales)
    # half-split: low nibbles are logical rows [0, half), high [half, n_pad)
    lo = (qs & 0xF).astype(np.int8) - 8
    hi = (qs >> 4).astype(np.int8) - 8
    vals = np.concatenate([lo, hi], axis=0)
    scale_full = np.repeat(scales, QK, axis=0)
    return (vals.astype(np.float32) * scale_full)[: qm.n, : qm.d]


# ---------------------------------------------------------------------------
# Legacy block-interleaved feature basis (migration shims only)
# ---------------------------------------------------------------------------
#
# Rounds 5-13 reordered kernel-eligible input rows so block membership was
# p % nb, letting the f32 VPU-dequant kernel broadcast scales with the cheap
# tiled pltpu.repeat (measured ~+18% on a 7B decode). The int8 MXU path made
# that win moot — its scale product is a per-block epilogue, not a per-row
# broadcast — so the basis (and its load-time permutes of every producer)
# is RETIRED: the kernels below dispatch on the standard basis only, and
# ``q40_matmul`` rejects interleaved packs outright. What remains here is
# the migration surface: the permutation math, the legacy producers (so
# tests can synthesize basis-era params trees), and the EXACT inverse
# gathers (``deinterleave_*``) that move an interleaved checkpoint back to
# the standard basis at load time (engine.weights.remove_basis_interleave).


def interleave_window(n_pad: int) -> int | None:
    """The packed-row window the interleave is built for: half the kernel's
    block_n tile. None = matrix not kernel-eligible (no interleave)."""
    bn = _largest_divisor_tile(n_pad, BLOCK_N, 512)
    # the hi half must start on a window boundary: (n_pad/2) % W == 0
    if bn is None or (n_pad // 2) % (bn // 2) != 0:
        return None
    return bn // 2


def interleave_perm(n: int, W: int) -> np.ndarray:
    """Permutation over a feature axis of size ``n`` (a multiple of W):
    new position p holds original feature perm[p]."""
    nb = W // QK
    o = np.arange(W)
    idx = (o % nb) * QK + o // nb  # in-window source offsets
    base = (np.arange(n) // W) * W
    return base + idx[np.arange(n) % W]


def interleave_input_rows(qm: QuantizedMatrix) -> QuantizedMatrix:
    """LEGACY producer: reorder a standard pack's input rows into the
    interleaved basis — a pure row gather (scales unchanged); exact. The
    runtime no longer consumes this basis; the producer is retained so
    migration tests can synthesize basis-era packs and round-trip them
    through :func:`deinterleave_input_rows`.
    Returns the matrix unchanged if not kernel-eligible or already done."""
    if qm.interleaved:
        return qm
    n_pad = qm.n_padded
    W = interleave_window(n_pad)
    if W is None:
        return qm
    half = n_pad // 2
    perm = jnp.asarray(interleave_perm(half, W))
    qs = jnp.take(jnp.asarray(qm.qs), perm, axis=0)
    return QuantizedMatrix(
        qs, qm.scales, qm.n_logical, qm.d_logical,
        interleaved=True, packed_bn=2 * W,
    )


def deinterleave_input_rows(qm: QuantizedMatrix) -> QuantizedMatrix:
    """The migration shim: move an interleaved pack's input rows back to
    the standard basis — the EXACT inverse gather of
    :func:`interleave_input_rows` (scales were never permuted, so only the
    packed qs rows move). Standard packs pass through unchanged, so the
    loader can apply this unconditionally to a checkpoint of unknown
    vintage."""
    if not qm.interleaved:
        return qm
    half = qm.n_padded // 2
    perm = interleave_perm(half, qm.packed_bn // 2)
    inv = jnp.asarray(np.argsort(perm))
    qs = jnp.take(jnp.asarray(qm.qs), inv, axis=0)
    return QuantizedMatrix(qs, qm.scales, qm.n_logical, qm.d_logical)


def deinterleave_output_cols(
    qm: QuantizedMatrix, n_consumer_logical: int, halves: int = 1
) -> QuantizedMatrix:
    """Inverse of :func:`interleaved_output_cols`: gather the producer's
    output columns back to the standard feature order and restore the
    original d padding (the consumer-basis pad positions sourced zero-scale
    columns, and zero-scale columns are exactly what the standard pack's d
    padding holds — so the round trip is bit-exact)."""
    npc = _n_padded(n_consumer_logical)
    W = interleave_window(npc)
    if W is None or qm.d != halves * npc:
        return qm  # never moved to the consumer basis
    perm = interleave_perm(npc, W)
    inv = np.argsort(perm)[:n_consumer_logical]  # drop consumer-basis pads
    cols = np.concatenate([h * npc + inv for h in range(halves)])
    d_orig = halves * n_consumer_logical
    d_pad = _d_padded(d_orig)
    qs = np.asarray(jnp.take(jnp.asarray(qm.qs), jnp.asarray(cols), axis=1))
    scales = np.asarray(
        jnp.take(jnp.asarray(qm.scales), jnp.asarray(cols), axis=1)
    )
    if d_pad != d_orig:
        qs = np.pad(qs, ((0, 0), (0, d_pad - d_orig)))
        scales = np.pad(scales, ((0, 0), (0, d_pad - d_orig)))
    return QuantizedMatrix(
        jnp.asarray(qs), jnp.asarray(scales), qm.n_logical, d_orig,
        interleaved=qm.interleaved, packed_bn=qm.packed_bn,
    )


def deinterleave_vector(v, n_logical: int):
    """Inverse of :func:`interleave_vector`: un-permute a feature vector
    (or an embedding table's last axis) and trim the basis padding."""
    npc = _n_padded(n_logical)
    W = interleave_window(npc)
    v = jnp.asarray(v)
    if W is None or v.shape[-1] != npc:
        return v
    perm = interleave_perm(npc, W)
    inv = jnp.asarray(np.argsort(perm))
    return jnp.take(v, inv, axis=-1)[..., :n_logical]


def interleaved_output_cols(
    qm: QuantizedMatrix, n_consumer_logical: int, halves: int = 1
) -> QuantizedMatrix:
    """Permute a producer's OUTPUT columns into the consumer basis's
    interleaved order, padding-aware: the consumer reads n_pad features, so
    positions mapping to original features >= n_consumer_logical source a
    zero-scale pad column (exact zeros). ``halves`` = 2 applies the same
    per-half permutation to a fused [a|b] output (gate_up). The returned
    d_logical grows to halves * n_pad_consumer — consumers must NOT trim."""
    d_pad_src = qm.d_padded
    npc = _n_padded(n_consumer_logical)
    W = interleave_window(npc)
    if W is None:
        return qm
    perm = interleave_perm(npc, W)
    cols = np.empty(halves * npc, np.int64)
    # a guaranteed zero-scale column for consumer-basis pad positions
    has_pad_col = d_pad_src > qm.d
    for h in range(halves):
        src_base = h * n_consumer_logical
        valid = perm < n_consumer_logical
        if not has_pad_col and not valid.all():
            raise ValueError(
                "consumer basis needs pad columns but the producer has no "
                f"zero d-padding (d={qm.d}, d_pad={d_pad_src})"
            )
        cols[h * npc : (h + 1) * npc] = np.where(
            valid, src_base + perm, d_pad_src - 1
        )
    cols_j = jnp.asarray(cols)
    return QuantizedMatrix(
        jnp.take(jnp.asarray(qm.qs), cols_j, axis=1),
        jnp.take(jnp.asarray(qm.scales), cols_j, axis=1),
        qm.n_logical, halves * npc,
        interleaved=qm.interleaved, packed_bn=qm.packed_bn,
    )


def interleave_vector(v, n_logical: int):
    """Permute a feature vector (rmsnorm weight) or the last axis of an
    embedding table into the interleaved basis; pads with zeros when the
    basis is padded."""
    npc = _n_padded(n_logical)
    W = interleave_window(npc)
    if W is None:
        return v
    perm = interleave_perm(npc, W)
    v = jnp.asarray(v)
    if v.shape[-1] < npc:
        pad = [(0, 0)] * (v.ndim - 1) + [(0, npc - v.shape[-1])]
        v = jnp.pad(v, pad)
    return jnp.take(v, jnp.asarray(perm), axis=-1)


def _make_q40_kernel(compute_dtype, interpret: bool = False):
    """Kernel factory: one (d-tile, n-tile) grid step dequantizes the weight
    tile in VMEM and accumulates into the f32 accumulator.

    Half-split pairing: the packed tile's low nibbles are logical rows
    [j*bn/2, (j+1)*bn/2) and the high nibbles rows half + the same window,
    so the two dots contract against two CONTIGUOUS windows of x delivered
    as separate BlockSpec views — no strided splits, no relayouts anywhere.

    ``compute_dtype`` is bf16 on TPU (Q40's quantization noise dwarfs bf16
    round-off, and bf16 halves VMEM footprint and VPU work) and f32 in
    interpret mode (XLA:CPU cannot execute bf16 x bf16 dots)."""

    def kernel(xlo_ref, xhi_ref, qs_ref, slo_ref, shi_ref, out_ref, acc_ref):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        qs = qs_ref[:].astype(jnp.int32)  # [bn/2, bd]; mosaic has no u8->f32 cast
        # nibbles stay BIASED (0..15): the -8 would cost two more full-size
        # VPU passes here; the caller subtracts 8*sum(x_block)@scales computed
        # on the MXU instead (see q40_matmul)
        lo = (qs & 0xF).astype(compute_dtype)
        # qs holds u8 values, so >>4 is already in 0..15 — no mask needed
        # (dropping the redundant & 0xF is worth ~25% on the VPU-bound unpack)
        hi = (qs >> 4).astype(compute_dtype)
        # CONSECUTIVE logical rows: each scale row broadcasts over its
        # 32-row block. jnp.repeat expands the SMALL scales tile to
        # [bn2, bd] and multiplies in 2-D — reshaping the big nibble
        # tile to [blocks, 32, bd] and back instead costs Mosaic
        # relayouts on the large array (measured 61 -> 68 tok/s
        # end-to-end on a 7B decode).
        wlo = lo * jnp.repeat(slo_ref[:].astype(compute_dtype), QK, axis=0)
        whi = hi * jnp.repeat(shi_ref[:].astype(compute_dtype), QK, axis=0)
        acc_ref[:] += jnp.dot(xlo_ref[:], wlo, preferred_element_type=jnp.float32)
        acc_ref[:] += jnp.dot(xhi_ref[:], whi, preferred_element_type=jnp.float32)

        @pl.when(j == pl.num_programs(1) - 1)
        def _():
            out_ref[:] = acc_ref[:]

    return kernel


def _resolve_tiles(qm: QuantizedMatrix, T: int, block_n: int, block_d: int):
    """The kernel-eligibility decision, shared by every path: (bn, bd)
    tiles dividing the padded dims, or None → the XLA fallback. block_n
    granule 512: the x window (T, bn/2) needs bn/2 % 128 == 0 and the
    scales tile (bn/64, bd) needs bn/64 % 8 == 0 (mosaic sublane/lane
    tiling rules) — smaller matrices take the XLA fallback."""
    _validate_env_tiles()
    block_d = _shrink_block_d(T, block_d)
    block_n = _largest_divisor_tile(qm.n_padded, block_n, 512)
    block_d = _largest_divisor_tile(qm.d_padded, block_d, 128)
    if block_n is None or block_d is None:
        return None
    return block_n, block_d


def default_q40_path() -> str:
    """The q40 kernel path when the caller doesn't pin one: the int8 MXU
    Q40×Q80 kernel where it runs interpreted (CPU — the parity-gated
    mode), the chip-proven f32-dequant kernel on accelerators until a
    chip smoke validates the int8 Mosaic build (its per-block batched
    ``dot_general`` has never been lowered on hardware; a failure would
    surface at XLA compile of the whole decode program, past any
    fallback — the same prudence as the fused-attention and ring
    defaults). ``DLT_Q40_INT8=1`` opts the int8 kernel in anywhere,
    ``=0`` pins f32. Read per dispatch decision (trace time)."""
    env = _os.environ.get("DLT_Q40_INT8")
    if env is not None:
        return "int8" if env != "0" else "f32"
    return "int8" if jax.devices()[0].platform == "cpu" else "f32"


def q40_matmul(
    x: jax.Array,
    qm: QuantizedMatrix,
    block_n: int = BLOCK_N,
    block_d: int = BLOCK_D,
    interpret: bool | None = None,
    path: str | None = None,
) -> jax.Array:
    """y[T, d] = x[T, n] @ dequant(qm), f32 accumulation — the ONE Q40
    matmul entry point (``models.llama._matmul`` routes every quantized
    weight through here). Dispatches between three implementations behind
    one signature:

    * ``"int8"`` (default): the int8 MXU kernel — activations quantized to
      Q80 (per-32-block int8 + f32 scale), per-block exact int32
      accumulation on the MXU, scale-product epilogue (ROADMAP item 1).
    * ``"f32"``: the round-5 VPU-dequant kernel (nibbles cast+scaled in
      VMEM, bf16 MXU dots) — the fallback path for the int8 A/B.
    * XLA fallback for matrices too small/odd to tile (either ``path``).

    Every dispatch decision is counted in ``dllama_kernel_path_total``
    (mxu_int8 / mxu_int8_fusedq / vpu_f32 / xla_fallback) so a silent
    fallback to the slow path is visible in /metrics."""
    if qm.interleaved:
        raise ValueError(
            "interleaved pack: the block-interleaved basis is retired — "
            "de-interleave at load (q40.deinterleave_input_rows / "
            "weights.remove_basis_interleave)"
        )
    tiles = _resolve_tiles(qm, x.shape[0], block_n, block_d)
    if tiles is None:
        _note_path("q40_matmul", "xla_fallback")
        return _q40_matmul_fallback_jit(x, qm)
    if interpret is None:
        # platform may be a plugin name (not literally "tpu"); interpret only
        # on CPU, where mosaic can't compile
        interpret = jax.devices()[0].platform == "cpu"
    if path is None:
        path = default_q40_path()
    bn, bd = tiles
    if path == "int8":
        _note_path("q40_matmul", "mxu_int8")
        return _q40_matmul_int8(x, qm, bn, bd, interpret)
    _note_path("q40_matmul", "vpu_f32")
    return _q40_matmul_f32(x, qm, bn, bd, interpret)


@functools.partial(jax.jit, static_argnames=("block_n", "block_d", "interpret"))
def _q40_matmul_f32(
    x: jax.Array,
    qm: QuantizedMatrix,
    block_n: int,
    block_d: int,
    interpret: bool,
) -> jax.Array:
    """The f32-dequant kernel path: tiles are pre-resolved (the dispatch in
    :func:`q40_matmul` owns eligibility); internally the kernel runs on the
    padded arrays (zero-scale padding → exact-zero contributions) and trims
    the output."""
    n, d = qm.n, qm.d
    np_, dp = qm.n_padded, qm.d_padded
    T = x.shape[0]

    if x.shape[-1] != np_:
        x = jnp.pad(x, ((0, 0), (0, np_ - x.shape[-1])))
    compute_dtype = jnp.float32 if interpret else jnp.bfloat16
    xb = x.astype(compute_dtype)
    nj = np_ // block_n
    grid = (dp // block_d, nj)
    # x is NOT split on the host: the lo/hi halves arrive as two BlockSpec
    # views over the same array — window j for the low nibbles, window
    # nj + j (the upper half) for the high nibbles. Contiguous, gather-free.
    out = pl.pallas_call(
        _make_q40_kernel(compute_dtype, interpret=interpret),
        grid=grid,
        in_specs=[
            pl.BlockSpec((T, block_n // 2), lambda i, j: (0, j)),
            pl.BlockSpec((T, block_n // 2), lambda i, j, nj=nj: (0, nj + j)),
            pl.BlockSpec((block_n // 2, block_d), lambda i, j: (j, i)),
            pl.BlockSpec((block_n // 2 // QK, block_d), lambda i, j: (j, i)),
            pl.BlockSpec((block_n // 2 // QK, block_d), lambda i, j, nj=nj: (nj + j, i)),
        ],
        out_specs=pl.BlockSpec((T, block_d), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((T, dp), jnp.float32),
        scratch_shapes=[pltpu.VMEM((T, block_d), jnp.float32)],
        interpret=interpret,
        **tpu_compiler_params(dimension_semantics=("parallel", "arbitrary")),
    )(xb, xb, qm.qs, qm.scales, qm.scales)
    # the kernel dequantized BIASED nibbles (0..15); subtract the +8 bias as
    # a rank-reduced correction on the MXU instead of 2 VPU passes over every
    # weight element: sum(x per 32-block) @ scales = sum_i x_i * s_b(i),d.
    # The sum MUST accumulate in f32: the correction is ~5x the output
    # magnitude, so bf16 accumulation error here would dominate the result
    # (measured 6x accuracy loss) — f32 makes it the exact sum of the same
    # bf16 x values the kernel consumed.
    xsum = jnp.sum(xb.astype(jnp.float32).reshape(T, np_ // QK, QK), axis=-1)
    corr = jax.lax.dot_general(
        xsum, qm.scales,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        # true-f32 multiplies: the correction cancels against a 5x-larger
        # kernel sum, so TPU's default bf16 demotion would leak error; the
        # dot is rank-n/32 — 3-pass f32 costs nothing measurable
        precision=jax.lax.Precision.HIGHEST,
    )
    out = out - 8.0 * corr
    return out[:, :d] if dp != d else out


# ---------------------------------------------------------------------------
# int8 MXU path: Q40 weights × Q80 activations (ROADMAP item 1)
# ---------------------------------------------------------------------------
#
# The f32 kernel above is VPU-bound in the nibble unpack: every weight
# element pays a cast + mask/shift + scale multiply on the 8×128 VPU before
# the MXU sees it (PERF.md measured ~55% of HBM roofline; the numerically-
# wrong pltpu.repeat experiment bounded the remaining VPU-broadcast win at
# ~+9%). The int8 path moves the arithmetic onto the MXU's native int8
# systolic array instead (reference: matmulQ40vQ80, src/funcs.cpp:287-396 —
# the reference's production combination for exactly this reason):
#
#   * activations quantize to Q80 — per-32-block int8 + f32 scale, the
#     reference's buffer format — ONE cheap pass over the [T, n] x (tiny
#     next to the [n, d] weight);
#   * the kernel contracts BIASED int8 nibbles against int8 activations
#     with exact int32 accumulation, one 32-deep dot PER QUANT BLOCK: the
#     pack layout is restructured (reshape, not relayout — the half-split
#     windows already group whole blocks) so the blocks ride the MXU batch
#     axis while the 128-multiple output tile fills the 128-wide lane axis
#     of the contraction;
#   * the scale product sx[t,b]·sw[b,d] folds in AFTER the integer dot (a
#     [T, nb, bd]-sized epilogue — 32× less VPU work than scaling every
#     weight element, and exact: int32 block sums are exact, so the only
#     new noise is the Q80 activation rounding itself, ~0.4% per element
#     against Q40's own ~3%);
#   * the +8 nibble bias stays a rank-reduced MXU correction exactly like
#     the f32 path, computed from the DEQUANTIZED Q80 block sums (the same
#     values the kernel consumed, so the cancellation is exact in f32).


def quantize_q80(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Quantize activations [T, n_pad] to Q80: (int8 values [T, n_pad],
    f32 scales [T, n_pad/32]), one scale per 32 consecutive elements —
    the standard basis, matching the weight scales' block order directly
    (symmetric, scale = max|x|/127 — the reference's Q80 rule,
    src/quants.cpp:98-122)."""
    T = x.shape[0]
    np_ = x.shape[-1]
    xf = x.astype(jnp.float32)
    xb = xf.reshape(T, np_ // QK, QK)
    amax = jnp.max(jnp.abs(xb), axis=-1)
    sx = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xb / sx[..., None]), -127, 127).astype(jnp.int8)
    return q.reshape(T, np_), sx


def _make_q40_int8_kernel():
    """int8 MXU kernel factory: one (d-tile, n-tile) grid step runs one
    exact int32 block-dot per quant block and folds the scale products into
    the f32 accumulator.

    Block layout per half-split window (bn2 = block_n/2 packed rows,
    nbt = bn2/32 blocks): 32 CONSECUTIVE rows per block → reshape
    [bn2, bd] → [nbt, 32, bd] — a pure reshape of the resident tile (the
    layout restructuring is free) feeding ONE batched ``dot_general`` with
    the blocks on the batch axis, 32-deep int8 contraction, and the
    128-multiple output tile on the lane axis; int32 accumulation is
    exact."""

    def kernel(xlo_ref, xhi_ref, sxlo_ref, sxhi_ref, qs_ref, slo_ref,
               shi_ref, out_ref, acc_ref):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        qs = qs_ref[:]
        # nibbles stay BIASED (0..15, exact in int8); the -8 is the caller's
        # rank-reduced MXU correction, same as the f32 kernel
        lo = (qs & 0xF).astype(jnp.int8)
        hi = (qs >> 4).astype(jnp.int8)
        bn2, bd = qs.shape
        nbt = bn2 // QK

        def half(xq_ref, sx_ref, w_nibbles, sw_ref):
            T = xq_ref.shape[0]
            xb = xq_ref[:].reshape(T, nbt, QK)
            wb = w_nibbles.reshape(nbt, QK, bd)
            # exact per-block int32 accumulation on the MXU int8 path
            P = jax.lax.dot_general(
                xb, wb, (((2,), (1,)), ((1,), (0,))),
                preferred_element_type=jnp.int32,
            )  # [nbt, T, bd]
            # scale-product epilogue: sum_b sx[t,b] * sw[b,d] * P[b,t,d] —
            # [T, nbt, bd]-sized VPU work vs the f32 kernel's per-weight-
            # element scale multiply
            scaled = P.astype(jnp.float32) * sw_ref[:][:, None, :]
            return jnp.sum(scaled * jnp.transpose(sx_ref[:])[:, :, None], axis=0)

        acc_ref[:] += half(xlo_ref, sxlo_ref, lo, slo_ref)
        acc_ref[:] += half(xhi_ref, sxhi_ref, hi, shi_ref)

        @pl.when(j == pl.num_programs(1) - 1)
        def _():
            out_ref[:] = acc_ref[:]

    return kernel


def _int8_core(
    xq: jax.Array,
    sx: jax.Array,
    qm: QuantizedMatrix,
    block_n: int,
    block_d: int,
    interpret: bool,
) -> jax.Array:
    """The int8 kernel launch + bias epilogue on ALREADY-QUANTIZED Q80
    activations (xq int8 [T, n_pad], sx f32 [T, n_pad/32]) — shared by the
    standalone matmul, the fused rmsnorm→Q80 entry, and the fused
    matmul+all-reduce seam (ops.collectives), so every fusion is
    arithmetic-identical to the standalone path by construction. Not
    jitted: callers own the program boundary."""
    d, dp = qm.d, qm.d_padded
    np_ = qm.n_padded
    T = xq.shape[0]
    nj = np_ // block_n
    grid = (dp // block_d, nj)
    nbt = block_n // 2 // QK
    out = pl.pallas_call(
        _make_q40_int8_kernel(),
        grid=grid,
        in_specs=[
            # Q80 activations: lo/hi halves as two contiguous BlockSpec
            # views, exactly like the f32 kernel's x windows
            pl.BlockSpec((T, block_n // 2), lambda i, j: (0, j)),
            pl.BlockSpec((T, block_n // 2), lambda i, j, nj=nj: (0, nj + j)),
            # per-block activation scales, same window split
            pl.BlockSpec((T, nbt), lambda i, j: (0, j)),
            pl.BlockSpec((T, nbt), lambda i, j, nj=nj: (0, nj + j)),
            pl.BlockSpec((block_n // 2, block_d), lambda i, j: (j, i)),
            pl.BlockSpec((nbt, block_d), lambda i, j: (j, i)),
            pl.BlockSpec((nbt, block_d), lambda i, j, nj=nj: (nj + j, i)),
        ],
        out_specs=pl.BlockSpec((T, block_d), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((T, dp), jnp.float32),
        scratch_shapes=[pltpu.VMEM((T, block_d), jnp.float32)],
        interpret=interpret,
        **tpu_compiler_params(dimension_semantics=("parallel", "arbitrary")),
    )(xq, xq, sx, sx, qm.qs, qm.scales, qm.scales)
    # bias correction on the DEQUANTIZED Q80 block sums: sum_{i in b} of
    # sx[t,b]*xq[t,i] — f32-exact given the int sums are exact
    qsum = jnp.sum(xq.astype(jnp.float32).reshape(T, np_ // QK, QK), axis=-1)
    xsum = sx * qsum
    corr = jax.lax.dot_general(
        xsum, qm.scales,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    out = out - 8.0 * corr
    return out[:, :d] if dp != d else out


@functools.partial(jax.jit, static_argnames=("block_n", "block_d", "interpret"))
def _q40_matmul_int8(
    x: jax.Array,
    qm: QuantizedMatrix,
    block_n: int,
    block_d: int,
    interpret: bool,
) -> jax.Array:
    """The int8 MXU path of :func:`q40_matmul`: Q80-quantize x, run the
    per-block int8 kernel, subtract the +8 bias as the rank-reduced MXU
    correction computed from the DEQUANTIZED Q80 sums (exactly the values
    the kernel consumed, so the f32 cancellation is exact)."""
    np_ = qm.n_padded
    if x.shape[-1] != np_:
        x = jnp.pad(x, ((0, 0), (0, np_ - x.shape[-1])))
    xq, sx = quantize_q80(x)
    return _int8_core(xq, sx, qm, block_n, block_d, interpret)


# ---------------------------------------------------------------------------
# Fused rmsnorm → Q80 quantize → int8 matmul (decode superstep, part a)
# ---------------------------------------------------------------------------
#
# At T=1 the standalone Q80 quantize is one whole extra program per matmul
# (dispatch overhead ≈ the quantize's own arithmetic), and XLA cannot fuse
# across the pallas_call boundary. Folding the rmsnorm AND the quantize
# into the same jitted program as the kernel launch deletes that boundary:
# rmsnorm → cast → pad → quantize → kernel is ONE program, with the
# quantize fused into the rmsnorm epilogue by XLA (both are elementwise
# over [T, n]).


def rmsnorm_ref(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMS-normalize over the last axis (f32 math, result in x.dtype) —
    THE reference rmsnorm: ``models.llama.rmsnorm`` delegates here and the
    fused entry below inlines these exact ops, so the fused/unfused paths
    are bit-identical by construction (test-enforced in
    tests/test_kernel_parity.py)."""
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (weight.astype(jnp.float32) * (xf * jax.lax.rsqrt(ms + eps))).astype(x.dtype)


def _fused_q80_enabled() -> bool:
    """DLT_FUSED_Q80=0 pins the standalone quantize (A/B arm); default on —
    the fusion reuses the parity-gated int8 kernel unchanged, so the only
    behavior change is the number of program boundaries. Accelerator
    prudence is inherited from :func:`default_q40_path`: the fusion only
    engages when the path resolves to int8."""
    env = _os.environ.get("DLT_FUSED_Q80")
    return env != "0" if env is not None else True


@functools.partial(
    jax.jit, static_argnames=("block_n", "block_d", "interpret", "eps")
)
def _rmsnorm_q40_matmul_int8(
    x: jax.Array,
    weight: jax.Array,
    qm: QuantizedMatrix,
    block_n: int,
    block_d: int,
    interpret: bool,
    eps: float,
) -> jax.Array:
    # the EXACT unfused op sequence — rmsnorm_ref ops, the bf16 activation
    # cast models.llama._matmul would apply, end-padding, quantize — in one
    # program; any arithmetic drift here breaks the fused-vs-unfused
    # bit-parity gate
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    xn = (weight.astype(jnp.float32) * (xf * jax.lax.rsqrt(ms + eps))).astype(x.dtype)
    xb = xn.astype(jnp.bfloat16)
    np_ = qm.n_padded
    if xb.shape[-1] != np_:
        xb = jnp.pad(xb, ((0, 0), (0, np_ - xb.shape[-1])))
    xq, sx = quantize_q80(xb)
    return _int8_core(xq, sx, qm, block_n, block_d, interpret)


def rmsnorm_q40_matmul(
    x: jax.Array,
    weight: jax.Array,
    qm: QuantizedMatrix,
    eps: float = 1e-5,
    block_n: int = BLOCK_N,
    block_d: int = BLOCK_D,
    interpret: bool | None = None,
    path: str | None = None,
) -> jax.Array:
    """y = rmsnorm(x, weight) @ dequant(qm) as ONE fused program when the
    int8 kernel path is eligible (noted ``mxu_int8_fusedq``); otherwise the
    unfused reference sequence through :func:`q40_matmul` (which notes its
    own path). Bit-identical to the unfused sequence either way."""
    if qm.interleaved:
        raise ValueError(
            "interleaved pack: the block-interleaved basis is retired — "
            "de-interleave at load (q40.deinterleave_input_rows / "
            "weights.remove_basis_interleave)"
        )
    tiles = _resolve_tiles(qm, x.shape[0], block_n, block_d)
    if path is None:
        path = default_q40_path()
    if tiles is None or path != "int8" or not _fused_q80_enabled():
        # the standalone rmsnorm is its own program ahead of the matmul's —
        # counted so dllama_kernel_path_total sums to programs-per-step
        # (the fused path absorbs it; docs/OBSERVABILITY.md)
        _note_path("rmsnorm", "xla_standalone")
        xb = rmsnorm_ref(x, weight, eps).astype(jnp.bfloat16)
        return q40_matmul(xb, qm, block_n, block_d, interpret, path)
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    bn, bd = tiles
    _note_path("q40_matmul", "mxu_int8_fusedq")
    return _rmsnorm_q40_matmul_int8(x, weight, qm, bn, bd, interpret, eps)


def _shrink_block_d(T: int, block_d: int) -> int:
    """Batch-size-dependent output-tile cap, tuned on the real v5e by
    measuring the FULL 7B prefill program per config (round 5; per-kernel
    microbenchmarks are unusable behind the tunnel — the ~100 ms round trip
    jitter swamps sub-ms kernels):

      T=16:  bd512 15.9 ms | bd2048 21.2      -> keep 512
      T=32:  bd512 17.4 | bd1024 14.7 | bd2048 16.1 -> 1024
      T=64:  bd512 24.0 | bd1024 16.8 | bd2048 14.8 -> full (38% faster
             than the round-4 decode-tuned 512 cap)
      T=128: bd512 21.3 | bd2048 17.5           -> full
      T=256: bd256 34.2 | bd2048 30.5           -> full
      T=512: bd2048 fails to compile (VMEM), bd1024 75.8 | bd256 84.8 -> 1024

    DLT_NO_SHRINK=1 disables the cap (tile-tuning experiments only)."""
    if _os.environ.get("DLT_NO_SHRINK"):
        return block_d
    if T <= 8:
        return block_d  # decode regime: 2048 profiled ~4% over 1024 (round 3)
    if T <= 16:
        return min(block_d, 512)
    if T <= 32 or T > 256:
        return min(block_d, 1024)
    return block_d


def _largest_divisor_tile(dim: int, target: int, granule: int) -> int | None:
    """Largest multiple of ``granule`` that divides ``dim`` and is ≤ target."""
    if dim % granule:
        return None
    best = None
    for k in range(1, target // granule + 1):
        b = k * granule
        if dim % b == 0:
            best = b
    return best


@jax.jit
def _q40_matmul_fallback_jit(x: jax.Array, qm: QuantizedMatrix) -> jax.Array:
    return _q40_matmul_fallback(x, qm)


def _q40_matmul_fallback(x: jax.Array, qm: QuantizedMatrix) -> jax.Array:
    np_, dp = qm.n_padded, qm.d_padded
    lo = (qm.qs & 0xF).astype(jnp.int8) - 8
    hi = (qm.qs >> 4).astype(jnp.int8) - 8
    # half-split: low nibbles are rows [0, half), high [half, n_pad)
    w_int = jnp.concatenate([lo, hi], axis=-2)
    w = w_int.astype(jnp.float32).reshape(-1, QK, dp) * qm.scales[..., None, :]
    w = w.reshape(np_, dp)
    if x.shape[-1] != np_:
        x = jnp.pad(x, ((0, 0), (0, np_ - x.shape[-1])))
    out = jax.lax.dot_general(
        x.astype(jnp.float32),
        w,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    return out[:, : qm.d] if dp != qm.d else out
