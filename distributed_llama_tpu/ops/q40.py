"""Fused Q40 matmul: weights stay 4-bit in HBM, dequantize in VMEM, MXU dot.

This replaces the reference's production kernel path — hand-written NEON/AVX2
`matmulQ40vQ80` (reference: src/funcs.cpp:287-396) — with a Pallas TPU kernel.
The reference's entire throughput story is "keep weights 4-bit so a Pi's
memory bus can feed the cores"; the TPU version is the same story at HBM
scale: a bf16 7B model is ~13.5 GB of HBM traffic per decoded token, the Q40
form is ~4.2 GB, so the bandwidth-bound decode roofline rises ~3×.

Layout (``pack_q40_tpu``): for a matmul ``y[T,d] = x[T,n] @ W[n,d]``
  * ``qs``     uint8 [n/2, d] — W[2i,j] in the low nibble, W[2i+1,j] in the
               high nibble, values biased by +8 (the file format's bias,
               reference: src/quants.cpp:171-182)
  * ``scales`` f32 [n/32, d] — per-(32-input-block, output-column) scale

The repack from the file's row-major block form is *exact*: nibbles are
reordered, never re-quantized. Unpacking in-kernel is two masks and a
sub; the dequantized tile feeds ``jnp.dot`` with f32 accumulation.

On non-TPU backends (tests) the kernel runs in Pallas interpret mode.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_llama_tpu.quants import QK

# Tile sizes tuned on v5e (slope-timed to exclude the remote tunnel's fixed
# dispatch cost): with the split-x kernel, (1024, 1024) runs a 4096x11008
# T=1 matvec at ~300 GB/s of packed bytes vs ~45 GB/s for the old
# interleaving kernel. Small divisor tiles (256x256) are ~10x slower — the
# per-grid-step overhead dominates.
BLOCK_N = 1024  # input-dim tile (must be a multiple of 32)
BLOCK_D = 1024  # output-dim tile (must be a multiple of 128)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedMatrix:
    """Q40 weight for ``x @ W``: packed nibbles + block scales.

    Registered as a pytree so it can live inside the params tree like a
    plain array. The packed arrays may be PADDED up to tile-friendly sizes
    (padding carries zero *scales*, so padded rows/columns dequantize to
    exact zeros); ``n``/``d`` are the logical (unpadded) matmul dims.
    """

    qs: jax.Array  # uint8 [..., n_pad/2, d_pad]
    scales: jax.Array  # f32 [..., n_pad/32, d_pad]
    n_logical: int = 0  # 0 = unpadded (use packed size)
    d_logical: int = 0

    @property
    def n(self) -> int:
        return self.n_logical or self.qs.shape[-2] * 2

    @property
    def d(self) -> int:
        return self.d_logical or self.qs.shape[-1]

    @property
    def n_padded(self) -> int:
        return self.qs.shape[-2] * 2

    @property
    def d_padded(self) -> int:
        return self.qs.shape[-1]

    @property
    def shape(self) -> tuple[int, ...]:
        return (*self.qs.shape[:-2], self.n, self.d)

    @property
    def dtype(self):
        return jnp.bfloat16  # activation dtype the matmul expects

    def tree_flatten(self):
        return (self.qs, self.scales), (self.n_logical, self.d_logical)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


def _pad_packed(packed: np.ndarray, scales: np.ndarray, n: int, d: int,
                n_mult: int = 512, d_mult: int = 1024) -> QuantizedMatrix:
    """Zero-scale padding up to tile multiples. Padded regions contribute
    exact zeros to the matmul (scale 0), so no output slicing is needed for
    chained layers — only logits consumers must trim to d_logical."""
    # only pad dims that exceed the tile target — small matrices take small
    # tiles (or the XLA fallback) without a padding blow-up
    n_pad = -(-n // n_mult) * n_mult if n > n_mult else n
    d_pad = -(-d // d_mult) * d_mult if d > d_mult else d
    if n_pad != n or d_pad != d:
        packed = np.pad(packed, ((0, (n_pad - n) // 2), (0, d_pad - d)))
        scales = np.pad(scales, ((0, (n_pad - n) // 32), (0, d_pad - d)))
    return QuantizedMatrix(
        qs=jnp.asarray(packed), scales=jnp.asarray(scales),
        n_logical=n, d_logical=d,
    )


def pack_q40_tpu(file_qs: np.ndarray, file_scales: np.ndarray, shape: tuple[int, int]) -> QuantizedMatrix:
    """Repack file-form Q40 (row-major [d_out, d_in] blocks, reference:
    converter/writer.py:29-53) into the transposed TPU layout — exactly.

    ``file_qs``: uint8 [n_blocks, 16]; ``file_scales``: f16 [n_blocks];
    ``shape``: the file tensor's (d_out, d_in). Returns the packed form for
    computing ``x[T, d_in] @ W.T[d_in, d_out]``.
    """
    d_out, d_in = shape
    if d_in % QK:
        raise ValueError(f"d_in {d_in} not divisible by {QK}")
    if d_out % 2:
        raise ValueError(f"d_out {d_out} must be even for nibble pairing")
    blocks_per_row = d_in // QK

    try:  # native repack (native/q40_native.cpp) — same output, much faster
        from distributed_llama_tpu import native

        raw = np.empty((d_out * blocks_per_row, 2 + QK // 2), np.uint8)
        raw[:, :2] = (
            np.ascontiguousarray(file_scales).astype(np.float16).view(np.uint8).reshape(-1, 2)
        )
        raw[:, 2:] = np.asarray(file_qs).reshape(-1, QK // 2)
        fast = native.q40_repack_tpu(raw.reshape(-1), d_out, d_in)
        if fast is not None:
            packed_n, scales_n = fast
            return _pad_packed(packed_n, scales_n, d_in, d_out)
    except Exception:
        pass
    qs = file_qs.reshape(d_out, blocks_per_row, QK // 2)
    # biased nibble values 0..15 in file order: low nibble = value j,
    # high = value j+16 within the 32-block
    lo = qs & 0xF
    hi = qs >> 4
    vals = np.concatenate([lo, hi], axis=-1).reshape(d_out, d_in)  # uint8 biased
    scales = file_scales.reshape(d_out, blocks_per_row).astype(np.float32)

    vals_t = vals.T  # [d_in, d_out]
    packed = (vals_t[0::2] | (vals_t[1::2] << 4)).astype(np.uint8)  # [d_in/2, d_out]
    return _pad_packed(packed, np.ascontiguousarray(scales.T), d_in, d_out)


def pack_q40_raw(raw: np.ndarray | bytes, shape: tuple[int, int]) -> QuantizedMatrix:
    """Repack a tensor directly from its raw `.m` bytes (the loader path).
    Uses the native repacker when built; falls back to numpy."""
    d_out, d_in = shape
    try:
        from distributed_llama_tpu import native

        fast = native.q40_repack_tpu(np.frombuffer(raw, np.uint8), d_out, d_in)
        if fast is not None:
            packed, scales = fast
            return _pad_packed(packed, scales, d_in, d_out)
    except Exception:
        pass
    from distributed_llama_tpu.quants import q40_from_bytes

    qs, scales = q40_from_bytes(raw, d_out * d_in)
    return pack_q40_tpu(qs, scales, shape)


def quantize_q40_tpu(w: np.ndarray) -> QuantizedMatrix:
    """Quantize a float matrix W [n, d] (already in x@W orientation) directly
    to the TPU layout. Quantization blocks run along the input dim n,
    mirroring the file format's along-row blocks after transpose. An odd
    output dim is zero-padded to even (nibble pairing needs row pairs)."""
    from distributed_llama_tpu.quants import quantize_q40

    n, d = w.shape
    d_even = d + (d % 2)
    if d_even != d:
        w = np.pad(w, ((0, 0), (0, 1)))
    qs_file, scales_file = quantize_q40(np.ascontiguousarray(w.T))  # blocks along n
    qm = pack_q40_tpu(
        qs_file.reshape(-1, QK // 2), scales_file.reshape(-1), (d_even, n)
    )
    if d_even != d:
        qm = QuantizedMatrix(qm.qs, qm.scales, n_logical=qm.n, d_logical=d)
    return qm


def concat_shard_packs(mats: list[QuantizedMatrix], axis: str) -> QuantizedMatrix:
    """Assemble per-shard packs into ONE host-layout matrix whose equal-size
    blocks along the sharded axis are the shards, so a ``device_put`` with a
    ``NamedSharding`` places each shard's pack on its device verbatim.

    ``axis``: "out" for output-dim (column) shards (qkv / gate_up / wcls —
    RowMatmulSlice layout, reference: src/commands.cpp:11-43), "in" for
    input-dim (row) shards (wo / down — ColMatmulSlice, :45-73).

    The returned aux dims (n_logical/d_logical) are the PER-SHARD logical
    dims: the matrix is only ever consumed inside shard_map, where each
    device sees exactly one shard's block.
    """
    m0 = mats[0]
    for m in mats[1:]:
        if m.qs.shape != m0.qs.shape or (m.n, m.d) != (m0.n, m0.d):
            raise ValueError("shard packs must be identically shaped")
    ax = -1 if axis == "out" else -2
    qs = np.concatenate([np.asarray(m.qs) for m in mats], axis=ax)
    scales = np.concatenate([np.asarray(m.scales) for m in mats], axis=ax)
    return QuantizedMatrix(qs, scales, n_logical=m0.n, d_logical=m0.d)


def dequantize_tpu(qm: QuantizedMatrix) -> np.ndarray:
    """Reference unpacking of the TPU layout → f32 [n, d] (for tests).
    Trims any tile padding back to the logical dims."""
    qs = np.asarray(qm.qs)
    scales = np.asarray(qm.scales)
    n2, d = qs.shape
    vals = np.empty((n2 * 2, d), np.int8)
    vals[0::2] = (qs & 0xF).astype(np.int8) - 8
    vals[1::2] = (qs >> 4).astype(np.int8) - 8
    scale_full = np.repeat(scales, QK, axis=0)
    return (vals.astype(np.float32) * scale_full)[: qm.n, : qm.d]


def _make_q40_kernel(compute_dtype):
    """Kernel factory: one (d-tile, n-tile) grid step dequantizes the weight
    tile in VMEM and accumulates into the f32 accumulator.

    The packed tile's low nibbles are even input rows, high nibbles odd rows.
    Instead of interleaving them back to natural order (a sublane relayout
    that dominated the old kernel's runtime, ~6x slower), the caller splits x
    into even/odd columns once outside and the kernel runs two half-size dots
    — a matmul's contraction is permutation-invariant when both operands are
    permuted alike.

    ``compute_dtype`` is bf16 on TPU (Q40's quantization noise dwarfs bf16
    round-off, and bf16 halves VMEM footprint and VPU work) and f32 in
    interpret mode (XLA:CPU cannot execute bf16 x bf16 dots)."""

    def kernel(xe_ref, xo_ref, qs_ref, scales_ref, out_ref, acc_ref):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        qs = qs_ref[:].astype(jnp.int32)  # [bn/2, bd]; mosaic has no u8->f32 cast
        lo = (qs & 0xF).astype(compute_dtype) - 8.0
        # qs holds u8 values, so >>4 is already in 0..15 — no mask needed
        # (dropping the redundant & 0xF is worth ~25% on the VPU-bound unpack)
        hi = (qs >> 4).astype(compute_dtype) - 8.0
        s = scales_ref[:].astype(compute_dtype)  # [bn/32, bd]
        bn2, bd = qs.shape
        # packed row i = logical rows (2i, 2i+1), both in 32-block i//16: the
        # scale row broadcasts over 16 packed rows for lo and hi alike
        wlo = (lo.reshape(-1, 16, bd) * s[:, None, :]).reshape(bn2, bd)
        whi = (hi.reshape(-1, 16, bd) * s[:, None, :]).reshape(bn2, bd)
        acc_ref[:] += jnp.dot(xe_ref[:], wlo, preferred_element_type=jnp.float32)
        acc_ref[:] += jnp.dot(xo_ref[:], whi, preferred_element_type=jnp.float32)

        @pl.when(j == pl.num_programs(1) - 1)
        def _():
            out_ref[:] = acc_ref[:]

    return kernel


@functools.partial(jax.jit, static_argnames=("block_n", "block_d", "interpret"))
def q40_matmul(
    x: jax.Array,
    qm: QuantizedMatrix,
    block_n: int = BLOCK_N,
    block_d: int = BLOCK_D,
    interpret: bool | None = None,
) -> jax.Array:
    """y[T, d] = x[T, n] @ dequant(qm), f32 accumulation. ``n``/``d`` are the
    logical dims; internally the kernel runs on the padded arrays (zero-scale
    padding → exact-zero contributions) and trims the output."""
    n, d = qm.n, qm.d
    np_, dp = qm.n_padded, qm.d_padded
    T = x.shape[0]
    # VMEM budget (measured on v5e, 16MB scoped limit): the dominant tiles
    # are the int32 + 2x bf16 dequant forms (~8 B per packed element) plus
    # the [T, bd] f32 accumulator; shrink the output tile as T grows
    if T > 8:
        block_d = min(block_d, 512)
    if T > 256:
        block_d = min(block_d, 256)
    # tiles must divide the (padded) dims
    block_n = _largest_divisor_tile(np_, block_n, 32)
    block_d = _largest_divisor_tile(dp, block_d, 128)
    if block_n is None or block_d is None:
        return _q40_matmul_fallback(x, qm)
    if interpret is None:
        # platform may be a plugin name (not literally "tpu"); interpret only
        # on CPU, where mosaic can't compile
        interpret = jax.devices()[0].platform == "cpu"

    if x.shape[-1] != np_:
        x = jnp.pad(x, ((0, 0), (0, np_ - x.shape[-1])))
    compute_dtype = jnp.float32 if interpret else jnp.bfloat16
    xb = x.astype(compute_dtype)
    xe = xb[:, 0::2]  # pairs with the low nibbles (logical rows 2i)
    xo = xb[:, 1::2]  # pairs with the high nibbles (logical rows 2i+1)
    grid = (dp // block_d, np_ // block_n)
    out = pl.pallas_call(
        _make_q40_kernel(compute_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((T, block_n // 2), lambda i, j: (0, j)),
            pl.BlockSpec((T, block_n // 2), lambda i, j: (0, j)),
            pl.BlockSpec((block_n // 2, block_d), lambda i, j: (j, i)),
            pl.BlockSpec((block_n // QK, block_d), lambda i, j: (j, i)),
        ],
        out_specs=pl.BlockSpec((T, block_d), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((T, dp), jnp.float32),
        scratch_shapes=[pltpu.VMEM((T, block_d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(xe, xo, qm.qs, qm.scales)
    return out[:, :d] if dp != d else out


def _largest_divisor_tile(dim: int, target: int, granule: int) -> int | None:
    """Largest multiple of ``granule`` that divides ``dim`` and is ≤ target."""
    if dim % granule:
        return None
    best = None
    for k in range(1, target // granule + 1):
        b = k * granule
        if dim % b == 0:
            best = b
    return best


def _q40_matmul_fallback(x: jax.Array, qm: QuantizedMatrix) -> jax.Array:
    np_, dp = qm.n_padded, qm.d_padded
    lo = (qm.qs & 0xF).astype(jnp.int8) - 8
    hi = (qm.qs >> 4).astype(jnp.int8) - 8
    w_int = jnp.stack([lo, hi], axis=-2).reshape(np_, dp)
    w = w_int.astype(jnp.float32).reshape(-1, QK, dp) * qm.scales[..., None, :]
    w = w.reshape(np_, dp)
    if x.shape[-1] != np_:
        x = jnp.pad(x, ((0, 0), (0, np_ - x.shape[-1])))
    out = jax.lax.dot_general(
        x.astype(jnp.float32),
        w,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    return out[:, : qm.d] if dp != qm.d else out
