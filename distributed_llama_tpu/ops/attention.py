"""Online-softmax (flash-style) attention building blocks.

Shared by the dense/TP blocked attention (:func:`blocked_attention`, used by
``models.llama.attention``) and sequence parallelism's ring/sharded
attention (``parallel.context_parallel``). The reference computes attention
as a per-head scalar loop over every past position
(reference: src/llama2-tasks.cpp:54-94); here a chunk of key/value rows is
scored at once and partials merge with the standard flash-attention
(max, exp-sum, weighted-sum) algebra — no full [T, S] score tensor ever
materializes, and a dynamic chunk bound skips cache slots beyond the live
context entirely.
"""

from __future__ import annotations

import os as _os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_llama_tpu.ops import kv_cache as kvc


def chunk_attention(
    q: jax.Array,  # [Tq, K, M, hd] f32 (grouped: K kv-heads × M q-per-kv)
    k: jax.Array,  # [Tk, K, hd] — cache dtype (NOT pre-cast to f32)
    v: jax.Array,  # [Tk, K, hd]
    q_positions: jax.Array,  # [Tq] global positions
    k_positions: jax.Array,  # [Tk]
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Masked scores of one (q-chunk, kv-chunk) pair → (m, l, o) partials.

    m: running max [Tq, K, M]; l: exp-sum [Tq, K, M]; o: weighted V sum
    [Tq, K, M, hd]. Entirely local — no collectives. The einsums run with
    k/v in their storage dtype and f32 accumulation: pre-casting a bf16
    cache slice to f32 would materialize 2x the cache bytes per layer per
    token (the same fix as llama.attention's score/value einsums).
    """
    hd = q.shape[-1]
    # compute dtype follows the cache half (bf16 for an i8 half); f32 caches
    # (parity tests) keep true-f32 multiplies, mirroring llama.attention —
    # otherwise TPU's default bf16 demotion makes f32 runs diverge from the
    # dense f32 path
    cdt = kvc.compute_dtype(k)
    prec = kvc.einsum_precision(k)
    scores = kvc.scores_einsum(q.astype(cdt), k, prec) / jnp.sqrt(jnp.float32(hd))
    mask = (k_positions[None, :] <= q_positions[:, None])[:, None, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    m = jnp.max(scores, axis=-1)  # [Tq, K, M]
    # fully-masked rows (no kv visible in this chunk) keep m = -inf: the
    # EMPTY partial. merge_partials treats it as an exact identity, which
    # is what makes a multi-token verify step bit-identical to the plain
    # decode it replaces (the extra chunks its larger dynamic bound scans
    # are fully masked for the early queries — a finite sentinel here would
    # rescale their l/o by exp(m) and perturb the final quotient in ulps).
    # The exp below still needs a finite reference, hence the local safe_m.
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = kvc.mix_einsum(p, v, cdt, prec)
    return m, l, o


def merge_partials(m1, l1, o1, m2, l2, o2):
    """Merge two online-softmax partials (standard flash-attention merge).

    An EMPTY partial (m = -inf, l = 0, o = 0 — a fully-masked chunk) merges
    as an exact identity: its scale factor is forced to 0 and the other
    side's to exp(0) = 1, so the survivor's l/o pass through bit-unchanged
    instead of being rescaled by a finite sentinel max."""
    m = jnp.maximum(m1, m2)
    safe = jnp.where(jnp.isfinite(m), m, 0.0)
    a1 = jnp.where(jnp.isfinite(m1), jnp.exp(m1 - safe), 0.0)
    a2 = jnp.where(jnp.isfinite(m2), jnp.exp(m2 - safe), 0.0)
    l = l1 * a1 + l2 * a2
    o = o1 * a1[..., None] + o2 * a2[..., None]
    return m, l, o


def paged_segments(matched, chunk: int, n_chunks):
    """Segment bounds of a paged blocked scan: chunks [0, a) hold positions
    below EVERY row's ``matched`` (pool-only reads), chunks [a, b) mix pool
    and slab per position, chunks [b, n_chunks) are past every row's matched
    length (slab-only — zero pool traffic once decode is deep). ``matched``
    may be a scalar (single row) or a [B] vector."""
    a = jnp.minimum(jax.lax.div(jnp.min(matched), chunk), n_chunks)
    b = jnp.clip(jax.lax.div(jnp.max(matched) + chunk - 1, chunk), a, n_chunks)
    return a, b


def _segmented_batched_scan(partial, keys, values, paged, chunk: int, n_chunks, init, rows: int):
    """The batched paged chunk scan shared by decode and verify attention:
    run ``partial(kc, vc, start, carry)`` over every chunk, reading each
    chunk from the slab (``paged`` None), or through the pool-only / mixed /
    slab-only segment split (:func:`paged_segments`) with per-position
    byte selects in the mixed span. One definition so a fix to the segment
    logic can never reach one caller and skip the other.

    Parity scope: the segments keep one fori_loop each — decode must not
    pay a pool gather on slab-only chunks — which means a backend whose
    per-loop codegen differs could perturb the merge by ulps (the
    mechanism that forced :func:`blocked_attention`'s paged prefill to a
    single mixed loop). Bit-parity vs the copy path is test-enforced on
    the CPU mesh; the hit-vs-cold parity tests are the tripwire on any
    new backend."""

    def slab_chunk(i):
        return (
            kvc.slice_rows_batched(keys, i * chunk, chunk, rows=rows),
            kvc.slice_rows_batched(values, i * chunk, chunk, rows=rows),
        )

    def body_slab(i, carry):
        kc, vc = slab_chunk(i)
        return partial(kc, vc, i * chunk, carry)

    if paged is None:
        return jax.lax.fori_loop(0, n_chunks, body_slab, init)

    pool_k, pool_v, tables, matched = paged
    ppc = chunk // kvc.pool_page_size(pool_k)
    a, b = paged_segments(matched, chunk, n_chunks)

    def body_pool(i, carry):
        kc = kvc.pool_chunk(pool_k, tables, i, ppc)
        vc = kvc.pool_chunk(pool_v, tables, i, ppc)
        return partial(kc, vc, i * chunk, carry)

    def body_mixed(i, carry):
        kc_s, vc_s = slab_chunk(i)
        kc_p = kvc.pool_chunk(pool_k, tables, i, ppc)
        vc_p = kvc.pool_chunk(pool_v, tables, i, ppc)
        sel = (i * chunk + jnp.arange(chunk))[None, :] < matched[:, None]
        return partial(
            kvc.select_kv(sel, kc_p, kc_s), kvc.select_kv(sel, vc_p, vc_s),
            i * chunk, carry,
        )

    carry = jax.lax.fori_loop(0, a, body_pool, init)
    carry = jax.lax.fori_loop(a, b, body_mixed, carry)
    return jax.lax.fori_loop(b, n_chunks, body_slab, carry)


def blocked_partials(
    qg: jax.Array,  # [T, K, M, hd] f32 grouped queries
    keys,  # local cache slice [Sl, K, hd] (array or QuantizedKV)
    values,
    q_pos: jax.Array,  # [T] absolute positions (ascending)
    base: jax.Array,  # absolute position of local slot 0
    chunk: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Online-softmax partials of T queries over a LOCAL cache slice with a
    DYNAMIC chunk bound: slots past the last live position (q_pos[-1]) are
    never read. The (m, l, o) triple feeds a cross-shard merge (sequence
    parallelism's pmax/psum) or a local normalization. A shard whose slice
    holds no live slots returns (-inf, 0, 0) — a zero contribution after
    any merge. Requires Sl % chunk == 0."""
    T, K, M, hd = qg.shape
    Sl = keys.shape[0]
    live = jnp.clip(q_pos[-1] + 1 - base, 0, Sl)
    n_chunks = jax.lax.div(live + chunk - 1, chunk)

    def body(i, carry):
        m, l, o = carry
        start = i * chunk
        kc = kvc.slice_rows(keys, start, chunk)
        vc = kvc.slice_rows(values, start, chunk)
        k_pos = base + start + jnp.arange(chunk)
        ms, ls, os_ = chunk_attention(qg, kc, vc, q_pos, k_pos)
        return merge_partials(m, l, o, ms, ls, os_)

    m0 = jnp.full((T, K, M), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((T, K, M), jnp.float32)
    o0 = jnp.zeros((T, K, M, hd), jnp.float32)
    return jax.lax.fori_loop(0, n_chunks, body, (m0, l0, o0))


def _decode_partial(qg, pos, chunk: int, cdt, prec):
    """The per-chunk online-softmax arithmetic of the batched decode scan —
    ONE definition consumed by both the XLA segmented scan and the fused
    Pallas kernel body, so the two paths emit the identical op sequence on
    identical chunk bytes (the mechanism behind their bit-parity)."""
    hd = qg.shape[-1]

    def partial(kc, vc, start, carry):
        m, l, o = carry
        k_pos = start + jnp.arange(chunk)
        scores = kvc.scores_einsum_batched(qg.astype(cdt), kc, prec) / jnp.sqrt(
            jnp.float32(hd)
        )  # [B, K, M, chunk]
        mask = (k_pos[None, :] <= pos[:, None])[:, None, None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
        ms = jnp.max(scores, axis=-1)
        # keep m = -inf for fully-masked chunks (the exact-identity empty
        # partial — see merge_partials); exp still needs a finite reference
        safe_m = jnp.where(jnp.isfinite(ms), ms, 0.0)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(mask, p, 0.0)
        ls = jnp.sum(p, axis=-1)
        os_ = kvc.mix_einsum_batched(p, vc, cdt, prec)
        return merge_partials(m, l, o, ms, ls, os_)

    return partial


def _verify_partial(qg, pos, chunk: int, cdt, prec):
    """The per-chunk online-softmax arithmetic of the batched verify scan
    (speculative decode: T-query windows at pos[b]..pos[b]+T-1) — ONE
    definition consumed by both the XLA segmented scan and the fused Pallas
    kernel body, exactly like :func:`_decode_partial`: identical op
    sequence on identical chunk bytes is the bit-parity mechanism."""
    B, T, K, M, hd = qg.shape
    q_pos = pos[:, None] + jnp.arange(T)[None, :]  # [B, T]

    def partial(kc, vc, start, carry):
        m, l, o = carry
        k_pos = start + jnp.arange(chunk)
        scores = kvc.scores_einsum_verify(qg.astype(cdt), kc, prec) / jnp.sqrt(
            jnp.float32(hd)
        )  # [B, T, K, M, chunk]
        mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, :, None, None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
        ms = jnp.max(scores, axis=-1)
        safe_m = jnp.where(jnp.isfinite(ms), ms, 0.0)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(mask, p, 0.0)
        ls = jnp.sum(p, axis=-1)
        os_ = kvc.mix_einsum_verify(p, vc, cdt, prec)
        return merge_partials(m, l, o, ms, ls, os_)

    return partial


def batched_decode_attention(
    qg: jax.Array,  # [B, K, M, hd] f32 grouped queries (one token per row)
    keys,  # slab cache half [B, S, K, hd] (array or QuantizedKV)
    values,
    pos: jax.Array,  # [B] per-row absolute positions (inactive rows: 0)
    chunk: int,
    paged=None,  # (pool_k, pool_v, tables [B, n_table], matched [B])
) -> jax.Array:
    """Blocked causal attention of B independent single-token queries, each
    over its OWN slab cache row, masked by its OWN position: row ``b`` sees
    slots 0..pos[b]. One fori_loop covers all rows with a shared DYNAMIC
    chunk bound (max over pos), so slots beyond the longest live context are
    never read; rows shorter than the bound are masked per chunk and fully-
    masked chunks contribute zero via the online-softmax merge. Returns
    [B, K, M, hd] f32. Requires S % chunk == 0 (callers fall back to the
    full-S einsum otherwise, exactly like the single-stream path). The
    slab may hold MORE rows than B (a dispatch bucket below B_max): only
    the first B rows are read.

    With ``paged`` set (zero-copy prefix aliasing), row ``b``'s positions
    below ``matched[b]`` are read from the shared page pool THROUGH its page
    table instead of the slab: the scan splits into pool-only, mixed and
    slab-only segments (:func:`paged_segments`) visiting the SAME chunk
    indices in the same merge order with byte-identical KV (pages hold the
    exact bytes the copy design gathered), so the output is bit-identical
    to the copy path's. Requires chunk % page == 0 (callers fall back to
    the virtual-row einsum otherwise)."""
    B, K, M, hd = qg.shape
    S = keys.shape[1]
    if paged is not None and _fused_paged_eligible(qg, keys, values, paged, chunk):
        from distributed_llama_tpu import telemetry

        telemetry.note_kernel_path("paged_attention", "pallas_fused")
        return fused_paged_decode_attention(qg, keys, values, pos, chunk, paged)
    if paged is not None:
        from distributed_llama_tpu import telemetry

        # the hit path fell back to the chain of segmented-scan programs —
        # visible in /metrics so a silent slow path can be alerted on
        telemetry.note_kernel_path("paged_attention", "xla_segmented")
    cdt = kvc.compute_dtype(keys)
    prec = kvc.einsum_precision(keys)
    live = jnp.clip(jnp.max(pos) + 1, 0, S)
    n_chunks = jax.lax.div(live + chunk - 1, chunk)
    partial = _decode_partial(qg, pos, chunk, cdt, prec)
    m0 = jnp.full((B, K, M), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, K, M), jnp.float32)
    o0 = jnp.zeros((B, K, M, hd), jnp.float32)
    m, l, o = _segmented_batched_scan(
        partial, keys, values, paged, chunk, n_chunks, (m0, l0, o0), rows=B
    )
    return o / jnp.maximum(l, 1e-30)[..., None]


# ---------------------------------------------------------------------------
# Fused paged decode-attention (ROADMAP item 1): ONE Pallas program replaces
# the chain of separate XLA programs the segmented scan compiles on the
# prefix-hit path (per-segment fori_loops, per-chunk pool gathers, select,
# einsums, merges — each a separate HLO loop body with its own HBM round
# trips for the m/l/o carries). The kernel walks the SAME chunk indices in
# the SAME three segments (pool-only / mixed / slab-only — zero pool
# traffic on slab-only chunks, exactly like the scan), assembles each
# chunk's KV bytes with explicit async DMA into VMEM scratch (slab slice,
# or per-page copies routed through the row's page table), and runs the
# SHARED per-chunk arithmetic (:func:`_decode_partial`) with the online-
# softmax carries resident on-chip — so the merge math is the identical op
# sequence on identical bytes and the output is BIT-IDENTICAL to the
# eager composition of those per-chunk partials (the EXACT-EMPTY-PARTIAL
# semantics ride along for free; test-enforced across bf16/f32/i8 and
# bucket shapes in tests/test_kernel_parity.py). The XLA scan is the same
# math but its fori_loop codegen may reassociate the merge by ulps at
# verify widths T>1 (the mechanism _segmented_batched_scan documents) —
# parity vs the scan is bit-exact at the pinned decode/verify test shapes
# and within-ulp in general (bench.py --kernels records the divergence).
#
# Compiled-mode notes: operands sit in ANY (HBM) memory space, chunks are
# DMA'd into VMEM scratch, page tables/ids read from SMEM — the Mosaic-
# shaped structure. The page/slab DMAs are DOUBLE-BUFFERED: chunk i+1's
# copies start into the other scratch slot before chunk i's einsums run, so
# the loads fly under the compute (``DLT_FUSED_DB=0`` keeps the serial
# start+wait schedule — the A/B baseline in bench.py --kernels; the
# schedule only reorders copy issue around unchanged compute, so both arms
# are bit-identical by construction). The same kernel body serves the
# speculative-decode verify hit path (T-query windows per row — decode is
# its T=1 degenerate case; :func:`fused_paged_verify_attention`). The
# authoritative gate in this tree is interpret-mode bit-parity on the CPU
# mesh — the container's jax cannot compile Mosaic.
# ---------------------------------------------------------------------------


def _fused_paged_enabled() -> bool:
    """Default: ON where the kernel runs interpreted (CPU — the fully
    parity-gated mode), OFF on accelerators until a chip smoke validates
    the Mosaic build (a compiled-mode lowering failure would surface at
    XLA compile of the whole decode program, past any dispatch-level
    fallback — the same prudence as the ring all-reduce default).
    ``DLT_FUSED_PAGED`` overrides either way; read per dispatch decision
    (trace time)."""
    env = _os.environ.get("DLT_FUSED_PAGED")
    if env is not None:
        return env != "0"
    return jax.devices()[0].platform == "cpu"


def _fused_paged_eligible(qg, keys, values, paged, chunk: int) -> bool:
    """Shape/dtype gate for the fused kernel: slab and pool halves must
    agree on quantization class, chunks must be whole pages, and the slab
    must block evenly (callers already guarantee the last two on the
    production path — the checks make the fallback safe, not rare)."""
    if not _fused_paged_enabled():
        return False
    pool_k, pool_v, tables, matched = paged
    quant = isinstance(keys, kvc.QuantizedKV)
    if any(
        isinstance(h, kvc.QuantizedKV) is not quant
        for h in (values, pool_k, pool_v)
    ):
        return False
    page = kvc.pool_page_size(pool_k)
    S = keys.shape[1]
    return chunk % page == 0 and S % chunk == 0


def _double_buffer_default() -> bool:
    """``DLT_FUSED_DB`` gates the double-buffered DMA schedule (default ON:
    the schedule only reorders copy issue/wait around unchanged compute, so
    both arms produce identical bytes by construction — pinned by the A/B
    arm in bench.py --kernels and tests/test_kernel_parity.py).
    ``DLT_FUSED_DB=0`` keeps the serial start+wait schedule. Read per
    dispatch decision (trace time)."""
    env = _os.environ.get("DLT_FUSED_DB")
    return env != "0" if env is not None else True


def _fused_paged_attention(
    qg, keys, values, pos, chunk: int, paged, interpret, double_buffer, verify: bool
):
    """Shared builder behind :func:`fused_paged_decode_attention` and
    :func:`fused_paged_verify_attention` — decode is the T=1 degenerate
    case of the verify window, so ONE kernel body serves both and a parity
    fix can never reach one entry point and skip the other."""
    from distributed_llama_tpu.ops.q40 import tpu_compiler_params

    pool_k, pool_v, tables, matched = paged
    if verify:
        B, T, K, M, hd = qg.shape
        lead = (B, T, K, M)
    else:
        B, K, M, hd = qg.shape
        T = 1  # decode: one query per row, live bound max(pos) + 1
        lead = (B, K, M)
    S = keys.shape[1]
    quant = isinstance(keys, kvc.QuantizedKV)
    page = kvc.pool_page_size(pool_k)
    ppc = chunk // page
    n_table = tables.shape[1]
    nh = 2 if quant else 1
    cdt = kvc.compute_dtype(keys)
    prec = kvc.einsum_precision(keys)
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    if double_buffer is None:
        double_buffer = _double_buffer_default()
    nslots = 2 if double_buffer else 1

    def halves(h):
        return (h.data, h.scales) if quant else (h,)

    def scratch_for(h, n_rows: int):
        """VMEM chunk-scratch shapes mirroring one source's halves — one
        buffer per DMA slot (two double-buffered, one serial)."""
        if quant:
            return [
                pltpu.VMEM((nslots, n_rows, chunk, K, hd), h.data.dtype),
                pltpu.VMEM((nslots, n_rows, chunk, K, 1), h.scales.dtype),
            ]
        return [pltpu.VMEM((nslots, n_rows, chunk, K, hd), h.dtype)]

    def kernel(*refs):
        pos_ref, matched_ref, tables_ref, qg_ref = refs[:4]
        body = refs[4 : 4 + 4 * nh]
        out_ref = refs[4 + 4 * nh]
        scr = refs[5 + 4 * nh :]
        slab_k, slab_v = body[:nh], body[nh : 2 * nh]
        pk, pv = body[2 * nh : 3 * nh], body[3 * nh : 4 * nh]
        sk_scr, sv_scr = scr[:nh], scr[nh : 2 * nh]
        pk_scr, pv_scr = scr[2 * nh : 3 * nh], scr[3 * nh : 4 * nh]
        sem = scr[4 * nh]

        pos_ = pos_ref[:]
        matched_ = matched_ref[:]
        mk_partial = _verify_partial if verify else _decode_partial
        partial = mk_partial(qg_ref[:], pos_, chunk, cdt, prec)
        live = jnp.clip(jnp.max(pos_) + T, 0, S)
        n_chunks = jax.lax.div(live + chunk - 1, chunk)
        a, b_seg = paged_segments(matched_, chunk, n_chunks)

        def slab_copies(i, slot):
            # one sliced DMA per half: the first B slab rows' chunk window
            # (a dispatch bucket below B_max reads only its own rows,
            # mirroring kvc.slice_rows_batched(rows=B))
            return [
                pltpu.make_async_copy(
                    r.at[pl.ds(0, B), pl.ds(i * chunk, chunk)],
                    s.at[slot],
                    sem.at[slot],
                )
                for r, s in zip(slab_k + slab_v, sk_scr + sv_scr)
            ]

        def pool_copies(i, slot):
            # page-table-routed copies: page p of chunk i for row b comes
            # from pool page tables[b, i*ppc + p]. The table window start
            # clamps exactly like the scan's lax.dynamic_slice on tables.
            base = jnp.clip(i * ppc, 0, n_table - ppc)
            cs = []
            for b in range(B):
                for p in range(ppc):
                    pid = tables_ref[b, base + p]
                    cs.extend(
                        pltpu.make_async_copy(
                            r.at[pid],
                            s.at[slot, b, pl.ds(p * page, page)],
                            sem.at[slot],
                        )
                        for r, s in zip(pk + pv, pk_scr + pv_scr)
                    )
            return cs

        def start_loads(i, slot):
            # chunk i's sources by segment: slab from chunk a up, pool
            # below chunk b_seg — slab-only chunks issue ZERO pool
            # traffic, exactly like the scan's segment split
            @pl.when(i >= a)
            def _():
                for c in slab_copies(i, slot):
                    c.start()

            @pl.when(i < b_seg)
            def _():
                for c in pool_copies(i, slot):
                    c.start()

        def wait_loads(i, slot):
            # recreate the started descriptors (same refs, same sem slot);
            # every copy of the chunk is drained before any scratch read.
            # slots alternate, so chunk i+1's in-flight copies signal the
            # OTHER slot's semaphore and can never satisfy these waits.
            @pl.when(i >= a)
            def _():
                for c in slab_copies(i, slot):
                    c.wait()

            @pl.when(i < b_seg)
            def _():
                for c in pool_copies(i, slot):
                    c.wait()

        def read(scrs, slot):
            if quant:
                return kvc.QuantizedKV(scrs[0][slot], scrs[1][slot])
            return scrs[0][slot]

        def with_loads(compute):
            """Wrap a segment body with the DMA schedule. Double-buffered:
            start chunk i+1's copies into the other slot FIRST, so they fly
            under chunk i's einsums; segment membership is resolved per
            chunk index, so the prefetch crosses segment (and fori_loop)
            boundaries without special cases. Serial: start+wait the
            chunk's own copies, nothing in flight during compute."""

            def body_fn(i, carry):
                slot = jax.lax.rem(i, nslots)
                if double_buffer:
                    @pl.when(i + 1 < n_chunks)
                    def _():
                        start_loads(i + 1, jax.lax.rem(i + 1, nslots))
                else:
                    start_loads(i, slot)
                wait_loads(i, slot)
                return compute(i, slot, carry)

            return body_fn

        def compute_pool(i, slot, carry):
            return partial(read(pk_scr, slot), read(pv_scr, slot), i * chunk, carry)

        def compute_mixed(i, slot, carry):
            sel = (i * chunk + jnp.arange(chunk))[None, :] < matched_[:, None]
            kc = kvc.select_kv(sel, read(pk_scr, slot), read(sk_scr, slot))
            vc = kvc.select_kv(sel, read(pv_scr, slot), read(sv_scr, slot))
            return partial(kc, vc, i * chunk, carry)

        def compute_slab(i, slot, carry):
            return partial(read(sk_scr, slot), read(sv_scr, slot), i * chunk, carry)

        if double_buffer:
            # warm-up: chunk 0's copies have no prior compute to hide under
            @pl.when(n_chunks > 0)
            def _():
                start_loads(0, 0)

        m0 = jnp.full(lead, -jnp.inf, jnp.float32)
        l0 = jnp.zeros(lead, jnp.float32)
        o0 = jnp.zeros(lead + (hd,), jnp.float32)
        carry = jax.lax.fori_loop(0, a, with_loads(compute_pool), (m0, l0, o0))
        carry = jax.lax.fori_loop(a, b_seg, with_loads(compute_mixed), carry)
        m, l, o = jax.lax.fori_loop(b_seg, n_chunks, with_loads(compute_slab), carry)
        out_ref[:] = o / jnp.maximum(l, 1e-30)[..., None]

    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    in_specs = (
        [any_spec, any_spec, pl.BlockSpec(memory_space=pltpu.SMEM), any_spec]
        + [any_spec] * (4 * nh)
    )
    scratch = (
        scratch_for(keys, B) + scratch_for(values, B)
        + scratch_for(pool_k, B) + scratch_for(pool_v, B)
        + [pltpu.SemaphoreType.DMA((nslots,))]
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(lead + (hd,), jnp.float32),
        in_specs=in_specs,
        out_specs=any_spec,
        scratch_shapes=scratch,
        interpret=interpret,
        **tpu_compiler_params(),
    )(
        pos.astype(jnp.int32), matched.astype(jnp.int32),
        tables.astype(jnp.int32), qg,
        *halves(keys), *halves(values), *halves(pool_k), *halves(pool_v),
    )


def fused_paged_decode_attention(
    qg: jax.Array,  # [B, K, M, hd] f32 grouped queries (one token per row)
    keys,  # slab cache half [B, S, K, hd] (array or QuantizedKV)
    values,
    pos: jax.Array,  # [B] per-row absolute positions
    chunk: int,
    paged,  # (pool_k, pool_v, tables [B, n_table], matched [B])
    interpret: bool | None = None,
    double_buffer: bool | None = None,
) -> jax.Array:
    """The fused Pallas form of the paged :func:`batched_decode_attention`
    hit path — same segment split, same chunk order, same merge arithmetic,
    bit-identical output. ``double_buffer`` (default: env ``DLT_FUSED_DB``,
    on) overlaps chunk i+1's page/slab DMAs with chunk i's einsums.
    Returns [B, K, M, hd] f32."""
    return _fused_paged_attention(
        qg, keys, values, pos, chunk, paged, interpret, double_buffer, verify=False
    )


def fused_paged_verify_attention(
    qg: jax.Array,  # [B, T, K, M, hd] f32 grouped queries (T = draft k + 1)
    keys,  # slab cache half [B, S, K, hd] (array or QuantizedKV)
    values,
    pos: jax.Array,  # [B] per-row positions of query t=0
    chunk: int,
    paged,  # (pool_k, pool_v, tables [B, n_table], matched [B])
    interpret: bool | None = None,
    double_buffer: bool | None = None,
) -> jax.Array:
    """The fused Pallas form of the paged :func:`batched_verify_attention`
    hit path (speculative decode) — the same kernel as the decode form with
    the T-query verify arithmetic (:func:`_verify_partial`) in the chunk
    body, so each query's output stays bit-identical to the single-token
    decode step at the same position. Returns [B, T, K, M, hd] f32."""
    return _fused_paged_attention(
        qg, keys, values, pos, chunk, paged, interpret, double_buffer, verify=True
    )


def batched_verify_attention(
    qg: jax.Array,  # [B, T, K, M, hd] f32 grouped queries (T = draft k + 1)
    keys,  # slab cache half [B, S, K, hd] (array or QuantizedKV)
    values,
    pos: jax.Array,  # [B] per-row positions of query t=0 (inactive rows: 0)
    chunk: int,
    paged=None,  # (pool_k, pool_v, tables [B, n_table], matched [B])
) -> jax.Array:
    """Blocked causal attention of B independent T-token verify windows
    (speculative decode): row ``b``'s query ``t`` sits at absolute position
    ``pos[b] + t`` and sees slots 0..pos[b]+t of its OWN slab row. One
    fori_loop covers all rows with a shared dynamic chunk bound
    (max(pos) + T), so slots beyond the longest live window are never
    read; fully-masked chunks merge as exact identities (empty partials),
    which keeps each query's output bit-identical to the single-token
    decode step at the same position. Returns [B, T, K, M, hd] f32.
    Requires S % chunk == 0 (callers fall back to the full-S einsum).

    ``paged``: the zero-copy prefix read, segmented exactly like
    :func:`batched_decode_attention` — the verify window always sits at
    pos >= matched, so every paged position is causally visible to every
    query offset and the per-chunk math is unchanged. The paged hit path
    dispatches to the fused Pallas kernel under the same eligibility gate
    as decode (:func:`_fused_paged_eligible`, ``DLT_FUSED_PAGED``)."""
    B, T, K, M, hd = qg.shape
    S = keys.shape[1]
    if paged is not None and _fused_paged_eligible(qg, keys, values, paged, chunk):
        from distributed_llama_tpu import telemetry

        telemetry.note_kernel_path("paged_attention", "pallas_fused_verify")
        return fused_paged_verify_attention(qg, keys, values, pos, chunk, paged)
    if paged is not None:
        from distributed_llama_tpu import telemetry

        telemetry.note_kernel_path("paged_attention", "xla_segmented")
    cdt = kvc.compute_dtype(keys)
    prec = kvc.einsum_precision(keys)
    live = jnp.clip(jnp.max(pos) + T, 0, S)
    n_chunks = jax.lax.div(live + chunk - 1, chunk)
    partial = _verify_partial(qg, pos, chunk, cdt, prec)
    m0 = jnp.full((B, T, K, M), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, T, K, M), jnp.float32)
    o0 = jnp.zeros((B, T, K, M, hd), jnp.float32)
    m, l, o = _segmented_batched_scan(
        partial, keys, values, paged, chunk, n_chunks, (m0, l0, o0), rows=B
    )
    return o / jnp.maximum(l, 1e-30)[..., None]


def blocked_attention(
    qg: jax.Array,  # [T, K, M, hd] f32 grouped queries
    keys,  # cache half [S, K, hd] (array or QuantizedKV)
    values,
    pos: jax.Array,  # scalar: absolute position of query row 0
    chunk: int,
    paged=None,  # (pool_k, pool_v, table [n_table], matched scalar)
) -> jax.Array:
    """Causal attention of T query rows over a KV cache, blocked along the
    key axis with a DYNAMIC chunk bound: only chunks holding positions
    <= pos+T-1 are read at all, so attention cost is O(live context), not
    O(seq_len) — the full-S masked einsum it replaces reads (and scores)
    every allocated slot every call. Returns [T, K, M, hd] f32.

    Requires S % chunk == 0 (callers fall back to the full einsum
    otherwise). The boundary chunk's causal edge is masked inside
    :func:`chunk_attention` by position comparison.

    ``paged``: zero-copy prefix aliasing for the slab-row prefill — cache
    positions below ``matched`` are read from the page pool through the
    row's page table. ONE fori_loop covers every chunk with a per-position
    pool-vs-slab byte select: splitting the scan into pool/mixed/slab
    segment loops (as the batched decode does) compiles the shared body
    once PER SEGMENT LOOP, and XLA's per-loop codegen perturbs the o-merge
    FMA by ulps — a single loop is the only structure whose chunk-1..n
    math is bit-identical to the non-paged single-loop scan. The extra
    pool read on suffix-only chunks is a prefill-only cost (decode's hot
    path keeps the segmented scan). Requires chunk % page == 0."""
    T = qg.shape[0]
    q_pos = pos + jnp.arange(T)
    if paged is None:
        # same chunk scan as the sequence-parallel local-slice partials, with
        # the whole cache as the "local slice" (base 0) and a local normalize
        m, l, o = blocked_partials(qg, keys, values, q_pos, 0, chunk)
        return o / jnp.maximum(l, 1e-30)[..., None]

    pool_k, pool_v, table, matched = paged
    K, M, hd = qg.shape[1:]
    Sl = keys.shape[0]
    ppc = chunk // kvc.pool_page_size(pool_k)
    live = jnp.clip(q_pos[-1] + 1, 0, Sl)
    n_chunks = jax.lax.div(live + chunk - 1, chunk)

    def body_mixed(i, carry):
        kc_s = kvc.slice_rows(keys, i * chunk, chunk)
        vc_s = kvc.slice_rows(values, i * chunk, chunk)
        kc_p = kvc.pool_chunk_row(pool_k, table, i, ppc)
        vc_p = kvc.pool_chunk_row(pool_v, table, i, ppc)
        sel = (i * chunk + jnp.arange(chunk)) < matched
        kc = kvc.select_kv(sel, kc_p, kc_s)
        vc = kvc.select_kv(sel, vc_p, vc_s)
        ms, ls, os_ = chunk_attention(qg, kc, vc, q_pos, i * chunk + jnp.arange(chunk))
        m, l, o = carry
        return merge_partials(m, l, o, ms, ls, os_)

    m0 = jnp.full((T, K, M), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((T, K, M), jnp.float32)
    o0 = jnp.zeros((T, K, M, hd), jnp.float32)
    m, l, o = jax.lax.fori_loop(0, n_chunks, body_mixed, (m0, l0, o0))
    return o / jnp.maximum(l, 1e-30)[..., None]
