"""User-facing apps: CLI (inference/generate/chat/worker) and helpers."""
