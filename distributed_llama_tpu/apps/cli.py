"""`dllama-tpu` CLI: inference / generate / chat / worker modes.

Command surface parity with the reference's dllama app
(reference: src/apps/dllama/dllama.cpp:223-254, arg parsing
src/app.cpp:28-113), adapted to the TPU runtime:

* ``--workers host:port...`` (TCP worker list) becomes ``--tp N`` (shard over
  N local chips) plus multi-host flags (``--coordinator``, ``--num-hosts``,
  ``--host-id``) that drive ``jax.distributed`` — the SPMD equivalent of the
  reference's root/worker split where every host runs the *same* program.
* ``--nthreads`` is accepted but ignored: the thread pool's job is done by
  XLA inside one chip (SURVEY.md §2, intra-node thread parallelism).
* ``--buffer-float-type`` is accepted but advisory: the wire-quantization it
  controls in the reference (Q80 activations over TCP, src/tasks.cpp:96-135)
  does not exist here — activations never leave the chip mesh except over ICI.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from distributed_llama_tpu.telemetry import Stopwatch
from distributed_llama_tpu.tokenizer import (
    ChatItem,
    ChatTemplate,
    ChatTemplateType,
    EosDetector,
    EosDetectorResult,
    Sampler,
    Tokenizer,
    chat_stops,
    is_safe_piece,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dllama-tpu")
    p.add_argument("mode", choices=["inference", "generate", "chat", "worker"])
    p.add_argument("--model", required=True)
    p.add_argument("--tokenizer", required=True)
    p.add_argument("--prompt", default=None)
    p.add_argument("--steps", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--topp", type=float, default=0.9)
    p.add_argument(
        "--topk", type=int, default=0,
        help="top-k sampling filter (0 = off); composes with --topp as "
        "min(top-k, nucleus), fused into the device decode program",
    )
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--max-seq-len", type=int, default=None)
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel shards (chips)")
    p.add_argument(
        "--pod", type=str, default=None, metavar="DATAxMODEL",
        help="one-process pod serving on a single ('data','model') mesh "
        "(e.g. 2x2): tensor parallelism over 'model' inside every slice, "
        "data-parallel replicas as slices of the SAME mesh sharing ONE "
        "weights tree (no N-replica weight copies; ROADMAP item 3). The "
        "server runs one supervised replica per data slice — a mesh-slice "
        "failure IS a replica loss with the PR 9/10 failover/replay/"
        "restart contract, and a slice rebuild never reloads weights. "
        "Mutually exclusive with --tp/--sp/--ep; testable under "
        "JAX_PLATFORMS=cpu with --xla_force_host_platform_device_count",
    )
    p.add_argument(
        "--sp", type=int, default=1,
        help="sequence-parallel shards: KV cache sharded over the sequence, "
        "ring-attention prefill (long-context mode; composes with --tp on a "
        "2-D tp x sp mesh)",
    )
    p.add_argument(
        "--ep", type=int, default=1,
        help="expert-parallel shards (MoE models): each shard owns "
        "n_experts/ep whole experts; prefill routes tokens with all_to_all "
        "dispatch/combine, decode runs local experts + psum (composes with "
        "--tp on a 2-D tp x ep mesh)",
    )
    p.add_argument(
        "--moe-capacity", type=float, default=0.0,
        help="MoE prefill capacity factor: per-expert buckets hold "
        "ceil(F*T*k/E) rows, overflow DROPS (lossy, standard capacity "
        "semantics; ~15%% faster Mixtral prefill at 2.0). 0 = exact "
        "(default): worst-case drop-free buckets. Applies to the q40 "
        "per-expert layout (prompts >= 32 tokens) and the --ep dispatch; "
        "the bf16 stacked-bank prefill ignores it (already one batched "
        "einsum)",
    )
    p.add_argument(
        "--dtype",
        choices=["bf16", "f32", "q40"],
        default="bf16",
        help="on-device weight dtype (q40 = packed 4-bit via the fused Pallas kernel)",
    )
    p.add_argument("--chat-template", default=None,
                   choices=[None, "llama2", "llama3", "zephyr", "chatml"])
    p.add_argument(
        "--decode",
        choices=["device", "host"],
        default="device",
        help="device = chunked on-device decode+sampling (fast path: fused "
        "temperature/top-k/top-p + counter-PRNG coins inside the decode "
        "program); host = per-token host sampling (the reference's regime, "
        "one host<->device round trip per token; the counter-mode xorshift "
        "sampler replays the device stream token for token per seed)",
    )
    p.add_argument(
        "--decode-chunk", type=int, default=32,
        help="tokens per device dispatch for --decode device",
    )
    p.add_argument(
        "--spec-draft", type=int, default=0,
        help="self-speculative decoding: up to K prompt-lookup draft tokens "
        "(n-gram matches over the request's own prompt + output — no draft "
        "model) verified per decode step in ONE weight read; greedy output "
        "is bit-identical to plain decode, sampled output preserves the "
        "distribution (Leviathan rejection sampling). Wins on repetitive/"
        "structured output, degenerates gracefully when acceptance "
        "collapses. 0 (default) = off; single-chip --decode device only",
    )
    p.add_argument(
        "--spec-ngram", type=int, default=3,
        help="widest n-gram the prompt-lookup drafter matches (falls "
        "through to shorter n-grams; --spec-draft must be > 0)",
    )
    p.add_argument(
        "--cache-dtype",
        choices=["auto", "bf16", "f32", "i8"],
        default="auto",
        help="KV-cache dtype (auto = bf16, or f32 with --dtype f32). i8 "
        "stores int8 rows with per-(slot, head) scales: half the cache HBM "
        "of bf16 — the TPU-native replacement for the reference's "
        "disc-backed --kv-cache-storage (longer contexts in the same memory)",
    )
    p.add_argument(
        "--telemetry", action="store_true", default=False,
        help="enable the telemetry subsystem: metrics registry (served at "
        "GET /metrics by dllama-tpu-api) + span tracer (Chrome trace JSON "
        "written to --trace-out after a generate/inference run). "
        "DLLAMA_TELEMETRY=1 in the environment enables it too; off by "
        "default — disabled instruments are no-ops on the decode hot path",
    )
    p.add_argument(
        "--trace-out", default="dllama-trace.json", metavar="PATH",
        help="where a --telemetry generate/inference run writes its Chrome "
        "trace-event JSON (open in chrome://tracing or ui.perfetto.dev)",
    )
    p.add_argument(
        "--compile-cache-dir", default=None, metavar="DIR",
        help="XLA persistent compilation-cache directory: a fresh process "
        "reuses compiled programs instead of paying the cold compile "
        "(8.6 s for the 7B 64-token prefill program, BENCH_r05). Default: "
        "DLLAMA_COMPILE_CACHE env, else ~/.cache/distributed_llama_tpu/xla; "
        "DLLAMA_COMPILE_CACHE='' disables. Cache-served compiles count in "
        "dllama_compile_cache_hits_total under --telemetry",
    )
    # accepted-for-parity flags (see module docstring)
    p.add_argument("--nthreads", type=int, default=None, help=argparse.SUPPRESS)
    p.add_argument("--buffer-float-type", default=None, help=argparse.SUPPRESS)
    p.add_argument("--weights-float-type", default=None, help=argparse.SUPPRESS)
    p.add_argument("--kv-cache-storage", default=None, help=argparse.SUPPRESS)
    # multi-host (jax.distributed) participation
    p.add_argument("--coordinator", default=None, help="host:port of jax.distributed coordinator")
    p.add_argument("--num-hosts", type=int, default=1)
    p.add_argument("--host-id", type=int, default=0)
    return p


def _parse_dtypes(args):
    import jax.numpy as jnp

    from distributed_llama_tpu.engine.weights import QUANTIZED_DTYPE

    if getattr(args, "kv_cache_storage", None) not in (None, "ram"):
        # the reference spills the KV cache to disc-backed mmap buffers
        # (reference: src/utils.cpp:50-67); on TPU the cache lives in HBM
        # inside a jitted program and cannot be file-backed — rejected
        # here so BOTH engine paths (classic and --pod) refuse it
        raise SystemExit(
            f"--kv-cache-storage {args.kv_cache_storage} is not supported on "
            "TPU (the KV cache is device HBM); use --cache-dtype i8 for 2x "
            "cache-memory headroom and/or --max-seq-len to bound it"
        )
    dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32, "q40": QUANTIZED_DTYPE}[args.dtype]
    cache_dtype = {
        "auto": None, "bf16": jnp.bfloat16, "f32": jnp.float32, "i8": "i8",
    }[getattr(args, "cache_dtype", "auto")]
    return dtype, cache_dtype


def _make_sampler(args, vocab_size: int) -> Sampler:
    # wall-clock as entropy for a default sampling seed, never a duration
    seed = args.seed if args.seed is not None else int(time.time())  # dllama: noqa[CLK-001]
    # counter mode: the host sampler draws the SAME coins the fused device
    # sampler draws (stateless, keyed on (seed, position)), so a --decode
    # host run replays a --decode device stream token for token — the
    # xorshift-parity verification mode (ISSUE 13)
    return Sampler(
        vocab_size=vocab_size,
        temperature=args.temperature,
        topp=args.topp,
        topk=args.topk,
        seed=seed,
        counter=True,
    )


def make_pod_group(args):
    """Build the one-process pod substrate from the serving flags: ONE
    model load placed on the single ('data','model') mesh, plus the
    tokenizer/sampler pair ``make_engine`` would return. The returned
    group IS the serving layer's engine factory (slice engines share the
    pod's weights and compiled programs; a replica rebuild never reloads
    the file)."""
    from distributed_llama_tpu.parallel.pod import PodGroup, parse_pod

    if getattr(args, "tp", 1) > 1 or getattr(args, "sp", 1) > 1 or getattr(args, "ep", 1) > 1:
        raise SystemExit(
            "--pod owns the whole mesh layout; it does not compose with "
            "--tp/--sp/--ep (the pod's 'model' axis IS the tensor-parallel "
            "degree)"
        )
    data, model = parse_pod(args.pod)
    dtype, cache_dtype = _parse_dtypes(args)
    group = PodGroup.build(
        args.model, data, model,
        dtype=dtype,
        max_seq_len=args.max_seq_len,
        cache_dtype=cache_dtype,
        moe_capacity_factor=getattr(args, "moe_capacity", 0.0) or 0.0,
    )
    tokenizer = Tokenizer.from_file(args.tokenizer, group.cfg.vocab_size)
    return group, tokenizer, _make_sampler(args, group.cfg.vocab_size)


def make_engine(args):
    from distributed_llama_tpu.engine import InferenceEngine

    if getattr(args, "pod", None):
        # one-off pod engine (generate/chat/inference modes): one slice of
        # a freshly built pod group — the long-lived group path is
        # serve()'s (the factory must outlive the engine for rebuilds)
        group, tokenizer, sampler = make_pod_group(args)
        return group.slice_engine(), tokenizer, sampler
    dtype, cache_dtype = _parse_dtypes(args)
    engine = InferenceEngine(
        args.model, dtype=dtype, max_seq_len=args.max_seq_len, tp=args.tp,
        sp=getattr(args, "sp", 1), ep=getattr(args, "ep", 1),
        cache_dtype=cache_dtype,
        moe_capacity_factor=getattr(args, "moe_capacity", 0.0) or 0.0,
    )
    tokenizer = Tokenizer.from_file(args.tokenizer, engine.cfg.vocab_size)
    return engine, tokenizer, _make_sampler(args, engine.cfg.vocab_size)


def _print(s: str) -> None:
    sys.stdout.write(s)
    sys.stdout.flush()


def generate(args, benchmark: bool) -> None:
    """The generate/inference loop (reference: src/apps/dllama/dllama.cpp:17-94).

    TPU-first deviations: the prompt is prefilled in one batched forward
    instead of token-by-token (per-token stats lines cover the decode phase,
    prefill is its own line), and with ``--decode device`` (the default) the
    decode loop runs on device in chunks — sampling included — so no
    host<->device round trip is paid per token. ``--decode host`` restores
    the reference's regime (host xorshift sampler, stepwise).
    """
    if args.prompt is None:
        raise SystemExit("Prompt is required")
    engine, tokenizer, sampler = make_engine(args)
    add_bos = engine.cfg.arch.name != "GROK1"  # (reference: dllama.cpp:26)
    prompt_tokens = tokenizer.encode(args.prompt, add_bos=add_bos)

    n_prompt = len(prompt_tokens)
    if n_prompt < 1:
        raise SystemExit("Expected at least 1 prompt token")

    total_sw = Stopwatch()
    if args.decode == "device":
        # prefill→decode fusion: the first token is sampled on device and the
        # first decode chunk is dispatched before anything is fetched — one
        # tunnel round trip per request instead of two (engine.prefill_device)
        first_dev = engine.prefill_device(
            prompt_tokens, args.temperature, args.topp, seed=sampler.seed,
            topk=args.topk,
        )
        logits = None
    else:
        logits = engine.prefill(prompt_tokens)
    # fused path: the prefill stats entry only gains its device-compute
    # drain time when the first token is fetched (engine._fetch_fused_first),
    # so the P line is deferred until then — printing it here would report
    # async dispatch overhead, not prefill latency
    p_entry = engine.stats[-1] if benchmark else None
    p_printed = False
    if benchmark and args.decode != "device":
        _print(f"🔷 P {p_entry.generation_ms:5.0f} ms ({n_prompt} prompt tokens) ")
        p_printed = True
    _print(tokenizer.decode(prompt_tokens))
    if benchmark:
        _print("\n")

    def print_p_line() -> None:
        nonlocal p_printed
        if benchmark and not p_printed:
            _print(f"🔷 P {p_entry.generation_ms:5.0f} ms ({n_prompt} prompt tokens)\n")
            p_printed = True

    def emit(prev: int, tok: int) -> None:
        print_p_line()
        stats = engine.stats[-1]
        if benchmark:
            _print(
                f"🔶 G {stats.generation_ms:4.0f} ms I {stats.inference_ms:4.0f} ms "
                f"T {stats.transfer_ms:4.0f} ms "
            )
        piece = tokenizer.decode_piece(prev, tok)
        if is_safe_piece(piece):
            _print(piece.decode("utf-8", errors="replace"))
        if benchmark:
            _print("\n")

    token = prompt_tokens[-1]
    generated = 0
    if args.decode == "device":

        def on_token(prev: int, t: int) -> bool:
            nonlocal generated, token
            if t == tokenizer.bos_id:
                return False  # BOS delimits sequences (dllama.cpp:68-71)
            emit(prev, t)
            generated += 1
            token = t
            return True

        engine.stream_decode(
            first_dev, on_token, args.temperature, args.topp,
            seed=sampler.seed, chunk=args.decode_chunk, limit=args.steps,
            first_prev=prompt_tokens[-1],
            spec_draft=getattr(args, "spec_draft", 0),
            spec_ngram=getattr(args, "spec_ngram", 3),
            prompt_tokens=prompt_tokens,
            topk=args.topk,
        )
        print_p_line()  # zero-token streams (immediate BOS) still report P
    else:
        # first generated token samples on host from the prefill logits;
        # the counter sampler keys each coin on the consumed position, so
        # this stepwise stream is token-identical to --decode device
        next_token = sampler.sample(logits, pos=engine.pos - 1)
        if next_token != tokenizer.bos_id:  # BOS delimits sequences (dllama.cpp:68-71)
            emit(token, next_token)
            generated += 1
            token = next_token
            while engine.pos < args.steps:
                logits = engine.decode_step(token)
                next_token = sampler.sample(logits, pos=engine.pos - 1)
                if next_token == tokenizer.bos_id:
                    break
                emit(token, next_token)
                generated += 1
                token = next_token

    avg = engine.avg_stats()
    total_ms = total_sw.elapsed_ms()
    n = max(1, engine.total_tokens())
    _print("\n")
    _print(f"Generated tokens:    {generated}\n")
    _print(f"Avg tokens / second: {1000.0 * n / max(total_ms, 1e-9):.2f}\n")
    _print(f"Avg generation time: {avg.generation_ms:.2f} ms\n")
    _print(f"Avg inference time:  {avg.inference_ms:.2f} ms\n")
    _print(f"Avg transfer time:   {avg.transfer_ms:.2f} ms\n")


def chat(args) -> None:
    """Multi-turn REPL (reference: src/apps/dllama/dllama.cpp:111-203)."""
    engine, tokenizer, sampler = make_engine(args)
    stops = chat_stops(tokenizer)
    template_type = args.chat_template or ChatTemplateType.UNKNOWN
    template = ChatTemplate(template_type, tokenizer.chat_template, stops[0])
    max_stop = max(len(s) for s in stops)

    items: list[ChatItem] = []
    sys_prompt = input("💻 System prompt (optional): ")
    if sys_prompt:
        items.append(ChatItem("system", sys_prompt))

    seq_len = engine.cfg.seq_len
    while engine.pos < seq_len:
        user = ""
        while not user:
            user = input("\n👱 User\n> ")
        items.append(ChatItem("user", user))
        prompt = template.generate(items, append_generation_prompt=True)
        items = []  # only deltas are fed each turn (reference keeps full list; we re-feed deltas against the live KV cache)
        tokens = tokenizer.encode(prompt, add_bos=engine.pos == 0)

        budget = seq_len - engine.pos
        tokens = tokens[:budget]
        turn_seed = sampler.seed + engine.pos  # vary the stream per turn
        sampler.set_seed(turn_seed)  # counter coins re-key per turn too
        if args.decode == "device":
            # prefill→decode fusion (see generate): first token sampled on
            # device, no host round trip between prompt and reply
            first_dev = engine.prefill_device(
                tokens, args.temperature, args.topp, seed=turn_seed,
                topk=args.topk,
            )
            logits = None
        else:
            logits = engine.prefill(tokens)
        _print("\n🤖 Assistant\n")

        detector = EosDetector(
            {tokenizer.chat_eos_id}, stops, padding_left=max_stop, padding_right=max_stop
        )

        def feed(prev: int, token: int) -> EosDetectorResult:
            piece = tokenizer.decode_piece(prev, token)
            res = detector.append(token, piece if is_safe_piece(piece) else b"")
            if res in (EosDetectorResult.NOT_EOS, EosDetectorResult.EOS):
                delta = detector.get_delta()
                if delta:
                    _print(delta.decode("utf-8", errors="replace"))
                detector.clear()
            return res

        if args.decode == "device":
            res = EosDetectorResult.NOT_EOS

            def on_token(prev: int, t: int) -> bool:
                nonlocal res, token
                res = feed(prev, t)
                token = t
                return res != EosDetectorResult.EOS

            engine.stream_decode(
                first_dev, on_token, args.temperature, args.topp,
                seed=turn_seed, chunk=args.decode_chunk,
                limit=seq_len, first_prev=tokens[-1],
                spec_draft=getattr(args, "spec_draft", 0),
                spec_ngram=getattr(args, "spec_ngram", 3),
                prompt_tokens=tokens,
                topk=args.topk,
            )
        else:
            prev = tokens[-1]
            token = sampler.sample(logits, pos=engine.pos - 1)
            res = feed(prev, token)
            if res != EosDetectorResult.EOS and engine.pos < seq_len:
                while engine.pos < seq_len:
                    logits = engine.decode_step(token)
                    prev = token
                    token = sampler.sample(logits, pos=engine.pos - 1)
                    res = feed(prev, token)
                    if res == EosDetectorResult.EOS:
                        break
        if res != EosDetectorResult.EOS:
            # context-limit exit: flush text held back as a possible
            # stop-string prefix so the reply tail is not lost
            tail = detector.flush_delta()
            if tail:
                _print(tail.decode("utf-8", errors="replace"))
    _print("\n(end of context)\n")


def worker(args) -> None:
    """Multi-host participant: joins the jax.distributed mesh and runs the
    same SPMD program as the root host.

    The reference's worker blocks on a TCP accept and receives streamed
    weight slices (reference: dllama.cpp:205-221, transformer.cpp:541-616);
    here every host loads its own shard of the `.m` file and the collective
    mesh is formed by jax.distributed.
    """
    if args.coordinator is None:
        raise SystemExit(
            "worker mode needs --coordinator host:port, --num-hosts and --host-id "
            "(every host runs the same program; start the root with the same flags "
            "and --host-id 0)"
        )
    import jax

    jax.distributed.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.num_hosts,
        process_id=args.host_id,
    )
    # after initialization, every host must execute the same SPMD program
    # with identical flags (the multi-host contract: same --prompt, --steps,
    # --tp, --seed on all hosts). A missing prompt is a contract violation —
    # a silently defaulted one would diverge from the root's program and
    # deadlock the collectives, so fail loudly instead.
    if args.prompt is None:
        raise SystemExit(
            "worker mode requires the SAME --prompt (and --steps/--tp/--seed) "
            "as every other host: all hosts execute one SPMD program"
        )
    generate(args, benchmark=False)


def main(argv=None) -> None:
    from distributed_llama_tpu.platform import (
        enable_compilation_cache,
        reassert_jax_platforms,
    )

    reassert_jax_platforms()
    args = build_parser().parse_args(argv)
    from distributed_llama_tpu import telemetry

    # must happen BEFORE make_engine: instruments bind at construction,
    # and the compile cache must be configured before the first jit
    if args.telemetry:
        telemetry.enable()
    enable_compilation_cache(args.compile_cache_dir)
    if args.mode == "inference":
        generate(args, benchmark=True)
    elif args.mode == "generate":
        generate(args, benchmark=False)
    elif args.mode == "chat":
        chat(args)
    elif args.mode == "worker":
        worker(args)
    if telemetry.is_enabled() and args.mode in ("inference", "generate"):
        path = telemetry.export_chrome_trace(args.trace_out)
        _print(f"📊 telemetry: Chrome trace written to {path}\n")


if __name__ == "__main__":
    main()
