"""Shared summary statistics: percentiles and medians for latency samples.

The ONE implementation behind every latency summary in the tree: bench.py's
median-of-N decode/TTFT numbers and the load generator's per-tenant
TTFT/TPOT/E2E p50/p90/p99 report (``distributed_llama_tpu/loadgen``) both
call these, so "p99" means the same estimator everywhere a number is
published. Pure stdlib, no numpy — loadgen's report path must stay
importable in a client-only process.

Estimator: linear interpolation between closest ranks (the numpy default,
``q/100 * (n-1)`` fractional index). For odd-length inputs the median is
exactly the middle order statistic — bit-identical to the ``sorted(xs)[1]``
median-of-3 idiom this module replaced in bench.py.
"""

from __future__ import annotations

import math
from typing import Callable, Iterable, Sequence, TypeVar

T = TypeVar("T")

# the percentiles every summary() reports — the serving-latency contract
# (docs/SERVING.md): median, common-case tail, SLO tail
SUMMARY_PERCENTILES = (50.0, 90.0, 99.0)


def percentile(values: Iterable[float], q: float) -> float:
    """The ``q``-th percentile (0..100) by linear interpolation between
    closest ranks. Raises on an empty input — a missing sample set must
    surface as an error at the call site, not as a silent 0 that reads
    like a great latency."""
    xs = sorted(float(v) for v in values)
    if not xs:
        raise ValueError("percentile() of an empty sample set")
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile q must be in [0, 100], got {q}")
    idx = (q / 100.0) * (len(xs) - 1)
    lo = math.floor(idx)
    hi = math.ceil(idx)
    if lo == hi:
        return xs[lo]
    frac = idx - lo
    return xs[lo] * (1.0 - frac) + xs[hi] * frac


def median(values: Iterable[float]) -> float:
    """Median by :func:`percentile`; for odd N this is exactly the middle
    order statistic (``sorted(xs)[n // 2]``)."""
    return percentile(values, 50.0)


def median_by(items: Sequence[T], key: Callable[[T], float]) -> T:
    """The ITEM whose key is the lower-median order statistic — for
    median-of-N over structured results (bench round dicts) where the
    caller needs the whole record, not an interpolated scalar."""
    if not items:
        raise ValueError("median_by() of an empty sequence")
    ranked = sorted(items, key=key)
    return ranked[(len(ranked) - 1) // 2]


def summarize(values: Iterable[float], unit: str = "") -> dict:
    """p50/p90/p99 + count/mean/min/max of a sample set, as the plain dict
    shape the loadgen report embeds (``{"n": ..., "mean": ..., "p50": ...,
    "p90": ..., "p99": ..., "min": ..., "max": ...}``). Empty input returns
    ``{"n": 0}`` — an absent percentile is distinguishable from a zero one."""
    xs = sorted(float(v) for v in values)
    if not xs:
        return {"n": 0}
    out: dict = {
        "n": len(xs),
        "mean": round(sum(xs) / len(xs), 3),
        "min": round(xs[0], 3),
        "max": round(xs[-1], 3),
    }
    for q in SUMMARY_PERCENTILES:
        out[f"p{int(q)}"] = round(percentile(xs, q), 3)
    if unit:
        out["unit"] = unit
    return out
