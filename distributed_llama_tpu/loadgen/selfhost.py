"""In-process self-hosted target: the REAL serving stack (ApiState +
ThreadingHTTPServer + BatchScheduler) on a tiny synthetic model.

The CI-scale loadgen gate needs a server it can build in seconds on a CPU
runner; this module stands one up from the same pieces ``serve()`` wires
in production — telemetry enabled (the report scrapes ``/metrics``), an
optional ``--faults`` chaos plan installed BEFORE construction (the
bind-once contract, docs/ROBUSTNESS.md), batched decode with the paged
prefix cache on — so a smoke run exercises admission, fairness,
preemption, quarantine and the radix cache through real HTTP, not mocks.

Zero production use: the point of `--self-host` is the zero-to-report
path (`python -m distributed_llama_tpu.loadgen --self-host`) and the CI
fairness/chaos gates in .github/workflows/main.yml.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import types
from http.server import ThreadingHTTPServer


@dataclasses.dataclass
class SelfHost:
    url: str
    state: object  # ApiState
    server: ThreadingHTTPServer
    plan: object | None = None  # the installed FaultPlan, if any
    # registered rollout target (ISSUE 18): the version id the runner's
    # mid-window POST /admin/rollout upgrades to, or None
    rollout_version: str | None = None

    def reset_faults(self) -> None:
        """Rewind the chaos plan's hit/fired counters (same plan object the
        scheduler bound). Called after warmup so ``after=``/``count=`` rule
        gates count MEASURED-window hits — otherwise warmup's decode fetches
        consume them and the chaos run silently injects nothing."""
        if self.plan is not None:
            self.plan.reset()

    def stop(self) -> None:
        self.server.shutdown()
        # stop replica supervision + scheduler watchdogs (a dead replica's
        # restart loop must not outlive the run it belongs to)
        pool = getattr(self.state, "pool", None)
        if pool is not None:
            pool.close()


def start_selfhost(
    parallel: int = 4,
    seq_len: int = 256,
    tenants: str | None = None,
    preempt: bool = True,
    faults_spec: str | None = None,
    faults_seed: int = 0,
    decode_chunk: int = 4,
    kv_page_size: int = 16,
    kv_pages: int | None = None,
    host_spill_mb: float = 16.0,
    admission_queue: int | None = None,
    deadline_ms: float | None = None,
    seed: int = 0,
    replicas: int | None = None,
    pod: str | None = None,
    canary_interval_s: float = 0.0,
    shadow_rate: float = 0.0,
    topk: int = 0,
    rollout_weights: str | None = None,
    rollout_version: str = "v1",
) -> SelfHost:
    """Build the tiny synthetic model + tokenizer, construct the real
    ApiState (batched decode, prefix cache, weighted-fair admission) and
    serve it on an ephemeral port. Mirrors ``server.api.serve``'s
    construction ORDER: telemetry before instruments bind, the fault plan
    before the scheduler binds its hooks."""
    import jax.numpy as jnp

    from distributed_llama_tpu import telemetry
    from distributed_llama_tpu.engine import InferenceEngine, faults
    from distributed_llama_tpu.formats.synthetic import (
        synthetic_tokenizer_data,
        tiny_spec,
        write_synthetic_model,
    )
    from distributed_llama_tpu.server.api import ApiState, make_handler
    from distributed_llama_tpu.tokenizer import Sampler, Tokenizer

    telemetry.enable()
    plan = None
    if faults_spec:
        plan = faults.parse(faults_spec, seed=faults_seed)
        faults.install(plan)
    tok = Tokenizer(synthetic_tokenizer_data())
    spec = tiny_spec(seq_len=seq_len, vocab_size=tok.vocab_size)
    path = write_synthetic_model(
        os.path.join(tempfile.mkdtemp(prefix="dllama-loadgen-"), "m.m"),
        spec, seed=seed,
    )
    group = None
    if pod:
        # one-process pod target (ISSUE 15): the whole replica set runs as
        # slices of ONE ('data','model') mesh sharing one weights tree —
        # the CI pod smoke drives the real serving stack through this
        # under --xla_force_host_platform_device_count CPU mesh mocks
        from distributed_llama_tpu.parallel.pod import PodGroup, parse_pod

        data, model = parse_pod(pod)
        group = PodGroup.build(path, data, model, dtype=jnp.float32)
        engine = group.slice_engine()
        # an EXPLICIT replicas=1 keeps the CONSOLIDATED single-domain pod
        # (all lanes in one batched program); the default is one replica
        # per data slice (slice-level failover) — same contract and same
        # warning as server/api.py's serve()
        if replicas not in (None, 1, data):
            print(
                f"⚠️ --replicas {replicas} ignored under --pod: one "
                f"replica per data slice ({data}), or 1 for the "
                "consolidated single-domain pod"
            )
        replicas = 1 if replicas == 1 else data
    else:
        replicas = 1 if replicas is None else replicas
        engine = InferenceEngine(path, dtype=jnp.float32)
    # counter mode (ISSUE 13): production shape — any host-sampled token is
    # a counted fallback, and a host replay matches the device stream
    sampler = Sampler(
        vocab_size=spec.vocab_size, temperature=0.0, topp=0.9, topk=topk,
        seed=1, counter=True,
    )
    args = types.SimpleNamespace(
        temperature=0.0, topp=0.9, topk=topk, seed=1, chat_template=None,
        parallel=parallel, batch_decode=True, decode="device",
        decode_chunk=decode_chunk, prefill_chunk=64,
        # tiered prefix cache (ISSUE 11): kv_pages deliberately tiny in
        # the spill smoke (forces eviction → host-RAM spill → reload);
        # None keeps the slab-sized default
        prefix_cache=True, kv_pages=kv_pages, kv_page_size=kv_page_size,
        host_spill_mb=host_spill_mb, spill_disk_dir=None, spill_disk_mb=0,
        tenants=tenants, preempt=preempt,
        admission_queue=admission_queue, deadline_ms=deadline_ms,
        stall_timeout_s=60.0,
        # replica-kill chaos (ISSUE 9): N supervised replicas over the
        # SAME synthetic model file, so a failover replay on a survivor
        # is bit-identical to the original stream; fast restart backoff
        # keeps the dead-replica-returns window inside a CI smoke
        replicas=replicas,
        replica_restart_backoff_s=0.1,
        # SDC integrity chaos (ISSUE 10): a fast canary cadence keeps the
        # detect→failover→checksum-verified-restart story inside a CI
        # smoke window; short probes keep them cheap next to real traffic
        sdc_canary_interval_s=canary_interval_s,
        sdc_canary_tokens=8,
        sdc_shadow_rate=shadow_rate,
    )
    # each replica loads the same weights (compiled programs are shared
    # across engines — same shapes, same static config); under --pod the
    # group IS the factory and replicas share ONE weights tree
    state = ApiState(
        engine, tok, sampler, args,
        engine_factory=(
            group if group is not None
            else lambda: InferenceEngine(path, dtype=jnp.float32)
        ),
    )
    registered_rollout = None
    if rollout_weights is not None:
        # blue-green rollout target (ISSUE 18): a SECOND synthetic model
        # file registered as a new weights version the runner upgrades
        # to mid-window. "same" writes byte-identical weights (same
        # seed) under a NEW version id — the full rollout pipeline
        # (drain, rebuild, checksum gate, per-version golden) runs while
        # cross-version streams stay bit-identical, so the runner's
        # consistency assert holds across the upgrade; an integer spec
        # writes genuinely different weights instead
        path2 = os.path.join(os.path.dirname(path), "m2.m")
        seed2 = seed if rollout_weights == "same" else int(rollout_weights)
        write_synthetic_model(path2, spec, seed=seed2)
        if group is not None:
            state.register_weights_version(
                rollout_version, group.sibling(path2)
            )
        else:
            state.register_weights_version(
                rollout_version,
                lambda: InferenceEngine(path2, dtype=jnp.float32),
            )
        registered_rollout = rollout_version
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    server.daemon_threads = True
    threading.Thread(
        target=server.serve_forever, name="dllama-selfhost", daemon=True
    ).start()
    return SelfHost(
        url=f"http://127.0.0.1:{server.server_address[1]}",
        state=state, server=server, plan=plan,
        rollout_version=registered_rollout,
    )
