"""The open-loop HTTP driver: fire each scheduled request at its instant,
stream the SSE response, measure TTFT / TPOT / E2E.

Open-loop is the point (Schroeder et al.'s closed-vs-open distinction the
serving literature leans on): a closed-loop client waits for completions
before sending more, so server queueing throttles the offered load and the
measured tail flatters the system precisely when it is collapsing. Here
arrivals come from the SCHEDULE — a slow server just accumulates in-flight
requests (bounded by ``max_inflight``; arrivals past the bound are
recorded as ``dropped``, never silently skipped).

Measurement points, per request:
* **TTFT** — request sent → first SSE delta with content (prefill + queue
  wait + first token; the user-visible "it started" latency).
* **TPOT** — mean gap between content deltas after the first (the decode
  cadence; one delta ≈ one token on the greedy path).
* **E2E** — request sent → terminal ``[DONE]`` (or error/failure).

Everything uses ``time.monotonic``/``Stopwatch`` — wall-clock steps must
not corrupt latency samples (the PR 1 clock discipline).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
import urllib.parse

from distributed_llama_tpu.loadgen.workload import ScheduledRequest

# terminal classification buckets the report aggregates (docs/SERVING.md)
OUTCOMES = (
    "completed", "rejected_429", "draining_503", "deadline_504",
    "error", "dropped",
)


@dataclasses.dataclass
class RequestResult:
    """One arrival's measured outcome. ``outcome`` is one of
    :data:`OUTCOMES`; latency fields are None when the phase was never
    reached (a 429 has no TTFT)."""

    index: int
    tenant: str
    at_s: float
    body_key: str
    prefix_id: int
    outcome: str
    status: int | None = None
    ttft_ms: float | None = None
    tpot_ms: float | None = None
    e2e_ms: float | None = None
    n_deltas: int = 0
    content: str = ""
    error_type: str | None = None
    retry_after: int | None = None
    sched_lag_ms: float = 0.0  # actual fire time - scheduled time


def _classify_status(status: int) -> str:
    if status == 429:
        return "rejected_429"
    if status == 503:
        return "draining_503"
    if status == 504:
        return "deadline_504"
    return "error"


def _run_one(
    host: str, port: int, req: ScheduledRequest, timeout_s: float,
    lag_ms: float,
) -> RequestResult:
    """Execute one streaming completion over a fresh connection (each
    arrival is an independent client; connection reuse would serialize
    the open loop)."""
    res = RequestResult(
        index=req.index, tenant=req.tenant, at_s=req.at_s,
        body_key=req.body_key, prefix_id=req.prefix_id, outcome="error",
        sched_lag_ms=round(lag_ms, 3),
    )
    t0 = time.monotonic()
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request(
            "POST", "/v1/chat/completions", json.dumps(req.body),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        res.status = resp.status
        if resp.status != 200:
            ra = resp.getheader("Retry-After")
            res.retry_after = int(ra) if ra and ra.isdigit() else None
            try:
                err = json.loads(resp.read())
                res.error_type = err.get("error", {}).get("type")
            except (ValueError, OSError):
                pass
            res.outcome = _classify_status(resp.status)
            res.e2e_ms = (time.monotonic() - t0) * 1000.0
            return res
        # SSE: frames are "data: <payload>\r\n\r\n"; read line-wise so the
        # first-delta timestamp is taken the moment it arrives
        first_t = last_t = None
        done = False
        parts: list[str] = []
        for raw in resp:
            line = raw.strip()
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):]
            if payload == b"[DONE]":
                done = True
                break
            try:
                evt = json.loads(payload)
            except ValueError:
                res.error_type = "bad_sse_json"
                break
            if "error" in evt:
                res.error_type = evt["error"].get("type", "server_error")
                break
            choice = (evt.get("choices") or [{}])[0]
            text = (choice.get("delta") or {}).get("content", "")
            if text:
                now = time.monotonic()
                if first_t is None:
                    first_t = now
                last_t = now
                res.n_deltas += 1
                parts.append(text)
        res.e2e_ms = (time.monotonic() - t0) * 1000.0
        res.content = "".join(parts)
        if first_t is not None:
            res.ttft_ms = (first_t - t0) * 1000.0
            if res.n_deltas > 1:
                res.tpot_ms = (
                    (last_t - first_t) * 1000.0 / (res.n_deltas - 1)
                )
        if done and res.error_type is None:
            res.outcome = "completed"
        elif res.error_type == "deadline_exceeded":
            res.outcome = "deadline_504"  # mid-stream expiry: same class
        else:
            res.outcome = "error"
        return res
    except OSError as e:
        res.error_type = f"transport:{type(e).__name__}"
        res.e2e_ms = (time.monotonic() - t0) * 1000.0
        return res
    finally:
        conn.close()


def warm_server(url: str, schedule, n: int = 2, timeout_s: float = 300.0) -> int:
    """Fire ``n`` SEQUENTIAL unmeasured requests (bodies from the schedule
    head) so jit compiles and cold caches land outside the measured
    window. Returns how many completed."""
    if not schedule:
        return 0
    parsed = urllib.parse.urlsplit(url)
    ok = 0
    for i in range(n):
        req = schedule[i % len(schedule)]
        r = _run_one(parsed.hostname, parsed.port, req, timeout_s, 0.0)
        ok += r.outcome == "completed"
    return ok


def run_schedule(
    url: str,
    schedule: list[ScheduledRequest],
    max_inflight: int = 128,
    timeout_s: float = 120.0,
) -> tuple[list[RequestResult], float]:
    """Drive ``schedule`` open-loop against ``url``. Returns (results in
    schedule order, wall seconds). Arrivals that would exceed
    ``max_inflight`` concurrent requests are recorded as ``dropped`` —
    bounded client memory, never a silent hole in the accounting."""
    parsed = urllib.parse.urlsplit(url)
    host, port = parsed.hostname, parsed.port
    results: list[RequestResult | None] = [None] * len(schedule)
    inflight = threading.Semaphore(max_inflight)
    threads: list[threading.Thread] = []
    t0 = time.monotonic()

    def fire(req: ScheduledRequest, lag_ms: float):
        try:
            results[req.index] = _run_one(host, port, req, timeout_s, lag_ms)
        finally:
            inflight.release()

    for req in schedule:
        now = time.monotonic() - t0
        if req.at_s > now:
            time.sleep(req.at_s - now)
        lag_ms = max(0.0, (time.monotonic() - t0 - req.at_s) * 1000.0)
        if not inflight.acquire(blocking=False):
            results[req.index] = RequestResult(
                index=req.index, tenant=req.tenant, at_s=req.at_s,
                body_key=req.body_key, prefix_id=req.prefix_id,
                outcome="dropped", sched_lag_ms=round(lag_ms, 3),
            )
            continue
        th = threading.Thread(
            target=fire, args=(req, lag_ms), name=f"loadgen-{req.index}",
            daemon=True,
        )
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout_s)
    wall_s = time.monotonic() - t0
    out: list[RequestResult] = []
    for req, r in zip(schedule, results):
        if r is None:  # a join timeout: the thread is stuck in transport
            r = RequestResult(
                index=req.index, tenant=req.tenant, at_s=req.at_s,
                body_key=req.body_key, prefix_id=req.prefix_id,
                outcome="error", error_type="client_timeout",
            )
        out.append(r)
    return out, wall_s
