"""The SLO report: percentile summaries, goodput, server-metric deltas,
and the machine-checked invariants (fairness / isolation / consistency).

The report is ONE JSON document (docs/SERVING.md defines the shape) built
from three inputs: the deterministic schedule, the measured per-request
results, and a before/after scrape of the server's ``/metrics`` — so
client-observed latency and server-side counters (preemptions,
quarantines, 429s, prefix-cache hits) land in the same artifact and can
be cross-checked.

Checks (each returns ``{"ok": bool, "violations": [...]}``, the CI gate
fails on any violation):

* **consistency** — greedy requests with byte-identical bodies must
  stream byte-identical content. Under a chaos plan this is the
  no-survivor-corruption proof: quarantined/errored requests are excluded,
  so any surviving mismatch is a real cross-request corruption.
* **fairness** — every tenant's arrivals are fully accounted (completed +
  rejected + deadline + errors + dropped == scheduled) and no tenant with
  scheduled work starved to zero completions while another tenant
  completed (the count-level starvation witness; the DRR share-convergence
  proof is deterministic and lives in tests/test_fair_sched.py).
* **isolation** — tenant B's contended p99 TTFT stays within
  ``bound × uncontended + slack`` of its solo run (the two-phase
  ``--isolation`` mode drives this).
"""

from __future__ import annotations

import json
import re
import urllib.request

from distributed_llama_tpu.stats import percentile, summarize
from distributed_llama_tpu.loadgen.runner import OUTCOMES, RequestResult
from distributed_llama_tpu.loadgen.workload import (
    ScheduledRequest,
    Workload,
    scheduled_counts,
)

# server counters whose run delta lands in the report (labeled series are
# summed per base name; absent series read as 0 — telemetry may be off)
SERVER_COUNTERS = (
    "dllama_preemptions_total",
    "dllama_preempted_requeued_total",
    "dllama_rows_quarantined_total",
    "dllama_admission_rejected_total",
    "dllama_deadline_exceeded_total",
    "dllama_tenant_admitted_total",
    "dllama_tenant_rejected_total",
    # prefix-cache counter family (ISSUE 11): device-tier hit/miss/evict
    # plus the spill ladder and the cross-replica routing hits — a tiered-
    # cache chaos or capacity run gates on these (--expect-delta /
    # --expect-zero)
    "dllama_prefix_cache_hits_total",
    "dllama_prefix_cache_misses_total",
    "dllama_prefix_cache_evictions_total",
    "dllama_prefix_spill_pages_total",
    "dllama_prefix_spill_reloads_total",
    "dllama_prefix_spill_dropped_total",
    "dllama_prefix_shared_hits_total",
    # hit DEPTH, not just hit count: prompt tokens actually served from
    # cached pages over the window (the histogram's _sum series). The
    # hit/miss ratio alone can't see eviction damage when every prompt
    # shares a template-preamble block — this can
    "dllama_prefix_cache_matched_tokens_sum",
    "dllama_faults_injected_total",
    "dllama_watchdog_stalls_total",
    # replica-loss fault tolerance (ISSUE 9): the failover/replay ledger —
    # a replica-kill chaos run gates on these deltas (--expect-delta)
    "dllama_replica_failovers_total",
    "dllama_replica_restarts_total",
    "dllama_replayed_requests_total",
    # silent-data-corruption detection (ISSUE 10): the SDC chaos smoke
    # gates --expect-delta on mismatches/failovers and --expect-zero on
    # the clean run's mismatch counter (zero false positives)
    "dllama_sdc_checks_total",
    "dllama_sdc_mismatches_total",
    # device-resident sampling (ISSUE 13): the sampled-traffic smoke
    # gates --expect-delta on device-sampled tokens and --expect-zero on
    # the host-sampler fallback (the no-host-round-trip happy path)
    "dllama_device_sampled_tokens_total",
    "dllama_host_sampler_fallback_total",
    # server-side SLO attribution (ISSUE 16): the fairness smoke gates
    # --expect-delta on the TTFT count (server-side latency histograms
    # actually observed traffic); the skew section below reads the
    # per-tenant stage _sum series directly
    "dllama_ttft_seconds_count",
    "dllama_tpot_seconds_count",
    "dllama_request_stage_seconds_count",
    # zero-downtime fleet ops (ISSUE 18): the rollout smoke gates
    # --expect-delta on replicas moved and --expect-zero on aborts; the
    # elasticity smoke gates on scale events; the version info gauge
    # flips 0/1 per version label on rollout completion
    "dllama_rollout_replicas_moved_total",
    "dllama_rollout_aborts_total",
    "dllama_fleet_scale_events_total",
    "dllama_weights_version",
)


def parse_prometheus(text: str) -> dict[str, float]:
    """Minimal Prometheus text-exposition parser: ``name{labels} value``
    lines → {series: value}. Histogram sub-series keep their suffixed
    names; comments and blanks drop."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def scrape_metrics(url: str, timeout_s: float = 10.0) -> dict[str, float]:
    """GET ``url``/metrics → parsed series. A scrape failure returns {}
    (the report then shows null deltas rather than aborting the run)."""
    try:
        with urllib.request.urlopen(url + "/metrics", timeout=timeout_s) as r:
            return parse_prometheus(r.read().decode())
    except OSError:
        return {}


def _sum_series(metrics: dict[str, float], base: str) -> float:
    """Sum every series of ``base`` across its label sets (exact-name
    match or ``base{...}``)."""
    return sum(
        v for k, v in metrics.items()
        if k == base or k.startswith(base + "{")
    )


def metric_deltas(
    before: dict[str, float], after: dict[str, float],
    names=SERVER_COUNTERS,
) -> dict[str, float]:
    return {
        n: round(_sum_series(after, n) - _sum_series(before, n), 3)
        for n in names
    }


_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _sum_series_by_label(
    metrics: dict[str, float], base: str, label: str
) -> dict[str, float]:
    """Sum ``base{...}`` series grouped by one label's value (e.g. the
    per-tenant stage-attribution sums)."""
    out: dict[str, float] = {}
    for k, v in metrics.items():
        if not k.startswith(base + "{"):
            continue
        labels = dict(_LABEL_RE.findall(k[len(base):]))
        key = labels.get(label)
        if key is not None:
            out[key] = out.get(key, 0.0) + v
    return out


def client_server_skew(
    results: list["RequestResult"],
    before: dict[str, float], after: dict[str, float],
) -> dict:
    """Per-tenant client-vs-server skew (ISSUE 16): the sum of
    client-measured E2E over completed requests minus the run delta of
    the server-attributed `dllama_request_stage_seconds_sum` (all stages,
    that tenant). The difference is what the server cannot see — network,
    HTTP framing, client-side queuing. A large skew with healthy server
    attribution moves the investigation off the server process."""
    base = "dllama_request_stage_seconds_sum"
    srv_before = _sum_series_by_label(before, base, "tenant")
    srv_after = _sum_series_by_label(after, base, "tenant")
    out: dict[str, dict] = {}
    for tenant in sorted({r.tenant for r in results}):
        done = [
            r for r in results
            if r.tenant == tenant and r.outcome == "completed"
            and r.e2e_ms is not None
        ]
        client_s = sum(r.e2e_ms for r in done) / 1000.0
        server_s = srv_after.get(tenant, 0.0) - srv_before.get(tenant, 0.0)
        out[tenant] = {
            "completed": len(done),
            "client_e2e_s": round(client_s, 3),
            "server_attributed_s": round(server_s, 3),
            "skew_s": round(client_s - server_s, 3),
            "skew_per_request_ms": (
                round((client_s - server_s) / len(done) * 1000.0, 3)
                if done else None
            ),
        }
    return out


# ----------------------------------------------------------------------
# Aggregation
# ----------------------------------------------------------------------


def _summarize_group(
    results: list[RequestResult], wall_s: float,
    slo_ttft_ms: float | None = None, slo_e2e_ms: float | None = None,
) -> dict:
    counts = {o: 0 for o in OUTCOMES}
    for r in results:
        counts[r.outcome] += 1
    completed = [r for r in results if r.outcome == "completed"]
    good = [
        r for r in completed
        if (slo_ttft_ms is None or (r.ttft_ms or 0) <= slo_ttft_ms)
        and (slo_e2e_ms is None or (r.e2e_ms or 0) <= slo_e2e_ms)
    ]
    out = {
        "scheduled": len(results),
        "counts": counts,
        "ttft_ms": summarize([r.ttft_ms for r in completed if r.ttft_ms is not None]),
        "tpot_ms": summarize([r.tpot_ms for r in completed if r.tpot_ms is not None]),
        "e2e_ms": summarize([r.e2e_ms for r in completed if r.e2e_ms is not None]),
        "sched_lag_ms": summarize([r.sched_lag_ms for r in results]),
        "tokens_streamed": sum(r.n_deltas for r in completed),
        # goodput: completions INSIDE their SLO targets, as a rate and as
        # a fraction of everything that was scheduled (not of completions —
        # shed load must hurt the number, that is its job)
        "goodput_rps": round(len(good) / wall_s, 3) if wall_s > 0 else 0.0,
        "goodput_under_slo": (
            round(len(good) / len(results), 4) if results else 0.0
        ),
    }
    # the observed Retry-After values across 429/503 responses: more than
    # one distinct value is the visible proof the jitter satellite works
    # (a fixed header re-synchronizes every rejected client's retry)
    ras = sorted({
        r.retry_after for r in results if r.retry_after is not None
    })
    if ras:
        out["retry_after_s_seen"] = ras
    if slo_ttft_ms is not None or slo_e2e_ms is not None:
        out["slo"] = {"ttft_ms": slo_ttft_ms, "e2e_ms": slo_e2e_ms}
    return out


def build_report(
    workload: Workload,
    schedule: list[ScheduledRequest],
    results: list[RequestResult],
    wall_s: float,
    fingerprint: str,
    replay_verified: bool,
    metrics_before: dict[str, float] | None = None,
    metrics_after: dict[str, float] | None = None,
) -> dict:
    """Assemble the SLO report (docs/SERVING.md "Report format")."""
    slos = {t.name: (t.slo_ttft_ms, t.slo_e2e_ms) for t in workload.tenants}
    tenants: dict[str, dict] = {}
    for name in sorted({r.tenant for r in results}):
        rs = [r for r in results if r.tenant == name]
        ttft, e2e = slos.get(name, (None, None))
        tenants[name] = _summarize_group(rs, wall_s, ttft, e2e)
    report = {
        "workload": workload.spec_dict(),
        "schedule": {
            "fingerprint": fingerprint,
            "replay_verified": replay_verified,
            "n_requests": len(schedule),
            "per_tenant": scheduled_counts(schedule),
        },
        "wall_s": round(wall_s, 3),
        "aggregate": _summarize_group(results, wall_s),
        "tenants": tenants,
        "server": (
            metric_deltas(metrics_before, metrics_after)
            if metrics_before is not None and metrics_after is not None
            else None
        ),
        "client_vs_server_skew": (
            client_server_skew(results, metrics_before, metrics_after)
            if metrics_before is not None and metrics_after is not None
            else None
        ),
        "checks": {"consistency": check_consistency(results)},
    }
    report["checks"]["fairness"] = check_fairness(report)
    return report


# ----------------------------------------------------------------------
# Invariant checks
# ----------------------------------------------------------------------


def check_consistency(results: list[RequestResult]) -> dict:
    """Greedy determinism across the run: every group of byte-identical
    request bodies must have streamed byte-identical content. Only
    completed requests participate — under chaos, quarantined victims are
    EXPECTED casualties; a mismatch among the survivors is corruption."""
    groups: dict[str, set[str]] = {}
    sizes: dict[str, int] = {}
    for r in results:
        if r.outcome != "completed":
            continue
        groups.setdefault(r.body_key, set()).add(r.content)
        sizes[r.body_key] = sizes.get(r.body_key, 0) + 1
    violations = [
        f"body {k}: {sizes[k]} completions streamed "
        f"{len(variants)} distinct contents"
        for k, variants in groups.items()
        if len(variants) > 1
    ]
    return {
        "ok": not violations,
        "groups": len(groups),
        "repeated_groups": sum(1 for k in groups if sizes[k] > 1),
        "violations": violations,
    }


def check_fairness(report: dict) -> dict:
    """Count-level fairness/accounting invariants over the finished run
    (see module docstring)."""
    violations: list[str] = []
    tenants: dict[str, dict] = report.get("tenants", {})
    completed_anywhere = any(
        t["counts"]["completed"] > 0 for t in tenants.values()
    )
    for name, t in tenants.items():
        accounted = sum(t["counts"].values())
        if accounted != t["scheduled"]:
            violations.append(
                f"tenant {name!r}: {accounted} outcomes for "
                f"{t['scheduled']} scheduled arrivals (requests lost)"
            )
        if (
            completed_anywhere
            and t["scheduled"] > 0
            and t["counts"]["completed"] == 0
        ):
            violations.append(
                f"tenant {name!r} starved: 0 of {t['scheduled']} arrivals "
                "completed while other tenants were served"
            )
    return {"ok": not violations, "violations": violations}


def check_isolation(
    tenant: str,
    uncontended: list[RequestResult],
    contended: list[RequestResult],
    bound: float = 10.0,
    slack_ms: float = 1000.0,
) -> dict:
    """Two-phase tenant-isolation check: tenant ``tenant``'s p99 TTFT
    under full contention must stay within ``bound × uncontended p99 +
    slack_ms``. The slack term absorbs tiny-model CI noise where the
    uncontended p99 is single-digit milliseconds and a multiplicative
    bound alone would be a coin flip."""
    solo = [
        r.ttft_ms for r in uncontended
        if r.tenant == tenant and r.outcome == "completed"
        and r.ttft_ms is not None
    ]
    mixed = [
        r.ttft_ms for r in contended
        if r.tenant == tenant and r.outcome == "completed"
        and r.ttft_ms is not None
    ]
    if not solo or not mixed:
        return {
            "ok": False,
            "violations": [
                f"tenant {tenant!r}: no completed samples in "
                f"{'solo' if not solo else 'mixed'} phase"
            ],
        }
    p99_solo = percentile(solo, 99)
    p99_mixed = percentile(mixed, 99)
    limit = bound * p99_solo + slack_ms
    ok = p99_mixed <= limit
    return {
        "ok": ok,
        "tenant": tenant,
        "uncontended_p99_ttft_ms": round(p99_solo, 3),
        "contended_p99_ttft_ms": round(p99_mixed, 3),
        "bound": bound,
        "slack_ms": slack_ms,
        "limit_ms": round(limit, 3),
        "violations": [] if ok else [
            f"tenant {tenant!r}: contended p99 TTFT {p99_mixed:.1f} ms "
            f"exceeds {limit:.1f} ms ({bound}x uncontended "
            f"{p99_solo:.1f} ms + {slack_ms:.0f} ms slack)"
        ],
    }


def check_goodput(report: dict, floor: float) -> dict:
    """Aggregate goodput floor (the replica-kill chaos gate's teeth): the
    fraction of SCHEDULED arrivals that completed inside their SLO must
    not fall below ``floor`` — a failover that sheds the whole window
    (instead of replaying its victims on survivors) fails here even when
    every surviving stream is individually consistent."""
    got = report.get("aggregate", {}).get("goodput_under_slo", 0.0)
    ok = got >= floor
    return {
        "ok": ok,
        "goodput_under_slo": got,
        "floor": floor,
        "violations": [] if ok else [
            f"aggregate goodput {got:.3f} below the {floor:.3f} floor"
        ],
    }


def check_expected_deltas(report: dict, specs: list[str]) -> dict:
    """Gate on server-side counter movement: each spec is ``name:min`` —
    the run's /metrics delta for ``name`` must be ≥ ``min``. This is how
    a chaos smoke proves its fault actually FIRED (a replica-kill run
    with zero `dllama_replica_failovers_total` movement tested nothing)."""
    server = report.get("server") or {}
    violations: list[str] = []
    expected: dict[str, float] = {}
    for spec in specs:
        name, _, floor_s = spec.partition(":")
        name = name.strip()
        try:
            floor = float(floor_s) if floor_s.strip() else 1.0
        except ValueError:
            # a malformed MIN is a reportable violation, not a traceback
            # after minutes of traffic: the run's report must still land
            violations.append(
                f"malformed --expect-delta spec {spec!r} (want NAME:MIN)"
            )
            continue
        expected[name] = floor
        got = server.get(name)
        if got is None:
            violations.append(
                f"counter {name!r} not in the report's server deltas"
            )
        elif got < floor:
            violations.append(
                f"counter {name!r} moved {got:g}, expected >= {floor:g}"
            )
    return {"ok": not violations, "expected": expected,
            "violations": violations}


def check_expected_zero(report: dict, names: list[str]) -> dict:
    """Gate on server-side counter STILLNESS: each ``name``'s run delta
    must be exactly 0. The mirror image of :func:`check_expected_deltas`
    (ISSUE 10): a clean run proving `dllama_sdc_mismatches_total` did NOT
    move is the zero-false-positive witness — an integrity layer that
    cries wolf on healthy replicas would fail over the whole pool for
    nothing. An absent series reads as 0 (telemetry may be off)."""
    violations: list[str] = []
    server = report.get("server")
    if server is None:
        # a failed /metrics scrape would make every stillness claim
        # vacuously true — that is not a passing gate
        return {"ok": False, "expected_zero": list(names),
                "violations": ["no server metric deltas in the report"]}
    checked: list[str] = []
    for name in names:
        name = name.strip()
        if not name:
            continue
        checked.append(name)
        got = server.get(name, 0.0) or 0.0
        if got != 0:
            violations.append(
                f"counter {name!r} moved {got:g}, expected exactly 0"
            )
    return {"ok": not violations, "expected_zero": checked,
            "violations": violations}


def check_rollout(rollout: dict, results) -> dict:
    """The zero-downtime gate (ISSUE 18): the mid-window POST
    /admin/rollout must have returned 200 (every replica moved to the
    new version, checksum- and canary-certified) AND no request in the
    window may have failed — arrivals that straddled a drain must have
    finished on the old version or replayed on a survivor, not errored.
    429s are admission shedding (workload pressure, not the rollout) and
    stay out of this gate; the goodput floor judges those."""
    violations: list[str] = []
    status = rollout.get("status")
    if status != 200:
        detail = rollout.get("error") or rollout.get("response")
        violations.append(
            f"POST /admin/rollout returned {status!r} ({detail!r}), "
            "expected 200"
        )
    failed = [
        {"index": r.index, "tenant": r.tenant, "outcome": r.outcome,
         "status": r.status, "error_type": r.error_type}
        for r in results
        if r.outcome not in ("completed", "rejected_429")
    ]
    violations.extend(
        f"request {f['index']} ({f['tenant']}) failed during the rollout "
        f"window: {f['outcome']}" for f in failed
    )
    return {
        "ok": not violations,
        "status": status,
        "response": rollout.get("response"),
        "failed_requests": failed,
        "violations": violations,
    }


def fetch_flight(url: str, timeout_s: float = 10.0) -> dict | None:
    """GET ``url``/debug/flight → the flight-recorder snapshot (ISSUE 16);
    None on failure (the gate then reports a violation, not a traceback)."""
    try:
        with urllib.request.urlopen(
            url + "/debug/flight", timeout=timeout_s
        ) as r:
            return json.loads(r.read().decode())
    except (OSError, ValueError):
        return None


def check_expected_flight(snapshot: dict | None, specs: list[str]) -> dict:
    """Gate on flight-recorder lifecycle events (ISSUE 16): each spec is
    ``kind[@site][:min]`` — at least ``min`` (default 1) events of
    ``kind`` (optionally with that ``site`` field, for `fault_fire`) must
    appear across the replica rings. The replica-kill CI smoke gates
    ``fault_fire@replica.crash:1`` and ``failover:1``: the black box must
    show the injection AND the recovery it caused."""
    violations: list[str] = []
    expected: list[dict] = []
    if snapshot is None:
        return {"ok": False, "expected": specs,
                "violations": ["/debug/flight snapshot unavailable"]}
    events = [
        ev for ring in (snapshot.get("replicas") or {}).values()
        for ev in ring
    ]
    for spec in specs:
        head, colon, floor_s = spec.rpartition(":")
        if not colon:
            head, floor_s = spec, ""
        try:
            floor = float(floor_s) if floor_s.strip() else 1.0
        except ValueError:
            violations.append(
                f"malformed --expect-flight spec {spec!r} "
                "(want KIND[@SITE][:MIN])"
            )
            continue
        kind, _, site = head.partition("@")
        kind, site = kind.strip(), site.strip()
        got = sum(
            1 for ev in events
            if ev.get("kind") == kind
            and (not site or ev.get("site") == site)
        )
        expected.append({"kind": kind, "site": site or None, "min": floor})
        if got < floor:
            violations.append(
                f"flight events kind={kind!r}"
                + (f" site={site!r}" if site else "")
                + f": saw {got}, expected >= {floor:g}"
            )
    return {"ok": not violations, "expected": expected,
            "violations": violations}


def failed_checks(report: dict) -> list[str]:
    """Flatten every check's violations (the CLI's --assert exit path)."""
    out: list[str] = []
    for name, chk in (report.get("checks") or {}).items():
        if chk and not chk.get("ok", True):
            out.extend(f"[{name}] {v}" for v in chk.get("violations", []))
    return out


def dump_report(report: dict, path: str | None) -> str:
    text = json.dumps(report, indent=2, sort_keys=False)
    if path:
        with open(path, "w") as f:
            f.write(text + "\n")
    return text
