"""Seeded workload specs and the deterministic schedule builder.

A :class:`Workload` describes traffic SHAPE (arrival process, rate, prompt
mix, tenant mix); :func:`build_schedule` expands it into a concrete list
of timestamped requests using ONLY ``random.Random(seed)`` — no wall
clock, no entropy — so the same (spec, seed) always yields the
byte-identical schedule (:func:`schedule_fingerprint` is the replay
proof the CI gate asserts).

Workload shape follows the serving-benchmark literature the ISSUE names:
* **Zipf-shared prefixes** — prompts draw their system-prompt prefix from
  ``n_prefixes`` pools with Zipf(``zipf_s``) popularity, the
  production-shaped workload for the radix prefix cache (a hot prefix is
  published once and hit by its whole tail of requests).
* **Open-loop arrivals** — ``poisson`` (exponential gaps at ``rate_rps``),
  ``burst`` (``burst_size`` back-to-back arrivals every
  ``burst_period_s`` — the admission-queue / Retry-After stressor), or
  ``uniform`` (fixed gaps; the quiet-loop control).
* **Tenant mixes** — each arrival is assigned a tenant by ``share``;
  tenants carry priority, ``deadline_ms`` and SLO targets into the
  request bodies and the report.

Suffixes draw from a small pool (``n_suffixes``) ON PURPOSE: repeated
identical greedy bodies form the consistency groups the chaos gate uses
to prove survivors are uncorrupted (report.check_consistency).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import random

# deterministic filler vocabulary for prompt text (byte-level synthetic
# tokenizers encode ~1 token/char, real tokenizers ~1 token/word — lengths
# are approximate by design; the schedule records characters)
_WORDS = (
    "alpha bravo charlie delta echo foxtrot golf hotel india juliet kilo "
    "lima mike november oscar papa quebec romeo sierra tango uniform "
    "victor whiskey xray yankee zulu"
).split()


@dataclasses.dataclass
class TenantLoad:
    """One tenant's slice of the workload. ``share`` is its fraction of
    arrivals (normalized across tenants); ``priority``/``deadline_ms``
    ride into request bodies; the ``slo_*`` targets classify completions
    for goodput-under-SLO (a completion outside any set target is
    throughput but not goodput)."""

    name: str
    share: float = 1.0
    priority: int | None = None
    deadline_ms: float | None = None
    slo_ttft_ms: float | None = None
    slo_e2e_ms: float | None = None
    max_tokens: int = 8

    def __post_init__(self):
        if self.share < 0:
            raise ValueError(f"tenant {self.name!r}: share must be >= 0")
        if self.max_tokens < 1:
            raise ValueError(f"tenant {self.name!r}: max_tokens must be >= 1")


@dataclasses.dataclass
class Workload:
    """The full workload spec; every field participates in the schedule
    fingerprint. Defaults are the CI-scale smoke shape."""

    seed: int = 0
    n_requests: int = 32
    rate_rps: float = 16.0
    arrival: str = "poisson"  # poisson | burst | uniform
    burst_size: int = 8
    burst_period_s: float = 1.0
    n_prefixes: int = 4
    zipf_s: float = 1.1
    prefix_chars: int = 48
    n_suffixes: int = 6
    suffix_chars: int = 12
    # sampling shape (ISSUE 13): temperature > 0 drives the fused
    # DEVICE-sampled path instead of greedy argmax. Bodies always pin
    # seed 0, and the counter PRNG keys coins on (seed, position), so
    # byte-identical sampled bodies still stream byte-identically — the
    # survivor-consistency contract holds for sampled traffic too
    temperature: float = 0.0
    topp: float = 0.9
    topk: int = 0
    tenants: list[TenantLoad] = dataclasses.field(
        default_factory=lambda: [TenantLoad("default")]
    )

    def __post_init__(self):
        if self.arrival not in ("poisson", "burst", "uniform"):
            raise ValueError(f"unknown arrival process {self.arrival!r}")
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1")
        if self.rate_rps <= 0:
            raise ValueError("rate_rps must be > 0")
        if not self.tenants:
            raise ValueError("workload needs at least one tenant")
        if self.n_prefixes < 1 or self.n_suffixes < 1:
            raise ValueError("n_prefixes and n_suffixes must be >= 1")

    def spec_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["tenants"] = [dataclasses.asdict(t) for t in self.tenants]
        return d


def parse_tenant_loads(spec: str | None) -> list[TenantLoad]:
    """Parse the CLI tenant-mix spec: ``;``-separated
    ``name:key=val,key=val`` with numeric fields ``share``/``priority``/
    ``deadline_ms``/``slo_ttft_ms``/``slo_e2e_ms``/``max_tokens`` — e.g.
    ``"gold:share=0.3,priority=5,slo_ttft_ms=2000;free:share=0.7"``."""
    if not (spec or "").strip():
        return [TenantLoad("default")]
    out: list[TenantLoad] = []
    seen = set()
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, kvs = part.partition(":")
        name = name.strip()
        if not name or name in seen:
            raise ValueError(f"bad or duplicate tenant entry: {part!r}")
        seen.add(name)
        kw: dict = {"name": name}
        for kv in filter(None, (x.strip() for x in kvs.split(","))):
            k, _, v = kv.partition("=")
            k = k.strip()
            if k in ("priority", "max_tokens"):
                kw[k] = int(v)
            elif k in ("share", "deadline_ms", "slo_ttft_ms", "slo_e2e_ms"):
                kw[k] = float(v)
            else:
                raise ValueError(f"unknown tenant-load field {k!r}")
        out.append(TenantLoad(**kw))
    return out


@dataclasses.dataclass
class ScheduledRequest:
    """One concrete arrival: fire the ``body`` at ``at_s`` seconds after
    run start. ``body_key`` groups byte-identical greedy bodies for the
    survivor-consistency check; ``prefix_id`` tracks radix-cache
    popularity."""

    index: int
    at_s: float
    tenant: str
    prefix_id: int
    body: dict
    body_key: str


def _zipf_cdf(n: int, s: float) -> list[float]:
    weights = [1.0 / (i + 1) ** s for i in range(n)]
    total = sum(weights)
    cdf, acc = [], 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    return cdf


def _pick(cdf: list[float], u: float) -> int:
    for i, c in enumerate(cdf):
        if u <= c:
            return i
    return len(cdf) - 1


def _text(rng: random.Random, n_chars: int, tag: str) -> str:
    words = [tag]
    while sum(len(w) + 1 for w in words) < n_chars:
        words.append(rng.choice(_WORDS))
    return " ".join(words)


def build_schedule(w: Workload) -> list[ScheduledRequest]:
    """Expand ``w`` into its deterministic arrival schedule. Pure in
    (spec, seed): every draw comes from one ``random.Random(w.seed)`` in a
    fixed order, so replays are byte-identical (the fingerprint proves
    it)."""
    rng = random.Random(w.seed)
    # prompt material first, in a fixed order independent of arrivals
    prefixes = [
        _text(rng, w.prefix_chars, f"ctx{i}") for i in range(w.n_prefixes)
    ]
    suffixes = [
        _text(rng, w.suffix_chars, f"q{i}") for i in range(w.n_suffixes)
    ]
    cdf = _zipf_cdf(w.n_prefixes, w.zipf_s)
    total_share = sum(t.share for t in w.tenants)
    if total_share <= 0:
        raise ValueError("tenant shares sum to zero")
    tenant_cdf, acc = [], 0.0
    for t in w.tenants:
        acc += t.share / total_share
        tenant_cdf.append(acc)

    out: list[ScheduledRequest] = []
    t_s = 0.0
    for i in range(w.n_requests):
        if w.arrival == "poisson":
            t_s += rng.expovariate(w.rate_rps)
            at = t_s
        elif w.arrival == "uniform":
            at = i / w.rate_rps
        else:  # burst
            at = (
                (i // w.burst_size) * w.burst_period_s
                + (i % w.burst_size) * 1e-3
            )
        tenant = w.tenants[_pick(tenant_cdf, rng.random())]
        pid = _pick(cdf, rng.random())
        sid = rng.randrange(w.n_suffixes)
        body: dict = {
            "messages": [
                {"role": "system", "content": prefixes[pid]},
                {"role": "user", "content": suffixes[sid]},
            ],
            "max_tokens": tenant.max_tokens,
            # identical bodies MUST stream identically (the consistency
            # contract): seed 0 pins the counter PRNG, so it holds for
            # sampled (temperature > 0) traffic exactly as for greedy
            "temperature": w.temperature,
            "seed": 0,
            "stream": True,
            "tenant": tenant.name,
        }
        if w.temperature > 0.0:
            body["top_p"] = w.topp
            if w.topk > 0:
                body["top_k"] = w.topk
        if tenant.priority is not None:
            body["priority"] = tenant.priority
        if tenant.deadline_ms is not None:
            body["deadline_ms"] = tenant.deadline_ms
        key = hashlib.sha256(
            json.dumps(body, sort_keys=True).encode()
        ).hexdigest()[:16]
        out.append(
            ScheduledRequest(
                index=i, at_s=round(at, 6), tenant=tenant.name,
                prefix_id=pid, body=body, body_key=key,
            )
        )
    return out


def schedule_fingerprint(schedule: list[ScheduledRequest]) -> str:
    """sha256 over every arrival's (time, tenant, prefix, body key): the
    deterministic-replay witness — two builds of the same (spec, seed)
    must produce the same fingerprint, and the CI gate rebuilds to check."""
    h = hashlib.sha256()
    for r in schedule:
        h.update(
            f"{r.index}|{r.at_s:.6f}|{r.tenant}|{r.prefix_id}|{r.body_key}\n".encode()
        )
    return h.hexdigest()


def scheduled_counts(schedule: list[ScheduledRequest]) -> dict[str, int]:
    """Per-tenant scheduled request counts (the deterministic aggregate
    the replay check compares)."""
    out: dict[str, int] = {}
    for r in schedule:
        out[r.tenant] = out.get(r.tenant, 0) + 1
    return out
