"""Production traffic harness: a deterministic, seeded load generator that
drives the real HTTP server (`server/api.py`) with contended, bursty,
multi-tenant workloads and reports tail latency — the harness every perf
PR is judged against (ISSUE 8; ROADMAP open item 5).

Everything PRs 1–7 built (batching, fault tolerance, prefix caching,
speculative decode) was measured median-of-a-quiet-loop; this package is
where "fast" gets a p99 and "robust" gets goodput-under-SLO evidence:

* :mod:`~distributed_llama_tpu.loadgen.workload` — seeded workload specs:
  Zipf-distributed shared prompt prefixes (exercising the radix prefix
  cache), mixed prompt/output lengths, open-loop Poisson / bursty /
  uniform arrivals, per-tenant shares, priorities, deadlines and SLOs.
  ``build_schedule`` is a pure function of (spec, seed): same seed → the
  byte-identical arrival schedule, fingerprinted for replay proofs.
* :mod:`~distributed_llama_tpu.loadgen.runner` — the open-loop HTTP
  driver: requests fire at their scheduled instants regardless of
  completions (closed-loop clients hide queueing collapse), stream SSE,
  and record TTFT / TPOT / E2E per request.
* :mod:`~distributed_llama_tpu.loadgen.report` — the SLO report:
  per-tenant and aggregate p50/p90/p99, goodput-under-SLO, 429/504/
  preemption/quarantine counts scraped from ``/metrics`` (before/after
  deltas), plus fairness / isolation / greedy-consistency checks.
* :mod:`~distributed_llama_tpu.loadgen.selfhost` — an in-process server
  on a tiny synthetic model for CI-scale runs, composable with a
  ``--faults`` chaos plan (chaos-under-load).

CLI: ``python -m distributed_llama_tpu.loadgen --help``; workload and
report formats: docs/SERVING.md.
"""

from distributed_llama_tpu.loadgen.report import build_report  # noqa: F401
from distributed_llama_tpu.loadgen.runner import run_schedule  # noqa: F401
from distributed_llama_tpu.loadgen.workload import (  # noqa: F401
    TenantLoad,
    Workload,
    build_schedule,
    schedule_fingerprint,
)
