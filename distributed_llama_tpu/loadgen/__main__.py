"""``python -m distributed_llama_tpu.loadgen`` — drive a server, print the
SLO report.

Examples (docs/SERVING.md has the full walkthrough):

  # CI-scale zero-to-report: tiny synthetic model, in-process server
  JAX_PLATFORMS=cpu python -m distributed_llama_tpu.loadgen --self-host \\
      --requests 24 --rate 20 \\
      --tenants "gold:share=0.3,priority=5,slo_ttft_ms=5000;free:share=0.7" \\
      --assert --out loadgen-report.json

  # chaos-under-load: same run with a fault plan on the server side
  ... --self-host --faults "batch.row:kind=nan,row=1,after=2,count=1"

  # two-phase tenant-isolation proof for tenant "gold"
  ... --self-host --isolation gold

  # an external server (the report scrapes <url>/metrics for deltas)
  python -m distributed_llama_tpu.loadgen --url http://127.0.0.1:9990

Exit codes: 0 = report produced (all asserted checks passed), 1 = a
``--assert``/``--isolation`` check failed, 2 = the run itself failed.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.error
import urllib.request

from distributed_llama_tpu.loadgen import report as rep
from distributed_llama_tpu.loadgen import runner, workload


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m distributed_llama_tpu.loadgen",
        description="deterministic multi-tenant load generator for the "
        "dllama API server (docs/SERVING.md)",
    )
    tgt = p.add_mutually_exclusive_group(required=True)
    tgt.add_argument("--url", type=str, help="base URL of a running server")
    tgt.add_argument(
        "--self-host", action="store_true",
        help="serve a tiny synthetic model in-process (CI-scale; "
        "JAX_PLATFORMS=cpu recommended)",
    )
    # workload shape (defaults = the CI smoke)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--rate", type=float, default=16.0, help="arrival rate rps")
    p.add_argument(
        "--arrival", choices=("poisson", "burst", "uniform"),
        default="poisson",
    )
    p.add_argument("--burst-size", type=int, default=8)
    p.add_argument("--burst-period-s", type=float, default=1.0)
    p.add_argument("--prefixes", type=int, default=4,
                   help="Zipf-shared prompt prefix pool size")
    p.add_argument("--zipf-s", type=float, default=1.1)
    p.add_argument("--prefix-chars", type=int, default=48)
    p.add_argument("--suffixes", type=int, default=6)
    p.add_argument("--suffix-chars", type=int, default=12)
    p.add_argument(
        "--tenants", type=str, default=None,
        help="tenant mix: 'name:share=S,priority=P,deadline_ms=D,"
        "slo_ttft_ms=T,slo_e2e_ms=E,max_tokens=M;...' (default: one "
        "'default' tenant)",
    )
    # sampled traffic (ISSUE 13): temperature > 0 exercises the fused
    # device sampler end to end; bodies pin seed 0, so the consistency
    # check still holds (counter-PRNG streams are deterministic per seed)
    p.add_argument(
        "--temperature", type=float, default=0.0,
        help="request-body temperature (0 = greedy; > 0 drives the fused "
        "device-sampled decode path with pinned seeds)",
    )
    p.add_argument(
        "--topp", type=float, default=0.9,
        help="request-body top_p for sampled (--temperature > 0) traffic",
    )
    p.add_argument(
        "--topk", type=int, default=0,
        help="request-body top_k for sampled traffic (0 = off)",
    )
    # driving
    p.add_argument("--max-inflight", type=int, default=128)
    p.add_argument("--timeout-s", type=float, default=120.0)
    p.add_argument(
        "--warmup", type=int, default=3,
        help="sequential unmeasured requests before the open loop "
        "(jit compiles land outside the measured window)",
    )
    # self-host server knobs
    p.add_argument("--parallel", type=int, default=4,
                   help="self-host serving slots (batch rows) per replica")
    p.add_argument(
        "--replicas", type=int, default=None,
        help="self-host supervised data-parallel replicas (ISSUE 9; "
        "default 1, or one per data slice under --pod, where an explicit "
        "--replicas 1 picks the consolidated single-domain pod): a "
        "replica-kill chaos run composes this with --faults "
        "'replica.crash:...' and gates on --expect-delta/--goodput-floor",
    )
    p.add_argument(
        "--pod", type=str, default=None, metavar="DATAxMODEL",
        help="self-host ONE-PROCESS pod serving (ISSUE 15): the replica "
        "set runs as slices of a single ('data','model') mesh sharing "
        "one weights tree (replicas = the data extent). Needs "
        "data*model CPU devices (--xla_force_host_platform_device_count "
        "in XLA_FLAGS); a mid-window 'replica.crash' fault IS the "
        "mesh-slice kill of the CI pod smoke",
    )
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument(
        "--kv-pages", type=int, default=None,
        help="self-host prefix-cache pool budget in pages (default: "
        "slab-sized). A deliberately tiny value forces eviction → "
        "host-RAM spill → reload, the ISSUE 11 capacity-ladder smoke",
    )
    p.add_argument(
        "--host-spill-mb", type=float, default=16.0,
        help="self-host host-RAM spill arena budget in MiB "
        "(--host-spill-mb on the server; 0 disables the tier)",
    )
    p.add_argument(
        "--server-tenants", type=str, default=None,
        help="self-host --tenants spec (weights/priorities/queues); "
        "defaults to the workload tenants at weight 1",
    )
    p.add_argument(
        "--faults", type=str, default=None,
        help="self-host chaos plan spec (docs/ROBUSTNESS.md) — "
        "chaos-under-load composition",
    )
    p.add_argument("--faults-seed", type=int, default=0)
    p.add_argument("--no-preempt", action="store_true")
    p.add_argument(
        "--admission-queue", type=int, default=None,
        help="self-host admission queue bound (default 2x --parallel; "
        "raise it to measure queueing latency instead of 429 shedding)",
    )
    # report / checks
    p.add_argument("--out", type=str, default=None, help="report JSON path")
    p.add_argument(
        "--assert", dest="assert_checks", action="store_true",
        help="exit 1 unless fairness + consistency checks pass",
    )
    p.add_argument(
        "--isolation", type=str, default=None, metavar="TENANT",
        help="two-phase isolation proof: run TENANT's arrivals alone, "
        "then the full mix; asserts contended p99 TTFT <= bound x "
        "uncontended + slack",
    )
    p.add_argument("--isolation-bound", type=float, default=10.0)
    p.add_argument("--isolation-slack-ms", type=float, default=1000.0)
    p.add_argument(
        "--goodput-floor", type=float, default=None, metavar="FRACTION",
        help="assert aggregate goodput_under_slo >= FRACTION (the "
        "replica-kill chaos gate: a failover must replay its victims, "
        "not shed the window)",
    )
    p.add_argument(
        "--expect-delta", action="append", default=[], metavar="NAME:MIN",
        help="assert a server counter's run delta moved at least MIN "
        "(default 1) — proves a chaos fault actually fired, e.g. "
        "'dllama_replica_failovers_total:1'; repeatable",
    )
    p.add_argument(
        "--expect-zero", action="append", default=[], metavar="NAME",
        help="assert a server counter's run delta did NOT move — the "
        "mirror of --expect-delta (ISSUE 10): a clean run gating "
        "'dllama_sdc_mismatches_total' to zero proves the integrity "
        "layer raises no false positives; repeatable",
    )
    p.add_argument(
        "--expect-flight", action="append", default=[],
        metavar="KIND[@SITE][:MIN]",
        help="assert the server's /debug/flight rings hold at least MIN "
        "(default 1) lifecycle events of KIND, optionally with a given "
        "fault site — e.g. 'fault_fire@replica.crash:1' proves the chaos "
        "injection landed in the black box, 'failover:1' the recovery it "
        "caused; repeatable (ISSUE 16)",
    )
    p.add_argument(
        "--canary-interval-s", type=float, default=0.0,
        help="self-host SDC canary period (--sdc-canary-interval-s on "
        "the server): pinned greedy probes per replica compared against "
        "the pool golden; 0 disables",
    )
    p.add_argument(
        "--shadow-rate", type=float, default=0.0,
        help="self-host cross-replica shadow-vote sampling fraction "
        "(--sdc-shadow-rate on the server)",
    )
    # live blue-green rollout (ISSUE 18): upgrade the pool mid-window and
    # gate on zero failed requests — the zero-downtime proof
    p.add_argument(
        "--rollout-weights", type=str, default=None, metavar="SPEC",
        help="fire POST /admin/rollout mid-window. Self-host: 'same' "
        "writes a second synthetic model with identical bytes under a "
        "new version id (the consistency check holds across the "
        "upgrade); an integer writes genuinely different weights from "
        "that seed. URL mode: a server-side weights path passed "
        "through in the rollout body",
    )
    p.add_argument(
        "--rollout-at", type=float, default=0.5, metavar="FRACTION",
        help="when to fire the rollout, as a fraction of the last "
        "scheduled arrival's offset (default 0.5 = mid-window)",
    )
    p.add_argument(
        "--rollout-version", type=str, default="v1",
        help="version id the rollout upgrades to",
    )
    return p


def make_workload(args) -> workload.Workload:
    return workload.Workload(
        seed=args.seed,
        n_requests=args.requests,
        rate_rps=args.rate,
        arrival=args.arrival,
        burst_size=args.burst_size,
        burst_period_s=args.burst_period_s,
        n_prefixes=args.prefixes,
        zipf_s=args.zipf_s,
        prefix_chars=args.prefix_chars,
        n_suffixes=args.suffixes,
        suffix_chars=args.suffix_chars,
        temperature=args.temperature,
        topp=args.topp,
        topk=args.topk,
        tenants=workload.parse_tenant_loads(args.tenants),
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    w = make_workload(args)
    schedule = workload.build_schedule(w)
    fingerprint = workload.schedule_fingerprint(schedule)
    # deterministic-replay proof: a second independent build of the same
    # (spec, seed) must fingerprint identically — asserted on EVERY run,
    # it is cheap and it is the contract
    replay_ok = (
        workload.schedule_fingerprint(workload.build_schedule(w))
        == fingerprint
    )
    host = None
    if args.self_host:
        from distributed_llama_tpu.loadgen.selfhost import start_selfhost

        host = start_selfhost(
            parallel=args.parallel,
            seq_len=args.seq_len,
            tenants=args.server_tenants,
            preempt=not args.no_preempt,
            faults_spec=args.faults,
            faults_seed=args.faults_seed,
            kv_pages=args.kv_pages,
            host_spill_mb=args.host_spill_mb,
            admission_queue=args.admission_queue,
            replicas=args.replicas,
            pod=args.pod,
            canary_interval_s=args.canary_interval_s,
            shadow_rate=args.shadow_rate,
            topk=args.topk,
            rollout_weights=args.rollout_weights,
            rollout_version=args.rollout_version,
        )
        url = host.url
        print(f"self-hosted server at {url}", file=sys.stderr)
    else:
        url = args.url.rstrip("/")
    try:
        if args.warmup > 0:
            warmed = runner.warm_server(
                url, schedule, n=args.warmup, timeout_s=max(args.timeout_s, 300.0)
            )
            print(f"warmup: {warmed}/{args.warmup} completed", file=sys.stderr)
        if host is not None:
            # chaos determinism: rule gates (after/count) must count hits of
            # the MEASURED window, not warmup's — rewind the plan counters
            host.reset_faults()
        solo_results = None
        if args.isolation:
            solo = [r for r in schedule if r.tenant == args.isolation]
            if not solo:
                print(
                    f"isolation tenant {args.isolation!r} has no arrivals",
                    file=sys.stderr,
                )
                return 2
            # phase 1: the probe tenant alone, same instants (uncontended)
            solo_results, _ = runner.run_schedule(
                url, _reindexed(solo), max_inflight=args.max_inflight,
                timeout_s=args.timeout_s,
            )
        rollout_thread = None
        rollout_result: dict = {}
        if args.rollout_weights is not None:
            body = {"version": args.rollout_version}
            if not args.self_host:
                # URL mode: the server resolves the weights path itself
                body["weights"] = args.rollout_weights
            # fire mid-window, scaled to the schedule's actual span, so
            # in-flight old-version streams straddle the upgrade
            delay_s = max(0.0, args.rollout_at * schedule[-1].at_s)
            rollout_thread = threading.Thread(
                target=_rollout_trigger,
                args=(url, body, delay_s, args.timeout_s, rollout_result),
                name="loadgen-rollout", daemon=True,
            )
        before = rep.scrape_metrics(url)
        if rollout_thread is not None:
            rollout_thread.start()
        results, wall_s = runner.run_schedule(
            url, schedule, max_inflight=args.max_inflight,
            timeout_s=args.timeout_s,
        )
        if rollout_thread is not None:
            # the POST is synchronous server-side — joining means the
            # rollout (or its rollback) has fully settled, so the metric
            # deltas scraped next include every replica move
            rollout_thread.join(timeout=args.timeout_s)
        after = rep.scrape_metrics(url)
        report = rep.build_report(
            w, schedule, results, wall_s, fingerprint, replay_ok,
            metrics_before=before, metrics_after=after,
        )
        if solo_results is not None:
            report["checks"]["isolation"] = rep.check_isolation(
                args.isolation, solo_results, results,
                bound=args.isolation_bound, slack_ms=args.isolation_slack_ms,
            )
        if args.goodput_floor is not None:
            report["checks"]["goodput"] = rep.check_goodput(
                report, args.goodput_floor
            )
        if args.expect_delta:
            report["checks"]["expected_deltas"] = rep.check_expected_deltas(
                report, args.expect_delta
            )
        if args.expect_zero:
            report["checks"]["expected_zero"] = rep.check_expected_zero(
                report, args.expect_zero
            )
        if args.expect_flight:
            report["checks"]["expected_flight"] = rep.check_expected_flight(
                rep.fetch_flight(url), args.expect_flight
            )
        if rollout_thread is not None:
            report["checks"]["rollout"] = rep.check_rollout(
                rollout_result, results
            )
        text = rep.dump_report(report, args.out)
        print(text)
        if not replay_ok:
            print("FATAL: schedule replay fingerprint mismatch", file=sys.stderr)
            return 2
        # explicitly requested gates (--goodput-floor/--expect-delta/
        # --expect-zero/--expect-flight) are ALWAYS enforced: asking for a
        # gate and then
        # ignoring its verdict tests nothing. --assert additionally
        # enforces the built-in consistency/fairness checks — an SDC
        # chaos run skips it on purpose: requests a corrupt replica
        # served before detection stream wrong-but-completed bodies,
        # which is exactly the failure mode under test, not a harness bug
        gate_names = (
            "goodput", "expected_deltas", "expected_zero", "expected_flight",
            "rollout",
        )
        requested = [report["checks"].get(k) for k in gate_names]
        bad = [
            f"[{k}] {v}"
            for k, chk in zip(gate_names, requested)
            if chk and not chk.get("ok", True)
            for v in chk.get("violations", [])
        ]
        if args.assert_checks or args.isolation:
            bad = rep.failed_checks(report)
        if bad:
            for v in bad:
                print(f"CHECK FAILED: {v}", file=sys.stderr)
            return 1
        if args.assert_checks or args.isolation or any(requested):
            print("all checks passed", file=sys.stderr)
        return 0
    finally:
        if host is not None:
            host.stop()


def _rollout_trigger(
    url: str, body: dict, delay_s: float, timeout_s: float, out: dict
) -> None:
    """Sleep to the mid-window instant, then POST /admin/rollout and
    record (status, response JSON) into ``out``. Runs on its own thread
    so the open loop keeps firing arrivals while the pool upgrades —
    which is the entire point of the zero-downtime gate."""
    time.sleep(delay_s)
    req = urllib.request.Request(
        url + "/admin/rollout", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            out["status"] = r.status
            out["response"] = json.loads(r.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        # 4xx/5xx still carry the server's JSON error payload (e.g. the
        # RolloutAborted rollback summary) — keep it for the report
        out["status"] = e.code
        try:
            out["response"] = json.loads(e.read().decode() or "{}")
        except Exception:
            out["response"] = None
    except Exception as e:  # connection-level failure
        out["status"] = None
        out["error"] = f"{type(e).__name__}: {e}"


def _reindexed(subset):
    """Re-index a schedule subset from 0 (run_schedule stores results by
    index) without mutating the original entries."""
    import dataclasses as dc

    return [dc.replace(r, index=i) for i, r in enumerate(subset)]


if __name__ == "__main__":
    sys.exit(main())
