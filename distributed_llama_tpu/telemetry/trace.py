"""Request-scoped tracing (ISSUE 16): one span tree per request.

The PR 1 ring tracer answers "what is the engine doing" in aggregate; this
module answers the production question "why was THIS request slow". A
:class:`TraceContext` is created per completion request at the HTTP front
door and threaded through every layer the request touches — fair-admission
queue wait, replica placement, prefix-cache match/reload, prefill chunks,
the shared batched decode dispatches (each fanning out to a per-row child
span), speculative verify, failover replays, SSE sends — so the server can
assemble a complete per-request tree and serve it at
``GET /debug/trace/<request_id>`` (JSON, or Chrome trace-event format).

Design constraints inherited from the PR 1 telemetry contract:

* **Zero overhead off** — with telemetry disabled the serving layer never
  constructs a store, every stream's ``trace`` attribute stays ``None``,
  and each hook is one attribute check. The module-level :func:`span`
  helper returns a shared no-op context manager for a ``None`` context.
* **Bounded** — a context's event list is a ring (``MAX_EVENTS``); the
  store retains a bounded deque of finished traces plus the in-flight map.
* **Sampled at retention, not at recording** — every request records while
  telemetry is on (recording is a lock + list append per span), and the
  store decides at completion whether to KEEP the trace: a seeded
  Bernoulli draw at ``sample_rate``, overridden to always-keep when the
  request's TTFT crossed ``slow_ttft_s`` (the trace you want most is the
  slow one you didn't know to sample).

Attribution: the serving layer calls :meth:`TraceContext.add_stage` with
wall time measured around each stage boundary (queue / placement /
prefill / decode); stages recorded during a replayed attempt fold into
``replay``. The per-tenant ``dllama_ttft_seconds`` / ``dllama_tpot_seconds``
histograms and the ``dllama_request_stage_seconds`` breakdown are observed
from the same timestamps, so the server-side SLO surface and the trace
tree can never disagree about what they measured.
"""

from __future__ import annotations

import collections
import random
import threading
import time

MAX_EVENTS = 2048


class _TraceSpan:
    """Context manager recording one complete span on a TraceContext."""

    __slots__ = ("_ctx", "_name", "_args", "_t0")

    def __init__(self, ctx: "TraceContext", name: str, args: dict):
        self._ctx = ctx
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._ctx.add_span(
            self._name, self._t0, time.perf_counter() - self._t0, **self._args
        )
        return False


class _NullTraceSpan:
    """Shared no-op for untraced requests: zero state, zero recording."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_TRACE_SPAN = _NullTraceSpan()


def span(ctx: "TraceContext | None", name: str, **args):
    """``with trace.span(ctx, "queue_wait"):`` — records a span on ``ctx``,
    or nothing when the request is untraced (``ctx is None``)."""
    if ctx is None:
        return NULL_TRACE_SPAN
    return _TraceSpan(ctx, name, args)


class TraceContext:
    """One request's trace: events tagged with the attempt that recorded
    them (a failover replay is a NEW sibling attempt in the same tree),
    per-stage attribution accumulators, and the first/last-token
    timestamps TTFT/TPOT derive from."""

    __slots__ = (
        "request_id", "tenant", "_lock", "_t0", "attempt", "attempts",
        "events", "stages", "notes", "first_token_s", "last_token_s",
        "emitted", "e2e_s", "sampled",
    )

    def __init__(self, request_id: str, tenant: str):
        self.request_id = request_id
        self.tenant = tenant
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self.attempt = 0
        # one dict per attempt; [-1] is the live one. ``replayed`` marks
        # attempts re-run after a replica loss / preemption requeue.
        self.attempts: list[dict] = []
        self.events: collections.deque = collections.deque(maxlen=MAX_EVENTS)
        self.stages: dict[str, float] = {}
        self.notes: dict = {}
        self.first_token_s: float | None = None
        self.last_token_s: float | None = None
        self.emitted = 0
        self.e2e_s: float | None = None
        self.sampled: bool | None = None

    # -- recording ------------------------------------------------------

    def begin_attempt(self, replayed: bool = False, replica: int | None = None):
        with self._lock:
            self.attempts.append(
                {
                    "replayed": bool(replayed),
                    "replica": replica,
                    "start_us": (time.perf_counter() - self._t0) * 1e6,
                }
            )
            self.attempt = len(self.attempts) - 1

    def set_replica(self, replica: int) -> None:
        """Stamp the live attempt with the replica that placement chose
        (placement resolves AFTER begin_attempt, so this back-fills)."""
        with self._lock:
            if not self.attempts:
                self.attempts.append(
                    {"replayed": False, "replica": None, "start_us": 0.0}
                )
            self.attempts[-1]["replica"] = int(replica)

    def add_span(self, name: str, t0: float, dur_s: float, **args) -> None:
        """Record a completed span (``t0`` an absolute ``perf_counter``
        instant; sub-perf_counter-resolution spans keep dur 0)."""
        with self._lock:
            if not self.attempts:
                self.attempts.append(
                    {"replayed": False, "replica": None, "start_us": 0.0}
                )
            self.events.append(
                {
                    "name": name,
                    "ts_us": (t0 - self._t0) * 1e6,
                    "dur_us": dur_s * 1e6,
                    "attempt": self.attempt,
                    "args": args,
                }
            )

    def span(self, name: str, **args) -> _TraceSpan:
        return _TraceSpan(self, name, args)

    def add_stage(self, stage: str, seconds: float) -> None:
        """Accumulate attribution; stages of a replayed attempt fold into
        ``replay`` (the breakdown stays queue/placement/prefill/decode
        for the attempt that actually streamed)."""
        with self._lock:
            if self.attempts and self.attempts[-1]["replayed"]:
                stage = "replay"
            self.stages[stage] = self.stages.get(stage, 0.0) + float(seconds)

    def note(self, **fields) -> None:
        with self._lock:
            self.notes.update(fields)

    def mark_token(self) -> None:
        """Per-emitted-token stamp (the serving layer's feed loop): the
        first stamp is TTFT, the spread of the rest is TPOT."""
        now = time.perf_counter() - self._t0
        with self._lock:
            if self.first_token_s is None:
                self.first_token_s = now
            self.last_token_s = now
            self.emitted += 1

    def finish(self) -> None:
        self.e2e_s = time.perf_counter() - self._t0

    # -- derived --------------------------------------------------------

    @property
    def ttft_s(self) -> float | None:
        return self.first_token_s

    @property
    def tpot_s(self) -> float | None:
        if (
            self.first_token_s is None
            or self.last_token_s is None
            or self.emitted < 2
        ):
            return None
        return (self.last_token_s - self.first_token_s) / (self.emitted - 1)

    # -- assembly -------------------------------------------------------

    def tree(self) -> dict:
        """The assembled span tree: request root → attempt siblings →
        recorded spans (docs/OBSERVABILITY.md "Request tracing")."""
        with self._lock:
            events = list(self.events)
            attempts = [dict(a) for a in self.attempts]
        nodes = []
        for i, meta in enumerate(attempts):
            spans = [e for e in events if e["attempt"] == i]
            end = max(
                (e["ts_us"] + e["dur_us"] for e in spans),
                default=meta["start_us"],
            )
            nodes.append(
                {
                    "name": "attempt",
                    "index": i,
                    "replayed": meta["replayed"],
                    "replica": meta["replica"],
                    "start_us": meta["start_us"],
                    "dur_us": end - meta["start_us"],
                    "spans": spans,
                }
            )
        return {
            "request_id": self.request_id,
            "tenant": self.tenant,
            "sampled": self.sampled,
            "e2e_s": self.e2e_s,
            "ttft_s": self.ttft_s,
            "tpot_s": self.tpot_s,
            "emitted": self.emitted,
            "stages": dict(self.stages),
            "notes": dict(self.notes),
            "attempts": nodes,
        }

    def chrome_trace(self) -> dict:
        """The same tree as Chrome trace-event JSON (chrome://tracing /
        ui.perfetto.dev): attempts map to tids, spans to complete events."""
        tree = self.tree()
        out = []
        for node in tree["attempts"]:
            out.append(
                {
                    "name": f"attempt{node['index']}"
                    + (" (replay)" if node["replayed"] else ""),
                    "ph": "X",
                    "ts": node["start_us"],
                    "dur": node["dur_us"],
                    "pid": 0,
                    "tid": node["index"],
                    "args": {"replayed": node["replayed"]},
                }
            )
            for e in node["spans"]:
                out.append(
                    {
                        "name": e["name"],
                        "ph": "X",
                        "ts": e["ts_us"],
                        "dur": e["dur_us"],
                        "pid": 0,
                        "tid": node["index"],
                        "args": dict(e["args"]),
                    }
                )
        return {"traceEvents": out, "displayTimeUnit": "ms"}


class RequestTraceStore:
    """Bounded retention for finished traces + the in-flight map.

    ``sample_rate`` draws from a seeded RNG (deterministic per process —
    trace retention must never depend on wall entropy in tests);
    ``slow_ttft_s`` always-keeps a trace whose TTFT crossed the threshold,
    whatever the draw said."""

    def __init__(
        self,
        capacity: int = 256,
        sample_rate: float = 1.0,
        slow_ttft_s: float = 1.0,
    ):
        self.capacity = max(1, int(capacity))
        self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        self.slow_ttft_s = float(slow_ttft_s)
        self._lock = threading.Lock()
        self._rng = random.Random(0)
        self._inflight: dict[str, TraceContext] = {}
        self._done: collections.deque = collections.deque(maxlen=self.capacity)
        self.started_total = 0
        self.kept_total = 0
        self.slow_kept_total = 0

    def begin(self, request_id: str, tenant: str) -> TraceContext:
        ctx = TraceContext(request_id, tenant)
        with self._lock:
            self.started_total += 1
            self._inflight[ctx.request_id] = ctx
        return ctx

    def finish(self, ctx: TraceContext) -> bool:
        """Close out ``ctx`` and decide retention. Returns True if kept."""
        ctx.finish()
        with self._lock:
            self._inflight.pop(ctx.request_id, None)
            keep = self._rng.random() < self.sample_rate
            slow = (
                ctx.ttft_s is not None
                and self.slow_ttft_s > 0
                and ctx.ttft_s >= self.slow_ttft_s
            )
            if slow and not keep:
                keep = True
                self.slow_kept_total += 1
            ctx.sampled = keep
            if keep:
                self.kept_total += 1
                self._done.append(ctx)
        return keep

    def get(self, request_id: str) -> TraceContext | None:
        with self._lock:
            for ctx in reversed(self._done):
                if ctx.request_id == request_id:
                    return ctx
            return self._inflight.get(request_id)

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "sample_rate": self.sample_rate,
                "slow_ttft_s": self.slow_ttft_s,
                "inflight": len(self._inflight),
                "retained": len(self._done),
                "started_total": self.started_total,
                "kept_total": self.kept_total,
                "slow_kept_total": self.slow_kept_total,
            }
