"""Per-replica flight recorder (ISSUE 16): the black box that survives.

When a replica dies, the evidence of WHY — the fault that fired, the
health-state walk, the canary verdicts leading up to the kill — used to
die with it (scattered prints, a ring tracer that scrolled past). This
module keeps a bounded per-replica ring of structured lifecycle events,
always on (the events are rare: state transitions, fault fires, row
quarantines, canary/shadow/checksum verdicts, failovers, watchdog
stalls), and auto-dumps a JSON snapshot of the victim's ring on replica
death, SDC detection, or a watchdog stall. Live at ``GET /debug/flight``
(server/api.py), printable via ``python -m
distributed_llama_tpu.telemetry.dump --flight``, and asserted by the
loadgen ``--expect-flight`` gate.

The fault-fire feed hooks :meth:`FaultPlan._match` through
``faults.add_fire_observer`` — every ACTUAL injection is recorded with the
``faults.SITES`` site that fired (docs/ROBUSTNESS.md), so a flight dump
always names the chaos rule behind an injected death.

Lock discipline: the recorder's lock is a LEAF — records arrive from under
the scheduler cond, the pool cond, and the fault plan's own lock. Nothing
here calls out while holding it; an optional ``dump_dir`` file write
happens on a spawned daemon thread.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from distributed_llama_tpu import lockcheck

# unattributed events (a fault fire with no row/replica context) land here
UNSCOPED = -1

MAX_EVENTS_PER_REPLICA = 512
MAX_DUMPS = 16


class FlightRecorder:
    def __init__(
        self,
        capacity: int = MAX_EVENTS_PER_REPLICA,
        max_dumps: int = MAX_DUMPS,
        dump_dir: str | None = None,
    ):
        self.capacity = max(1, int(capacity))
        self.max_dumps = max(1, int(max_dumps))
        self.dump_dir = dump_dir
        self._lock = lockcheck.make_lock("FlightRecorder._lock")
        self._epoch = time.perf_counter()
        self._rings: dict[int, collections.deque] = {}
        self._dumps: collections.deque = collections.deque(maxlen=self.max_dumps)
        self._seq = 0
        self.recorded_total = 0
        self.dumps_total = 0

    def record(self, replica: int, kind: str, **fields) -> None:
        """Append one lifecycle event to ``replica``'s ring. ``fields``
        must be JSON-serializable scalars/lists (the dump is the wire
        format)."""
        ev = {
            "seq": 0,  # patched under the lock: a global order across rings
            "t_s": round(time.perf_counter() - self._epoch, 6),
            "replica": int(replica),
            "kind": kind,
        }
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            ring = self._rings.get(int(replica))
            if ring is None:
                ring = collections.deque(maxlen=self.capacity)
                self._rings[int(replica)] = ring
            ring.append(ev)
            self.recorded_total += 1

    def dump(self, replica: int, reason: str, **fields) -> dict:
        """Snapshot ``replica``'s ring into the bounded dump list (the
        auto-dump on death/SDC/stall). Returns the dump object; when
        ``dump_dir`` is set the JSON artifact is also written from a
        daemon thread (never under a caller's lock)."""
        with self._lock:
            events = list(self._rings.get(int(replica), ()))
            self.dumps_total += 1
            n = self.dumps_total
        d = {
            "dump": n,
            "t_s": round(time.perf_counter() - self._epoch, 6),
            "replica": int(replica),
            "reason": reason,
            "events": events,
        }
        d.update(fields)
        with self._lock:
            self._dumps.append(d)
        if self.dump_dir:
            path = os.path.join(
                self.dump_dir, f"dllama-flight-r{int(replica)}-{n}.json"
            )
            threading.Thread(
                target=self._write, args=(path, d),
                name="dllama-flight-dump", daemon=True,
            ).start()
        return d

    @staticmethod
    def _write(path: str, d: dict) -> None:
        try:
            with open(path, "w") as f:
                json.dump(d, f, indent=2)
            print(f"🛬 flight recorder dump written: {path}")
        except Exception as e:
            print(f"⚠️ flight recorder dump write failed: {e}")

    def snapshot(self) -> dict:
        """The live view served at /debug/flight: every ring plus the
        retained dumps (docs/OBSERVABILITY.md "Flight recorder")."""
        with self._lock:
            return {
                "recorded_total": self.recorded_total,
                "dumps_total": self.dumps_total,
                "replicas": {
                    str(rid): list(ring) for rid, ring in self._rings.items()
                },
                "dumps": list(self._dumps),
            }

    def dumps(self) -> list[dict]:
        with self._lock:
            return list(self._dumps)

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()
            self._dumps.clear()


# The process-wide recorder (always on — lifecycle events are rare enough
# that there is nothing to gate; components call record() directly).
RECORDER = FlightRecorder()


def record(replica: int, kind: str, **fields) -> None:
    RECORDER.record(replica, kind, **fields)


def _on_fault_fire(site: str, rule, row) -> None:
    """faults.add_fire_observer hook: every actual injection lands in the
    ring of the row/replica the rule targeted (``row=`` selects the
    replica id for replica.*/engine.sdc/engine.spill sites and the batch
    row elsewhere — recorded as-is; UNSCOPED when untargeted)."""
    RECORDER.record(
        UNSCOPED if row is None else int(row),
        "fault_fire",
        site=site,
        fault_kind=getattr(rule, "kind", ""),
    )


_installed = False


def install_fault_observer() -> None:
    """Wire the recorder into the fault plan's injection point. Idempotent;
    the import is deferred so this module stays importable without the
    engine package (the dump CLI's remote mode)."""
    global _installed
    if _installed:
        return
    from distributed_llama_tpu.engine import faults

    faults.add_fire_observer(_on_fault_fire)
    _installed = True
