"""Lightweight span tracer: nested wall-time spans in a ring buffer, with
Chrome trace-event JSON export.

Spans mark the engine's phase structure (prefill, decode chunk dispatch,
chunk fetch, transfer probe) on a wall-clock timeline — the offline
complement to the registry's aggregates. The buffer is a fixed-size ring
(old spans fall off; a long-running server never grows), and the export is
the Chrome ``traceEvents`` format, loadable in chrome://tracing or
https://ui.perfetto.dev.

Enter/exit costs two ``perf_counter`` calls plus one deque append; the
disabled path never reaches this module (the telemetry facade hands out a
shared no-op span instead).
"""

from __future__ import annotations

import collections
import json
import threading
import time


class SpanEvent:
    __slots__ = ("name", "ts_us", "dur_us", "tid", "depth", "args")

    def __init__(self, name, ts_us, dur_us, tid, depth, args):
        self.name = name
        self.ts_us = ts_us
        self.dur_us = dur_us
        self.tid = tid
        self.depth = depth
        self.args = args


class _Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_depth")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        local = self._tracer._local
        self._depth = getattr(local, "depth", 0)
        local.depth = self._depth + 1
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer._local.depth = self._depth
        self._tracer._record(
            SpanEvent(
                self.name,
                (self._t0 - self._tracer._epoch) * 1e6,
                (t1 - self._t0) * 1e6,
                threading.get_ident(),
                self._depth,
                self.args,
            )
        )
        return False


class _NullSpan:
    """Shared no-op span for disabled telemetry: zero state, zero recording."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class SpanTracer:
    def __init__(self, capacity: int = 65536):
        self._epoch = time.perf_counter()
        self._lock = threading.Lock()
        self._local = threading.local()
        self._events: collections.deque[SpanEvent] = collections.deque(maxlen=capacity)

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args)

    def _record(self, ev: SpanEvent) -> None:
        with self._lock:
            self._events.append(ev)

    def events(self) -> list[SpanEvent]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def chrome_trace(self) -> dict:
        """The buffered spans as a Chrome trace-event JSON object."""
        trace_events = [
            {
                "name": ev.name,
                "ph": "X",
                "ts": ev.ts_us,
                "dur": ev.dur_us,
                "pid": 0,
                "tid": ev.tid,
                "args": {**ev.args, "depth": ev.depth},
            }
            for ev in self.events()
        ]
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path
