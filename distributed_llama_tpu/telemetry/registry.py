"""Process-global metrics registry: counters, gauges, fixed-bucket histograms.

The reference engine has no metrics at all — its only operational signals
are the per-token G/I/T stat prints (reference: src/apps/dllama/dllama.cpp:
49-93). This registry is the shared sink those ad-hoc prints never had:
every instrument is a named, typed, optionally-labelled value that can be
read live (Prometheus text exposition, server /metrics) or snapshotted
(bench.py, `python -m distributed_llama_tpu.telemetry.dump`).

Design constraints (ISSUE 1):

* **Zero overhead when disabled.** Callers bind instruments ONCE (engine
  construction, server startup) through :mod:`distributed_llama_tpu.telemetry`,
  which hands back shared null singletons when telemetry is off — the hot
  loop then pays one attribute-bound no-op method call per *dispatch* (not
  per token), no dict lookups, and the registry is never touched.
* **Thread safety.** The API server records from several completion threads
  at once; instrument mutation takes a per-instrument lock (the enabled
  path only — null instruments have no state).
* **Fixed buckets.** Histograms are fixed-boundary (Prometheus semantics:
  cumulative bucket counts + sum + count); the default boundaries span
  10 µs → 10 s, tuned for token-level latency work.
"""

from __future__ import annotations

import threading

# 10 µs → 10 s: wide enough for a Pallas kernel tile at the bottom and a
# cold-compile prefill at the top, log-ish spaced for token-level latency
DEFAULT_LATENCY_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers without a trailing .0."""
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_text(items: tuple[tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(str(v))}"' for k, v in items]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Instrument:
    """Common machinery: a name/help pair and (optional) label children.

    An instrument created with ``labelnames`` is a parent: call
    ``.labels(key=value, ...)`` to get (or lazily create) the child that
    actually holds a value. Without labelnames the instrument holds its own
    value directly.
    """

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], _Instrument] = {}
        self._label_items: tuple[tuple[str, str], ...] = ()

    def labels(self, **kw):
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kw)} != declared {sorted(self.labelnames)}"
            )
        key = tuple(str(kw[k]) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                child._label_items = tuple(zip(self.labelnames, key))
                self._children[key] = child
            return child

    def _make_child(self) -> "_Instrument":
        raise NotImplementedError

    def _check_unlabelled(self) -> None:
        if self.labelnames:
            raise ValueError(
                f"{self.name} declares labels {self.labelnames}; use .labels(...)"
            )

    def _series(self):
        """The value-holding instruments: self, or the label children."""
        if self.labelnames:
            with self._lock:
                return list(self._children.values())
        return [self]


class Counter(_Instrument):
    """Monotonically increasing count (Prometheus counter)."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._value = 0.0

    def _make_child(self):
        return Counter(self.name, self.help)

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (inc {n})")
        self._check_unlabelled()
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def _exposition_lines(self, series):
        return [
            f"{self.name}{_labels_text(s._label_items)} {_fmt(s._value)}"
            for s in series
        ]


class Gauge(_Instrument):
    """A value that can go up and down (occupancy, in-flight requests)."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._value = 0.0

    def _make_child(self):
        return Gauge(self.name, self.help)

    def set(self, v: float) -> None:
        self._check_unlabelled()
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self._check_unlabelled()
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def _exposition_lines(self, series):
        return [
            f"{self.name}{_labels_text(s._label_items)} {_fmt(s._value)}"
            for s in series
        ]


class Histogram(_Instrument):
    """Fixed-bucket histogram with Prometheus cumulative-bucket semantics."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, labelnames)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError(f"{self.name}: at least one bucket boundary required")
        self.buckets = b
        self._counts = [0] * (len(b) + 1)  # last slot = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def _make_child(self):
        return Histogram(self.name, self.help, buckets=self.buckets)

    def observe(self, v: float) -> None:
        self._check_unlabelled()
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _read_consistent(self) -> tuple[dict[float, int], float, int]:
        """(cumulative bucket counts, sum, count) under the instrument lock:
        a reader racing observe() must never see count != the +Inf bucket
        (the Prometheus histogram invariant promtool lints for)."""
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        out, acc = {}, 0
        for b, c in zip(self.buckets, counts):
            acc += c
            out[b] = acc
        out[float("inf")] = acc + counts[-1]
        return out, total, n

    def bucket_counts(self) -> dict[float, int]:
        """CUMULATIVE counts keyed by upper bound (inf included), the
        Prometheus ``le`` semantics."""
        return self._read_consistent()[0]

    def _exposition_lines(self, series):
        lines = []
        for s in series:
            buckets, total, n = s._read_consistent()
            for b, c in buckets.items():
                le = _labels_text(s._label_items, extra=f'le="{_fmt(b)}"')
                lines.append(f"{self.name}_bucket{le} {c}")
            lt = _labels_text(s._label_items)
            lines.append(f"{self.name}_sum{lt} {_fmt(total)}")
            lines.append(f"{self.name}_count{lt} {n}")
        return lines


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Name → instrument map with idempotent registration and text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Instrument] = {}

    def _register(self, cls, name, help, labelnames, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as {existing.kind} "
                        f"with labels {existing.labelnames}"
                    )
                if "buckets" in kw and existing.buckets != tuple(
                    sorted(float(x) for x in kw["buckets"])
                ):
                    # a silent bucket mismatch would land observations in
                    # boundaries the second registrant never asked for
                    raise ValueError(
                        f"histogram {name!r} already registered with buckets "
                        f"{existing.buckets}"
                    )
                return existing
            inst = cls(name, help, labelnames=labelnames, **kw)
            self._metrics[name] = inst
            return inst

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(
        self, name: str, help: str = "", labelnames=(),
        buckets=DEFAULT_LATENCY_BUCKETS,
    ) -> Histogram:
        return self._register(Histogram, name, help, labelnames, buckets=buckets)

    def get(self, name: str) -> _Instrument | None:
        return self._metrics.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        """Drop every registered metric (tests)."""
        with self._lock:
            self._metrics.clear()

    def prometheus_text(self) -> str:
        """The full registry in Prometheus text exposition format 0.0.4.

        Counters with zero increments and histograms with zero observations
        still expose their series, so a freshly started server advertises
        its metric names before the first request."""
        lines: list[str] = []
        for name in self.names():
            m = self._metrics[name]
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            lines.extend(m._exposition_lines(m._series()))
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """One-shot JSON-able view of every metric (the dump helper's and
        bench.py's read path)."""
        out: dict[str, dict] = {}
        for name in self.names():
            m = self._metrics[name]
            entry: dict = {"type": m.kind, "help": m.help}
            series = []
            for s in m._series():
                item: dict = {"labels": dict(s._label_items)}
                if isinstance(s, Histogram):
                    buckets, total, count = s._read_consistent()
                    item.update(
                        sum=total, count=count,
                        buckets={_fmt(b): c for b, c in buckets.items()},
                    )
                else:
                    item["value"] = s._value
                series.append(item)
            entry["series"] = series
            out[name] = entry
        return out
