"""Unified telemetry: metrics registry + span tracer + exposition (ISSUE 1).

One process-global :class:`~distributed_llama_tpu.telemetry.registry.MetricsRegistry`
and one :class:`~distributed_llama_tpu.telemetry.tracer.SpanTracer` back every
instrument in the engine, the parallel backends, the API server, and bench.py.
The reference engine's only observability is ad-hoc stat prints
(reference: src/apps/dllama/dllama.cpp:49-93); this module is the shared sink.

Toggling
--------
Telemetry is OFF by default. Enable with the ``--telemetry`` CLI flag
(dllama-tpu / dllama-tpu-api / bench.py) or ``DLLAMA_TELEMETRY=1`` in the
environment (read once at import). ``enable()`` / ``disable()`` switch the
process at runtime, but instruments are BOUND at component construction:
code binds once (engine ``__init__``, server startup) via :func:`counter` /
:func:`gauge` / :func:`histogram` / the ``span`` factory, and gets back

* the real registry-registered instrument when telemetry is enabled, or
* a shared null singleton whose methods are no-ops when it is disabled.

That bind-once contract is the zero-overhead-when-disabled design: the hot
decode loop holds direct attribute references, pays one no-op method call
per *dispatch* (never per token), performs no dict lookups, and never
mutates the registry. Components constructed before ``enable()`` keep their
null instruments — construct (or rebind) after enabling.

Metric names are listed in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import os
import time

from distributed_llama_tpu.telemetry.registry import (  # noqa: F401  (re-export)
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from distributed_llama_tpu.telemetry.tracer import (  # noqa: F401  (re-export)
    NULL_SPAN,
    SpanTracer,
)

REGISTRY = MetricsRegistry()
TRACER = SpanTracer()

_ENV_VAR = "DLLAMA_TELEMETRY"
_enabled = os.environ.get(_ENV_VAR, "").strip().lower() in ("1", "true", "on", "yes")


def is_enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Clear the registry and the span ring buffer (tests)."""
    REGISTRY.reset()
    TRACER.clear()


# ----------------------------------------------------------------------
# Null instruments: the disabled-mode bind targets. One shared stateless
# singleton per kind — no locks, no values, no registry entry. Tradeoff:
# .labels(...) cannot validate label NAMES here (the shared singleton
# knows no declaration, and a per-call check would tax the disabled hot
# path), so a labelnames typo only surfaces when telemetry is enabled —
# every labelled call site must therefore be covered by an enabled-mode
# test (tests/test_telemetry.py does this for all current sites).
# ----------------------------------------------------------------------


class _NullCounter:
    __slots__ = ()
    value = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def labels(self, **kw):
        return self


class _NullGauge:
    __slots__ = ()
    value = 0.0

    def set(self, v: float) -> None:
        pass

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def labels(self, **kw):
        return self


class _NullHistogram:
    __slots__ = ()
    count = 0
    sum = 0.0

    def observe(self, v: float) -> None:
        pass

    def labels(self, **kw):
        return self


NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()


def counter(name: str, help: str = "", labelnames=()) -> Counter | _NullCounter:
    if not _enabled:
        return NULL_COUNTER
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames=()) -> Gauge | _NullGauge:
    if not _enabled:
        return NULL_GAUGE
    return REGISTRY.gauge(name, help, labelnames)


def histogram(
    name: str, help: str = "", labelnames=(), buckets=DEFAULT_LATENCY_BUCKETS
) -> Histogram | _NullHistogram:
    if not _enabled:
        return NULL_HISTOGRAM
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def _null_span(name: str, **args):
    return NULL_SPAN


def _real_span(name: str, **args):
    return TRACER.span(name, **args)


def span_factory():
    """The span entry point to BIND at construction time: returns either the
    live tracer's span() or a factory handing out the shared no-op span."""
    return _real_span if _enabled else _null_span


def trace_span(name: str, **args):
    """``with trace_span("decode", step=pos):`` — checks the enable flag per
    call; hot paths should bind :func:`span_factory` once instead."""
    return (_real_span if _enabled else _null_span)(name, **args)


def prometheus_text() -> str:
    return REGISTRY.prometheus_text()


def chrome_trace() -> dict:
    return TRACER.chrome_trace()


def export_chrome_trace(path: str) -> str:
    return TRACER.export_chrome_trace(path)


# ----------------------------------------------------------------------
# Shared wall-clock helper: the ONE copy of the perf-timing pattern that
# engine/engine.py and parallel/tensor_parallel.py used to hand-roll.
# ----------------------------------------------------------------------


class Stopwatch:
    """``sw = Stopwatch(); ...; ms = sw.elapsed_ms()`` — monotonic, restartable."""

    __slots__ = ("_t0",)

    def __init__(self):
        self._t0 = time.perf_counter()

    def restart(self) -> None:
        self._t0 = time.perf_counter()

    def elapsed_s(self) -> float:
        return time.perf_counter() - self._t0

    def elapsed_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1000.0


# ----------------------------------------------------------------------
# Instrument bundles: each subsystem binds its instruments in one place so
# hot code holds plain attributes (and can skip whole blocks on .enabled).
# ----------------------------------------------------------------------


class EngineInstruments:
    """The engine's metric surface (bound once per InferenceEngine)."""

    def __init__(self):
        self.enabled = _enabled
        self.span = span_factory()
        self.tokens_generated = counter(
            "dllama_tokens_generated_total",
            "Decoded (generated) tokens across all engine streams",
        )
        self.prompt_tokens = counter(
            "dllama_prompt_tokens_total",
            "Prompt tokens prefilled across all engine streams",
        )
        self.prefill_latency = histogram(
            "dllama_prefill_latency_seconds",
            "Wall time of one batched prefill (dispatch+fetch, whole prompt)",
        )
        self.decode_latency = histogram(
            "dllama_decode_latency_seconds",
            "PER-TOKEN decode wall time, observed once per device dispatch "
            "(a chunked dispatch contributes one observation at its per-token "
            "average; dllama_tokens_generated_total counts the tokens)",
        )
        # device-resident sampling (ISSUE 13): the happy-path witness —
        # tokens whose temperature/top-k/top-p draw ran INSIDE the decode
        # program (counter-PRNG coins, no logits fetch, no host sort);
        # dllama_host_sampler_fallback_total counts the complement
        self.device_sampled_tokens = counter(
            "dllama_device_sampled_tokens_total",
            "Tokens sampled on device by the fused decode-scan sampler "
            "(greedy argmax rows included); only int32 token ids crossed "
            "the host for these",
        )
        self.kv_occupancy = gauge(
            "dllama_kv_cache_occupancy",
            "KV-cache occupancy of the most recently active stream "
            "(position / seq_len, 0..1)",
        )
        self.active_streams = gauge(
            "dllama_engine_streams",
            "Engine streams constructed (each owns one KV cache of HBM)",
        )
        self.batch_occupancy = gauge(
            "dllama_batch_occupancy",
            "Active rows / dispatched bucket rows of the most recent batched "
            "decode chunk (0..1; 1.0 = every slab row in the bucket is a "
            "live request sharing the step's weight reads)",
        )
        # fault-tolerance surface (ISSUE 3): quarantines, retries, stalls
        self.rows_quarantined = counter(
            "dllama_rows_quarantined_total",
            "Batch rows retired after a failed or corrupted chunk "
            "(bounded retries exhausted); co-batched rows kept streaming",
        )
        batch_retries = counter(
            "dllama_batch_retries_total",
            "Batched dispatch/fetch attempts retried after a transient "
            "failure (bounded, with backoff)",
            labelnames=("stage",),
        )
        self.dispatch_retries = batch_retries.labels(stage="dispatch")
        self.fetch_retries = batch_retries.labels(stage="fetch")
        self.watchdog_stalls = counter(
            "dllama_watchdog_stalls_total",
            "Hung batched chunks the stall watchdog failed cleanly",
        )
        # multi-tenant serving (ISSUE 8): priority preemption evicts the
        # lowest-priority decode row to a clean requeue — count evictions
        # here (the serving layer counts the successful requeues)
        self.preemptions = counter(
            "dllama_preemptions_total",
            "Decode rows evicted by a higher-priority arrival and requeued "
            "(clean RowPreempted evictions; a chaos-failed eviction counts "
            "as a quarantine instead)",
        )
        # speculative decoding (--spec-draft): draft volume, acceptance and
        # per-step advance — the health read is accepted/draft (the
        # prompt-lookup hit rate) and the advance histogram's mass above 1
        # (how many weight reads the drafts actually saved)
        self.spec_draft_tokens = counter(
            "dllama_spec_draft_tokens_total",
            "Prompt-lookup draft tokens proposed to speculative verify steps",
        )
        self.spec_accepted_tokens = counter(
            "dllama_spec_accepted_tokens_total",
            "Draft tokens accepted by speculative verify (excludes the "
            "per-step bonus/correction token)",
        )
        self.spec_acceptance = histogram(
            "dllama_spec_acceptance_ratio",
            "Accepted/drafted ratio per speculative verify step that "
            "proposed at least one draft token (0..1)",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
        )
        self.spec_step_advance = histogram(
            "dllama_spec_step_advance_tokens",
            "Positions advanced per row per speculative verify step "
            "(accepted drafts + 1; plain decode is identically 1)",
            buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0),
        )


class PrefixCacheInstruments:
    """The radix prefix cache's metric surface (bound once per PrefixCache;
    engine/prefix_cache.py + docs/PERF.md)."""

    # matched-prefix length is a token count, not a latency: power-of-two
    # buckets up to a 16k context
    MATCHED_TOKEN_BUCKETS = (
        1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
        1024.0, 2048.0, 4096.0, 8192.0, 16384.0,
    )

    def __init__(self):
        self.enabled = _enabled
        self.hits = counter(
            "dllama_prefix_cache_hits_total",
            "Admission prefills that reused at least one published KV page "
            "(the matched prefix skipped recomputation)",
        )
        self.misses = counter(
            "dllama_prefix_cache_misses_total",
            "Admission prefills that matched no published prefix page",
        )
        self.evictions = counter(
            "dllama_prefix_cache_evictions_total",
            "KV pages reclaimed from the radix tree by the LRU evictor "
            "(leaf-first; refcounted pages are never evicted)",
        )
        self.pages = gauge(
            "dllama_prefix_cache_pages",
            "KV pages currently held by the radix tree (the pool size "
            "--kv-pages bounds this; free = pool - this)",
        )
        self.bytes = gauge(
            "dllama_prefix_cache_bytes",
            "Logical KV bytes held by the radix tree's pool pages (pages "
            "gauge x per-page bytes across all layers and both halves) — "
            "with zero-copy aliasing this is the ONLY resident copy of "
            "cached prefixes",
        )
        self.pinned_pages = gauge(
            "dllama_prefix_cache_pinned_pages",
            "Pool pages ref-pinned against eviction — held for the "
            "lifetime of rows reading them zero-copy through their page "
            "tables (plus publishes in flight)",
        )
        self.copy_bytes_saved = counter(
            "dllama_prefix_cache_copy_bytes_saved_total",
            "HBM copy traffic avoided by zero-copy paged attention: bytes "
            "the copy design would have gathered into the slab row per "
            "prefix hit (matched pages x per-page bytes)",
        )
        self.matched_tokens = histogram(
            "dllama_prefix_cache_matched_tokens",
            "Prompt tokens satisfied from the prefix cache per hit "
            "(page-granular)",
            buckets=self.MATCHED_TOKEN_BUCKETS,
        )
        # host-RAM / disk spill tier (ISSUE 11, engine/spill.py): the
        # capacity ladder below the HBM pool
        self.spill_pages = counter(
            "dllama_prefix_spill_pages_total",
            "Evicted prefix pages whose bytes spilled to the host-RAM "
            "arena instead of vanishing (data+scales verbatim for i8)",
        )
        self.spill_reloads = counter(
            "dllama_prefix_spill_reloads_total",
            "Spilled prefix pages re-uploaded into a device pool on a "
            "later admission match (re-upload ≪ re-prefill; CRC-verified)",
        )
        self.spill_dropped = counter(
            "dllama_prefix_spill_dropped_total",
            "Spilled prefix pages LOST from the capacity ladder: LRU "
            "overflow past the host/disk budgets, or a CRC mismatch "
            "detected at reload (the entry is dropped, the block "
            "prefills cold)",
        )
        self.spill_resident_pages = gauge(
            "dllama_prefix_spill_resident_pages",
            "Spilled pages currently resident in the arena (host RAM + "
            "disk tier), across all replicas",
        )
        self.spill_bytes = gauge(
            "dllama_prefix_spill_bytes",
            "Bytes currently resident in the host-RAM spill arena "
            "(the --host-spill-mb budget bounds this; disk-tier bytes "
            "are not included)",
        )


def note_compile_cache_hit() -> None:
    """Count one persistent-compilation-cache hit (a compile served from
    ``--compile-cache-dir`` instead of a fresh XLA build — the 8.6 s
    cold-prefill attack, BENCH_r05). Called from the jax monitoring
    listener platform.enable_compilation_cache installs; cache events are
    rare, so the registry lookup per event is fine (no bind-once needed)."""
    if _enabled:
        REGISTRY.counter(
            "dllama_compile_cache_hits_total",
            "jit compiles served from the persistent XLA compilation cache",
        ).inc()


def note_kernel_path(kernel: str, path: str) -> None:
    """Count one hot-path kernel DISPATCH DECISION by (kernel, path) —
    ``dllama_kernel_path_total`` (docs/OBSERVABILITY.md). Decisions happen
    at trace time (once per compiled program build, or once per eager
    call), not per token, so the rate is tiny and the registry lookup per
    event is fine (the note_compile_cache_hit pattern, no bind-once
    needed). The operational read: any ``fallback``/``xla``-labelled
    series moving on a TPU deployment means a hot-path program silently
    took the slow path — the Pallas-kernel A/B gate as a live metric."""
    if _enabled:
        REGISTRY.counter(
            "dllama_kernel_path_total",
            "Kernel dispatch decisions by kernel (q40_matmul / "
            "paged_attention / all_reduce) and selected path (mxu_int8 / "
            "vpu_f32 / pallas_fused / xla_segmented / ici_ring / ring_xla / "
            "psum / xla_fallback); counted at trace time per program build",
            labelnames=("kernel", "path"),
        ).labels(kernel=kernel, path=path).inc()


class CollectiveInstruments:
    """The parallel backends' transfer-probe surface (TransferProbeMixin)."""

    def __init__(self):
        self.enabled = _enabled
        self.span = span_factory()
        self.allreduce_latency = histogram(
            "dllama_allreduce_latency_seconds",
            "Measured per-token collective (all-reduce/all-gather) cost from "
            "the transfer probe, replayed on the real mesh",
        )
        self.allreduce_bytes = counter(
            "dllama_allreduce_bytes_total",
            "Estimated logical payload bytes moved by the collectives the "
            "transfer probe replayed (per-token estimate x probe tokens)",
        )
        self.probe_runs = counter(
            "dllama_transfer_probe_runs_total",
            "Transfer-probe measurements taken (engine cadence: ~1/512 tokens)",
        )


class MeshInstruments:
    """The named-mesh topology surface (bound at backend/pod build):
    what shape the pod is and how many weight bytes are resident."""

    def __init__(self):
        self.enabled = _enabled
        self.mesh_devices = gauge(
            "dllama_mesh_devices",
            "Devices along each named mesh axis of the serving backend "
            "(pod axes 'data'/'model'; classic 1-D backends 'tp'/'sp'/'ep')",
            labelnames=("axis",),
        )
        self.resident_weight_bytes = gauge(
            "dllama_resident_weight_bytes",
            "Logical weight bytes resident per group: 'pod' = the ONE "
            "params tree every mesh slice shares, 'per_replica' = that "
            "tree attributed across the pod's data slices (the N-engine "
            "pool's equivalent figure is one full tree PER replica)",
            labelnames=("group",),
        )


class ServerInstruments:
    """The API server's metric surface (bound once per ApiState)."""

    def __init__(self):
        self.enabled = _enabled
        self.requests = counter(
            "dllama_http_requests_total",
            "HTTP requests by route and status code",
            labelnames=("route", "status"),
        )
        self.request_duration = histogram(
            "dllama_http_request_duration_seconds",
            "End-to-end completion-request wall time (monotonic clock)",
        )
        self.inflight = gauge(
            "dllama_http_requests_in_flight",
            "Completion requests currently being served",
        )
        self.queue_wait = histogram(
            "dllama_slot_queue_wait_seconds",
            "Time a completion request waited for a free engine stream slot",
        )
        # fault-tolerance surface (ISSUE 3): admission control + deadlines
        self.admission_rejected = counter(
            "dllama_admission_rejected_total",
            "Completion requests rejected 429 because the bounded admission "
            "queue was full (clients should honor Retry-After)",
        )
        self.deadline_exceeded = counter(
            "dllama_deadline_exceeded_total",
            "Completion requests ended 504 because their deadline_ms expired "
            "(queued or mid-stream)",
        )
        self.draining = gauge(
            "dllama_server_draining",
            "1 while the server is draining (SIGTERM received: no new "
            "admissions, in-flight completions finishing)",
        )
        # multi-tenant fairness surface (ISSUE 8): per-tenant admission
        # accounting behind the weighted-fair queues (server/admission.py)
        self.tenant_admitted = counter(
            "dllama_tenant_admitted_total",
            "Completion requests admitted to a serving slot, by tenant "
            "(weighted-fair DRR dequeue; docs/SERVING.md)",
            labelnames=("tenant",),
        )
        self.tenant_rejected = counter(
            "dllama_tenant_rejected_total",
            "Completion requests rejected 429 at a full tenant (or global) "
            "admission queue, by tenant",
            labelnames=("tenant",),
        )
        self.tenant_queue_depth = gauge(
            "dllama_tenant_queue_depth",
            "Requests currently queued for admission, by tenant",
            labelnames=("tenant",),
        )
        self.tenant_active = gauge(
            "dllama_tenant_active",
            "Completion requests currently holding a serving slot, by tenant",
            labelnames=("tenant",),
        )
        self.preempt_requeues = counter(
            "dllama_preempted_requeued_total",
            "Preempted requests requeued through weighted-fair admission "
            "(each resumes from the prefix cache's published pages; pairs "
            "with dllama_preemptions_total on the eviction side)",
        )
        # replica-loss fault tolerance (ISSUE 9, server/replicas.py):
        # per-replica health plus the failover/restart/replay ledger
        self.replica_state = gauge(
            "dllama_replica_state",
            "Health of each data-parallel replica in the supervised pool: "
            "0 = healthy, 1 = suspect (skipped for new placements), "
            "2 = dead (failing over; the supervisor is restarting it)",
            labelnames=("replica",),
        )
        self.replica_failovers = counter(
            "dllama_replica_failovers_total",
            "Replicas declared dead by the pool (crash, or a stall the "
            "watchdog escalated); each failover requeues every in-flight "
            "request on the dead replica through fair admission",
        )
        self.replica_restarts = counter(
            "dllama_replica_restarts_total",
            "Dead replicas successfully rebuilt and returned to the pool "
            "by the jittered-backoff restart supervisor",
        )
        self.replayed_requests = counter(
            "dllama_replayed_requests_total",
            "Requests replayed on a surviving replica after their replica "
            "died mid-flight (pinned seed, sent SSE deltas suppressed — "
            "the stream is bit-identical to an unfaulted run)",
        )
        # global prefix-cache tier (ISSUE 11): placement routed by the
        # shared radix index (engine/prefix_cache.py SharedPrefixIndex)
        self.shared_prefix_hits = counter(
            "dllama_prefix_shared_hits_total",
            "Requests placed onto a replica because the shared radix "
            "index says it owns (part of) the prompt's published prefix "
            "chain — the cross-replica routing that keeps the Zipf head "
            "from being re-prefilled once per replica",
        )
        # silent-data-corruption detection (ISSUE 10, engine/integrity.py
        # + server/replicas.py): canary probes, shadow votes and restart
        # weight-checksum verifications all count as checks; mismatches
        # carry which check caught the corruption
        self.sdc_checks = counter(
            "dllama_sdc_checks_total",
            "Conclusive integrity checks performed: canary golden "
            "comparisons, cross-replica shadow votes, and rebuild "
            "weight-checksum verifications (a clean fleet moves this "
            "without ever moving the mismatch counter)",
        )
        self.sdc_mismatches = counter(
            "dllama_sdc_mismatches_total",
            "Integrity checks that detected silent data corruption, by "
            "which check caught it (canary = pinned-greedy golden "
            "mismatch, shadow = cross-replica divergence, checksum = a "
            "rebuilt replica's weights disagree with the load-time "
            "reference)",
            labelnames=("check",),
        )
        self.canary_latency = histogram(
            "dllama_canary_latency_seconds",
            "Wall time of one SDC canary probe (pinned greedy prompt "
            "through the replica's real batched path on a reserved lane)",
        )
        # request-scoped SLO attribution (ISSUE 16, telemetry/trace.py):
        # server-side TTFT/TPOT so client p99s decompose without trusting
        # the client clock, plus the per-stage breakdown the trace tree's
        # attribution sums are observed from (same timestamps — the
        # metric surface and /debug/trace can never disagree)
        self.ttft = histogram(
            "dllama_ttft_seconds",
            "Server-side time to first streamed token, by tenant "
            "(request arrival to the first SSE content delta; replays "
            "keep the original arrival instant)",
            labelnames=("tenant",),
        )
        self.tpot = histogram(
            "dllama_tpot_seconds",
            "Server-side mean time per output token after the first, by "
            "tenant ((last - first token instant) / (emitted - 1))",
            labelnames=("tenant",),
        )
        self.stage_seconds = histogram(
            "dllama_request_stage_seconds",
            "Per-request latency attribution by stage (queue = fair-"
            "admission wait, placement = replica/lane selection, prefill, "
            "decode = the streaming loop, replay = all stages of "
            "requeued re-attempts after a failover/preemption) and "
            "tenant; sums approximate dllama_http_request_duration_seconds",
            labelnames=("stage", "tenant"),
        )
        # zero-downtime fleet ops (ISSUE 18, server/fleet.py): the
        # blue-green rollout and SLO-elasticity ledger
        self.rollout_moved = counter(
            "dllama_rollout_replicas_moved_total",
            "Replicas moved to a new weight version by a blue-green "
            "rollout (drained, rebuilt on the new weights, checksum-"
            "verified and canary-certified against the new version's "
            "golden); rollback rebuilds do NOT count as moves",
        )
        self.rollout_aborts = counter(
            "dllama_rollout_aborts_total",
            "Rollouts aborted (checksum gate or canary certification "
            "failed on the new version, or the server began draining "
            "mid-rollout); each abort rolls every moved replica back to "
            "the old version and raises a typed RolloutAborted",
        )
        self.fleet_scale = counter(
            "dllama_fleet_scale_events_total",
            "Elastic replica-count changes applied by the FleetController "
            "(up = grew one replica under sustained queue pressure, "
            "down = drained and retired one idle replica); hysteresis "
            "keeps this counter quiet on a stable fleet",
            labelnames=("direction",),
        )
        self.weights_version_info = gauge(
            "dllama_weights_version",
            "Info gauge: 1 on the label of the pool's CURRENT weight "
            "version (the old version's label drops to 0 when a rollout "
            "completes, so a scrape always names exactly one live pool "
            "version; mid-rollout per-replica versions are in /readyz)",
            labelnames=("version",),
        )


class SamplerInstruments:
    """Host-sampler distribution counters (bound once per Sampler)."""

    def __init__(self):
        self.enabled = _enabled
        self.sampled = counter(
            "dllama_sampled_tokens_total",
            "Host-sampled tokens by method (greedy / topp); device-sampled "
            "tokens are counted by dllama_tokens_generated_total instead",
            labelnames=("method",),
        )
        # device-resident sampling (ISSUE 13): with the fused sampler every
        # decode token is drawn inside the device program — any host
        # Sampler.sample() call is by definition the fallback path
        # (--decode host, or a caller doing its own logits fetch); the
        # happy-path CI smoke gates --expect-zero on this
        self.fallback = counter(
            "dllama_host_sampler_fallback_total",
            "Tokens sampled by the HOST Sampler (the --decode host "
            "fallback): every one paid a full-vocab logits fetch and a "
            "host sort the fused device sampler exists to delete; 0 on "
            "the device-resident happy path",
        )
