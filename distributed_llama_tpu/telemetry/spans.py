"""The span-name registry (ISSUE 16).

Every string literal passed as a span name — ``tel.span("...")`` /
``trace_span("...")`` on the ring tracer, or ``trace.span(ctx, "...")`` /
``ctx.add_span("...")`` on a request trace — must be registered here and
documented in docs/OBSERVABILITY.md's span-name table. The static
analyzer's TRC-001 rule (analysis/rules/registries.py) cross-checks every
call-site literal against this tuple exactly the way FLT-001 checks fault
sites against ``faults.SITES``: an unregistered name can't drift into the
trace surface unseen, and a registered-but-never-emitted name is flagged
as a dead entry. Keep this tuple, the call sites, and the doc table in
sync when adding spans.
"""

from __future__ import annotations

SPAN_NAMES = (
    # engine ring-tracer spans (PR 1, engine/engine.py + parallel/)
    "forward",
    "prefill",
    "prefill_dispatch",
    "device_sample",
    "first_token_fetch",
    "decode_chunk_dispatch",
    "decode_chunk_fetch",
    "spec_verify",
    "transfer_probe",
    # batched-scheduler ring-tracer spans (engine/batch.py)
    "batch_decode_chunk",
    "batch_decode_fetch",
    "spec_verify_chunk",
    "prefix_spill_reload",
    "prefix_publish",
    # request-trace spans (ISSUE 16, telemetry/trace.py): the per-request
    # tree assembled by RequestTraceStore and served at /debug/trace/<id>
    "queue_wait",
    "placement",
    "prefill_chunk",
    "decode_stream",
    "batch_decode_chunk_row",
    "spec_verify_row",
    "prefix_match",
    "sse_send",
)
