"""One-shot telemetry snapshots: ``python -m distributed_llama_tpu.telemetry.dump``.

Two modes:

* ``--url http://host:port`` — scrape a running server's ``/metrics``
  endpoint and print the exposition text (or ``--format json`` to parse the
  in-process snapshot is not possible remotely, so json mode is local-only).
* no ``--url`` — print THIS process's registry (useful from a REPL or a
  script that imported the engine; a fresh CLI invocation has an empty
  registry unless ``DLLAMA_TELEMETRY=1`` and something ran).

``--trace PATH`` additionally writes the span ring buffer as Chrome trace
JSON (local mode only).
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="python -m distributed_llama_tpu.telemetry.dump")
    p.add_argument(
        "--url", default=None,
        help="base URL (or full /metrics URL) of a running dllama-tpu-api "
        "server to scrape instead of this process's registry",
    )
    p.add_argument(
        "--format", choices=["prom", "json"], default="prom",
        help="prom = Prometheus text exposition; json = registry snapshot "
        "(local mode only)",
    )
    p.add_argument(
        "--trace", default=None, metavar="PATH",
        help="also write this process's span buffer as Chrome trace JSON",
    )
    return p


def scrape(url: str, timeout: float = 10.0) -> str:
    import urllib.request

    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8", errors="replace")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from distributed_llama_tpu import telemetry

    if args.url:
        if args.format == "json":
            sys.stderr.write("--format json is local-only; scraping returns exposition text\n")
        if args.trace:
            sys.stderr.write(
                "--trace is local-only (a scrape cannot read the remote span "
                "buffer); no trace written\n"
            )
        sys.stdout.write(scrape(args.url))
        return 0
    if args.format == "json":
        json.dump(telemetry.REGISTRY.snapshot(), sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(telemetry.prometheus_text())
    if args.trace:
        telemetry.export_chrome_trace(args.trace)
        sys.stderr.write(f"wrote Chrome trace: {args.trace}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
