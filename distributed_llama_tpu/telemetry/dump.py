"""One-shot telemetry snapshots: ``python -m distributed_llama_tpu.telemetry.dump``.

Two modes:

* ``--url http://host:port`` — scrape a running server. By default the
  ``/metrics`` exposition text; ``--trace <request_id>`` fetches that
  request's assembled span tree from ``/debug/trace/<id>`` instead
  (``--format json`` prints the tree, the default prom format prints the
  Chrome trace-event export ready for ui.perfetto.dev), and ``--flight``
  fetches the live flight-recorder snapshot from ``/debug/flight``.
* no ``--url`` — print THIS process's registry (useful from a REPL or a
  script that imported the engine; a fresh CLI invocation has an empty
  registry unless ``DLLAMA_TELEMETRY=1`` and something ran). ``--trace``
  is then a PATH: the ring span buffer is written as Chrome trace JSON.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="python -m distributed_llama_tpu.telemetry.dump")
    p.add_argument(
        "--url", default=None,
        help="base URL (or full /metrics URL) of a running dllama-tpu-api "
        "server to scrape instead of this process's registry",
    )
    p.add_argument(
        "--format", choices=["prom", "json"], default="prom",
        help="prom = Prometheus text exposition; json = registry snapshot "
        "(local mode) / raw trace tree (--url --trace)",
    )
    p.add_argument(
        "--trace", default=None, metavar="ID_OR_PATH",
        help="with --url: a request id — fetch its span tree from "
        "/debug/trace/<id> (Chrome trace-event JSON by default, "
        "--format json for the raw tree). Without --url: a PATH to write "
        "this process's span buffer as Chrome trace JSON",
    )
    p.add_argument(
        "--flight", action="store_true",
        help="with --url: fetch the live flight-recorder snapshot from "
        "/debug/flight (per-replica lifecycle rings + retained dumps)",
    )
    return p


def scrape(url: str, timeout: float = 10.0) -> str:
    import urllib.request

    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8", errors="replace")


def fetch_json(base: str, path: str, timeout: float = 10.0) -> dict:
    """GET ``base``+``path`` and parse the JSON body (debug endpoints)."""
    import urllib.request

    url = base.rstrip("/") + path
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8", errors="replace"))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from distributed_llama_tpu import telemetry

    if args.url:
        if args.flight:
            json.dump(
                fetch_json(args.url, "/debug/flight"), sys.stdout, indent=2
            )
            sys.stdout.write("\n")
            return 0
        if args.trace:
            suffix = "" if args.format == "json" else "?format=chrome"
            try:
                tree = fetch_json(
                    args.url, f"/debug/trace/{args.trace}{suffix}"
                )
            except Exception as e:
                sys.stderr.write(
                    f"trace fetch failed for {args.trace!r}: {e}\n"
                )
                return 1
            json.dump(tree, sys.stdout, indent=2)
            sys.stdout.write("\n")
            return 0
        if args.format == "json":
            sys.stderr.write("--format json is local-only; scraping returns exposition text\n")
        sys.stdout.write(scrape(args.url))
        return 0
    if args.flight:
        # local mode: this process's recorder (populated only if serving
        # components ran in-process)
        from distributed_llama_tpu.telemetry import flight

        json.dump(flight.RECORDER.snapshot(), sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 0
    if args.format == "json":
        json.dump(telemetry.REGISTRY.snapshot(), sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(telemetry.prometheus_text())
    if args.trace:
        telemetry.export_chrome_trace(args.trace)
        sys.stderr.write(f"wrote Chrome trace: {args.trace}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
