"""Llama-family transformer forward pass as a pure, jit-compiled function.

Capability parity with the reference's root+worker task lists
(reference: src/llama2-tasks.cpp:241-298) re-designed TPU-first:

* The reference runs 25 host tasks per layer in thread lock-step; here one
  ``lax.scan`` over stacked layer weights compiles the whole token step into a
  single XLA program (weights stacked on a leading layer axis).
* The reference prefills one token at a time (src/apps/dllama/dllama.cpp:45-59);
  ``forward_tokens`` takes T tokens at once, so prefill is a batched matmul
  workload that actually uses the MXU.
* The reference's sync tasks (llamaSyncAtt/llamaSyncFfn2 gathers + merge adds,
  src/llama2-tasks.cpp:115-131, 196-212) collapse into ``jax.lax.psum`` calls
  keyed by ``axis_name`` — a single ICI all-reduce instead of two TCP hops.
  With ``axis_name=None`` the same code is the single-chip program.

Numerical conventions matching the reference kernels:
  rmsnorm eps 1e-5 added to mean-square (src/funcs.cpp:120-122);
  attention scores scaled by 1/sqrt(head_size) (src/llama2-tasks.cpp:72);
  SwiGLU silu(w1 x) * (w3 x) then w2 (src/llama2-tasks.cpp:158-189). The
  reference's `hiddenDim == GELU` comparison bug (src/llama2-tasks.cpp:169)
  means its runtime always takes the silu path; we dispatch on hidden_act
  correctly.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from distributed_llama_tpu.formats.model_file import HiddenAct
from distributed_llama_tpu.models.config import LlamaConfig
from distributed_llama_tpu.models.rope import apply_rope

Params = dict[str, Any]

# key-axis chunk of the blocked dense attention (ops.attention): caches whose
# seq_len is a multiple of this use the online-softmax path with a dynamic
# chunk bound; smaller/odd caches (tiny test models) keep the full-S einsum.
# Measured on the real v5e (7B q40, S=2048, round 5): decode 10.0 vs 17.8
# ms/token at pos 256 and 11.6 vs 18.2 at pos 1800 — the full-S einsum both
# reads dead slots AND runs the masked softmax over all of S. For batched
# prefill the fori_loop serialization loses slightly (17.1 vs 15.2 ms at
# T=64), so T>8 keeps the einsum until S is long enough that dead-slot reads
# dominate (ATT_BLOCK_PREFILL_S). chunk 1024 measured no better (11.5 late,
# 10.8 early).
ATT_CHUNK = 512
ATT_BLOCK_PREFILL_S = 4096  # blocked attention for T>8 from this seq_len up


def rmsnorm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """y = w * x / sqrt(mean(x^2) + eps), computed in f32
    (reference: src/funcs.cpp:95-146 — note eps is added to the mean square).
    Delegates to ``ops.q40.rmsnorm_ref`` — the ONE rmsnorm definition, so
    the fused rmsnorm→Q80→matmul entry (:func:`_norm_matmul`) is
    bit-identical to this by construction."""
    from distributed_llama_tpu.ops.q40 import rmsnorm_ref

    return rmsnorm_ref(x, weight, eps)


def _activation(x: jax.Array, act: HiddenAct) -> jax.Array:
    if act == HiddenAct.GELU:
        # tanh-approximated gelu (reference: src/funcs.cpp:501-509)
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _matmul(x: jax.Array, w) -> jax.Array:
    """x [T, n] @ w [n, d] with f32 accumulation on the MXU.

    ``w`` is a plain array (bf16/f32) or a Q40 :class:`QuantizedMatrix`,
    which routes to the fused Pallas kernel (weights stay 4-bit in HBM).
    precision=HIGHEST keeps f32 operands in true f32 on TPU (parity mode);
    it is a no-op for the production bf16 path."""
    from distributed_llama_tpu.ops.q40 import QuantizedMatrix, q40_matmul

    if isinstance(w, QuantizedMatrix):
        return q40_matmul(x, w)
    return jax.lax.dot_general(
        x,
        w,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _norm_matmul(x: jax.Array, weight: jax.Array, w) -> jax.Array:
    """rmsnorm(x, weight) @ w — ONE fused program on the q40 int8 path
    (the decode superstep's part (a): the Q80 activation quantize rides
    the rmsnorm epilogue instead of paying its own program dispatch,
    ``ops.q40.rmsnorm_q40_matmul``); the unfused reference sequence for
    plain-array weights. Bit-identical either way (the fused entry inlines
    ``rmsnorm_ref``'s exact ops — test-enforced)."""
    from distributed_llama_tpu.ops.q40 import QuantizedMatrix, rmsnorm_q40_matmul

    if isinstance(w, QuantizedMatrix):
        return rmsnorm_q40_matmul(x, weight, w)
    return _matmul(rmsnorm(x, weight).astype(w.dtype), w)


def project_qkv(
    cfg: LlamaConfig,
    lp: Params,
    x: jax.Array,
    rope_rows: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Norm + QKV projection + rope for T tokens: [T, dim] ->
    (q [T, Hl, hd], k [T, Kl, hd], v [T, Kl, hd]). Shared by the dense,
    tensor-parallel and sequence-parallel attention paths (the reference's
    llamaRmsAtt/llamaQkv/llamaRope chain, src/llama2-tasks.cpp:10-52)."""
    T = x.shape[0]
    hd = cfg.head_size
    if "qkv" in lp:
        # q|k|v packed as one matmul on the output dim (the q40 path: one
        # large bandwidth-efficient kernel call instead of three small
        # ones) — and the norm + Q80 quantize fused into that same program
        # on the int8 path (_norm_matmul)
        fused = _norm_matmul(x, lp["rms_att"], lp["qkv"])  # [T, (Hl+2*Kl)*hd] f32
        d_q = lp["wo"].shape[-2]  # Hl*hd (wo's input dim)
        d_kv = (fused.shape[-1] - d_q) // 2
        q = fused[:, :d_q]
        k = fused[:, d_q : d_q + d_kv]
        v = fused[:, d_q + d_kv :]
    else:
        # three consumers of one normed activation: the norm cannot ride a
        # single matmul's epilogue here, so it stays standalone
        xc = rmsnorm(x, lp["rms_att"]).astype(lp["q"].dtype)
        q = _matmul(xc, lp["q"])  # [T, Hl*hd] f32
        k = _matmul(xc, lp["k"])  # [T, Kl*hd]
        v = _matmul(xc, lp["v"])  # [T, Kl*hd]
    Hl = q.shape[-1] // hd
    Kl = k.shape[-1] // hd
    q = apply_rope(q.reshape(T, Hl, hd), rope_rows, cfg)
    k = apply_rope(k.reshape(T, Kl, hd), rope_rows, cfg)
    return q, k, v.reshape(T, Kl, hd)


def block_tail(
    cfg: LlamaConfig,
    x: jax.Array,
    att: jax.Array,
    lp: Params,
    axis_name: str | None,
    ep_axis: str | None = None,
    n_real: jax.Array | None = None,
) -> jax.Array:
    """Everything after the attention mix: wo projection (+psum under TP),
    the arch-dependent residual/norm placement, and the FFN/MoE half.
    ``att``: [T, Hl*hd]. ``ep_axis``: expert-parallel mesh axis — expert
    banks are sharded over it and the MoE FFN runs the dispatch/combine
    exchange (parallel.expert_parallel). ``n_real``: number of REAL rows in
    a bucket-padded batch (rows >= n_real are engine pad zeros) — the
    capacity-bucketed MoE prefill masks pads out of its expert buckets."""
    if axis_name is None:
        out = _matmul(att.astype(lp["wo"].dtype), lp["wo"])  # [T, dim]
    else:
        # the TP all-reduce: replaces gather + merge-add on root
        # (reference: src/llama2-tasks.cpp:115-131) with one ICI collective,
        # routed through the matmul+all-reduce seam (ops.collectives): the
        # unfused matmul + psum/ring_xla arms off-TPU, and under
        # DLT_ALLREDUCE=ring the fused int8+ring kernel whose per-chunk
        # epilogue starts the reduce-scatter DMAs while the next chunk's
        # MXU work is in flight (decode superstep, part b)
        from distributed_llama_tpu.ops import collectives

        out = collectives.matmul_all_reduce(
            att.astype(lp["wo"].dtype), lp["wo"], axis_name
        )
    if cfg.arch.name == "GROK1":
        # grok rmsnorms the attention output with rmsFfn before the residual
        # add (reference: src/grok1-tasks.cpp:16-41)
        x = x + rmsnorm(out.astype(x.dtype), lp["rms_ffn"])
    else:
        x = x + out.astype(x.dtype)
    if cfg.is_moe:
        from distributed_llama_tpu.models import moe

        x = moe.moe_block(cfg, x, lp, axis_name, ep_axis=ep_axis, n_real=n_real)
    else:
        x = x + ffn(cfg, x, lp, axis_name).astype(x.dtype)
    return x


def final_logits(cfg: LlamaConfig, params: Params, x: jax.Array) -> jax.Array:
    """Final rmsnorm + logits head (+Grok's logit scale),
    reference: src/llama2-tasks.cpp:222-239, src/grok1-tasks.cpp:270-273.
    Norm + quantize + matmul fuse into one program on the q40 int8 path
    (_norm_matmul)."""
    logits = _norm_matmul(x, params["rms_final"], params["wcls"])
    if cfg.arch.name == "GROK1":
        logits = logits * 0.5773502691896257
    return logits


def embed(cfg: LlamaConfig, params: Params, tokens: jax.Array) -> jax.Array:
    """Embedding row gather (+Grok's input scale, src/grok1-tasks.cpp:11-14)."""
    x = params["embedding"][tokens].astype(jnp.float32)
    if cfg.arch.name == "GROK1":
        x = x * 78.38367176906169
    return x


def attention(
    cfg: LlamaConfig,
    x: jax.Array,
    lp: Params,
    cache_l,
    pos: jax.Array,
    rope_rows: jax.Array,
    axis_name: str | None,
    paged=None,
) -> tuple[jax.Array, jax.Array]:
    """Causal GQA attention for T new tokens at absolute positions
    pos..pos+T-1. ``cache_l``: this layer's cache — a ``(keys, values)``
    tuple of [S, Kl, hd] arrays (the layered layout, updated in place) or a
    stacked [2, S, Kl, hd] array (the lax.scan-over-layers layout); returns
    (attention mix [T, Hl*hd], updated cache in the same form).

    ``paged``: ``(pool_k, pool_v, table, matched)`` — zero-copy prefix
    aliasing for a slab row whose positions below ``matched`` live in the
    shared page pool (read through the page table) rather than the row
    itself. Blocked caches take the segmented paged scan; small/odd caches
    read a virtual row view (``kv_cache.virtual_row``) through the SAME
    einsum path, so both are bit-identical to a row holding page copies.

    Mirrors llamaQkv/llamaRope/llamaMultiheadAtt/llamaAtt
    (reference: src/llama2-tasks.cpp:33-108) with the per-timestep score loop
    replaced by one masked einsum over the whole cache.
    """
    from distributed_llama_tpu.ops import kv_cache as kvc

    T = x.shape[0]
    S = cache_l[0].shape[0]  # works for tuple (keys, values) and stacked [2, S, ...] forms
    hd = cfg.head_size
    q, k, v = project_qkv(cfg, lp, x, rope_rows)
    Hl, Kl = q.shape[1], k.shape[1]

    if kvc.is_fused_leaf(cache_l):
        # fused [2, S, Kl, hd] leaf: keys AND values land in ONE coalesced
        # dynamic_update_slice (the leading 2-axis is fully covered, so the
        # donated leaf aliases in place — unlike updating the two halves
        # separately and re-stacking, which copies the layer's entire cache).
        # This halves the per-layer update op count PERF.md puts on the
        # decode critical path, and a T>1 verify window writes all of its
        # draft K/V in the same single update.
        new_cache = kvc.fused_update_rows(cache_l, k, v, pos)
        keys, values = new_cache[0], new_cache[1]
    else:
        # per-layer TUPLE caches (the tp/sp/ep backends' sharded layout)
        # update in place per half
        keys = kvc.update_rows(cache_l[0], k, pos)  # [S, Kl, hd]
        values = kvc.update_rows(cache_l[1], v, pos)
        new_cache = (keys, values)

    kv_mul = Hl // Kl
    # score/value einsums run with operands in the CACHE dtype (bf16 for an
    # i8 cache — the HBM reads stay int8/bf16 either way) and f32
    # accumulation: casting a narrow cache to f32 first would materialize
    # 2-4x the cache bytes per layer per token (the attention reads are the
    # second-largest HBM stream after the weights). f32 caches (parity
    # tests) keep true-f32 multiplies via HIGHEST.
    cdt = kvc.compute_dtype(keys)
    prec = kvc.einsum_precision(keys)
    qg = q.reshape(T, Kl, kv_mul, hd).astype(cdt)
    use_blocked = (
        S % ATT_CHUNK == 0
        and S > ATT_CHUNK
        and (T <= 8 or S >= ATT_BLOCK_PREFILL_S)
    )
    if paged is not None and not (
        use_blocked and ATT_CHUNK % kvc.pool_page_size(paged[0]) == 0
    ):
        # general fallback: a virtual row view selecting pool bytes below
        # ``matched`` and the slab beyond, fed through the unchanged paths
        pool_k, pool_v, table, matched = paged
        keys = kvc.virtual_row(keys, pool_k, table, matched)
        values = kvc.virtual_row(values, pool_v, table, matched)
        paged = None
    if use_blocked:
        # blocked (flash-style) attention with a DYNAMIC chunk bound: no
        # [T, S] score tensor materializes and slots beyond pos+T are never
        # read — the full-S einsum below reads the entire allocated cache
        # every call (S*K*hd*2 dtype-bytes per half per layer), which at
        # long seq_len dwarfs the live context (see ATT_CHUNK note above
        # for the measured decode/prefill split); with ``paged`` still
        # set, the same call reads the matched prefix through the page
        # table (blocked_attention treats paged=None as the plain scan)
        from distributed_llama_tpu.ops.attention import blocked_attention

        att = blocked_attention(
            qg.astype(jnp.float32), keys, values, pos, ATT_CHUNK, paged=paged
        ).astype(jnp.float32).reshape(T, Hl * hd)
        return att, new_cache
    scores = kvc.scores_einsum(qg, keys, prec) / jnp.sqrt(jnp.float32(hd))
    # causal mask: query t (absolute pos+t) sees cache slots 0..pos+t
    t_idx = pos + jnp.arange(T)[:, None]
    s_idx = jnp.arange(S)[None, :]
    mask = s_idx <= t_idx  # [T, S]
    scores = jnp.where(mask[:, None, None, :], scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1)
    att = kvc.mix_einsum(weights, values, cdt, prec).reshape(T, Hl * hd)
    return att, new_cache


def ffn(cfg: LlamaConfig, x: jax.Array, lp: Params, axis_name: str | None) -> jax.Array:
    """SwiGLU FFN (reference: src/llama2-tasks.cpp:158-212)."""
    if "gate_up" in lp:
        # gate|up packed as one matmul (see the qkv note in attention),
        # with the norm + Q80 quantize fused in on the int8 path
        fused = _norm_matmul(x, lp["rms_ffn"], lp["gate_up"])
        hidden = fused.shape[-1] // 2
        h = _activation(fused[:, :hidden], cfg.hidden_act) * fused[:, hidden:]
    else:
        xn = rmsnorm(x, lp["rms_ffn"]).astype(lp["gate"].dtype)
        h = _activation(_matmul(xn, lp["gate"]), cfg.hidden_act) * _matmul(xn, lp["up"])
    if axis_name is None:
        return _matmul(h.astype(lp["down"].dtype), lp["down"])
    from distributed_llama_tpu.ops import collectives

    # down + TP all-reduce through the fused seam (see block_tail)
    return collectives.matmul_all_reduce(
        h.astype(lp["down"].dtype), lp["down"], axis_name
    )


def block_forward(
    cfg: LlamaConfig,
    x: jax.Array,
    lp: Params,
    cache_l,
    pos: jax.Array,
    rope_rows: jax.Array,
    axis_name: str | None,
    ep_axis: str | None = None,
    n_real: jax.Array | None = None,
    paged=None,
) -> tuple[jax.Array, jax.Array]:
    att, new_cache = attention(
        cfg, x, lp, cache_l, pos, rope_rows, axis_name, paged=paged
    )
    return (
        block_tail(cfg, x, att, lp, axis_name, ep_axis=ep_axis, n_real=n_real),
        new_cache,
    )


def forward_tokens(
    cfg: LlamaConfig,
    params: Params,
    tokens: jax.Array,
    cache: jax.Array,
    pos: jax.Array,
    axis_name: str | None = None,
    ep_axis: str | None = None,
    n_real: jax.Array | None = None,
    paged=None,
) -> tuple[jax.Array, jax.Array]:
    """Run T tokens through the model starting at absolute position ``pos``.

    tokens: int32 [T]; cache: a list of per-layer ``(keys, values)`` tuples
    (the layered layout) or a stacked [L, 2, S, Kl, hd] array; returns
    (logits f32 [T, vocab], updated cache in the same form). The per-token
    path of the reference's Inference::infer (src/tasks.cpp:173-184) is the
    T=1 case. ``n_real``: real (non-pad) token count of a bucket-padded
    prompt — only the capacity-bucketed MoE prefill consumes it (pad rows
    must not spend per-expert bucket capacity); None = all rows real.
    ``paged``: ``(pool, table, matched)`` — this row's cache positions
    below ``matched`` live in the shared prefix-page pool (per-layer
    ``(keys, values)`` halves, read through ``table``); requires the
    layered cache layout.
    """
    T = tokens.shape[0]
    x = embed(cfg, params, tokens)
    rope_rows = jax.lax.dynamic_slice(
        params["rope_table"], (pos, 0, 0), (T,) + params["rope_table"].shape[1:]
    )

    if isinstance(params["layers"], (list, tuple)):
        # unrolled layer loop: used by the q40 path, whose Pallas-call
        # operands must be the resident buffers themselves (scan-slicing a
        # stacked array makes XLA hoist a full copy of every layer's weights).
        # The cache should be a LIST of per-layer arrays here: indexing a
        # stacked cache and re-stacking the updates copies the ENTIRE cache
        # every call (~1.1 GB of HBM traffic per decoded token on a 7B,
        # ~7 ms/token of pure overhead); per-layer leaves alias in place.
        cache_is_list = isinstance(cache, (list, tuple))
        new_layers = []
        for l, lp in enumerate(params["layers"]):
            paged_l = None
            if paged is not None:
                pool, table, matched = paged
                paged_l = (pool[l][0], pool[l][1], table, matched)
            x, nc = block_forward(
                cfg, x, lp, cache[l], pos, rope_rows, axis_name, ep_axis=ep_axis,
                n_real=n_real, paged=paged_l,
            )
            new_layers.append(nc)
        new_cache = type(cache)(new_layers) if cache_is_list else jnp.stack(new_layers)
    else:
        if paged is not None:
            raise ValueError("the paged (pool-aliased) read requires the layered cache")

        def body(carry, scanned):
            xc = carry
            lp, cache_l = scanned
            xc, new_cache_l = block_forward(
                cfg, xc, lp, cache_l, pos, rope_rows, axis_name, ep_axis=ep_axis,
                n_real=n_real,
            )
            return xc, new_cache_l

        x, new_cache = jax.lax.scan(body, x, (params["layers"], cache))

    return final_logits(cfg, params, x), new_cache


def attention_batched(
    cfg: LlamaConfig,
    x: jax.Array,  # [B, dim] — one token per independent sequence
    lp: Params,
    cache_l,  # (keys, values) slab halves [B, S, Kl, hd]
    pos: jax.Array,  # [B] per-row absolute positions
    rope_rows: jax.Array,  # [B, hd/2, 2] per-row rope table rows
    active: jax.Array,  # [B] bool — False rows decode garbage, write nothing
    paged=None,  # (pool_k, pool_v, tables [B, n_table], matched [B])
) -> tuple[jax.Array, jax.Array]:
    """One decode step of B INDEPENDENT sequences over a slab cache with a
    leading batch axis: row ``b`` writes its K/V at its own ``pos[b]`` and
    attends over its own cache row masked by ``pos[b]``. Everything outside
    attention (norms, matmuls, FFN) is position-free, so the batch shares
    one weight read per matrix per step — the whole point of batching an
    HBM-bound decode. Inactive rows write at a DROPPED out-of-bounds slot
    (retired caches stay byte-identical for prefix reuse) and their outputs
    are garbage the scheduler discards. ``paged``: row ``b``'s positions
    below ``matched[b]`` are read from the shared page pool through its
    page table (zero-copy prefix aliasing) — bit-identical to a row holding
    copies of the pages."""
    from distributed_llama_tpu.ops import kv_cache as kvc

    B = x.shape[0]
    S = cache_l[0].shape[1]
    hd = cfg.head_size
    q, k, v = project_qkv(cfg, lp, x, rope_rows)  # [B, Hl, hd], [B, Kl, hd] x2
    Hl, Kl = q.shape[1], k.shape[1]

    write_slot = jnp.where(active & (pos < S), pos, S)  # S = dropped
    if kvc.is_fused_leaf(cache_l):
        # fused slab leaf [2, B, S, Kl, hd]: one coalesced scatter writes
        # every row's key AND value (see the fused note in attention())
        new_cache = kvc.fused_update_row_batched(cache_l, k, v, write_slot)
        keys, values = new_cache[0], new_cache[1]
    else:
        keys = kvc.update_row_batched(cache_l[0], k, write_slot)
        values = kvc.update_row_batched(cache_l[1], v, write_slot)
        new_cache = (keys, values)

    kv_mul = Hl // Kl
    cdt = kvc.compute_dtype(keys)
    prec = kvc.einsum_precision(keys)
    qg = q.reshape(B, Kl, kv_mul, hd).astype(cdt)
    # inactive rows read from position 0 so they cannot inflate the shared
    # dynamic chunk bound (their output is garbage either way)
    read_pos = jnp.where(active, pos, 0)
    use_blocked = S % ATT_CHUNK == 0 and S > ATT_CHUNK
    if use_blocked and (
        paged is None or ATT_CHUNK % kvc.pool_page_size(paged[0]) == 0
    ):
        from distributed_llama_tpu.ops.attention import batched_decode_attention

        att = batched_decode_attention(
            qg.astype(jnp.float32), keys, values, read_pos, ATT_CHUNK,
            paged=paged,
        ).astype(jnp.float32)
        return att.reshape(B, Hl * hd), new_cache
    # a dispatch bucket below B_max reads only its own slab rows
    keys_b = keys if keys.shape[0] == B else kvc.slice_rows_batched(keys, 0, S, rows=B)
    values_b = (
        values if values.shape[0] == B else kvc.slice_rows_batched(values, 0, S, rows=B)
    )
    if paged is not None:
        # virtual slab view (pool bytes below matched) through the same
        # einsum/blocked path — the small/odd-cache fallback
        pool_k, pool_v, tables, matched = paged
        keys_b = kvc.virtual_rows_batched(keys_b, pool_k, tables, matched)
        values_b = kvc.virtual_rows_batched(values_b, pool_v, tables, matched)
        if use_blocked:
            from distributed_llama_tpu.ops.attention import batched_decode_attention

            att = batched_decode_attention(
                qg.astype(jnp.float32), keys_b, values_b, read_pos, ATT_CHUNK
            ).astype(jnp.float32)
            return att.reshape(B, Hl * hd), new_cache
    scores = kvc.scores_einsum_batched(qg, keys_b, prec) / jnp.sqrt(jnp.float32(hd))
    mask = jnp.arange(S)[None, :] <= read_pos[:, None]  # [B, S]
    scores = jnp.where(mask[:, None, None, :], scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1)
    att = kvc.mix_einsum_batched(weights, values_b, cdt, prec).reshape(B, Hl * hd)
    return att, new_cache


def forward_step_batched(
    cfg: LlamaConfig,
    params: Params,
    tokens: jax.Array,  # int32 [B]
    cache,  # list of per-layer (keys, values) slab tuples [B, S, Kl, hd]
    pos: jax.Array,  # int32 [B] per-row positions
    active: jax.Array,  # bool [B]
    axis_name: str | None = None,
    paged=None,  # (pool, tables, matched) — zero-copy prefix aliasing
) -> tuple[jax.Array, jax.Array]:
    """One batched decode step: B tokens (one per sequence) at per-row
    positions through the whole model, reading each weight matrix ONCE.
    Returns (logits f32 [B, vocab], updated slab cache). Requires the
    layered (per-layer list) cache layout — the only engine layout; a
    stacked slab would copy itself every step (see forward_tokens).

    MoE note: with B > 1 the FFN takes the DENSE expert path (every expert
    computed, zero-weighted ones contributing exact zeros), not the T==1
    top-k switch — per-step expert HBM reads are E shared across B rows vs
    B·k for B separate streams, so batching still wins once B ≥ E/k
    (break-even at B=4 for Mixtral's 2-of-8). Per-row outputs match
    single-stream decode up to expert-sum reordering (the dense mix adds
    experts in bank order, the switch in top-k order); the BIT-parity
    contract of the batched path is exact for dense models only."""
    if not isinstance(cache, (list, tuple)):
        raise ValueError("batched decode requires the layered (per-layer list) cache")
    x = embed(cfg, params, tokens)  # [B, dim]
    rope_rows = params["rope_table"][jnp.clip(pos, 0, cfg.seq_len - 1)]
    layers = params["layers"]
    if not isinstance(layers, (list, tuple)):
        raise ValueError("batched decode requires the per-layer-list params layout")
    new_layers = []
    for l, lp in enumerate(layers):
        paged_l = None
        if paged is not None:
            pool, tables, matched = paged
            paged_l = (pool[l][0], pool[l][1], tables, matched)
        att, nc = attention_batched(
            cfg, x, lp, cache[l], pos, rope_rows, active, paged=paged_l
        )
        x = block_tail(cfg, x, att, lp, axis_name)
        new_layers.append(nc)
    return final_logits(cfg, params, x), type(cache)(new_layers)


def attention_verify_batched(
    cfg: LlamaConfig,
    x: jax.Array,  # [B, T, dim] — T-token verify window per sequence
    lp: Params,
    cache_l,  # fused [2, B, S, Kl, hd] slab leaf (or (keys, values) tuple)
    pos: jax.Array,  # [B] absolute position of each row's window start
    rope_rows: jax.Array,  # [B, T, hd/2, 2] per-(row, offset) rope rows
    active: jax.Array,  # [B] bool — False rows verify garbage, write nothing
    paged=None,  # (pool_k, pool_v, tables [B, n_table], matched [B])
) -> tuple[jax.Array, jax.Array]:
    """One speculative-verify attention step of B independent T-token
    windows (T = draft k + 1): row ``b``'s query ``t`` sits at ``pos[b]+t``,
    writes its K/V there, and attends its own slab row causally. The write
    is ONE coalesced scatter per layer covering all B·T keys AND values;
    out-of-bounds slots (inactive rows, context-limit clamps) drop, so a
    retired row's cache stays byte-identical. Returns
    (attention mix [B, T, Hl*hd], updated cache)."""
    from distributed_llama_tpu.ops import kv_cache as kvc

    B, T = x.shape[0], x.shape[1]
    S = cache_l[0].shape[1]
    hd = cfg.head_size
    # projections/rope are position-free per row: run them on the flattened
    # [B*T] token axis (one matmul per matrix — the whole point of scoring
    # draft + bonus positions in a single weight read)
    q, k, v = project_qkv(
        cfg, lp, x.reshape(B * T, -1), rope_rows.reshape(B * T, *rope_rows.shape[2:])
    )
    Hl, Kl = q.shape[1], k.shape[1]
    q = q.reshape(B, T, Kl * (Hl // Kl), hd)
    k = k.reshape(B, T, Kl, hd)
    v = v.reshape(B, T, Kl, hd)

    slots = pos[:, None] + jnp.arange(T)[None, :]  # [B, T]
    slots = jnp.where(active[:, None] & (slots < S), slots, S)  # S = dropped
    if kvc.is_fused_leaf(cache_l):
        new_cache = kvc.fused_update_verify_batched(cache_l, k, v, slots)
        keys, values = new_cache[0], new_cache[1]
    else:
        b_idx = jnp.arange(B)[:, None]
        keys = kvc.scatter_verify_rows(cache_l[0], b_idx, slots, k)
        values = kvc.scatter_verify_rows(cache_l[1], b_idx, slots, v)
        new_cache = (keys, values)

    kv_mul = Hl // Kl
    cdt = kvc.compute_dtype(keys)
    prec = kvc.einsum_precision(keys)
    qg = q.reshape(B, T, Kl, kv_mul, hd).astype(cdt)
    read_pos = jnp.where(active, pos, 0)
    use_blocked = S % ATT_CHUNK == 0 and S > ATT_CHUNK
    if use_blocked and (
        paged is None or ATT_CHUNK % kvc.pool_page_size(paged[0]) == 0
    ):
        from distributed_llama_tpu.ops.attention import batched_verify_attention

        att = batched_verify_attention(
            qg.astype(jnp.float32), keys, values, read_pos, ATT_CHUNK,
            paged=paged,
        ).astype(jnp.float32)
        return att.reshape(B, T, Hl * hd), new_cache
    keys_b = keys if keys.shape[0] == B else kvc.slice_rows_batched(keys, 0, S, rows=B)
    values_b = (
        values if values.shape[0] == B else kvc.slice_rows_batched(values, 0, S, rows=B)
    )
    if paged is not None:
        pool_k, pool_v, tables, matched = paged
        keys_b = kvc.virtual_rows_batched(keys_b, pool_k, tables, matched)
        values_b = kvc.virtual_rows_batched(values_b, pool_v, tables, matched)
        if use_blocked:
            from distributed_llama_tpu.ops.attention import batched_verify_attention

            att = batched_verify_attention(
                qg.astype(jnp.float32), keys_b, values_b, read_pos, ATT_CHUNK
            ).astype(jnp.float32)
            return att.reshape(B, T, Hl * hd), new_cache
    scores = kvc.scores_einsum_verify(qg, keys_b, prec) / jnp.sqrt(jnp.float32(hd))
    # causal mask per (row, offset): query t of row b sees slots 0..pos[b]+t
    q_pos = read_pos[:, None] + jnp.arange(T)[None, :]  # [B, T]
    mask = jnp.arange(S)[None, None, :] <= q_pos[:, :, None]  # [B, T, S]
    scores = jnp.where(mask[:, :, None, None, :], scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1)
    att = kvc.mix_einsum_verify(weights, values_b, cdt, prec).reshape(B, T, Hl * hd)
    return att, new_cache


def forward_verify_batched(
    cfg: LlamaConfig,
    params: Params,
    tokens: jax.Array,  # int32 [B, T] — [prev, draft_1..draft_k] per row
    cache,  # list of per-layer fused slab leaves (llama.init_batch_cache)
    pos: jax.Array,  # int32 [B] per-row positions of tokens[:, 0]
    active: jax.Array,  # bool [B]
    axis_name: str | None = None,
    paged=None,  # (pool, tables, matched) — zero-copy prefix aliasing
) -> tuple[jax.Array, jax.Array]:
    """The speculative-decode verify forward: score every row's T-token
    window (previous token + k prompt-lookup drafts) in ONE weight read.
    ``logits[b, t]`` is the next-token distribution after consuming
    ``tokens[b, :t+1]`` — the accept/reject pass (sampling._spec_accept_row)
    compares drafts against it positionwise. Causally masked at a per-row
    position offset, so it is the batched multi-token generalization of
    :func:`forward_step_batched` (whose T == 1 case it reproduces
    bit-exactly); the chunked-prefill machinery supplies the attention and
    cache-write building blocks. Returns (logits f32 [B, T, vocab],
    updated slab cache)."""
    if not isinstance(cache, (list, tuple)):
        raise ValueError("batched verify requires the layered (per-layer list) cache")
    B, T = tokens.shape
    x = embed(cfg, params, tokens.reshape(-1)).reshape(B, T, -1)
    offsets = pos[:, None] + jnp.arange(T)[None, :]
    rope_rows = params["rope_table"][jnp.clip(offsets, 0, cfg.seq_len - 1)]
    layers = params["layers"]
    if not isinstance(layers, (list, tuple)):
        raise ValueError("batched verify requires the per-layer-list params layout")
    new_layers = []
    for l, lp in enumerate(layers):
        paged_l = None
        if paged is not None:
            pool, tables, matched = paged
            paged_l = (pool[l][0], pool[l][1], tables, matched)
        att, nc = attention_verify_batched(
            cfg, x, lp, cache[l], pos, rope_rows, active, paged=paged_l
        )
        x = block_tail(
            cfg, x.reshape(B * T, -1), att.reshape(B * T, -1), lp, axis_name
        ).reshape(B, T, -1)
        new_layers.append(nc)
    logits = final_logits(cfg, params, x.reshape(B * T, -1))
    return logits.reshape(B, T, -1), type(cache)(new_layers)


def init_batch_cache(
    cfg: LlamaConfig,
    b_max: int,
    n_kv_heads_local: int | None = None,
    dtype=jnp.float32,
) -> list[tuple[jax.Array, jax.Array]]:
    """Slab KV cache for ``b_max`` concurrent decode streams: a list of
    per-layer FUSED [2, b_max, S, Kl, hd] leaves (keys and values on the
    leading 2-axis — one coalesced scatter per layer per step; i8 slabs
    quantize per (row, slot, head) exactly like the single-stream i8
    cache). ``leaf[0]``/``leaf[1]`` are the (keys, values) halves. The tp
    backend keeps its own sharded (keys, values)-tuple slab."""
    from distributed_llama_tpu.ops import kv_cache as kvc

    kl = n_kv_heads_local if n_kv_heads_local is not None else cfg.n_kv_heads
    shape = (b_max, cfg.seq_len, kl, cfg.head_size)
    return [kvc.init_fused(shape, dtype) for _ in range(cfg.n_layers)]


def init_page_pool(
    cfg: LlamaConfig,
    n_pages: int,
    page: int,
    n_kv_heads_local: int | None = None,
    dtype=jnp.float32,
) -> list[tuple[jax.Array, jax.Array]]:
    """Prefix-cache page pool: a list of per-layer ``(keys, values)`` halves
    of [n_pages, page, Kl, hd] (engine.prefix_cache). Pages hold immutable,
    refcounted KV prefixes published from slab rows; decode attention reads
    them zero-copy through per-row page tables (ops.attention paged
    variants), so each cached byte exists exactly once. The HBM budget is
    n_pages * :func:`page_pool_bytes` — configured with ``--kv-pages`` on
    the serving surface."""
    from distributed_llama_tpu.ops import kv_cache as kvc

    kl = n_kv_heads_local if n_kv_heads_local is not None else cfg.n_kv_heads
    return [
        (
            kvc.init_page_pool_half(n_pages, page, kl, cfg.head_size, dtype),
            kvc.init_page_pool_half(n_pages, page, kl, cfg.head_size, dtype),
        )
        for _ in range(cfg.n_layers)
    ]


def page_pool_bytes(cfg: LlamaConfig, page: int, dtype) -> int:
    """Logical KV bytes one pool page holds across all layers and both
    halves (the telemetry/bench accounting unit for pool occupancy and the
    copy traffic zero-copy aliasing avoids)."""
    from distributed_llama_tpu.ops import kv_cache as kvc

    kl, hd = cfg.n_kv_heads, cfg.head_size
    if kvc.is_quantized_cache_dtype(dtype):
        per_half = page * kl * hd + page * kl * 4  # int8 data + f32 scales
    else:
        per_half = page * kl * hd * jnp.dtype(dtype).itemsize
    return 2 * cfg.n_layers * per_half


def init_cache(
    cfg: LlamaConfig,
    n_kv_heads_local: int | None = None,
    dtype=jnp.float32,
    layered: bool = False,
) -> jax.Array | list[tuple[jax.Array, jax.Array]]:
    """Preallocated KV cache [L, 2, S, Kl, hd]
    (reference: KvCacheSlice, src/commands.cpp:97-102).

    ``layered=True`` returns a list of per-layer FUSED [2, S, Kl, hd]
    leaves (``leaf[0]``/``leaf[1]`` = keys/values) — the form the unrolled
    forward needs so in-place cache updates alias per leaf instead of
    copying the whole cache each step, with each layer's K/V pair written
    by ONE coalesced dynamic_update_slice (see attention). ``dtype="i8"``
    builds a quantized cache
    (:class:`distributed_llama_tpu.ops.kv_cache.QuantizedKV` with fused
    [2, S, Kl, hd] data — half the HBM of bf16; layered only). The tp/sp/ep
    backends build their own sharded ``(keys, values)``-tuple caches."""
    from distributed_llama_tpu.ops import kv_cache as kvc

    kl = n_kv_heads_local if n_kv_heads_local is not None else cfg.n_kv_heads
    shape = (cfg.seq_len, kl, cfg.head_size)
    if kvc.is_quantized_cache_dtype(dtype) and not layered:
        raise ValueError("the i8 KV cache requires the layered cache layout")
    if layered:
        return [kvc.init_fused(shape, dtype) for _ in range(cfg.n_layers)]
    return jnp.zeros((cfg.n_layers, 2) + shape, dtype=dtype)
