"""Static (hashable) model configuration used as a jit static argument.

Derived from the `.m` header's ModelSpec (reference: src/transformer.hpp:62-90)
but frozen, so traced functions can specialize on it.
"""

from __future__ import annotations

import dataclasses

from distributed_llama_tpu.formats.model_file import ArchType, HiddenAct, ModelSpec, RopeType


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    arch: ArchType
    dim: int
    hidden_dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    vocab_size: int
    seq_len: int
    head_size: int
    kv_dim: int
    n_experts: int = 0
    n_active_experts: int = 0
    hidden_act: HiddenAct = HiddenAct.SILU
    rope_type: RopeType = RopeType.LLAMA
    rope_theta: float = 10000.0
    rope_scaling_factor: float = 0.0
    rope_scaling_low_freq_factor: float = 0.0
    rope_scaling_high_freq_factor: float = 0.0
    rope_scaling_orig_max_seq_len: int = 0
    # bug-for-bug compat with the reference's Llama3_1RopeCommand, which
    # applies its frequency-scaling formula to the *rotated values* instead of
    # the frequencies (reference: src/commands.cpp:224-225). Off by default:
    # the correct frequency scaling matches HF and gives the intended
    # long-context behavior.
    rope_llama3_reference_quirk: bool = False
    # MoE prefill/dispatch capacity factor: per-expert bucket size is
    # ceil(factor * tokens * k / E) rows, overflow rows DROP (standard
    # capacity semantics — faster, but lossy under routing imbalance).
    # 0.0 (default) = exact: drop-free buckets sized for the worst case
    # (the parity-with-the-reference default); opt into e.g. 2.0 via the
    # CLI/server --moe-capacity flag for the measured prefill speedup.
    moe_capacity_factor: float = 0.0

    @property
    def kv_mul(self) -> int:
        return self.n_heads // self.n_kv_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0


def config_from_spec(spec: ModelSpec, **overrides) -> LlamaConfig:
    return LlamaConfig(
        arch=spec.arch_type,
        dim=spec.dim,
        hidden_dim=spec.hidden_dim,
        n_layers=spec.n_layers,
        n_heads=spec.n_heads,
        n_kv_heads=spec.n_kv_heads,
        vocab_size=spec.vocab_size,
        seq_len=spec.seq_len,
        head_size=spec.head_size,
        kv_dim=spec.kv_dim,
        n_experts=spec.n_experts,
        n_active_experts=spec.n_active_experts,
        hidden_act=spec.hidden_act,
        rope_type=spec.resolved_rope_type(),
        rope_theta=spec.rope_theta,
        rope_scaling_factor=spec.rope_scaling_factor,
        rope_scaling_low_freq_factor=spec.rope_scaling_low_freq_factor,
        rope_scaling_high_freq_factor=spec.rope_scaling_high_freq_factor,
        rope_scaling_orig_max_seq_len=spec.rope_scaling_orig_max_seq_len,
        **overrides,
    )
