"""Rotary position embeddings: llama (interleaved), falcon (neox halves),
llama-3.1 (frequency scaling for 128K contexts).

Reference behaviors: LlamaRopeCommand (src/commands.cpp:140-179) rotates
interleaved pairs (2j, 2j+1) with freq = theta^(-2j/head_size);
FalconRopeCommand (src/commands.cpp:229-257) rotates pairs (j, j+half);
Llama3_1RopeCommand (src/commands.cpp:181-227) adds wavelength-dependent
frequency scaling.

TPU-first design: cos/sin tables are precomputed once on host as [seq_len,
head_size/2] arrays and gathered by position inside the jitted step —
matching the reference's precomputed cache idea (commands.cpp:147-157) but
vectorized over all heads/positions at once.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.formats.model_file import RopeType
from distributed_llama_tpu.models.config import LlamaConfig


def _llama3_scale_freqs(freqs: np.ndarray, cfg: LlamaConfig) -> np.ndarray:
    """Llama 3.1 NTK-by-parts frequency scaling (the *correct* form, as in the
    original Meta/HF implementation; the reference's value-space variant is
    available via cfg.rope_llama3_reference_quirk)."""
    factor = cfg.rope_scaling_factor
    low = cfg.rope_scaling_low_freq_factor
    high = cfg.rope_scaling_high_freq_factor
    orig = cfg.rope_scaling_orig_max_seq_len
    if factor == 0 or orig == 0:
        return freqs
    wavelen = 2.0 * math.pi / freqs
    low_wavelen = orig / low
    high_wavelen = orig / high
    scaled = np.where(wavelen > low_wavelen, freqs / factor, freqs)
    smooth = (orig / wavelen - low) / (high - low)
    smoothed = (1 - smooth) * freqs / factor + smooth * freqs
    mid = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
    return np.where(mid, smoothed, scaled).astype(freqs.dtype)


def build_rope_table(cfg: LlamaConfig) -> np.ndarray:
    """Precompute [seq_len, head_size/2, 2] (cos, sin) in float32."""
    half = cfg.head_size // 2
    j = np.arange(half, dtype=np.float64)
    freqs = 1.0 / (cfg.rope_theta ** (2.0 * j / cfg.head_size))
    if cfg.rope_type == RopeType.LLAMA3_1 and not cfg.rope_llama3_reference_quirk:
        freqs = _llama3_scale_freqs(freqs.astype(np.float64), cfg)
    pos = np.arange(cfg.seq_len, dtype=np.float64)
    angles = pos[:, None] * freqs[None, :]
    table = np.stack([np.cos(angles), np.sin(angles)], axis=-1)
    return table.astype(np.float32)


def _reference_llama3_value_scale(v: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """The reference's Llama3_1RopeCommand::scale applied to rotated values
    (reference: src/commands.cpp:193-205, 224-225). Kept only for bit-parity
    experiments against the C++ runtime."""
    factor = cfg.rope_scaling_factor
    low = cfg.rope_scaling_low_freq_factor
    high = cfg.rope_scaling_high_freq_factor
    orig = cfg.rope_scaling_orig_max_seq_len
    wave_len = 2.0 * math.pi * v
    low_wavelen = orig / low
    high_wavelen = orig / high
    smooth = (orig / wave_len - low) / (high - low)
    smoothed = (1 - smooth) * v / factor + smooth * v
    return jnp.where(
        wave_len < high_wavelen, v, jnp.where(wave_len > low_wavelen, v / factor, smoothed)
    )


def apply_rope_interleaved(
    x: jax.Array, table_slice: jax.Array, cfg: LlamaConfig
) -> jax.Array:
    """Rotate interleaved pairs. ``x``: [T, n_heads, head_size];
    ``table_slice``: [T, head_size/2, 2] rows already gathered by position."""
    shape = x.shape
    xp = x.reshape(*shape[:-1], cfg.head_size // 2, 2)
    cos = table_slice[:, None, :, 0]
    sin = table_slice[:, None, :, 1]
    v0 = xp[..., 0]
    v1 = xp[..., 1]
    r0 = v0 * cos - v1 * sin
    r1 = v0 * sin + v1 * cos
    if cfg.rope_type == RopeType.LLAMA3_1 and cfg.rope_llama3_reference_quirk:
        r0 = _reference_llama3_value_scale(r0, cfg)
        r1 = _reference_llama3_value_scale(r1, cfg)
    return jnp.stack([r0, r1], axis=-1).reshape(shape)


def apply_rope_neox(x: jax.Array, table_slice: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """Falcon/neox-style rotation of pairs (j, j+half). Same table (the
    frequency for pair j is theta^(-2j/head_size) in both layouts)."""
    half = cfg.head_size // 2
    v0 = x[..., :half]
    v1 = x[..., half:]
    cos = table_slice[:, None, :, 0]
    sin = table_slice[:, None, :, 1]
    r0 = v0 * cos - v1 * sin
    r1 = v0 * sin + v1 * cos
    return jnp.concatenate([r0, r1], axis=-1)


def apply_rope(x: jax.Array, table_slice: jax.Array, cfg: LlamaConfig) -> jax.Array:
    if cfg.rope_type == RopeType.FALCON:
        return apply_rope_neox(x, table_slice, cfg)
    return apply_rope_interleaved(x, table_slice, cfg)
