"""Model architectures (Llama, Mixtral, Grok-1) as pure JAX functions.

The reference expresses a model as a flat task list executed in lock-step by a
thread pool (reference: src/llama2-tasks.cpp:241-298); here a model is a pure
``forward`` function over a pytree of stacked per-layer weights, scanned with
``jax.lax.scan`` and compiled once by XLA. Collective points (the reference's
sync tasks) are `psum`s keyed by an optional mesh axis name, so the same code
runs single-chip (axis None) and tensor-parallel (inside shard_map).
"""

from distributed_llama_tpu.models.config import LlamaConfig, config_from_spec
from distributed_llama_tpu.models.llama import forward_tokens, init_cache

__all__ = ["LlamaConfig", "config_from_spec", "forward_tokens", "init_cache"]
